"""Sharding-flow checks — client analyses over :mod:`.sharding_flow`
(ISSUE 4 tentpole).

Apex's parallelism pitch was that the collectives were *pre-audited*:
Megatron TP/PP and DDP buckets shipped with their communication pattern
already reasoned about. These checks machine-check the same properties
over the traced programs, where the failure modes are silent — a
mis-sharded boundary compiles fine and only shows up as a slow or
OOMing step on silicon:

- ``implicit-reshard``   the propagated sharding disagrees with a
  ``with_sharding_constraint``/out-sharding boundary in a way GSPMD can
  only satisfy by *moving* data (an axis hops dims ⇒ all-to-all, or a
  dim re-shards onto a different axis), or two differently-sharded
  operands meet in one elementwise op (one side gets resharded).
  An explicit constraint that simply *drops* an axis is not flagged:
  constraining to replicated is the documented GSPMD way to ASK for an
  all-gather (``gather_output``, sequence-parallel boundaries) — the
  hidden reshards are the ones nobody wrote down.
- ``replicated-large``   a large input (params, optimizer state) whose
  spec is fully replicated although some mesh axis divides one of its
  dims — TP master weights living whole on every device.
- ``psum-scatter``       a ``psum`` whose result is immediately sliced
  to this rank's chunk along the reduced mesh axis: half the bytes of
  the allreduce are thrown away; ``lax.psum_scatter`` moves ~half as
  much.
- ``dead-collective``    a collective whose operand cannot differ
  across the mesh axis it rides (``distinct`` lattice): the bytes move
  (or a tree reduction runs) to reproduce what every chip already has.
  The classic is ``psum(jnp.ones(()))`` as an axis-size probe — that is
  ``lax.axis_size``, a compile-time constant.
- ``hbm-budget``         live-range peak-HBM estimate (per-device
  local bytes under the propagated shardings, donation credit from the
  PR 1 donation wiring) against a configurable per-device budget
  (:func:`apex_tpu.ops.pallas_config.device_hbm_bytes`).

Entry point: :func:`analyze_sharding` (mirrors
``precision_checks.analyze_precision``); the registered customers live
in :mod:`.targets`. Every run also produces the per-target comms-bytes
and peak-HBM estimates bench.py ships in its JSON line and the metrics
JSONL (``analysis/sharding_*`` family).
"""

from __future__ import annotations

import contextlib

from apex_tpu.analysis.findings import Finding
from apex_tpu.analysis.sharding_flow import (
    COLLECTIVE_PRIMS,
    ShardVal,
    collective_bytes,
    estimate_hbm_and_comms,
    interpret_sharding,
    live_mesh_axis_sizes,
    local_bytes,
    normalize_spec,
)

SHARDING_CHECKS = (
    "implicit-reshard", "replicated-large", "psum-scatter",
    "dead-collective", "hbm-budget",
)

# Inputs below this size are never worth sharding (replicated-large).
DEFAULT_REPLICATED_THRESHOLD = 1 << 20  # 1 MiB

# When armed (a dict), analyze_sharding records each traced target's
# (fn, example_args, donate_argnums, closed jaxpr) under its name — the
# hook the memory-calibration tier (ISSUE 15) uses to AOT-compile the
# exact program the HBM estimator priced. Arm via capture_traces().
_TRACE_CAPTURE = None


@contextlib.contextmanager
def capture_traces(sink: dict):
    """Arm the per-target trace capture for the duration of the block;
    ``sink`` receives one entry per analyze_sharding call (keyed by
    target name). Re-entrant: the previous sink is restored on exit."""
    global _TRACE_CAPTURE
    prev, _TRACE_CAPTURE = _TRACE_CAPTURE, sink
    try:
        yield sink
    finally:
        _TRACE_CAPTURE = prev


def _fmt_spec(spec):
    if spec is None:
        return "?"
    return "P(" + ", ".join(
        ("None" if not e else "+".join(e) if len(e) > 1 else e[0])
        for e in spec) + ")"


def _fmt_bytes(n):
    if n >= 1 << 30:
        return f"{n / (1 << 30):.2f} GiB"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f} MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f} KiB"
    return f"{n} B"


# Binary/ternary ops whose operands GSPMD must co-locate elementwise —
# the only place the join-conflict flavor of implicit-reshard applies.
_ELEMENTWISE_JOIN_PRIMS = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "pow", "atan2",
    "and", "or", "xor", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "nextafter", "complex", "add_any",
    "eq", "ne", "lt", "le", "gt", "ge", "select_n",
})


class _Ctx:
    def __init__(self, name, path):
        self.name = name
        self.path = path
        self.findings = []
        self.seen = set()

    def add(self, check, severity, message, dedup_key=None):
        if dedup_key is not None:
            key = (check,) + tuple(dedup_key)
            if key in self.seen:
                return
            self.seen.add(key)
        self.findings.append(Finding(
            check, severity, self.path, 0, self.name, message))


# ------------------------------------------------------------- checks

def _visit_implicit_reshard(ctx, eqn, ins, outs, mctx):
    prim = eqn.primitive.name
    if prim == "sharding_constraint":
        src = ins[0] if ins else None
        if src is None or src.spec is None:
            return
        sharding = eqn.params.get("sharding")
        want = normalize_spec(getattr(sharding, "spec", None),
                              len(src.spec))
        have = src.spec
        if have == want:
            return
        have_dims = {a: d for d, e in enumerate(have) for a in e}
        want_dims = {a: d for d, e in enumerate(want) for a in e}
        moved = {a: (have_dims[a], want_dims[a]) for a in have_dims
                 if a in want_dims and have_dims[a] != want_dims[a]}
        aval = eqn.invars[0].aval
        if moved:
            nb = local_bytes(aval, src, mctx)
            axes = sorted(moved)
            moves = ", ".join(f"'{a}' dim {moved[a][0]}→{moved[a][1]}"
                              for a in axes)
            ctx.add(
                "implicit-reshard", "error",
                f"sharding constraint moves mesh axis "
                f"{moves}: propagated {_fmt_spec(have)} vs constrained "
                f"{_fmt_spec(want)} forces a hidden all-to-all of "
                f"~{_fmt_bytes(nb)} per device — reshard explicitly "
                f"(or fix the upstream with_sharding_constraint) so "
                f"the transfer is visible and schedulable",
                dedup_key=("moved", have, want))
            return
        for d, (h, w) in enumerate(zip(have, want)):
            if h and w and h != w:
                nb = local_bytes(aval, src, mctx)
                ctx.add(
                    "implicit-reshard", "error",
                    f"dim {d} arrives sharded over {'+'.join(h)} but "
                    f"the constraint wants {'+'.join(w)}: GSPMD "
                    f"inserts a hidden reshard (~{_fmt_bytes(nb)} per "
                    f"device) — align the producer's sharding with "
                    f"this boundary",
                    dedup_key=("axis", d, h, w))
        return

    # elementwise join of incompatibly-sharded operands: one side gets
    # an implicit all-gather/reshard nobody wrote down. Only genuinely
    # elementwise prims — a gather/pjit/concatenate legitimately mixes
    # operands whose shardings differ (e.g. an embedding lookup where
    # the table shards over a different dim than the indices).
    if prim not in _ELEMENTWISE_JOIN_PRIMS or len(eqn.invars) < 2:
        return
    known = [(v, iv) for v, iv in zip(ins, eqn.invars)
             if v is not None and v.spec is not None]
    if len(known) < 2:
        return
    ndims = {len(v.spec) for v, _ in known}
    if len(ndims) != 1:
        return
    base = known[0][0].spec
    base_dims = {a: d for d, e in enumerate(base) for a in e}
    for v, iv in known[1:]:
        for d, (a, b) in enumerate(zip(base, v.spec)):
            if a and b and a != b:
                nb = local_bytes(iv.aval, v, mctx)
                ctx.add(
                    "implicit-reshard", "error",
                    f"'{prim}' joins operands sharded differently on "
                    f"dim {d} ({'+'.join(a)} vs {'+'.join(b)}): XLA "
                    f"must reshard one side (~{_fmt_bytes(nb)} per "
                    f"device) on every step — add the missing "
                    f"with_sharding_constraint so both sides agree",
                    dedup_key=("join", prim, d, a, b))
        other_dims = {a: d for d, e in enumerate(v.spec) for a in e}
        for axis, d0 in sorted(base_dims.items()):
            d1 = other_dims.get(axis)
            if d1 is not None and d1 != d0:
                nb = local_bytes(iv.aval, v, mctx)
                ctx.add(
                    "implicit-reshard", "error",
                    f"'{prim}' joins operands carrying mesh axis "
                    f"'{axis}' on different dims ({d0} vs {d1}): XLA "
                    f"must all-to-all one side (~{_fmt_bytes(nb)} per "
                    f"device) on every step — add the missing "
                    f"with_sharding_constraint so both sides agree",
                    dedup_key=("join-move", prim, axis, d0, d1))


def _visit_psum_scatter(ctx, eqn, ins, outs, mctx):
    if eqn.primitive.name != "dynamic_slice":
        return
    op = ins[0] if ins else None
    if op is None or not op.psum_axes:
        return
    rank_axes = frozenset()
    for v in ins[1:]:
        if v is not None:
            rank_axes |= v.from_axis_index
    hit = op.psum_axes & rank_axes
    if not hit:
        return
    axis = sorted(hit)[0]
    n = mctx.size(axis)
    try:
        nb = local_bytes(eqn.invars[0].aval, op, mctx)
    except Exception:
        nb = 0
    ctx.add(
        "psum-scatter", "warning",
        f"psum over '{axis}' immediately sliced to this rank's chunk "
        f"(slice start derives from axis_index('{axis}')): the "
        f"allreduce moves ~{_fmt_bytes(collective_bytes('psum', nb, [n]))} "
        f"per device and {max(n - 1, 1)}/{n} of the result is thrown "
        f"away — lax.psum_scatter moves ~half the bytes and skips the "
        f"slice",
        dedup_key=(axis,))


def _visit_dead_collective(ctx, eqn, ins, outs, mctx):
    prim = eqn.primitive.name
    param = COLLECTIVE_PRIMS.get(prim)
    if param is None or prim in ("psum_scatter", "reduce_scatter"):
        # psum_scatter of replicated data still produces per-rank
        # chunks — not a pure no-op, so it stays out of this check
        return
    axes = [a for a in _axes_of(eqn.params.get(param))]
    if not axes:
        return
    # a fused tree psum carries several operands: the collective is
    # alive if ANY of them can differ (Literal/None operands are
    # definitionally identical everywhere)
    distinct = frozenset().union(
        *(v.distinct for v in ins if v is not None)) \
        if any(v is not None for v in ins) else frozenset()
    if distinct & frozenset(axes):
        return
    # unknown-provenance guard: a value varying over an axis we failed
    # to model would be distinct-empty too; only fire when the operand
    # world is one the lattice fully models (inside shard_map, where
    # every distinct source is in_names / axis_index / collectives)
    if not mctx.manual_axes.issuperset(axes):
        return
    ctx.add(
        "dead-collective", "warning",
        f"'{prim}' over {axes} moves data that cannot differ across "
        f"{'that axis' if len(axes) == 1 else 'those axes'}: every "
        f"device already holds the result"
        + (" — psum of a constant is just a scaled copy; use "
           "jax.lax.axis_size for size probes"
           if prim in ("psum", "psum2") else "")
        + ", drop the collective or compute it locally",
        dedup_key=(prim, tuple(axes)))


def _axes_of(value):
    if value is None:
        return ()
    if isinstance(value, (tuple, list, frozenset, set)):
        out = []
        for v in value:
            out.extend(_axes_of(v))
        return tuple(out)
    return (str(value),)


_VISITORS = {
    "implicit-reshard": _visit_implicit_reshard,
    "psum-scatter": _visit_psum_scatter,
    "dead-collective": _visit_dead_collective,
}


def _check_replicated_large(ctx, closed, in_vals, axis_sizes,
                            threshold):
    import numpy as np
    for i, var in enumerate(closed.jaxpr.invars):
        val = in_vals[i] if i < len(in_vals) else None
        if val is None or val.spec is None or val.axes_used():
            continue
        aval = var.aval
        shape = tuple(getattr(aval, "shape", ()) or ())
        nbytes = int(np.prod(shape or (1,)) *
                     np.dtype(str(aval.dtype)).itemsize)
        if nbytes < threshold:
            continue
        shardable = [
            (axis, size) for axis, size in sorted(axis_sizes.items())
            if size > 1 and any(d >= size and d % size == 0
                                for d in shape)]
        if not shardable:
            continue
        axis, size = shardable[0]
        ctx.add(
            "replicated-large", "warning",
            f"input {i} ({str(aval.dtype)}{list(shape)}, "
            f"{_fmt_bytes(nbytes)}) is fully replicated although mesh "
            f"axis '{axis}' (size {size}) divides one of its dims: "
            f"every device holds the whole array — shard it (master "
            f"weights/optimizer state shard over tp like the params "
            f"they mirror)",
            dedup_key=("input", i))


# -------------------------------------------------------------- entry

def _flatten_specs(example_args, in_specs):
    """Per-arg specs -> one PartitionSpec-or-None per flat leaf."""
    import jax
    from jax.sharding import PartitionSpec

    def is_spec(x):
        return x is None or isinstance(x, PartitionSpec)

    flat = []
    for argnum, arg in enumerate(example_args):
        leaves = jax.tree_util.tree_leaves(arg)
        entry = None
        if in_specs is not None and argnum < len(in_specs):
            entry = in_specs[argnum]
        if is_spec(entry):
            flat.extend([entry] * len(leaves))
            continue
        spec_leaves = jax.tree_util.tree_leaves(entry, is_leaf=is_spec)
        if len(spec_leaves) != len(leaves):
            raise ValueError(
                f"in_specs[{argnum}] has {len(spec_leaves)} spec "
                f"leaves for {len(leaves)} argument leaves")
        flat.extend(spec_leaves)
    return flat


def analyze_sharding(fn, *example_args, name=None, in_specs=None,
                     donate_argnums=(), axis_sizes=None, checks=None,
                     hbm_budget_bytes=None,
                     replicated_threshold_bytes=None, stats_out=None):
    """Trace ``fn`` and run the sharding-flow checks over its jaxpr.

    ``in_specs``: one entry per positional arg — a ``PartitionSpec``
    (or None) applied to every leaf, or a matching pytree of specs.
    ``donate_argnums`` mirrors ``jax.jit``'s and feeds the hbm-budget
    liveness credit. ``axis_sizes`` is the mesh universe (default: the
    live ``parallel_state`` mesh). ``hbm_budget_bytes`` defaults to
    :func:`apex_tpu.ops.pallas_config.device_hbm_bytes`.
    ``stats_out``: optional dict that receives the per-device
    ``comms_bytes`` / ``peak_hbm_bytes`` estimates even when no check
    fires — the numbers bench.py reports. Returns a list of
    :class:`Finding`.
    """
    import jax

    name = name or getattr(fn, "__name__", "fn")
    _validate_checks(checks)

    closed = jax.make_jaxpr(fn)(*example_args)

    if _TRACE_CAPTURE is not None:
        # ISSUE 15: the memory-calibration tier re-compiles the SAME
        # (fn, args) triple the estimator modeled, so measured-vs-
        # modeled compares like for like. Captured before the specs are
        # flattened so the sink owns everything a jit needs.
        _TRACE_CAPTURE[name] = {
            "fn": fn, "example_args": example_args,
            "donate_argnums": donate_argnums, "closed": closed,
        }

    flat_specs = _flatten_specs(example_args, in_specs)
    in_vals = []
    for i, var in enumerate(closed.jaxpr.invars):
        spec = flat_specs[i] if i < len(flat_specs) else None
        ndim = len(getattr(var.aval, "shape", ()) or ())
        # None means UNKNOWN (the engine stays quiet about this input);
        # an explicit P() asserts full replication and is checked
        in_vals.append(ShardVal(spec=None) if spec is None
                       else ShardVal(spec=normalize_spec(spec, ndim)))

    donated = set()
    if donate_argnums:
        import jax as _jax
        donate = {donate_argnums} if isinstance(donate_argnums, int) \
            else set(donate_argnums)
        idx = 0
        for argnum, arg in enumerate(example_args):
            n = len(_jax.tree_util.tree_leaves(arg))
            if argnum in donate:
                donated.update(range(idx, idx + n))
            idx += n

    return analyze_sharding_jaxpr(
        closed, in_vals, name=name, donated=donated,
        axis_sizes=axis_sizes, checks=checks,
        hbm_budget_bytes=hbm_budget_bytes,
        replicated_threshold_bytes=replicated_threshold_bytes,
        stats_out=stats_out)


def _validate_checks(checks):
    """The requested check-id set, validated loudly (and BEFORE any
    expensive trace a caller is about to pay for)."""
    run = set(checks or SHARDING_CHECKS)
    unknown = run - set(SHARDING_CHECKS)
    if unknown:
        raise ValueError(
            f"unknown sharding check(s) {sorted(unknown)}; valid: "
            f"{list(SHARDING_CHECKS)}")
    return run


def analyze_sharding_jaxpr(closed, in_vals, *, name, donated=frozenset(),
                           axis_sizes=None, checks=None,
                           hbm_budget_bytes=None,
                           replicated_threshold_bytes=None,
                           stats_out=None):
    """Jaxpr-level entry: run the sharding-flow checks over an
    already-traced ``ClosedJaxpr`` with explicit per-invar
    :class:`ShardVal` inputs and flat donated indices.

    This is :func:`analyze_sharding` minus the tracing — the hook the
    auto-sharding planner (:mod:`.planner`) uses to re-check every
    candidate layout against one trace, so the plan it emits is vetted
    by exactly the analyses that gate the repo."""
    path = f"<jaxpr:{name}>"
    run = _validate_checks(checks)
    if axis_sizes is None:
        axis_sizes = live_mesh_axis_sizes()
    if replicated_threshold_bytes is None:
        replicated_threshold_bytes = DEFAULT_REPLICATED_THRESHOLD

    ctx = _Ctx(name, path)
    visitors = [_VISITORS[c] for c in SHARDING_CHECKS
                if c in run and c in _VISITORS]

    def visit(eqn, ins, outs, mctx):
        for v in visitors:
            v(ctx, eqn, ins, outs, mctx)

    interpret_sharding(closed, in_vals, axis_sizes=axis_sizes,
                       visit=visit if visitors else None)

    if "replicated-large" in run:
        _check_replicated_large(ctx, closed, in_vals, axis_sizes,
                                replicated_threshold_bytes)

    stats = estimate_hbm_and_comms(closed, in_vals, donated=donated,
                                   axis_sizes=axis_sizes)
    if stats_out is not None:
        stats_out.update(stats)

    if "hbm-budget" in run:
        if hbm_budget_bytes is None:
            from apex_tpu.ops.pallas_config import device_hbm_bytes
            hbm_budget_bytes = device_hbm_bytes()
        peak = stats["peak_hbm_bytes"]
        if peak > hbm_budget_bytes:
            ctx.add(
                "hbm-budget", "error",
                f"estimated peak live HBM {_fmt_bytes(peak)} per "
                f"device (step {stats['peak_step']} of the linearized "
                f"program, donation credit applied) exceeds the "
                f"{_fmt_bytes(hbm_budget_bytes)} budget — shard or "
                f"donate the big buffers, or raise the budget "
                f"(APEX_TPU_HBM_BYTES / device_hbm_bytes) if the "
                f"target really has more HBM")

    return ctx.findings


def report_to_registry(results, registry=None):
    """Publish sharding findings + per-target estimates as the
    ``analysis/sharding_*`` metric family.

    ``results``: {target name: (findings list, stats dict)}. Counters:
    ``analysis/sharding_findings{check=}``; gauges:
    ``analysis/sharding_findings_total``,
    ``analysis/sharding_comms_bytes{target=}``,
    ``analysis/sharding_peak_hbm_bytes{target=}``. Returns
    {check id: count}.
    """
    from apex_tpu.observability import get_registry

    reg = registry if registry is not None else get_registry()
    counts = {c: 0 for c in SHARDING_CHECKS}
    for target, (findings, stats) in sorted(results.items()):
        for f in findings:
            if f.check in counts:
                counts[f.check] += 1
        if stats:
            reg.gauge("analysis/sharding_comms_bytes",
                      target=target).set(stats.get("comms_bytes", 0))
            reg.gauge("analysis/sharding_peak_hbm_bytes",
                      target=target).set(stats.get("peak_hbm_bytes", 0))
    for check, n in counts.items():
        if n:
            reg.counter("analysis/sharding_findings", check=check).inc(n)
    reg.gauge("analysis/sharding_findings_total").set(
        sum(counts.values()))
    return counts
