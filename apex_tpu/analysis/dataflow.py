"""Forward abstract interpretation over closed jaxprs — the flow engine
under the precision checks (ISSUE 3 tentpole).

PR 1's jaxpr engine (:mod:`.jaxpr_checks`) is per-equation pattern
matching: it can see *one* ``pallas_call``'s BlockSpecs or *one*
collective's axis name, but it cannot answer flow questions like "does
this bf16 value reach a sum without an fp32 accumulator?" or "did these
gradients pass through the scaler's unscale before touching the
params?". This module adds the missing machinery: a small forward
abstract interpreter whose value lattice tracks, per jaxpr ``Var``,

- ``dtype`` / ``origin``   current dtype and the dtype the value was
  born with (input, constant, or first producer);
- ``cast_chain``           the run of *consecutive*
  ``convert_element_type``s the value just went through (any compute op
  resets it) — the cast-churn signal;
- ``reduction_depth``      how many accumulating ops (``dot_general``,
  ``reduce_sum``, ...) lie on the value's history;
- ``taints``               client-assigned labels ("grad", "master",
  "scale", ...) propagated through every op — the dataflow analog of
  the roles apex documents (master weights, scaled gradients);
- ``unscaled``             True once a "grad"-tainted value has been
  multiplied/divided by a "scale"-tainted value (the loss-scaler's
  unscale);
- ``from_max`` / ``max_subtracted``  whether the value is (derived
  from) a running max, and whether a max was subtracted from it — the
  softmax-stability signal;
- ``fp8_scaled`` / ``fp8_scale_hist``  whether a delayed fp8 scale has
  been multiplied in (a value carrying the client taint
  ``"fp8_scale"``), and whether that scale derived from the
  amax-history state (taint ``"amax_hist"``) — the O4 signals the
  ``fp8-unscaled`` / ``fp8-stale-amax`` checks read.

Sub-jaxprs are entered, not skipped: ``pjit``/``closed_call``/
``remat``/``custom_jvp_call``/``custom_vjp_call`` bodies are
interpreted with the caller's abstract values bound to their invars;
``scan``/``while``/``cond`` bodies likewise (one pass, no fixpoint —
a loop-carried precision change is seen on its first iteration, which
is where every check here fires anyway). ``pallas_call`` is opaque by
design: its outputs are rebuilt from the out avals with the union of
the input taints (kernel internals are covered by the pallas-block
check and kernel unit tests, not by dataflow).

Clients subscribe with visitor callbacks; :mod:`.precision_checks`
builds the five shipped analyses on top. The engine itself never emits
a Finding.

The structural traversal (call prims, scan/while/cond, shard_map)
lives ONCE in :mod:`.interp`; this module contributes the
:class:`PrecisionLattice` value semantics, so precision and sharding
checks can share a single walk (ISSUE 8).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from apex_tpu.analysis import interp

__all__ = [
    "AbsVal", "HALF_DTYPES", "FP8_DTYPES", "ADDITIVE_REDUCTIONS",
    "ARITH_PRIMS", "PrecisionLattice", "PRECISION_LATTICE",
    "interpret", "abs_val_for_aval", "itemsize",
]

HALF_DTYPES = frozenset({"bfloat16", "float16"})

#: the MXU fp8 formats (O4 tier) — tracked separately from the halves:
#: an fp8 value's safety is about its SCALE provenance, not its
#: accumulator (the epilogues always pin fp32 accumulation).
FP8_DTYPES = frozenset({"float8_e4m3fn", "float8_e5m2"})

FLOAT_DTYPES = frozenset({
    "bfloat16", "float16", "float32", "float64",
    "float8_e4m3fn", "float8_e5m2",
})

# Accumulating primitives: a low-precision operand here loses mass.
ADDITIVE_REDUCTIONS = frozenset({
    "reduce_sum", "add_any", "cumsum", "reduce_window_sum",
    "dot_general", "conv_general_dilated",
})

# Ops that preserve the value's *identity* (broadcasts, layout moves,
# gradient stops): from_max / max_subtracted / cast_chain flow through.
_PRESERVE_PRIMS = frozenset({
    "broadcast_in_dim", "reshape", "squeeze", "expand_dims", "transpose",
    "slice", "dynamic_slice", "stop_gradient", "copy", "rev", "neg",
})

# Arithmetic primitives in the "touches the value's bits" sense the
# master-weight / loss-scale checks care about.
ARITH_PRIMS = frozenset({
    "add", "sub", "mul", "div", "dot_general", "conv_general_dilated",
    "pow", "integer_pow", "sqrt", "rsqrt", "exp", "log", "log1p",
    "tanh", "logistic", "max", "min", "square", "abs", "erf",
    "add_any", "atan2", "expm1", "cbrt",
})

_MAX_PRIMS = frozenset({"reduce_max", "cummax"})


def itemsize(dtype: str) -> int:
    return np.dtype(dtype).itemsize


def _is_float(dtype: str) -> bool:
    return dtype in FLOAT_DTYPES


@dataclasses.dataclass(frozen=True)
class AbsVal:
    """One point of the value lattice (see module docstring)."""

    dtype: str
    origin: str
    cast_chain: tuple = ()
    reduction_depth: int = 0
    taints: frozenset = frozenset()
    unscaled: bool = False
    from_max: bool = False
    max_subtracted: bool = False
    fp8_scaled: bool = False      # a delayed fp8 scale was applied
    fp8_scale_hist: bool = False  # ... and it derived from amax history

    def with_(self, **kw) -> "AbsVal":
        return dataclasses.replace(self, **kw)

    def touches_fp8(self) -> bool:
        """Is this value in (or a pure cast away from) an fp8 dtype?
        The cast chain resets on compute, so an f8 value upcast right
        before a dot still reads as fp8 here."""
        return self.dtype in FP8_DTYPES or \
            any(d in FP8_DTYPES for d in self.cast_chain)


def abs_val_for_aval(aval, taints=frozenset()) -> AbsVal:
    dtype = str(getattr(aval, "dtype", "float32"))
    return AbsVal(dtype=dtype, origin=dtype, taints=frozenset(taints))


def _join(vals, out_aval):
    """Default transfer: merge the float inputs into the output value."""
    dtype = str(getattr(out_aval, "dtype", "float32"))
    floats = [v for v in vals if v is not None and _is_float(v.dtype)]
    ins = [v for v in vals if v is not None]
    origin = floats[0].origin if floats else dtype
    taints = frozenset().union(*(v.taints for v in ins)) if ins \
        else frozenset()
    depth = max((v.reduction_depth for v in ins), default=0)
    unscaled = any(v.unscaled for v in ins)
    return AbsVal(dtype=dtype, origin=origin, reduction_depth=depth,
                  taints=taints, unscaled=unscaled,
                  fp8_scaled=any(v.fp8_scaled for v in ins),
                  fp8_scale_hist=any(v.fp8_scale_hist for v in ins))


def _transfer(eqn, in_vals, out_avals):
    """Abstract transfer function: in_vals (AbsVal | None for Literals)
    -> tuple of out AbsVals."""
    prim = eqn.primitive.name
    outs = []

    if prim == "convert_element_type":
        src = in_vals[0]
        for aval in out_avals:
            new_dtype = str(aval.dtype)
            if src is None:
                outs.append(AbsVal(dtype=new_dtype, origin=new_dtype))
                continue
            chain = src.cast_chain or (src.dtype,)
            outs.append(src.with_(
                dtype=new_dtype, cast_chain=chain + (new_dtype,)))
        return tuple(outs)

    if prim in _PRESERVE_PRIMS:
        src = next((v for v in in_vals if v is not None), None)
        for aval in out_avals:
            dtype = str(getattr(aval, "dtype", "float32"))
            if src is None:
                outs.append(AbsVal(dtype=dtype, origin=dtype))
            else:
                outs.append(src.with_(dtype=dtype, cast_chain=()))
        return tuple(outs)

    if prim in _MAX_PRIMS or (
            prim == "max" and any(v is not None and v.from_max
                                  for v in in_vals)):
        base = _join(in_vals, out_avals[0])
        return tuple(base.with_(dtype=str(a.dtype), from_max=True)
                     for a in out_avals)

    if prim == "sub":
        base = _join(in_vals, out_avals[0])
        rhs = in_vals[1] if len(in_vals) > 1 else None
        if rhs is not None and rhs.from_max:
            base = base.with_(max_subtracted=True)
        return (base,)

    if prim in ("mul", "div"):
        base = _join(in_vals, out_avals[0])
        present = [v for v in in_vals if v is not None]
        has_grad = any("grad" in v.taints for v in present)
        has_scale = any("scale" in v.taints and "grad" not in v.taints
                        for v in present)
        if has_grad and has_scale:
            base = base.with_(unscaled=True)
        # fp8 delayed-scale application (O4): multiplying/dividing by a
        # value descended from the fp8 scale state marks the product as
        # scaled; the scale counts as history-fresh only when it also
        # descends from the amax-history rings ("amax_hist" — assigned
        # to the threaded Fp8ScalingState by the target's roles)
        fp8_scales = [v for v in present if "fp8_scale" in v.taints
                      and v.dtype not in FP8_DTYPES]
        if fp8_scales:
            base = base.with_(
                fp8_scaled=True,
                fp8_scale_hist=base.fp8_scale_hist or any(
                    "amax_hist" in v.taints for v in fp8_scales))
        return (base,)

    if prim in ADDITIVE_REDUCTIONS:
        base = _join(in_vals, out_avals[0])
        return tuple(
            base.with_(dtype=str(a.dtype),
                       reduction_depth=base.reduction_depth + 1)
            for a in out_avals)

    if prim == "pallas_call":
        taints = frozenset().union(
            *(v.taints for v in in_vals if v is not None)) \
            if any(v is not None for v in in_vals) else frozenset()
        unscaled = any(v is not None and v.unscaled for v in in_vals)
        present = [v for v in in_vals if v is not None]
        return tuple(
            abs_val_for_aval(a, taints).with_(
                unscaled=unscaled,
                fp8_scaled=any(v.fp8_scaled for v in present),
                fp8_scale_hist=any(v.fp8_scale_hist for v in present))
            for a in out_avals)

    return tuple(_join(in_vals, a) for a in out_avals)


class PrecisionLattice(interp.Lattice):
    """The dtype/taint value semantics, plugged into the unified walk
    (:mod:`.interp`). Call-transparent everywhere — including
    ``shard_map``, which this engine enters like any call — and no
    carry fixpoint (every precision check fires on iteration 1)."""

    name = "precision"

    def for_aval(self, aval):
        return abs_val_for_aval(aval)

    def for_const(self, var, const):
        aval = getattr(var, "aval", None)
        return abs_val_for_aval(
            aval if aval is not None else np.asarray(const))

    def transfer(self, eqn, ins, out_avals, ctx):
        return _transfer(eqn, ins, out_avals)

    def bind_sub(self, aval, val):
        # positional binding keeps the caller taints; scan xs are
        # sliced along the leading axis but keep dtype, which is all
        # the lattice reads
        if val is None:
            return abs_val_for_aval(aval)
        return val.with_(dtype=str(aval.dtype))

    def fix_out(self, aval, val, restack=False):
        if val is None:
            return abs_val_for_aval(aval)
        return val.with_(dtype=str(aval.dtype))

    def join_branch(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return a.with_(
            taints=a.taints | b.taints,
            unscaled=a.unscaled or b.unscaled,
            reduction_depth=max(a.reduction_depth, b.reduction_depth),
            fp8_scaled=a.fp8_scaled or b.fp8_scaled,
            fp8_scale_hist=a.fp8_scale_hist or b.fp8_scale_hist,
        )


PRECISION_LATTICE = PrecisionLattice()


def interpret(closed, in_vals, visit=None):
    """Run the forward abstract interpretation over ``closed`` (a
    ``ClosedJaxpr``).

    ``in_vals``: one :class:`AbsVal` (or None for "derive from aval")
    per flat invar. ``visit(eqn, in_abs_vals, out_abs_vals)`` is called
    for every equation at every depth, after its transfer function.
    Returns the abstract values of the jaxpr outputs.
    """
    wrapped = None if visit is None else (
        lambda eqn, ins, outs, ctx: visit(eqn, ins, outs))
    (outs,) = interp.interpret_lattices(
        closed, [interp.LatticeRun(PRECISION_LATTICE, in_vals, wrapped)])
    return outs
