"""Host-concurrency engine: race/signal/callback safety for the
threaded host runtime (ISSUE 16 tentpole).

Every other engine in this package proves properties of *device-side*
jaxprs; the host side (SpanTracer, FlightRecorder + SIGQUIT,
MetricRegistry, AsyncCheckpointWriter, PreemptionWatcher + SIGTERM,
the recompile-listener observers, the prefetch ring) is plain threaded
Python where an unlocked shared mutation or a lock taken inside a
signal handler only ever surfaces as an unexplained hang. This engine
is the AST-level peer: one pass builds a class-scoped model — lock
attributes (``self._lock = threading.Lock()``, Lock vs RLock
distinguished), module-level locks, lock-held regions (``with lock:``
bodies plus linear ``acquire``/``release`` pairing), thread/signal
entry points (``threading.Thread(target=self.m)``,
``signal.signal(sig, self.m)``), per-method shared-attribute writes
tagged with the lockset held at the write, intra-class call edges, and
blocking-call sites — and five checks evaluate it:

``unlocked-shared-mutation``
    Inconsistent lockset (Eraser-lite): an attribute written under a
    lock in one method and written lock-free in a different method of
    a concurrent class (one with thread/signal entries, thread
    creation, or a lock attribute) — plus the read-modify-write case:
    ``self.x += 1`` outside any lock is a lost update even under the
    GIL. ``__init__`` writes are publication, never flagged.

``lock-in-signal-handler``
    A signal handler's intra-class call closure reaches a
    non-reentrant ``threading.Lock`` acquisition. The handler runs ON
    TOP of whatever frame the interrupted thread holds — if that frame
    holds the lock, the process deadlocks. RLock passes (reentrant);
    the sanctioned pattern is an Event/plain-attribute flag serviced
    by a polling thread (see FlightRecorder._on_signal).

``blocking-call-under-lock``
    File I/O (``open``, ``os.replace``/``makedirs``/…,
    ``shutil.rmtree``, ``json.dump``/``load``), ``subprocess``,
    ``time.sleep`` or ``block_until_ready`` while a lock is held —
    directly or through an intra-class call — turns every other
    thread's fast-path acquire into an I/O wait. Snapshot under the
    lock, do the slow work outside.

``callback-reentry``
    Stored callbacks (``for cb in self._observers: cb(...)``, or a
    copied alias of such a collection, or ``self._observers[i](...)``)
    invoked while holding the registry's own lock: a callback that
    calls back into ``add_observer``/``remove_observer`` deadlocks.
    The clean shape copies the list under the lock and invokes
    outside it (RecompileListener._notify).

``fork-unsafe-state``
    Threads started at import time (``parallel.multiproc`` children
    re-import every module — each import would silently start the
    thread again), or ``os.fork()``/default-context
    ``multiprocessing.Process`` in a module that also creates threads
    or locks (the child inherits locks in whatever state the fork
    caught them, and none of the threads that would release them).
    Module-level *locks* alone are fine under the re-exec/spawn model
    multiproc.launch uses — they are reinitialized fresh per child.

Scope: library code under ``apex_tpu/`` plus ``examples/`` (the same
ground as swallowed-exception — where the threaded host surface
lives); driver plumbing (tools/, bench.py) is exempt. Known
limitations, on purpose: the model is class-scoped (module-global
mutation under a module lock is tracked for lock *regions* but not for
check 1), thread targets that are local closures or other objects'
bound methods are invisible, and a method calling a module-level
function does not propagate lock context into it. Suppression uses
the shared ``# apex-lint: disable=<id>`` comment syntax.
"""

from __future__ import annotations

import ast
import collections
import os
import re

from apex_tpu.analysis.ast_checks import (
    _attr_chain as _attr_chain_list,
    _swallowed_exc_applies,
    iter_python_files,
)
from apex_tpu.analysis.findings import Finding, is_suppressed

__all__ = ["CONCURRENCY_CHECKS", "lint_source", "lint_paths",
           "run_concurrency_findings"]

CONCURRENCY_CHECKS = (
    "unlocked-shared-mutation",
    "lock-in-signal-handler",
    "blocking-call-under-lock",
    "callback-reentry",
    "fork-unsafe-state",
)

# lock constructors -> reentrancy kind. "lockish" primitives define a
# held region (blocking/reentry checks) but are not policed by the
# signal-handler check (Condition wraps a lock whose reentrancy we
# cannot see; Semaphores are not mutexes).
_LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "multiprocessing.Lock": "lock",
    "multiprocessing.RLock": "rlock",
    "threading.Condition": "lockish",
    "threading.Semaphore": "lockish",
    "threading.BoundedSemaphore": "lockish",
}

# attribute names that read as locks even when the constructor is out
# of sight (inherited from a base in another module, injected): the
# held-region checks honor them; reentrancy stays unknown.
_LOCKISH_NAME = re.compile(r"(^|_)(lock|mutex)$")

# calls that block the holder: anything here under a held lock turns
# every contending thread's acquire into an I/O wait
_BLOCKING_CALLS = {
    "time.sleep", "subprocess.run", "subprocess.Popen",
    "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "os.makedirs", "os.replace",
    "os.rename", "os.remove", "os.unlink", "shutil.rmtree",
    "shutil.copytree", "shutil.copy", "shutil.copyfile", "shutil.move",
    "json.dump", "json.load", "socket.create_connection",
}

# a call of one of these methods on self.X mutates X (container write)
_MUTATING_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "add", "clear", "update",
    "setdefault", "sort", "reverse",
})

_INIT_METHODS = frozenset({"__init__", "__new__", "__post_init__"})


def _chain(node):
    """ast_checks._attr_chain as a hashable tuple (or None)."""
    parts = _attr_chain_list(node)
    return tuple(parts) if parts else None


def _concurrency_applies(path: str) -> bool:
    """Library + examples — where the threaded host surface lives."""
    return _swallowed_exc_applies(path)


# ------------------------------------------------------------- model


class _MethodInfo:
    __slots__ = ("name", "lineno", "writes", "calls", "blocking",
                 "acquires", "cb_calls")

    def __init__(self, name, lineno):
        self.name = name
        self.lineno = lineno
        # (attr, lineno, frozenset[lockkey], style in assign|aug|mut)
        self.writes = []
        self.calls = []      # (callee, lineno, frozenset[lockkey])
        self.blocking = []   # (desc, lineno, frozenset[lockkey])
        self.acquires = []   # (lockkey, kind, lineno, via_with)
        self.cb_calls = []   # (lineno, frozenset[lockkey], src_attr)


class _ClassInfo:
    __slots__ = ("name", "lineno", "bases", "methods", "lock_attrs",
                 "thread_entries", "signal_entries", "creates_thread")

    def __init__(self, name, lineno, bases):
        self.name = name
        self.lineno = lineno
        self.bases = bases
        self.methods = {}     # name -> _MethodInfo
        self.lock_attrs = {}  # attr -> kind
        self.thread_entries = set()
        self.signal_entries = set()
        self.creates_thread = False

    def all_methods(self, classes, _seen=None):
        """Methods including same-module base classes (child wins)."""
        _seen = _seen or set()
        if self.name in _seen:
            return {}
        _seen.add(self.name)
        out = {}
        for base in self.bases:
            parent = classes.get(base)
            if parent is not None:
                out.update(parent.all_methods(classes, _seen))
        out.update(self.methods)
        return out


class _ModuleModel:
    def __init__(self):
        self.imports = {}          # alias -> dotted module/name
        self.classes = {}          # name -> _ClassInfo
        self.functions = {}        # name -> _MethodInfo (module level)
        self.module_locks = {}     # name -> kind
        self.global_instances = {} # name -> class name
        self.fn_thread_entries = set()
        self.fn_signal_entries = set()
        self.import_thread_sites = []  # (lineno, desc)
        self.fork_sites = []           # (lineno, symbol)

    def resolve(self, chain):
        if not chain:
            return None
        head = self.imports.get(chain[0], chain[0])
        return ".".join((head,) + tuple(chain[1:]))

    def uses_threads(self) -> bool:
        return bool(
            self.module_locks
            or any(c.lock_attrs or c.creates_thread or c.thread_entries
                   for c in self.classes.values())
            or self.import_thread_sites)


def _lock_kind_of_call(model, node):
    """threading.Lock() -> 'lock' etc, else None."""
    if not isinstance(node, ast.Call):
        return None
    chain = _chain(node.func)
    return _LOCK_FACTORIES.get(model.resolve(chain)) if chain else None


class _FnWalker:
    """Walk one callable, recording writes/calls/blocking with the
    lockset held at each site."""

    def __init__(self, model, method, cls=None, selfname=None,
                 at_module_scope=False):
        self.model = model
        self.m = method
        self.cls = cls
        self.selfname = selfname
        self.at_module_scope = at_module_scope
        self.cb_aliases = {}  # local var -> self attr it copies
        self.cb_vars = {}     # loop var -> source self attr

    # ------------------------------------------------ lock resolution

    def _lock_key(self, expr):
        """(key, kind) when ``expr`` names a known lock, else None."""
        chain = _chain(expr)
        if not chain:
            return None
        if (self.selfname and len(chain) == 2
                and chain[0] == self.selfname):
            attr = chain[1]
            if self.cls is not None and attr in self.cls.lock_attrs:
                return ("self", attr), self.cls.lock_attrs[attr]
            if _LOCKISH_NAME.search(attr):
                return ("self", attr), "unknown"
            return None
        if len(chain) == 1 and chain[0] in self.model.module_locks:
            return ("mod", chain[0]), self.model.module_locks[chain[0]]
        if len(chain) == 2 and chain[0] in self.model.global_instances:
            cls = self.model.classes.get(
                self.model.global_instances[chain[0]])
            if cls is not None and chain[1] in cls.lock_attrs:
                return (("g", chain[0], chain[1]),
                        cls.lock_attrs[chain[1]])
        if _LOCKISH_NAME.search(chain[-1]):
            return ("unk",) + tuple(chain), "unknown"
        return None

    # ------------------------------------------------------ statements

    def walk(self, stmts, held=frozenset()):
        held = set(held)
        for stmt in stmts:
            held = self._stmt(stmt, held)
        return frozenset(held)

    def _stmt(self, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, on whatever thread calls it —
            # never under the locks held at its definition site
            self.walk(stmt.body, frozenset())
            return held
        if isinstance(stmt, ast.ClassDef):
            return held
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in stmt.items:
                lk = self._lock_key(item.context_expr)
                if lk is not None:
                    key, kind = lk
                    inner.add(key)
                    self.m.acquires.append(
                        (key, kind, item.context_expr.lineno))
                else:
                    self._expr(item.context_expr, held)
            self.walk(stmt.body, inner)
            return held
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            fn = call.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in ("acquire", "release"):
                lk = self._lock_key(fn.value)
                if lk is not None:
                    key, kind = lk
                    if fn.attr == "acquire":
                        held.add(key)
                        self.m.acquires.append((key, kind, call.lineno))
                    else:
                        held.discard(key)
                    for a in call.args:
                        self._expr(a, held)
                    return held
            self._expr(call, held)
            return held
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                self._target(tgt, held, "assign", stmt.lineno)
            self._track_alias(stmt)
            self._expr(stmt.value, held)
            return held
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._target(stmt.target, held, "assign", stmt.lineno)
                self._expr(stmt.value, held)
            return held
        if isinstance(stmt, ast.AugAssign):
            self._target(stmt.target, held, "aug", stmt.lineno)
            self._expr(stmt.value, held)
            return held
        if isinstance(stmt, ast.Try):
            after = set(self.walk(stmt.body, held))
            for handler in stmt.handlers:
                self.walk(handler.body, after)
            self.walk(stmt.orelse, after)
            return set(self.walk(stmt.finalbody, after))
        if isinstance(stmt, ast.If):
            if self.at_module_scope and _is_main_guard(stmt.test):
                return held  # script entry, not import time
            self._expr(stmt.test, held)
            self.walk(stmt.body, held)
            self.walk(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, held)
            self._track_loop_target(stmt.target, stmt.iter)
            self.walk(stmt.body, held)
            self.walk(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, held)
            self.walk(stmt.body, held)
            self.walk(stmt.orelse, held)
            return held
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, held)
            elif isinstance(child, ast.stmt):
                held = self._stmt(child, held)
        return held

    # ----------------------------------------------- write / cb model

    def _target(self, tgt, held, style, lineno):
        if isinstance(tgt, ast.Attribute):
            chain = _chain(tgt)
            if (self.selfname and chain and len(chain) == 2
                    and chain[0] == self.selfname):
                self.m.writes.append(
                    (chain[1], lineno, frozenset(held), style))
        elif isinstance(tgt, ast.Subscript):
            chain = _chain(tgt.value)
            if (self.selfname and chain and len(chain) == 2
                    and chain[0] == self.selfname):
                self.m.writes.append(
                    (chain[1], lineno, frozenset(held), "mut"))
            self._expr(tgt.slice, held)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._target(elt, held, style, lineno)

    def _self_attr_of(self, expr):
        """The X of ``self.X`` / ``list(self.X)`` / ``self.X.copy()`` /
        ``self.X[:]``, else None — tracks callback-collection copies."""
        if not self.selfname:
            return None
        chain = _chain(expr)
        if chain and len(chain) == 2 and chain[0] == self.selfname:
            return chain[1]
        if isinstance(expr, ast.Call):
            fc = _chain(expr.func)
            if fc in (("list",), ("tuple",)) and len(expr.args) == 1:
                return self._self_attr_of(expr.args[0])
            if (fc and len(fc) == 3 and fc[0] == self.selfname
                    and fc[2] == "copy"):
                return fc[1]
        if isinstance(expr, ast.Subscript) and \
                isinstance(expr.slice, ast.Slice):
            return self._self_attr_of(expr.value)
        return None

    def _track_alias(self, assign):
        if len(assign.targets) == 1 and \
                isinstance(assign.targets[0], ast.Name):
            src = self._self_attr_of(assign.value)
            if src is not None:
                self.cb_aliases[assign.targets[0].id] = src

    def _track_loop_target(self, target, iter_expr):
        src = self._self_attr_of(iter_expr)
        if src is None and isinstance(iter_expr, ast.Name):
            src = self.cb_aliases.get(iter_expr.id)
        if src is None:
            return
        names = [target] if isinstance(target, ast.Name) else (
            target.elts if isinstance(target, (ast.Tuple, ast.List))
            else [])
        for name in names:
            if isinstance(name, ast.Name):
                self.cb_vars[name.id] = src

    # ----------------------------------------------------- expressions

    def _expr(self, node, held):
        if node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._call(sub, held)

    def _call(self, call, held):
        func = call.func
        chain = _chain(func)
        resolved = self.model.resolve(chain) if chain else None
        line = call.lineno

        if resolved in ("threading.Thread", "multiprocessing.Process"):
            self._thread_create(call, resolved, line)
        elif resolved == "signal.signal" and len(call.args) >= 2:
            self._signal_register(call.args[1])
        elif resolved == "os.fork":
            self.model.fork_sites.append((line, self._symbol()))
        elif resolved in ("multiprocessing.Pool",):
            self.model.fork_sites.append((line, self._symbol()))

        desc = None
        if chain == ("open",):
            desc = "open()"
        elif resolved in _BLOCKING_CALLS:
            desc = resolved
        elif chain and chain[-1] == "block_until_ready":
            desc = "block_until_ready"
        if desc is not None:
            self.m.blocking.append((desc, line, frozenset(held)))

        if (self.selfname and chain and len(chain) == 2
                and chain[0] == self.selfname):
            if chain[1] in _MUTATING_METHODS:
                pass  # self.append? not a method call we model
            else:
                self.m.calls.append((chain[1], line, frozenset(held)))
        elif (not self.selfname and chain and len(chain) == 1
                and chain[0] in self.model.functions):
            self.m.calls.append((chain[0], line, frozenset(held)))

        # self.X.append(...) and friends: container mutation of X
        if (self.selfname and chain and len(chain) == 3
                and chain[0] == self.selfname
                and chain[2] in _MUTATING_METHODS):
            self.m.writes.append(
                (chain[1], line, frozenset(held), "mut"))

        # stored-callback invocation
        if isinstance(func, ast.Name) and func.id in self.cb_vars:
            self.m.cb_calls.append(
                (line, frozenset(held), self.cb_vars[func.id]))
        elif isinstance(func, ast.Subscript):
            sub_chain = _chain(func.value)
            if (self.selfname and sub_chain and len(sub_chain) == 2
                    and sub_chain[0] == self.selfname):
                self.m.cb_calls.append(
                    (line, frozenset(held), sub_chain[1]))

    def _symbol(self):
        if self.cls is not None:
            return f"{self.cls.name}.{self.m.name}"
        return self.m.name

    def _thread_create(self, call, resolved, line):
        if self.cls is not None:
            self.cls.creates_thread = True
        if self.at_module_scope:
            self.model.import_thread_sites.append((line, resolved))
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and len(call.args) >= 2:
            target = call.args[1]
        if target is None:
            return
        chain = _chain(target)
        if (self.selfname and chain and len(chain) == 2
                and chain[0] == self.selfname and self.cls is not None):
            self.cls.thread_entries.add(chain[1])
        elif chain and len(chain) == 1 and \
                chain[0] in self.model.functions:
            self.model.fn_thread_entries.add(chain[0])

    def _signal_register(self, handler):
        chain = _chain(handler)
        if not chain:
            return
        if (self.selfname and len(chain) == 2
                and chain[0] == self.selfname and self.cls is not None):
            self.cls.signal_entries.add(chain[1])
        elif len(chain) == 1 and chain[0] in self.model.functions:
            self.model.fn_signal_entries.add(chain[0])


def _is_main_guard(test) -> bool:
    """``if __name__ == "__main__":`` — script entry, not import time."""
    return (isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and test.left.id == "__name__")


# ---------------------------------------------------------- build pass


def _first_arg_name(fndef):
    args = fndef.args.posonlyargs + fndef.args.args
    return args[0].arg if args else None


def _scan_lock_attrs(model, cls, body):
    """Phase 1: find ``self.X = threading.Lock()`` (any method) and
    class-body ``X = threading.Lock()`` before walking bodies — with
    blocks need the full lock-attr set up front."""
    for stmt in body:
        if isinstance(stmt, ast.Assign):
            kind = _lock_kind_of_call(model, stmt.value)
            if kind is not None:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        cls.lock_attrs[tgt.id] = kind
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            selfname = _first_arg_name(stmt)
            if selfname is None:
                continue
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Assign):
                    continue
                kind = _lock_kind_of_call(model, sub.value)
                if kind is None:
                    continue
                for tgt in sub.targets:
                    chain = _chain(tgt)
                    if chain and len(chain) == 2 and \
                            chain[0] == selfname:
                        cls.lock_attrs[chain[1]] = kind


def _build_model(tree) -> _ModuleModel:
    model = _ModuleModel()
    class_defs, fn_defs, module_stmts = [], [], []
    for stmt in tree.body:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                model.imports[alias.asname or
                              alias.name.split(".")[0]] = \
                    alias.name if alias.asname else \
                    alias.name.split(".")[0]
        elif isinstance(stmt, ast.ImportFrom):
            if stmt.module and stmt.level == 0:
                for alias in stmt.names:
                    model.imports[alias.asname or alias.name] = \
                        f"{stmt.module}.{alias.name}"
        elif isinstance(stmt, ast.ClassDef):
            class_defs.append(stmt)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_defs.append(stmt)
        else:
            module_stmts.append(stmt)

    # phase 1: class skeletons + lock attrs (with-bodies need them)
    for cdef in class_defs:
        bases = [b.id for b in cdef.bases if isinstance(b, ast.Name)]
        cls = _ClassInfo(cdef.name, cdef.lineno, bases)
        model.classes[cdef.name] = cls
        _scan_lock_attrs(model, cls, cdef.body)
    for cdef in class_defs:  # inherit lock attrs within the module
        cls = model.classes[cdef.name]
        merged, stack, seen = {}, list(cls.bases), set()
        while stack:
            base = stack.pop()
            if base in seen or base not in model.classes:
                continue
            seen.add(base)
            parent = model.classes[base]
            for attr, kind in parent.lock_attrs.items():
                merged.setdefault(attr, kind)
            stack.extend(parent.bases)
        for attr, kind in merged.items():
            cls.lock_attrs.setdefault(attr, kind)

    # module-level locks and singleton instances (with _STATE.lock:)
    for stmt in module_stmts:
        if not isinstance(stmt, ast.Assign):
            continue
        kind = _lock_kind_of_call(model, stmt.value)
        inst = None
        if kind is None and isinstance(stmt.value, ast.Call) and \
                isinstance(stmt.value.func, ast.Name) and \
                stmt.value.func.id in model.classes:
            inst = stmt.value.func.id
        for tgt in stmt.targets:
            if not isinstance(tgt, ast.Name):
                continue
            if kind is not None:
                model.module_locks[tgt.id] = kind
            elif inst is not None:
                model.global_instances[tgt.id] = inst

    # register module function names before walking (call edges)
    for fdef in fn_defs:
        model.functions[fdef.name] = _MethodInfo(fdef.name, fdef.lineno)

    # phase 2: walk bodies
    for cdef in class_defs:
        cls = model.classes[cdef.name]
        for stmt in cdef.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            method = _MethodInfo(stmt.name, stmt.lineno)
            cls.methods[stmt.name] = method
            is_static = any(
                isinstance(d, ast.Name) and d.id == "staticmethod"
                for d in stmt.decorator_list)
            selfname = None if is_static else _first_arg_name(stmt)
            _FnWalker(model, method, cls=cls,
                      selfname=selfname).walk(stmt.body)
    for fdef in fn_defs:
        _FnWalker(model, model.functions[fdef.name]).walk(fdef.body)

    # module scope (import time): check 5 + module-level registrations
    mod_info = _MethodInfo("<module>", 1)
    _FnWalker(model, mod_info, at_module_scope=True).walk(module_stmts)
    return model


# ----------------------------------------------------------- evaluate


def _lock_name(key) -> str:
    if key[0] == "self":
        return f"self.{key[1]}"
    if key[0] == "mod":
        return key[1]
    if key[0] == "g":
        return f"{key[1]}.{key[2]}"
    return ".".join(key[1:])


def _entry_desc(cls) -> str:
    bits = []
    if cls.thread_entries:
        bits.append("thread entry " + ", ".join(
            sorted(cls.thread_entries)))
    if cls.signal_entries:
        bits.append("signal handler " + ", ".join(
            sorted(cls.signal_entries)))
    if not bits:
        bits.append("its lock discipline")
    return " / ".join(bits)


def _check_unlocked_mutation(model, cls, relpath, out):
    concurrent = bool(cls.thread_entries or cls.signal_entries
                      or cls.creates_thread or cls.lock_attrs)
    if not concurrent:
        return
    methods = cls.all_methods(model.classes)
    locked_in = collections.defaultdict(set)   # attr -> {method}
    for m in methods.values():
        for attr, _line, held, _style in m.writes:
            if held:
                locked_in[attr].add(m.name)
    for m in methods.values():
        if m.name in _INIT_METHODS:
            continue  # publication: no other thread sees the object yet
        for attr, line, held, style in m.writes:
            if held:
                continue
            others = locked_in.get(attr, set()) - {m.name}
            if others:
                out.append(Finding(
                    "unlocked-shared-mutation", "error", relpath, line,
                    f"{cls.name}.{m.name}",
                    f"self.{attr} is written lock-free here but under "
                    f"a lock in {', '.join(sorted(others))}(): "
                    f"inconsistent lockset — a race given "
                    f"{_entry_desc(cls)}; hold the same lock at every "
                    f"write (reads of a single attribute may stay "
                    f"lock-free)"))
            elif style == "aug" and cls.lock_attrs:
                out.append(Finding(
                    "unlocked-shared-mutation", "error", relpath, line,
                    f"{cls.name}.{m.name}",
                    f"self.{attr} += ... outside any lock: "
                    f"read-modify-write is not atomic (GIL or not) — "
                    f"concurrent increments lose updates; wrap it in "
                    f"the class lock"))


def _closure(methods, start, pick):
    """DFS the intra-class/module call graph from ``start``; returns
    [(via_path, payload)] for every ``pick(method)`` payload found."""
    hits, seen = [], set()
    stack = [(start, ())]
    while stack:
        name, via = stack.pop()
        if name in seen:
            continue
        seen.add(name)
        m = methods.get(name)
        if m is None:
            continue
        for payload in pick(m):
            hits.append((via + (name,), payload))
        for callee, _line, _held in m.calls:
            stack.append((callee, via + (name,)))
    return hits


def _check_signal_handler(model, relpath, out):
    def scan(methods, handlers, owner):
        for handler in sorted(handlers):
            hits = _closure(
                methods, handler,
                lambda m: [a for a in m.acquires if a[1] == "lock"])
            for via, (key, _kind, line) in hits:
                path = " -> ".join(via)
                out.append(Finding(
                    "lock-in-signal-handler", "error", relpath, line,
                    f"{owner}{handler}",
                    f"signal handler {handler} reaches a non-reentrant "
                    f"threading.Lock acquisition of {_lock_name(key)} "
                    f"(via {path}): the handler runs on top of "
                    f"whatever frame the interrupted thread holds — "
                    f"if that frame holds the lock the process "
                    f"deadlocks; set a flag (plain attribute or "
                    f"Event.set) and service it on a polling thread"))

    for cls in model.classes.values():
        if cls.signal_entries:
            scan(cls.all_methods(model.classes), cls.signal_entries,
                 f"{cls.name}.")
    if model.fn_signal_entries:
        scan(model.functions, model.fn_signal_entries, "")


def _check_blocking(model, relpath, out):
    def scan(methods, owner):
        # per-method transitive "reaches a blocking call" summary
        for m in methods.values():
            for desc, line, held in m.blocking:
                if held:
                    locks = ", ".join(sorted(map(_lock_name, held)))
                    out.append(Finding(
                        "blocking-call-under-lock", "error", relpath,
                        line, f"{owner}{m.name}",
                        f"{desc} while holding {locks}: every "
                        f"contending thread's acquire becomes an I/O "
                        f"wait — snapshot state under the lock, do "
                        f"the slow work outside it"))
            for callee, line, held in m.calls:
                if not held or callee not in methods:
                    continue
                hits = _closure(methods, callee,
                                lambda mm: mm.blocking)
                if hits:
                    via, (desc, _bline, _bheld) = hits[0]
                    locks = ", ".join(sorted(map(_lock_name, held)))
                    out.append(Finding(
                        "blocking-call-under-lock", "error", relpath,
                        line, f"{owner}{m.name}",
                        f"calls {' -> '.join(via)} while holding "
                        f"{locks}, which reaches {desc}: the lock is "
                        f"held across blocking work — move the call "
                        f"outside the locked region"))

    for cls in model.classes.values():
        scan(cls.all_methods(model.classes), f"{cls.name}.")
    scan(model.functions, "")


def _check_callback_reentry(model, relpath, out):
    for cls in model.classes.values():
        for m in cls.all_methods(model.classes).values():
            for line, held, src in m.cb_calls:
                if not held:
                    continue
                locks = ", ".join(sorted(map(_lock_name, held)))
                out.append(Finding(
                    "callback-reentry", "error", relpath, line,
                    f"{cls.name}.{m.name}",
                    f"invokes callbacks stored in self.{src} while "
                    f"holding {locks}: a callback that re-enters this "
                    f"object (add/remove/observer APIs take the same "
                    f"lock) deadlocks — copy the list under the lock, "
                    f"invoke outside it"))


def _check_fork_unsafe(model, relpath, out):
    for line, desc in model.import_thread_sites:
        out.append(Finding(
            "fork-unsafe-state", "error", relpath, line, "<module>",
            f"{desc} created at import time: multiproc-launched "
            f"workers re-import this module, silently starting the "
            f"thread once per child — create threads from an "
            f"install()/main() entry point instead"))
    if model.uses_threads():
        for line, symbol in model.fork_sites:
            out.append(Finding(
                "fork-unsafe-state", "error", relpath, line, symbol,
                "os.fork/default-context multiprocessing in a module "
                "that also creates threads or locks: the child "
                "inherits every lock in whatever state the fork "
                "caught it, and none of the threads that would "
                "release them — use subprocess/spawn "
                "(parallel.multiproc) instead"))


# -------------------------------------------------------- entry points


def lint_source(source: str, relpath: str, checks=None, abspath=None):
    """Lint one file's source text; returns a list of Findings.

    Mirrors :func:`ast_checks.lint_source`: ``abspath`` (when known)
    drives path scoping so verdicts never depend on the caller's cwd.
    """
    checks = set(checks or CONCURRENCY_CHECKS)
    unknown = checks - set(CONCURRENCY_CHECKS)
    if unknown:
        raise ValueError(
            f"unknown concurrency check(s) {sorted(unknown)}; valid: "
            f"{list(CONCURRENCY_CHECKS)}")
    if not _concurrency_applies(abspath or relpath):
        return []
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError:
        return []  # the AST engine already reports syntax errors
    model = _build_model(tree)
    out: list = []
    if "unlocked-shared-mutation" in checks:
        for cls in model.classes.values():
            _check_unlocked_mutation(model, cls, relpath, out)
    if "lock-in-signal-handler" in checks:
        _check_signal_handler(model, relpath, out)
    if "blocking-call-under-lock" in checks:
        _check_blocking(model, relpath, out)
    if "callback-reentry" in checks:
        _check_callback_reentry(model, relpath, out)
    if "fork-unsafe-state" in checks:
        _check_fork_unsafe(model, relpath, out)
    lines = source.splitlines()
    return [f for f in out if not is_suppressed(f, lines)]


def lint_paths(paths, root=None, checks=None):
    """Lint every .py under ``paths``; findings relative to ``root``."""
    root = os.path.abspath(root or os.getcwd())
    findings = []
    for fpath in iter_python_files(paths):
        ap = os.path.abspath(fpath)
        rel = os.path.relpath(ap, root) if ap.startswith(root) else fpath
        with open(ap, encoding="utf-8") as f:
            source = f.read()
        findings.extend(lint_source(source, rel, checks, abspath=ap))
    return findings


def run_concurrency_findings(registry=None, paths=None, root=None):
    """Run the engine over the library and publish the per-check
    ``analysis/concurrency_findings{check=}`` counter family plus the
    ``analysis/concurrency_findings_total`` gauge — the bench.py
    observability hook (mirrors ``run_sharding_findings``)."""
    if registry is None:
        from apex_tpu.observability import get_registry
        registry = get_registry()
    root = os.path.abspath(root or os.getcwd())
    use = list(paths) if paths else [os.path.join(root, "apex_tpu")]
    findings = lint_paths(use, root=root)
    counts = collections.Counter(f.check for f in findings)
    for check in CONCURRENCY_CHECKS:
        registry.counter("analysis/concurrency_findings",
                         check=check).inc(counts.get(check, 0))
    registry.gauge("analysis/concurrency_findings_total").set(
        float(len(findings)))
    return findings
