"""Engine 2: AST-level lint for host-sync and trace-hygiene anti-patterns.

Runs over apex_tpu's own sources, ``examples/``, ``tools/`` and
``bench.py`` — the code that *drives* TPUs, where the r5 instrument bug
(an impossible MFU=330 timed around a tunnel-no-op ``block_until_ready``)
lived. Checks:

- ``sync-timing``     ``block_until_ready`` inside a function that also
                      reads a wall clock: over the axon tunnel it is a
                      no-op, so the "timed" region measures dispatch.
                      Use ``apex_tpu.runtime.timing.sync`` (host fetch).
- ``host-in-jit``     ``float()``/``int()``/``np.asarray``/``.item()``/
                      ``.tolist()`` inside a jit-decorated body: host
                      pulls that either fail to trace or silently sync.
- ``rng-in-jit``      Python/numpy RNG inside a jit-decorated body: the
                      sample is baked in at trace time, identical every
                      step. Use ``jax.random`` with a threaded key.
- ``mutable-default`` mutable default argument (list/dict/set): shared
                      across calls; with jit in play, also a cache-key
                      footgun.
- ``raw-clock``       a direct wall-clock read (``time.perf_counter`` &
                      co) in library code under ``apex_tpu/`` outside
                      ``runtime/timing.py`` and ``observability/``: all
                      timing must flow through the corrected-sync
                      helpers / the observability Timer, or the next
                      hand-rolled timer re-introduces the r5 dispatch-
                      time bug. Driver code (bench.py, tools/,
                      examples/) may read clocks — sync-timing still
                      polices HOW it times.
- ``swallowed-exception-in-step-loop``
                      ``except Exception/BaseException/bare: pass`` (or
                      ``continue``) inside a ``for``/``while`` body in
                      ``apex_tpu/`` or ``examples/``: a step loop that
                      silently eats per-iteration failures hides NaN
                      storms, torn checkpoint writes and dying
                      collectives until the run is unrecoverable.
                      Resilience must be explicit — retry transient
                      classes via ``apex_tpu.resilience.retry.Policy``,
                      or at least count/log before continuing.
- ``unclosed-span``   an ``apex_tpu.observability`` ``span(...)``/
                      ``scope(...)`` call in ``apex_tpu/`` or
                      ``examples/`` that is not the context expression
                      of a ``with`` (or an ``ExitStack.enter_context``
                      argument): a span opened without its guaranteed
                      close leaks an entry on the tracer's open-span
                      stack forever — the flight recorder then reports
                      a phantom in-flight region on every dump, nesting
                      depths of later spans are wrong, and the paired
                      profiler TraceAnnotation never pops. Manual
                      ``__enter__``/``__exit__`` pairing inside another
                      context manager's protocol is the one sanctioned
                      shape (suppress with a justification).
- ``host-isnan-in-step-loop``
                      a ``jnp.isnan``/``jnp.isinf`` result pulled to
                      host (``bool()``/``float()``/``.item()``/
                      ``.tolist()``, or used directly as an ``if``
                      condition) lexically inside a ``for``/``while``
                      body in ``apex_tpu/`` or ``examples/``: each
                      pull is a device round-trip PER TENSOR PER STEP
                      that serializes the dispatch pipeline — the
                      exact anti-pattern the numerics tier exists to
                      replace. Route finiteness checks through
                      ``apex_tpu.observability.numerics`` (one fused
                      on-device reduction for the whole tree, host
                      pull decimated to every N steps); the numerics
                      module itself is exempt — it IS the sanctioned
                      implementation.
- ``rank-unsafe-artifact-path``
                      a write-mode ``open()`` in ``apex_tpu/`` or
                      ``examples/`` (code that runs inside
                      multiproc-launched workers) whose path
                      expression bakes in a fixed artifact filename
                      (a string literal ending in .json/.jsonl/.csv/
                      .log/...) with no rank component anywhere in the
                      expression: two ranks handed the same path
                      interleave or clobber each other's telemetry —
                      the ISSUE 12 failure mode that raced every
                      ``APEX_TPU_METRICS`` dump. Route shared paths
                      through ``observability.fleet.rank_path`` (or
                      build the name from the rank/pid). A path that
                      arrives as a variable is the caller's problem at
                      the caller's site; a literal is this file's.
- ``hardcoded-tile-size``
                      an integer tile constant fed to ``pl.BlockSpec``
                      outside ``ops/pallas_config.py`` and the tuner's
                      search-space tables (``tuning/search_space.py``):
                      a literal >= 8 (tile-sized — sublane multiples
                      start at 8) directly in a block shape, or a
                      module-level ``_BLOCK*``/``_TILE*``/``*_COLS``-
                      style int constant in a file that builds
                      BlockSpecs. The right tile is a per-device,
                      per-shape search result (the fixed flat-adam
                      (rows, 1024) slab lost 3.2x on v5e to the tiling
                      it shipped with) — route geometry through
                      ``apex_tpu.tuning``.

- ``raw-memory-introspection``
                      a direct ``jax.live_arrays()`` /
                      ``jax.profiler.device_memory_profile()`` /
                      ``.memory_stats()`` call in ``apex_tpu/`` or
                      ``examples/`` outside the memory observability
                      package and ``ops/pallas_config.py``: the live
                      walk is a host-side sweep of every buffer (and
                      ``get_backend()`` forces backend init from a
                      telemetry read) — ad-hoc calls in a step loop
                      serialize the pipeline exactly like the
                      per-tensor isnan pulls the numerics tier retired,
                      and their numbers bypass the watermark/top-k
                      accounting the OOM forensics depend on. Route
                      through ``apex_tpu.observability.memory``
                      (``MemoryMonitor`` decimated snapshots,
                      ``device_memory_stats``); ``pallas_config`` owns
                      the ``bytes_limit`` budget read.
- ``nondeterministic-collective-order``
                      a ``for`` loop over an unordered iterable (set
                      literal/comprehension, ``set()``/``frozenset()``
                      or a set-method call, ``os.listdir``) whose body
                      builds buckets or issues collectives, in comms
                      scheduling code (``apex_tpu/parallel/``,
                      ``runtime/``, ``distributed/``): set iteration
                      order differs across processes (string hash
                      randomization) and listdir follows filesystem
                      order, so ranks disagree on bucket layout /
                      collective issue order — the plan_buckets-shaped
                      deadlock seed. Iterate ``sorted(...)``.

Suppress with ``# apex-lint: disable=<id>`` on (or above) the line.
"""

from __future__ import annotations

import ast
import os
import re

from apex_tpu.analysis.findings import Finding, is_suppressed

AST_CHECKS = ("sync-timing", "host-in-jit", "rng-in-jit",
              "mutable-default", "raw-clock",
              "swallowed-exception-in-step-loop",
              "hardcoded-tile-size", "unclosed-span",
              "host-isnan-in-step-loop", "rank-unsafe-artifact-path",
              "raw-fp8-cast", "nondeterministic-collective-order",
              "raw-memory-introspection")

# Modules whose job is the corrected sync itself.
_SYNC_ALLOWLIST = {os.path.join("apex_tpu", "runtime", "timing.py")}

# raw-clock applies only to library code under apex_tpu/; these own the
# sanctioned clocks (timing.py implements the corrected sync, the
# observability layer's Timer/StepReporter are built on it;
# resilience/ reads wall time for retry backoff/deadlines — host-side
# scheduling, not device phase timing; serving/ stamps request
# lifecycle times (latency/ttft) and paces loadgen arrivals — same
# host-side scheduling class as resilience/).
_RAW_CLOCK_ALLOW_FILES = {"apex_tpu/runtime/timing.py"}
_RAW_CLOCK_ALLOW_PREFIXES = ("apex_tpu/observability/",
                             "apex_tpu/resilience/",
                             "apex_tpu/serving/")


def _apex_tail(path: str):
    """``path`` from its last ``apex_tpu`` DIRECTORY segment on, or
    None when no such segment exists — the shared scoping idiom for
    library-code checks (absolute paths preferred: relpaths depend on
    the caller's cwd/root; matching from the LAST segment keeps
    checkouts that live under a directory named apex_tpu correct)."""
    norm = path.replace("\\", "/")
    if "apex_tpu" not in norm.split("/")[:-1]:
        return None
    return norm[norm.rindex("apex_tpu/"):]


def _raw_clock_applies(path: str) -> bool:
    """Is ``path`` library code the raw-clock check governs? Library
    code under apex_tpu/, minus the allowlisted clock owners."""
    tail = _apex_tail(path)
    if tail is None or tail in _RAW_CLOCK_ALLOW_FILES:
        return False
    return not any(tail.startswith(p) for p in _RAW_CLOCK_ALLOW_PREFIXES)


def _swallowed_exc_applies(path: str) -> bool:
    """Is ``path`` governed by swallowed-exception-in-step-loop? Library
    code under an ``apex_tpu`` package dir, or anything under an
    ``examples`` dir — the two places step loops live. Driver plumbing
    (bench.py launcher, tools/) may legitimately blanket-continue over
    secondary work."""
    parts = path.replace("\\", "/").split("/")[:-1]
    return "apex_tpu" in parts or "examples" in parts


# unclosed-span polices the same ground as swallowed-exception: the
# library + examples, where instrumented hot paths live. Span/scope
# names must resolve (through the module's imports) into the
# observability package — a local helper that happens to be called
# `span` is not a tracer span.
_SPAN_NAMES = ("span", "scope")


def _unclosed_span_applies(path: str) -> bool:
    return _swallowed_exc_applies(path)


# host-isnan-in-step-loop polices the same ground (library +
# examples step loops), minus the numerics package — it IS the
# sanctioned decimated/fused implementation of these checks.
_ISNAN_EXEMPT_PREFIX = "apex_tpu/observability/numerics/"


def _host_isnan_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    if _ISNAN_EXEMPT_PREFIX in norm:
        return False
    return _swallowed_exc_applies(path)


_ISNAN_NAMES = frozenset({"isnan", "isinf"})


# rank-unsafe-artifact-path: library + examples code (what
# multiproc-launched workers actually execute). The fleet identity
# module is exempt — it IS the sanctioned suffixing implementation.
_RANK_PATH_EXEMPT_PREFIX = "apex_tpu/observability/fleet/"

# filename extensions that mean "telemetry/artifact write" — a fixed
# one of these inside a worker is the shard-clobber pattern
_ARTIFACT_EXTS = (".json", ".jsonl", ".csv", ".log", ".txt", ".pb",
                  ".tsv")

# an identifier anywhere in the path expression that smells like a
# per-rank/per-process component ("...rank...", pid lookups, the
# sanctioned helper) clears the finding
_RANK_COMPONENT_RE = re.compile(
    r"rank|process_index|getpid|\bpid\b|worker|shard|proc_?id",
    re.IGNORECASE)

_WRITE_MODES = {"w", "a", "wb", "ab", "w+", "a+", "wt", "at", "x",
                "xb"}


def _rank_unsafe_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    if _RANK_PATH_EXEMPT_PREFIX in norm:
        return False
    return _swallowed_exc_applies(path)


# raw-memory-introspection (ISSUE 15): direct memory-introspection
# calls anywhere but the sanctioned owners — the memory observability
# package (MemoryMonitor's decimated snapshots, the compiled-stats
# capture) and ops/pallas_config.py (the bytes_limit budget read).
_MEMORY_INTROSPECT_EXEMPT_PREFIX = "apex_tpu/observability/memory/"
_MEMORY_INTROSPECT_ALLOW_FILES = {"apex_tpu/ops/pallas_config.py"}

#: function names that ARE memory introspection when they resolve into
#: jax (live_arrays / profiler.device_memory_profile).
_MEMORY_INTROSPECT_JAX_NAMES = frozenset({
    "live_arrays", "device_memory_profile",
})

#: PJRT-object methods matched by ATTRIBUTE name (their receivers —
#: `jax.devices()[0]`, a stashed `client` — break the dotted chain, so
#: jax-root resolution can never see them).
_MEMORY_INTROSPECT_ATTRS = frozenset({
    "memory_stats", "live_executables",
})


def _memory_introspect_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    if _MEMORY_INTROSPECT_EXEMPT_PREFIX in norm:
        return False
    tail = _apex_tail(path)
    if tail is not None and tail in _MEMORY_INTROSPECT_ALLOW_FILES:
        return False
    return _swallowed_exc_applies(path)


# raw-fp8-cast (ISSUE 13): a bare astype to an fp8 dtype anywhere but
# the sanctioned quantization owners. fp8 casts are only safe behind a
# delayed per-tensor scale + saturation (ops/precision.quantize_fp8 /
# matmul_fp8, fed by the amp Fp8DelayedScaler); a raw cast overflows to
# NaN (E4M3 has no inf encoding) the first time an activation leaves
# ±448. The owners: ops/precision.py (+ its Pallas kernel) and amp/.
_FP8_CAST_ALLOW_FILES = {"apex_tpu/ops/precision.py",
                         "apex_tpu/ops/fp8_cast_kernel.py"}
_FP8_CAST_ALLOW_PREFIXES = ("apex_tpu/amp/",)

# an astype argument that IS an fp8 dtype: jnp/jax.numpy float8_*
# members, the precision module's F8_* aliases (an alias is still a raw
# cast), or a dtype string literal
_FP8_DTYPE_NAME_RE = re.compile(r"^(float8_e4m3fn|float8_e5m2|"
                                r"F8_E4M3|F8_E5M2)$")


def _raw_fp8_applies(path: str) -> bool:
    tail = _apex_tail(path)
    if tail is not None:
        if tail in _FP8_CAST_ALLOW_FILES:
            return False
        if any(tail.startswith(p) for p in _FP8_CAST_ALLOW_PREFIXES):
            return False
    return True


# nondeterministic-collective-order (ISSUE 14): comms scheduling code —
# parallel/ (bucket plans, collective issue chains), runtime/
# (plan_buckets) and the distributed shims. Every rank must build the
# SAME bucket list and issue collectives in the SAME order; a loop over
# a set (hash-randomized for strings across processes) or os.listdir
# (filesystem order) deciding either is a cross-rank deadlock/desync
# seed: rank A packs {f32, bf16} buckets in one order, rank B in the
# other, and the psums pair the wrong buffers.
_NONDET_ORDER_PREFIXES = ("apex_tpu/parallel/", "apex_tpu/runtime/",
                          "apex_tpu/distributed/")

#: loop bodies that "issue comms / build buckets": a collective call, a
#: plan_buckets call, or any bucket-named identifier
_ORDER_COLLECTIVE_NAMES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "psum_scatter",
    "reduce_scatter", "all_to_all", "ppermute", "plan_buckets",
})

#: set-producing call tails a for-loop must not iterate unsorted
_SET_CALL_NAMES = frozenset({"set", "frozenset"})
_SET_METHOD_NAMES = frozenset({"difference", "union", "intersection",
                               "symmetric_difference"})


def _nondet_order_applies(path: str) -> bool:
    tail = _apex_tail(path)
    return tail is not None and any(
        tail.startswith(p) for p in _NONDET_ORDER_PREFIXES)


# hardcoded-tile-size: the two modules tile numbers are ALLOWED to live
# in — the dispatch-config defaults and the tuner's search-space tables.
_TILE_SIZE_ALLOW = ("apex_tpu/ops/pallas_config.py",
                    "apex_tpu/tuning/search_space.py")

# Below the fp32 sublane tile (8): a 1-singleton or a tiny scalar-block
# dim (the flat-adam (1, 4) scalar spec) is layout plumbing, not a
# tunable tile.
_TILE_LITERAL_MIN = 8

# Module-constant names that smell like a tile: _BLOCK_ROWS, _BLOCKED_BK,
# _TILE_N, _COLS, BQ/BK... (matched against the upper-cased name).
_TILE_NAME_RE = re.compile(r"(?:^|_)(BLOCK|TILE|COLS|ROWS|BQ|BKV|BK)"
                           r"(?:_|E?D?_|$)")


def _tile_size_applies(path: str) -> bool:
    norm = path.replace("\\", "/")
    return not any(norm.endswith(allow) for allow in _TILE_SIZE_ALLOW)


_BROAD_EXC = {"Exception", "BaseException"}


def _is_broad_handler(type_node) -> bool:
    """Bare ``except:``, ``except Exception``, ``except BaseException``
    — including inside a tuple of classes."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad_handler(e) for e in type_node.elts)
    chain = _attr_chain(type_node)
    return bool(chain) and chain[-1] in _BROAD_EXC


def _body_only_swallows(body) -> bool:
    """True when the handler body does nothing but pass/continue/... —
    no logging, no counter, no re-raise, no fallback value."""
    if not body:
        return True
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Constant) and stmt.value.value is ...:
            continue
        return False
    return True


_CLOCK_CALLS = {("time", "perf_counter"), ("time", "time"),
                ("time", "monotonic"), ("time", "perf_counter_ns"),
                ("timeit", "default_timer")}

_HOST_PULL_NAMES = {"float", "int"}
_HOST_PULL_NP = {"asarray", "array", "copyto"}
_HOST_PULL_METHODS = {"item", "tolist"}


def _attr_chain(node):
    """Dotted name parts of an Attribute/Name chain, outermost first."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize"}
_STATIC_FNS = {"len", "min", "max", "abs", "int", "float", "round"}


def _is_static_expr(node):
    """True when the WHOLE expression derives from static trace-time
    metadata (``x.shape[0] * 2``, ``len(xs)``): int()/float() on these
    is idiomatic jax, not a host pull. One static leaf is not enough —
    ``x.mean() / x.shape[0]`` still pulls the traced mean."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Index):  # py<3.9 slice wrapper
        return _is_static_expr(node.value)
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_static_expr(e) for e in node.elts)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id == "len":
            return True  # len() is a host int even on traced arrays
        return (node.func.id in _STATIC_FNS
                and all(_is_static_expr(a) for a in node.args))
    return False


def _is_jit_decorator(dec):
    """jax.jit / jit / pjit, possibly through functools.partial(...)."""
    chain = _attr_chain(dec)
    if chain and chain[-1] in ("jit", "pjit"):
        return True
    if isinstance(dec, ast.Call):
        chain = _attr_chain(dec.func)
        if chain and chain[-1] in ("jit", "pjit"):
            return True
        if chain and chain[-1] == "partial" and dec.args:
            inner = _attr_chain(dec.args[0])
            if inner and inner[-1] in ("jit", "pjit"):
                return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, path, relpath, checks):
        self.relpath = relpath
        self.checks = checks
        self.findings = []
        # stack of (symbol, in_jit); module scope counts as one frame
        self.stack = [("<module>", False)]
        # per-function-frame call records for sync-timing
        self.frames = [{"clock": [], "block": []}]
        # per-function-frame lexical loop depth (a handler inside a def
        # nested in a loop is NOT per-iteration code — depth resets)
        self.loop_depth = [0]
        # local name -> imported dotted module, so `from jax import
        # random` is not mistaken for the stdlib `random` module
        self.imports = {}
        # hardcoded-tile-size state: module-level tile-named int
        # constants only become findings when the file also builds
        # BlockSpecs (lint_source pairs the two after the walk)
        self.blockspec_seen = False
        self.tile_consts = []  # (lineno, name, value)
        # unclosed-span: Call nodes sanctioned as context-manager uses
        # (a with item's context expression, an enter_context argument)
        # — recorded by the parent before the call itself is visited
        self._cm_calls: set = set()
        # host-isnan-in-step-loop: Call nodes already reported through
        # an enclosing pull (an `if` test, an outer bool()) — one
        # finding per pull site, not one per nested call
        self._isnan_handled: set = set()

    def visit_Import(self, node):
        for alias in node.names:
            if alias.asname:
                self.imports[alias.asname] = alias.name
            else:
                # `import numpy.random` binds the ROOT name `numpy`
                root = alias.name.split(".")[0]
                self.imports[root] = root
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.module and node.level == 0:
            for alias in node.names:
                self.imports[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
        self.generic_visit(node)

    def _resolve(self, chain):
        """Expand the chain's root through the module's imports:
        ['random','normal'] under `from jax import random` resolves to
        ['jax','random','normal']."""
        root = self.imports.get(chain[0])
        if root is None:
            return chain
        return root.split(".") + chain[1:]

    def _sym(self):
        return self.stack[-1][0]

    def _in_jit(self):
        return self.stack[-1][1]

    def _emit(self, check, severity, line, message):
        if check in self.checks:
            self.findings.append(Finding(
                check, severity, self.relpath, line, self._sym(), message))

    # ------------------------------------------------- function frames

    def _enter_function(self, node):
        jit = self._in_jit() or any(
            _is_jit_decorator(d) for d in getattr(node, "decorator_list",
                                                  ()))
        name = getattr(node, "name", "<lambda>")
        if "mutable-default" in self.checks and hasattr(node, "args"):
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                        isinstance(d, ast.Call)
                        and isinstance(d.func, ast.Name)
                        and d.func.id in ("list", "dict", "set")):
                    self.findings.append(Finding(
                        "mutable-default", "warning", self.relpath,
                        d.lineno, name,
                        f"mutable default argument in '{name}': shared "
                        f"across calls (and a jit cache-key footgun); "
                        f"default to None and build inside"))
        self.stack.append((name, jit))
        self.frames.append({"clock": [], "block": []})
        self.loop_depth.append(0)

    def _exit_function(self):
        frame = self.frames.pop()
        if frame["clock"] and frame["block"]:
            for line in frame["block"]:
                self._emit(
                    "sync-timing", "error", line,
                    "block_until_ready in a function that also reads a "
                    "wall clock: it is a NO-OP over the axon tunnel "
                    "(r5 measured an impossible MFU=330 this way) — "
                    "sync timed regions with "
                    "apex_tpu.runtime.timing.sync / time_fn")
        elif len(self.frames) > 1:
            # an unpaired NESTED def usually runs inside its enclosing
            # function's timed region — propagate its records up so a
            # clock in the parent still pairs with a block in a closure.
            # Top-level functions do NOT propagate into the module frame:
            # pairing a clock in one sibling with a block in another
            # would flag unrelated correctness-sync helpers.
            # (Cross-FUNCTION helpers remain out of reach: documented
            # limitation in docs/analysis.md.)
            self.frames[-1]["block"] += frame["block"]
            self.frames[-1]["clock"] += frame["clock"]
        self.stack.pop()
        self.loop_depth.pop()

    def visit_FunctionDef(self, node):
        self._enter_function(node)
        self.generic_visit(node)
        self._exit_function()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter_function(node)
        self.generic_visit(node)
        self._exit_function()

    # ------------------------------------------------- loops / handlers

    def visit_For(self, node):
        if "nondeterministic-collective-order" in self.checks:
            self._check_nondet_order(node)
        self.loop_depth[-1] += 1
        self.generic_visit(node)
        self.loop_depth[-1] -= 1

    visit_AsyncFor = visit_For

    # --------------------------- nondeterministic collective order

    def _nondet_iterable(self, node):
        """A human-readable description when ``node`` (a for-loop's
        iter expression) has no deterministic order: a set
        literal/comprehension, a set()/frozenset()/set-method call, or
        os.listdir. ``sorted(...)`` around any of these never matches
        — that IS the fix."""
        if isinstance(node, (ast.Set, ast.SetComp)):
            return "a set literal"
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) and \
                    node.func.id in _SET_CALL_NAMES:
                return f"{node.func.id}(...)"
            chain = _attr_chain(node.func)
            if chain:
                if chain[-1] == "listdir":
                    return "os.listdir(...)"
                if chain[-1] in _SET_METHOD_NAMES and len(chain) >= 2:
                    return f".{chain[-1]}(...) (a set)"
        return None

    def _body_issues_comms(self, node) -> bool:
        """Does the loop body contain a collective/plan_buckets call or
        a bucket-named identifier? (the 'this loop decides comms or
        bucket order' signal)."""
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    chain = _attr_chain(sub.func)
                    if chain and chain[-1] in _ORDER_COLLECTIVE_NAMES:
                        return True
                if isinstance(sub, ast.Name) and \
                        "bucket" in sub.id.lower():
                    return True
                if isinstance(sub, ast.Attribute) and \
                        "bucket" in sub.attr.lower():
                    return True
        return False

    def _check_nondet_order(self, node):
        how = self._nondet_iterable(node.iter)
        if how is None or not self._body_issues_comms(node):
            return
        self._emit(
            "nondeterministic-collective-order", "error",
            node.iter.lineno,
            f"loop over {how} — an unordered iterable — decides bucket "
            f"construction or collective issue order: set iteration "
            f"order differs across processes (string hash "
            f"randomization) and os.listdir follows filesystem order, "
            f"so two ranks build different bucket lists / issue "
            f"collectives in different orders and the fleet deadlocks "
            f"or pairs the wrong buffers — iterate sorted(...) so "
            f"every rank sees the same order")

    def visit_While(self, node):
        # the While TEST re-evaluates every iteration: an isnan there
        # is a per-step host pull even when the loop itself is
        # top-level
        self._check_isnan_condition(node.test)
        self.loop_depth[-1] += 1
        self.generic_visit(node)
        self.loop_depth[-1] -= 1

    def visit_If(self, node):
        if self.loop_depth[-1] > 0:
            self._check_isnan_condition(node.test)
        self.generic_visit(node)

    # ---------------------------------------------- host isnan pulls

    def _isnan_call_in(self, node):
        """First ``jnp.isnan``/``jnp.isinf`` Call in the subtree (the
        jax one — resolved through the module's imports so a host-side
        ``np.isnan(loss)`` on a Python float never matches)."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            chain = _attr_chain(sub.func)
            if not chain or chain[-1] not in _ISNAN_NAMES:
                continue
            res = self._resolve(chain)
            if res[0] in ("jax", "jnp"):
                return sub
        return None

    def _emit_isnan_pull(self, container, line, via):
        for sub in ast.walk(container):
            if isinstance(sub, ast.Call):
                self._isnan_handled.add(id(sub))
        self._emit(
            "host-isnan-in-step-loop", "error", line,
            f"jnp.isnan/jnp.isinf result pulled to host ({via}) inside "
            f"a step loop: one device round-trip per tensor per "
            f"iteration, serializing the dispatch pipeline — use "
            f"apex_tpu.observability.numerics (tensor_stats / "
            f"StatsCollector: one fused on-device reduction for the "
            f"whole tree, host pull decimated to every N steps)")

    def _check_isnan_condition(self, test):
        if "host-isnan-in-step-loop" not in self.checks:
            return
        if self._isnan_call_in(test) is not None:
            self._emit_isnan_pull(test, test.lineno,
                                  "used as a branch condition")

    def visit_With(self, node):
        for item in node.items:
            if isinstance(item.context_expr, ast.Call):
                self._cm_calls.add(id(item.context_expr))
        self.generic_visit(node)

    visit_AsyncWith = visit_With

    def visit_Try(self, node):
        if self.loop_depth[-1] > 0:
            for handler in node.handlers:
                if _is_broad_handler(handler.type) and \
                        _body_only_swallows(handler.body):
                    caught = "except:" if handler.type is None else \
                        f"except {ast.unparse(handler.type)}:"
                    self._emit(
                        "swallowed-exception-in-step-loop", "error",
                        handler.lineno,
                        f"'{caught} pass/continue' inside a loop body "
                        f"silently swallows per-step failures (NaN "
                        f"storms, torn checkpoint writes, dying "
                        f"collectives) — retry transient classes via "
                        f"apex_tpu.resilience.retry.Policy, or count/"
                        f"log the failure before continuing")
        self.generic_visit(node)

    visit_TryStar = visit_Try

    # ------------------------------------------------------ call sites

    def visit_Assign(self, node):
        if len(self.stack) == 1 and "hardcoded-tile-size" in self.checks:
            for target in node.targets:
                if isinstance(target, ast.Name) and \
                        _TILE_NAME_RE.search(target.id.upper()) and \
                        isinstance(node.value, ast.Constant) and \
                        isinstance(node.value.value, int) and \
                        not isinstance(node.value.value, bool) and \
                        node.value.value >= _TILE_LITERAL_MIN:
                    self.tile_consts.append(
                        (node.lineno, target.id, node.value.value))
        self.generic_visit(node)

    def _check_blockspec_shape(self, node):
        """Flag tile-sized integer literals in a BlockSpec block shape
        (first positional arg or block_shape kwarg)."""
        self.blockspec_seen = True
        shape = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords
             if kw.arg == "block_shape"), None)
        if not isinstance(shape, (ast.Tuple, ast.List)):
            return
        for elt in shape.elts:
            if isinstance(elt, ast.Constant) and \
                    isinstance(elt.value, int) and \
                    not isinstance(elt.value, bool) and \
                    elt.value >= _TILE_LITERAL_MIN:
                self._emit(
                    "hardcoded-tile-size", "error", elt.lineno,
                    f"integer tile size {elt.value} hardcoded in a "
                    f"pl.BlockSpec block shape: the right tile is a "
                    f"per-device, per-shape search result — take it "
                    f"from apex_tpu.tuning (search space + cache) or "
                    f"ops/pallas_config, the only modules tile numbers "
                    f"may live in")

    # --------------------------------------- rank-unsafe artifact paths

    def _open_write_mode(self, node) -> bool:
        """Is this ``open(...)`` call a write? (positional or ``mode=``
        kwarg; a missing mode is the default read)."""
        mode = node.args[1] if len(node.args) >= 2 else next(
            (kw.value for kw in node.keywords if kw.arg == "mode"),
            None)
        return (isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and mode.value in _WRITE_MODES)

    def _check_rank_unsafe_open(self, node):
        if not node.args:
            return
        if not self._open_write_mode(node):
            return
        path_expr = node.args[0]
        fixed_artifact = None
        has_rank_component = False
        for sub in ast.walk(path_expr):
            if isinstance(sub, ast.Constant) and \
                    isinstance(sub.value, str):
                text = sub.value
                if text.lower().endswith(_ARTIFACT_EXTS):
                    fixed_artifact = text
                if _RANK_COMPONENT_RE.search(text):
                    has_rank_component = True
            elif isinstance(sub, ast.Name):
                if _RANK_COMPONENT_RE.search(sub.id):
                    has_rank_component = True
            elif isinstance(sub, ast.Attribute):
                if _RANK_COMPONENT_RE.search(sub.attr):
                    has_rank_component = True
        if fixed_artifact is None or has_rank_component:
            return
        self._emit(
            "rank-unsafe-artifact-path", "error", node.lineno,
            f"write-mode open() of a fixed artifact path "
            f"({fixed_artifact!r}) in code multiproc workers execute: "
            f"two ranks handed this path clobber or interleave each "
            f"other's telemetry — route it through "
            f"apex_tpu.observability.fleet.rank_path (automatic "
            f".rank{{i}} suffix) or build the name from the "
            f"rank/pid")

    def _check_raw_fp8_cast(self, node):
        """``x.astype(<fp8 dtype>)`` outside the sanctioned owners —
        positional or ``dtype=`` keyword form: a raw cast has neither
        the delayed scale nor the saturation clamp — quantization must
        go through ops.precision."""
        arg = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "dtype"),
            None)
        if arg is None:
            return
        name = None
        chain = _attr_chain(arg)
        if chain:
            name = self._resolve(chain)[-1]
        elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
        if name is None or not _FP8_DTYPE_NAME_RE.match(name):
            return
        self._emit(
            "raw-fp8-cast", "error", node.lineno,
            f"raw fp8 cast '.astype({name})': an unscaled, unsaturated "
            f"cast overflows to NaN past the format edge (E4M3 has no "
            f"inf) and flushes small tails to zero — quantize through "
            f"apex_tpu.ops.precision (quantize_fp8 / matmul_fp8) under "
            f"the amp Fp8DelayedScaler's delayed scales; only "
            f"ops/precision.py and amp/ may cast to fp8")

    def _check_memory_introspection(self, node, chain, tail):
        # matched on the attribute, not the chain: the common shapes —
        # `jax.devices()[0].memory_stats()`, `client.live_executables()`
        # — have subscripted/opaque receivers that break the
        # dotted-name chain
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MEMORY_INTROSPECT_ATTRS:
            self._emit(
                "raw-memory-introspection", "error", node.lineno,
                f"direct '.{node.func.attr}()' read: the PJRT "
                f"allocator/executable surface belongs to the memory "
                f"observability tier — use apex_tpu.observability."
                f"memory (device_memory_stats, the compiled-stats "
                f"capture; snapshots, watermarks and gauges ride "
                f"along) or pallas_config.device_hbm_bytes for the "
                f"budget; only those modules may read it directly")
            return
        if tail in _MEMORY_INTROSPECT_JAX_NAMES and chain:
            res = self._resolve(chain)
            if res and res[0] == "jax":
                self._emit(
                    "raw-memory-introspection", "error", node.lineno,
                    f"direct '{'.'.join(chain)}(...)' call: the live-"
                    f"buffer walk sweeps every array on host (and "
                    f"forces backend init through get_backend) — in a "
                    f"step loop it serializes the pipeline like the "
                    f"per-tensor isnan pulls the numerics tier "
                    f"retired. Route through apex_tpu.observability."
                    f"memory (MemoryMonitor's decimated snapshots / "
                    f"memory_snapshot), which also keeps the "
                    f"watermark + top-k accounting OOM forensics "
                    f"depend on")

    def visit_Call(self, node):
        chain = _attr_chain(node.func)
        tail = chain[-1] if chain else None

        if "raw-memory-introspection" in self.checks:
            self._check_memory_introspection(node, chain, tail)

        if "rank-unsafe-artifact-path" in self.checks and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "open":
            self._check_rank_unsafe_open(node)

        if "host-isnan-in-step-loop" in self.checks and \
                self.loop_depth[-1] > 0 and \
                id(node) not in self._isnan_handled:
            if isinstance(node.func, ast.Name) and \
                    node.func.id in ("bool", "float") and node.args and \
                    self._isnan_call_in(node.args[0]) is not None:
                self._emit_isnan_pull(node, node.lineno,
                                      f"via {node.func.id}()")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("item", "tolist") and \
                    self._isnan_call_in(node.func.value) is not None:
                self._emit_isnan_pull(node, node.lineno,
                                      f"via .{node.func.attr}()")

        if tail == "BlockSpec" and "hardcoded-tile-size" in self.checks:
            self._check_blockspec_shape(node)

        if tail == "astype" and "raw-fp8-cast" in self.checks and \
                isinstance(node.func, ast.Attribute):
            self._check_raw_fp8_cast(node)

        if tail == "enter_context":
            # stack.enter_context(span(...)) closes at stack exit —
            # sanction the argument before visiting it
            for arg in node.args:
                if isinstance(arg, ast.Call):
                    self._cm_calls.add(id(arg))
        if tail in _SPAN_NAMES and "unclosed-span" in self.checks and \
                id(node) not in self._cm_calls:
            res = self._resolve(chain)
            if "observability" in res:
                self._emit(
                    "unclosed-span", "error", node.lineno,
                    f"'{'.'.join(chain)}(...)' opened outside a 'with' "
                    f"(or ExitStack.enter_context): a span without its "
                    f"guaranteed close leaks an open-span stack entry "
                    f"the flight recorder reports forever and corrupts "
                    f"later spans' nesting — use 'with "
                    f"{'.'.join(chain)}(...):' around the region")

        if tail == "block_until_ready" or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"):
            self.frames[-1]["block"].append(node.lineno)
        # resolve through the import map so `from time import time` and
        # `import time as t` still count as clock reads
        res = self._resolve(chain) if chain else None
        is_clock = (res and len(res) >= 2
                    and (res[-2], res[-1]) in _CLOCK_CALLS) or (
            tail in ("perf_counter", "perf_counter_ns", "monotonic",
                     "default_timer"))
        if is_clock:
            self.frames[-1]["clock"].append(node.lineno)
            self._emit(
                "raw-clock", "error", node.lineno,
                f"direct wall-clock read ('{'.'.join(chain or [tail])}') "
                f"in apex_tpu library code: time through "
                f"apex_tpu.runtime.timing (corrected host-fetch sync) or "
                f"an apex_tpu.observability Timer instead — a bare clock "
                f"pair measures dispatch, not device time")

        if self._in_jit():
            if isinstance(node.func, ast.Name) and \
                    node.func.id in _HOST_PULL_NAMES and node.args and \
                    not isinstance(node.args[0], ast.Constant) and \
                    not _is_static_expr(node.args[0]):
                self._emit(
                    "host-in-jit", "error", node.lineno,
                    f"'{node.func.id}(...)' inside a jit-decorated body "
                    f"forces a host pull: it raises on traced values or "
                    f"silently syncs on constants — keep the value on "
                    f"device (jnp) or hoist it out of the jit")
            if res and len(res) >= 2 and \
                    res[0] in ("np", "numpy", "onp") and \
                    res[-1] in _HOST_PULL_NP:
                self._emit(
                    "host-in-jit", "error", node.lineno,
                    f"'{'.'.join(chain)}(...)' inside a jit-decorated "
                    f"body: numpy materializes on host at trace time — "
                    f"use jnp, or hoist the constant out of the jit")
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _HOST_PULL_METHODS:
                self._emit(
                    "host-in-jit", "error", node.lineno,
                    f"'.{node.func.attr}()' inside a jit-decorated body "
                    f"is a device sync / trace error")
            if res and (
                    res[0] == "random"
                    or (len(res) >= 2 and res[0] in ("np", "numpy")
                        and res[1] == "random")):
                self._emit(
                    "rng-in-jit", "error", node.lineno,
                    f"'{'.'.join(chain)}(...)' inside a jit-decorated "
                    f"body: the sample is drawn once at trace time and "
                    f"baked in as a constant — every step reuses it; "
                    f"use jax.random with a threaded key")
        self.generic_visit(node)


def lint_source(source: str, relpath: str, checks=None, abspath=None):
    """Lint one file's source text; returns a list of Findings.

    ``abspath``: the file's absolute path when known (lint_paths passes
    it) — path-scoped checks like raw-clock must not depend on what cwd
    the relpath happened to be computed against."""
    checks = set(checks or AST_CHECKS)
    unknown = checks - set(AST_CHECKS)
    if unknown:
        raise ValueError(f"unknown AST check(s) {sorted(unknown)}; "
                         f"valid: {list(AST_CHECKS)}")
    norm = relpath.replace("\\", "/")
    if any(norm.endswith(allow.replace("\\", "/"))
           for allow in _SYNC_ALLOWLIST):
        checks = checks - {"sync-timing"}
    # raw-clock: library code under an apex_tpu/ package dir only, minus
    # the modules that implement the sanctioned clocks themselves
    if not _raw_clock_applies(abspath or relpath):
        checks = checks - {"raw-clock"}
    # swallowed-exception: step loops live in apex_tpu/ and examples/
    if not _swallowed_exc_applies(abspath or relpath):
        checks = checks - {"swallowed-exception-in-step-loop"}
    # unclosed-span: same ground — instrumented library + example code
    if not _unclosed_span_applies(abspath or relpath):
        checks = checks - {"unclosed-span"}
    # host-isnan: step loops again, minus the numerics package (the
    # sanctioned fused/decimated implementation)
    if not _host_isnan_applies(abspath or relpath):
        checks = checks - {"host-isnan-in-step-loop"}
    # rank-unsafe-artifact-path: the same worker-executed ground, minus
    # the fleet identity package (the sanctioned suffixer)
    if not _rank_unsafe_applies(abspath or relpath):
        checks = checks - {"rank-unsafe-artifact-path"}
    # hardcoded-tile-size: pallas_config + the tuner search space are
    # the sanctioned homes for tile numbers
    if not _tile_size_applies(abspath or relpath):
        checks = checks - {"hardcoded-tile-size"}
    # raw-fp8-cast: ops/precision.py (+ its Pallas kernel) and amp/ are
    # the sanctioned quantization owners
    if not _raw_fp8_applies(abspath or relpath):
        checks = checks - {"raw-fp8-cast"}
    # nondeterministic-collective-order: comms scheduling code only
    # (parallel/, runtime/, distributed/)
    if not _nondet_order_applies(abspath or relpath):
        checks = checks - {"nondeterministic-collective-order"}
    # raw-memory-introspection: the memory observability package and
    # pallas_config are the sanctioned introspection owners
    if not _memory_introspect_applies(abspath or relpath):
        checks = checks - {"raw-memory-introspection"}
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError as e:
        return [Finding("syntax", "error", relpath, e.lineno or 0,
                        "<module>", f"does not parse: {e.msg}")]
    visitor = _Visitor(relpath, relpath, checks)
    visitor.visit(tree)
    # tile-named module constants are only tile sizes when the file
    # actually builds BlockSpecs (a _TILE_ROWS in a data loader is not
    # kernel geometry)
    if "hardcoded-tile-size" in checks and visitor.blockspec_seen:
        for lineno, name, value in visitor.tile_consts:
            visitor.findings.append(Finding(
                "hardcoded-tile-size", "error", relpath, lineno,
                "<module>",
                f"module tile constant {name} = {value} in a file that "
                f"builds pl.BlockSpecs: tile geometry must come from "
                f"apex_tpu.tuning (per-device search + cache) or "
                f"ops/pallas_config — a hardcoded tile outlives the "
                f"hardware it was guessed for"))
    # close the module-level frame (module-scope timing code, e.g. a
    # script body, gets the same sync-timing treatment)
    frame = visitor.frames[0]
    if "sync-timing" in checks and frame["clock"] and frame["block"]:
        for line in frame["block"]:
            visitor.findings.append(Finding(
                "sync-timing", "error", relpath, line, "<module>",
                "block_until_ready in module-level timing code — use "
                "apex_tpu.runtime.timing.sync"))
    lines = source.splitlines()
    return [f for f in visitor.findings
            if not is_suppressed(f, lines)]


def iter_python_files(paths):
    """Expand files/dirs into .py files, skipping caches and build dirs."""
    skip_dirs = {"__pycache__", ".git", "build", ".eggs", "node_modules"}
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in skip_dirs
                                 and not d.endswith(".egg-info"))
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        yield os.path.join(root, fname)


def lint_paths(paths, root=None, checks=None):
    """Lint every .py under ``paths``; paths in findings are relative to
    ``root`` (default: cwd)."""
    root = os.path.abspath(root or os.getcwd())
    findings = []
    for fpath in iter_python_files(paths):
        ap = os.path.abspath(fpath)
        rel = os.path.relpath(ap, root) if ap.startswith(root) else fpath
        with open(ap, encoding="utf-8") as f:
            source = f.read()
        findings.extend(lint_source(source, rel, checks, abspath=ap))
    return findings
