"""Precision-flow checks — client analyses over :mod:`.dataflow`
(ISSUE 3 tentpole).

Apex's value proposition is mixed precision *done safely*: O1/O2
boundary casting, fp32 master weights, loss scaling, fp32 statistics in
the fused kernels. These checks turn each of those documented
invariants into a machine-checked fact over the traced programs:

- ``lowprec-accum``      bf16/fp16 operands reaching ``dot_general`` /
  ``conv`` whose result stays half (no fp32
  ``preferred_element_type``), or an additive reduction
  (``reduce_sum``/``cumsum``/``reduce_window_sum``) running directly
  over a half-precision operand with no upcast on the path.
- ``master-weights``     a value tainted "master" (params / m / v on an
  optimizer update path) that is born half, touched by arithmetic while
  half, or stored half in a designated output slot.
- ``unsafe-exp``         ``exp`` on a half-precision value with no
  subtracted running max (the softmax-overflow recipe; fp16 overflows
  at x ≈ 11.1), and ``log``/``log1p`` on fp16.
- ``cast-churn``         consecutive ``convert_element_type`` runs that
  round-trip (f32→bf16→f32 or back) with no compute in between — pure
  VMEM/HBM bandwidth burn plus, on the down-up direction, a silent
  precision haircut.
- ``loss-scale-bypass``  a "grad"-tainted value that reaches arithmetic
  with "master"/"param"-tainted state without ever being multiplied or
  divided by a "scale"-tainted value (the scaler's unscale) — the skip
  that applies *scaled* gradients.
- ``fp8-unscaled``       an E4M3/E5M2 value (by dtype, or upcast from
  one with no compute in between) reaching a ``dot_general`` without a
  live delayed scale having been multiplied in before the cast — the
  raw-cast recipe that silently saturates/zeros tensor tails (ISSUE 13;
  the O4 differentiator: caught statically, not at loss-curve time).
- ``fp8-stale-amax``     a cast to fp8 whose applied scale does NOT
  descend from the amax-history state threaded into this step
  (a constant, a hand-rolled factor, a stashed scale from another
  run): the static proxy for "the scale tracks the amax rings" —
  delayed scaling is only safe when the factor follows the data.

Entry point: :func:`analyze_precision` (mirrors
``jaxpr_checks.analyze_fn``); the registered customers live in
:mod:`.targets`. ``roles`` assigns input taints by positional argnum;
``master_outs`` names flat output slots that must stay fp32 (the O2
re-materialized half model copy is *not* one of them — downcasting the
master into the model copy is the discipline, not a violation of it).
"""

from __future__ import annotations

from apex_tpu.analysis.dataflow import (
    ARITH_PRIMS,
    FP8_DTYPES,
    HALF_DTYPES,
    AbsVal,
    interpret,
    itemsize,
)
from apex_tpu.analysis.findings import Finding

PRECISION_CHECKS = (
    "lowprec-accum", "master-weights", "unsafe-exp", "cast-churn",
    "loss-scale-bypass", "fp8-unscaled", "fp8-stale-amax",
)

_REDUCE_PRIMS = ("reduce_sum", "cumsum", "reduce_window_sum")
_CONTRACT_PRIMS = ("dot_general", "conv_general_dilated")


class _Ctx:
    """Shared state for one analyze_precision run."""

    def __init__(self, name, path, checks):
        self.name = name
        self.path = path
        self.checks = checks
        self.findings = []
        self.seen = set()
        self.bypass_fired = False

    def add(self, check, severity, message, dedup_key=None):
        if dedup_key is not None:
            key = (check,) + tuple(dedup_key)
            if key in self.seen:
                return
            self.seen.add(key)
        self.findings.append(Finding(
            check, severity, self.path, 0, self.name, message))


def _visit_lowprec_accum(ctx, eqn, ins, outs):
    prim = eqn.primitive.name
    if prim in _CONTRACT_PRIMS:
        half_in = sorted({v.dtype for v in ins
                          if v is not None and v.dtype in HALF_DTYPES})
        if half_in and outs and outs[0].dtype in HALF_DTYPES:
            ctx.add(
                "lowprec-accum", "error",
                f"'{prim}' contracts {'/'.join(half_in)} operands into a "
                f"{outs[0].dtype} result: the accumulator is not fp32 — "
                f"pass preferred_element_type=jnp.float32 (and downcast "
                f"after) so the MXU accumulates in full precision",
                dedup_key=(prim, outs[0].dtype))
    elif prim in _REDUCE_PRIMS:
        op = ins[0] if ins else None
        if op is not None and op.dtype in HALF_DTYPES:
            ctx.add(
                "lowprec-accum", "error",
                f"'{prim}' accumulates directly over a {op.dtype} "
                f"operand: each partial sum rounds to "
                f"{op.dtype} — upcast to fp32 on the accumulation "
                f"path (x.astype(jnp.float32)) before reducing",
                dedup_key=(prim, op.dtype))


def _visit_master_weights(ctx, eqn, ins, outs):
    prim = eqn.primitive.name
    if prim == "convert_element_type" or prim not in ARITH_PRIMS:
        return
    for v in ins:
        if v is not None and "master" in v.taints \
                and v.dtype in HALF_DTYPES:
            ctx.add(
                "master-weights", "error",
                f"master-weight/optimizer-state value is touched in "
                f"{v.dtype} by '{prim}': O2 discipline keeps params, m "
                f"and v in fp32 through the whole update path",
                dedup_key=(prim, v.dtype))


def _visit_unsafe_exp(ctx, eqn, ins, outs):
    prim = eqn.primitive.name
    op = ins[0] if ins else None
    if op is None:
        return
    if prim == "exp" and op.dtype in HALF_DTYPES \
            and not op.max_subtracted:
        ctx.add(
            "unsafe-exp", "error",
            f"'exp' on a {op.dtype} value with no subtracted running "
            f"max: a softmax built this way overflows "
            f"({'x > ~11' if op.dtype == 'float16' else 'x > ~88'}) — "
            f"subtract the row max first (or upcast to fp32 and use "
            f"jax.nn.softmax)",
            dedup_key=(op.dtype,))
    elif prim in ("log", "log1p") and op.dtype == "float16":
        ctx.add(
            "unsafe-exp", "warning",
            f"'{prim}' on a float16 value: fp16's 10-bit mantissa and "
            f"6e-5 normal floor make log unstable near 0/1 — compute "
            f"it in fp32",
            dedup_key=(prim,))


def _visit_cast_churn(ctx, eqn, ins, outs):
    if eqn.primitive.name != "convert_element_type" or not outs:
        return
    chain = outs[0].cast_chain
    if len(chain) < 3:
        return
    a, b, c = chain[-3:]
    try:
        ia, ib = itemsize(a), itemsize(b)
    except TypeError:
        return
    # Two shapes of churn, both pure casts with no compute in between:
    # - N -> W -> N: the upcast recovered nothing, the round trip is an
    #   identity paid for in bandwidth;
    # - a down-up-down cycle (W -> N -> W -> N ...): the value keeps
    #   bouncing through the narrow dtype.
    # A single lossy W -> N -> W is deliberately NOT flagged: that is
    # the normal storage-dtype boundary (producer downcasts its output,
    # the next consumer upcasts to compute).
    noop_round_trip = c == a and ib > ia
    cycle = (len(chain) >= 4 and chain[-1] == chain[-3]
             and chain[-2] == chain[-4])
    if noop_round_trip or cycle:
        shown = chain[-4:] if cycle and not noop_round_trip \
            else chain[-3:]
        ctx.add(
            "cast-churn", "warning",
            f"cast churn: {' -> '.join(shown)} with no compute in "
            f"between — the round trip burns bandwidth for nothing"
            + ("" if noop_round_trip
               else " and silently rounds through the narrow dtype"),
            dedup_key=(shown,))


def _visit_loss_scale_bypass(ctx, eqn, ins, outs):
    if ctx.bypass_fired or eqn.primitive.name not in ARITH_PRIMS:
        return
    present = [v for v in ins if v is not None]
    raw_grads = [v for v in present
                 if "grad" in v.taints and not v.unscaled]
    state = [v for v in present
             if {"master", "param"} & v.taints and "grad" not in v.taints]
    if raw_grads and state:
        ctx.bypass_fired = True
        ctx.add(
            "loss-scale-bypass", "error",
            f"gradients reach '{eqn.primitive.name}' together with "
            f"param/optimizer state without passing through the "
            f"scaler's unscale: the update applies loss-SCALED "
            f"gradients (effective lr multiplied by the loss scale)")


def _visit_fp8_unscaled(ctx, eqn, ins, outs):
    if eqn.primitive.name not in _CONTRACT_PRIMS:
        return
    for side, v in zip(("lhs", "rhs"), ins):
        if v is None or not v.touches_fp8():
            continue
        if not v.fp8_scaled:
            ctx.add(
                "fp8-unscaled", "error",
                f"fp8 ({v.dtype if v.dtype in FP8_DTYPES else 'fp8-cast'})"
                f" {side} operand reaches '{eqn.primitive.name}' without "
                f"a live delayed scale: values outside ±448 (E4M3) / "
                f"±57344 (E5M2) saturate and small tails flush to zero "
                f"— multiply in the per-tensor scale from the "
                f"AmaxHistory rings before the cast "
                f"(ops.precision.matmul_fp8 does the whole epilogue)",
                dedup_key=(side, v.dtype))


def _visit_fp8_stale_amax(ctx, eqn, ins, outs):
    if eqn.primitive.name != "convert_element_type" or not outs:
        return
    out = outs[0]
    if out.dtype not in FP8_DTYPES:
        return
    if out.fp8_scaled and not out.fp8_scale_hist:
        ctx.add(
            "fp8-stale-amax", "error",
            f"cast to {out.dtype} under a scale that does not derive "
            f"from the amax-history state threaded into this step: a "
            f"constant or stashed factor stops tracking the tensor's "
            f"range the moment the loss landscape moves — compute the "
            f"scale from the carried Fp8ScalingState "
            f"(Fp8DelayedScaler.scales) every step",
            dedup_key=(out.dtype,))


_VISITORS = {
    "lowprec-accum": _visit_lowprec_accum,
    "master-weights": _visit_master_weights,
    "unsafe-exp": _visit_unsafe_exp,
    "cast-churn": _visit_cast_churn,
    "loss-scale-bypass": _visit_loss_scale_bypass,
    "fp8-unscaled": _visit_fp8_unscaled,
    "fp8-stale-amax": _visit_fp8_stale_amax,
}


def _taints_of(role):
    if role is None:
        return frozenset()
    if isinstance(role, str):
        return frozenset({role})
    return frozenset(role)


def analyze_precision(fn, *example_args, name=None, roles=None,
                      master_outs=(), checks=None):
    """Trace ``fn`` and run the precision-flow checks over its jaxpr.

    ``roles``: {argnum: taint | iterable-of-taints} applied to every
    leaf of that positional argument. Meaningful taints: ``"grad"``
    (loss-scaled gradients), ``"scale"`` (the scaler state /
    loss-scale value), ``"master"`` (params/m/v that must stay fp32 on
    this path), ``"param"`` (model params; only read by the bypass
    check), ``"fp8_scale"`` (values that act as fp8 delayed scales) and
    ``"amax_hist"`` (the carried Fp8ScalingState/AmaxHistory state —
    tag the fp8 state argument with BOTH so scales derived from it
    count as history-fresh for ``fp8-stale-amax``). ``master_outs``:
    flat output indices that must not be half precision. Returns a
    list of :class:`Finding`.
    """
    import jax
    import numpy as np

    name = name or getattr(fn, "__name__", "fn")
    path = f"<jaxpr:{name}>"
    run = set(checks or PRECISION_CHECKS)
    unknown = run - set(PRECISION_CHECKS)
    if unknown:
        raise ValueError(
            f"unknown precision check(s) {sorted(unknown)}; valid: "
            f"{list(PRECISION_CHECKS)}")

    closed = jax.make_jaxpr(fn)(*example_args)

    roles = roles or {}
    ctx = _Ctx(name, path, run)
    in_vals = []
    for argnum, arg in enumerate(example_args):
        taints = _taints_of(roles.get(argnum))
        for leaf in jax.tree_util.tree_leaves(arg):
            dtype = str(np.asarray(leaf).dtype) if not hasattr(
                leaf, "dtype") else str(leaf.dtype)
            val = AbsVal(dtype=dtype, origin=dtype, taints=taints)
            in_vals.append(val)
            if "master-weights" in run and "master" in taints \
                    and dtype in HALF_DTYPES:
                ctx.add(
                    "master-weights", "error",
                    f"master-weight/optimizer-state input (arg {argnum}) "
                    f"arrives in {dtype}: the optimizer must hold fp32 "
                    f"master copies (amp O2)",
                    dedup_key=("input", argnum, dtype))

    visitors = [_VISITORS[c] for c in PRECISION_CHECKS if c in run]

    def visit(eqn, ins, outs):
        for v in visitors:
            v(ctx, eqn, ins, outs)

    out_vals = interpret(closed, in_vals, visit=visit)

    if "master-weights" in run:
        for idx in master_outs:
            if idx < len(out_vals) and out_vals[idx] is not None \
                    and out_vals[idx].dtype in HALF_DTYPES:
                ctx.add(
                    "master-weights", "error",
                    f"output {idx} is a master-weight/optimizer-state "
                    f"slot but is stored in {out_vals[idx].dtype}: the "
                    f"fp32 master copy is being narrowed between steps",
                    dedup_key=("output", idx))

    return ctx.findings


def report_to_registry(findings, registry=None):
    """Publish precision finding counts as the ``analysis/precision``
    counter family (+ a total gauge) so bench runs carry them in their
    metrics JSONL. Returns {check id: count} over all five checks."""
    from apex_tpu.observability import get_registry

    reg = registry if registry is not None else get_registry()
    counts = {c: 0 for c in PRECISION_CHECKS}
    for f in findings:
        if f.check in counts:
            counts[f.check] += 1
    for check, n in counts.items():
        if n:
            reg.counter("analysis/precision_findings", check=check).inc(n)
    reg.gauge("analysis/precision_findings_total").set(
        sum(counts.values()))
    return counts
