"""Memory-liveness checks — static donation/remat/offload findings
plus the calibrated HBM priors (ISSUE 19).

PR 14's calibration loop measured the PR 4 HBM cost model off by up to
3.43x per target, and the paper's Apex blueprint wins exactly because
memory discipline (master weights, flat buffers, donation) is enforced
by construction. This engine makes HBM waste a *static* finding: it
rides the unified interpreter (:mod:`.interp`) with a
:class:`LiveIntervalLattice` for value provenance, and consumes the
SAME liveness record (:func:`~.sharding_flow.compute_liveness`) the
HBM estimator prices from — birth/death interval, donation credit, and
the peak-composition record per value — so the estimator and the
checks can never disagree on what is live when.

Five checks (:data:`MEMORY_CHECKS`):

- ``missed-donation``    an input buffer dies inside the jaxpr (last
  read, never returned) and an output of matching shape/dtype exists
  to alias into, but the call site passes no ``donate_argnums`` slot
  for it — free HBM, bytes named.
- ``remat-opportunity``  an intermediate held live across the modeled
  peak whose roofline recompute cost (producer FLOPs over the planning
  peak) is cheaper than spilling its bytes through HBM — suggests
  ``jax.checkpoint`` at the named site.
- ``peak-spike``         the transient peak exceeds the steady
  end-of-step watermark by a factor; the message names the ops whose
  values compose the spike.
- ``live-range-upcast``  a widening cast (e.g. bf16 -> fp32) born long
  before its first real consumer — cast later and the wide live range
  shrinks to the narrow one.
- ``offload-candidate``  a step-carried state leaf never read between
  step start and its own update — legal to park in host RAM between
  steps (the storage-tier item ROADMAP 3 asks for). Requires the
  caller to name the state args (``state_argnums``): without that
  signal the engine cannot know which inputs are step-carried.

The calibration-prior half: the committed ``analysis/hbm_priors.json``
(schema-versioned; :func:`load_hbm_priors` is loud on drift) distills
the bench ``memory_calibration`` captures into per-target
measured/modeled ratios, consumed by
``estimate_hbm_and_comms(priors=...)`` and the planner's
``pruned:hbm`` decisions (``tools/refresh_priors.py`` regenerates it
from the newest capture).

Entry point: :func:`analyze_memory` (mirrors ``analyze_sharding``);
the registered targets live in :mod:`.targets` (``MEMORY_TARGETS``)
and per-run counts land in the ``analysis/memory_findings{check=}``
family — zero-filled, so the binary ``--compare`` gate in
``tools/metrics_report.py`` sees an explicit 0, not an absent series.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os

from apex_tpu.analysis import interp
from apex_tpu.analysis.findings import Finding
from apex_tpu.analysis.sharding_flow import (
    ShardVal, compute_liveness, normalize_spec, prior_ratio_of,
)

MEMORY_CHECKS = (
    "missed-donation", "remat-opportunity", "peak-spike",
    "live-range-upcast", "offload-candidate",
)

#: Tunable floors/factors; override per call via ``thresholds=``.
#: Defaults are set so a well-disciplined step (donated state, fused
#: update, no held activations) is clean — see the registered
#: MEMORY_TARGETS contract in tests/run_analysis/test_memory_checks.py.
DEFAULT_THRESHOLDS = {
    "min_donation_bytes": 1 << 16,   # ignore sub-64KiB inputs
    "min_remat_bytes": 1 << 20,      # peak contribution worth holding
    "remat_min_steps": 16,           # tiny programs have no fwd/bwd
    "remat_span_frac": 0.35,         # live across >= 35% of the step
    "spike_factor": 3.0,             # peak > 3x steady watermark
    "min_spike_bytes": 1 << 20,      # and at least 1MiB above it
    "upcast_min_gap": 8,             # steps between cast and first use
    "upcast_gap_frac": 0.25,         # ... and >= 25% of the program
    "min_upcast_bytes": 1 << 16,     # wide bytes worth shrinking
    # first read in the last quarter: host offload pays a PCIe
    # round-trip, so state merely idle for half a step (an Adam moment
    # read mid-update) is not a candidate — only tail-read state is
    "offload_frac": 0.75,
    "offload_min_steps": 16,
    "min_offload_bytes": 1 << 16,
}


# ----------------------------------------------------- interval lattice


@dataclasses.dataclass(frozen=True)
class MemVal:
    """One point of the live-interval lattice: which flat input leaves
    this value derives from (``origins`` — ties an update output back
    to the state leaf it rewrites), and the narrow dtype it was widened
    from when the value is (a preserve-chain of) an upcast."""

    origins: frozenset = frozenset()
    upcast_from: object = None

    def with_upcast(self, mark):
        if mark == self.upcast_from:
            return self
        return dataclasses.replace(self, upcast_from=mark)


_EMPTY = MemVal()

# Ops that keep the widened bytes without consuming them: the upcast
# marker flows through (a reshaped fp32 upcast is still "the upcast").
_UPCAST_PRESERVE = frozenset({
    "reshape", "broadcast_in_dim", "squeeze", "expand_dims",
    "transpose", "copy", "stop_gradient",
})


def _join_mem(ins):
    present = [v for v in ins if v is not None]
    if not present:
        return _EMPTY
    origins = frozenset().union(*(v.origins for v in present))
    ups = {v.upcast_from for v in present}
    return MemVal(origins=origins,
                  upcast_from=ups.pop() if len(ups) == 1 else None)


def _itemsize(aval) -> int:
    import numpy as np

    try:
        return np.dtype(str(getattr(aval, "dtype", "float32"))).itemsize
    except TypeError:
        return getattr(getattr(aval, "dtype", None), "itemsize", 0) or 0


class LiveIntervalLattice(interp.Lattice):
    """Provenance over the unified walk: input-leaf origins (union-join
    — contagious through every compute op, ``warm_carry_join`` so a
    leaf read only through a carried loop still registers) plus the
    upcast marker the live-range-upcast check chases through preserve
    chains. The *intervals* themselves come from the shared
    :func:`~.sharding_flow.compute_liveness` walk; this lattice carries
    what the linearized view cannot see — which concrete input each
    value derives from across call/scan/shard_map boundaries."""

    name = "memory"
    warm_carry_join = True

    def for_aval(self, aval):
        return _EMPTY

    def transfer(self, eqn, ins, out_avals, ctx):
        prim = eqn.primitive.name
        if prim == "optimization_barrier":
            # elementwise over the tuple: a chain token must not taint
            # the bucket it orders (same rule as the state lattice)
            return tuple(
                (ins[i] if i < len(ins) and ins[i] is not None
                 else _EMPTY) for i in range(len(out_avals)))
        base = _join_mem(ins)
        if prim == "convert_element_type":
            src_aval = eqn.invars[0].aval if eqn.invars else None
            widened = (src_aval is not None and out_avals
                       and _itemsize(out_avals[0]) > _itemsize(src_aval))
            mark = str(getattr(src_aval, "dtype", "")) if widened \
                else None
            return tuple(base.with_upcast(mark) for _ in out_avals)
        if prim in _UPCAST_PRESERVE:
            return tuple(base for _ in out_avals)
        return tuple(base.with_upcast(None) for _ in out_avals)

    def join_branch(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return _join_mem((a, b))

    join_carry = join_branch


MEMORY_LATTICE = LiveIntervalLattice()


# -------------------------------------------------------------- priors

PRIORS_SCHEMA_VERSION = 1

HBM_PRIORS_PATH = os.path.join(os.path.dirname(__file__),
                               "hbm_priors.json")


def load_hbm_priors(path=None) -> dict:
    """Load and validate the committed calibration priors. LOUD on
    schema drift or malformed ratios: a priors file the loader cannot
    vouch for must never silently price planner pruning. Returns the
    full document (``priors`` maps target -> row with ``ratio``)."""
    path = path or HBM_PRIORS_PATH
    with open(path) as f:
        data = json.load(f)
    ver = data.get("schema_version")
    if ver != PRIORS_SCHEMA_VERSION:
        raise ValueError(
            f"{path}: hbm_priors schema_version {ver!r} != expected "
            f"{PRIORS_SCHEMA_VERSION} — regenerate with "
            f"tools/refresh_priors.py (or teach this loader the new "
            f"schema); refusing to price HBM on a drifted prior file")
    priors = data.get("priors")
    if not isinstance(priors, dict) or not priors:
        raise ValueError(
            f"{path}: 'priors' must be a non-empty "
            f"{{target: {{'ratio': ...}}}} map, got {priors!r}")
    for name, row in priors.items():
        try:
            prior_ratio_of(row)
        except ValueError as e:
            raise ValueError(f"{path}: prior for {name!r}: {e}") from e
    if "default_ratio" in data:
        prior_ratio_of(data["default_ratio"])
    return data


def prior_for(name, priors=None, default=False):
    """The calibration ratio for target ``name``, or None when no
    capture exists (callers annotate that loudly as ``prior:none``).
    ``priors``: a loaded priors document (default: the committed
    file). ``default=True`` falls back to the document's
    ``default_ratio`` instead of None."""
    data = priors if priors is not None else load_hbm_priors()
    row = (data.get("priors") or {}).get(name)
    if row is not None:
        return prior_ratio_of(row)
    if default and "default_ratio" in data:
        return prior_ratio_of(data["default_ratio"])
    return None


# ------------------------------------------------------------- findings


class _Ctx:
    def __init__(self, name, path, checks=frozenset(MEMORY_CHECKS)):
        self.name = name
        self.path = path
        self.checks = frozenset(checks)
        self.findings = []
        self.seen = set()

    def add(self, check, severity, message, dedup_key=None):
        if check not in self.checks:
            return
        if dedup_key is not None:
            key = (check,) + tuple(dedup_key)
            if key in self.seen:
                return
            self.seen.add(key)
        self.findings.append(Finding(
            check, severity, self.path, 0, self.name, message))


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


def _aval_desc(aval) -> str:
    shape = tuple(getattr(aval, "shape", ()) or ())
    dtype = getattr(aval, "dtype", "?")
    return f"{dtype}[{','.join(str(d) for d in shape)}]"


def _eqn_flops(eqn) -> int:
    """Roofline FLOP floor for recomputing one equation: dot_general
    counts 2*out*K; everything else one op per output element (the
    conservative elementwise floor)."""
    out_elems = sum(
        math.prod(tuple(getattr(v.aval, "shape", ()) or ()) or (1,))
        for v in eqn.outvars)
    if eqn.primitive.name == "dot_general":
        ((lc, _rc), _) = eqn.params["dimension_numbers"]
        lhs_shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
        k = math.prod([lhs_shape[d] for d in lc
                       if d < len(lhs_shape)] or [1])
        return 2 * out_elems * k
    return out_elems


# -------------------------------------------------- per-check evaluators


def _check_missed_donation(ctx, live, donated, leaf_label, th):
    out_avals = {}
    for v in live.out_vars:
        key = (tuple(getattr(v.aval, "shape", ()) or ()),
               str(getattr(v.aval, "dtype", "?")))
        out_avals[key] = out_avals.get(key, 0) + 1
    for i, cv in enumerate(live.invar_canon):
        if i in donated or cv in live.out_vars:
            continue
        last = live.last_use.get(cv)
        if last is None:
            continue  # never read: dead weight, not a donation miss
        nbytes = live.var_bytes(cv)
        if nbytes < th["min_donation_bytes"]:
            continue
        key = (tuple(getattr(cv.aval, "shape", ()) or ()),
               str(getattr(cv.aval, "dtype", "?")))
        if not out_avals.get(key):
            continue  # nothing to alias the donated buffer into
        ctx.add(
            "missed-donation", "warning",
            f"{leaf_label(i)} ({_aval_desc(cv.aval)}, "
            f"{_fmt_bytes(nbytes)}/device) is read for the last time "
            f"at step {last}/{live.n_steps} and never returned, but "
            f"the call site passes no donate_argnums slot for it: the "
            f"caller-owned buffer pins {_fmt_bytes(nbytes)} of HBM for "
            f"the whole step while an output of matching shape/dtype "
            f"exists to alias into — donate it and the bytes are free "
            f"from step {last + 1} on",
            dedup_key=(i,))


def _check_remat(ctx, live, th):
    if live.n_steps < th["remat_min_steps"]:
        return
    from apex_tpu.analysis.planner import (
        hbm_bandwidth, planning_peak_flops,
    )

    hbm_bw = hbm_bandwidth()
    peak_fl = planning_peak_flops()
    for cv, nbytes in live.live_at_peak():
        prod = live.producer.get(cv)
        if prod is None or cv in live.out_vars:
            continue  # inputs / outputs cannot be remat'd away
        if nbytes < th["min_remat_bytes"]:
            continue
        span = live.deaths[cv] - live.births[cv]
        if span < th["remat_span_frac"] * live.n_steps:
            continue
        idx, eqn = prod
        recompute_s = _eqn_flops(eqn) / peak_fl
        spill_s = 2 * nbytes / hbm_bw  # write it out + read it back
        if recompute_s >= spill_s:
            continue
        ctx.add(
            "remat-opportunity", "warning",
            f"value {_aval_desc(cv.aval)} ({_fmt_bytes(nbytes)}/device,"
            f" born at step {idx} by '{eqn.primitive.name}') stays "
            f"live across the modeled peak (step {live.peak_step}) for "
            f"{span} of {live.n_steps} steps; recomputing it costs "
            f"~{recompute_s * 1e6:.1f}us at the planning roofline vs "
            f"~{spill_s * 1e6:.1f}us of HBM traffic to hold it — wrap "
            f"the producing region in jax.checkpoint and the peak "
            f"drops by {_fmt_bytes(nbytes)}",
            dedup_key=(str(cv),))


def _check_peak_spike(ctx, live, th):
    steady = live.steady_bytes()
    peak = live.peak_hbm_bytes
    if steady <= 0 or peak <= th["spike_factor"] * steady:
        return
    if peak - steady < th["min_spike_bytes"]:
        return
    transients = [(cv, nb) for cv, nb in live.live_at_peak()
                  if live.deaths[cv] <= live.n_steps]
    top = []
    for cv, nb in transients[:3]:
        prod = live.producer.get(cv)
        prim = prod[1].primitive.name if prod else "input"
        top.append(f"'{prim}' {_aval_desc(cv.aval)} ({_fmt_bytes(nb)})")
    ctx.add(
        "peak-spike", "warning",
        f"transient peak {_fmt_bytes(peak)} at step {live.peak_step} "
        f"is {peak / steady:.1f}x the steady end-of-step watermark "
        f"({_fmt_bytes(steady)}) — the spike is composed of "
        f"{', '.join(top) if top else 'short-lived intermediates'}; "
        f"stagger or fuse those ops and the per-device HBM budget "
        f"follows the watermark, not the spike",
        dedup_key=("peak", live.peak_step))


def _check_upcast(ctx, live, th):
    # chase widening casts through preserve chains in the SAME
    # linearized world the intervals live in: the "first real use" of
    # an upcast is the first non-preserve consumer of its chain
    tracked = {}  # canonical var -> (birth idx, origin cv, narrow bytes)
    first_real = {}  # origin cv -> first non-preserve consuming step
    for idx, (eqn, reads) in enumerate(live.steps):
        prim = eqn.primitive.name
        hit = [tracked[r] for r in reads
               if r is not None and r in tracked]
        for rec in hit:
            if prim not in _UPCAST_PRESERVE:
                origin = rec[1]
                if origin not in first_real:
                    first_real[origin] = idx
        if prim == "convert_element_type" and eqn.invars:
            src, out = eqn.invars[0].aval, eqn.outvars[0].aval
            if _itemsize(out) > _itemsize(src):
                cv = live.canon(eqn.outvars[0])
                narrow = live.var_bytes(live.canon(eqn.invars[0])) \
                    if interp.is_var(eqn.invars[0]) else 0
                tracked[cv] = (idx, cv, narrow)
                continue
        if prim in _UPCAST_PRESERVE and hit and len(eqn.outvars) == 1:
            tracked[live.canon(eqn.outvars[0])] = hit[0]
    for origin, (birth, _cv, narrow) in sorted(
            ((o, t) for o, t in tracked.items() if o == t[1]),
            key=lambda p: p[1][0]):
        used = first_real.get(origin)
        if used is None:
            continue  # never really consumed
        gap = used - birth
        if gap < th["upcast_min_gap"] or \
                gap < th["upcast_gap_frac"] * live.n_steps:
            continue
        wide = live.var_bytes(origin)
        if wide < th["min_upcast_bytes"]:
            continue
        ctx.add(
            "live-range-upcast", "warning",
            f"value {_aval_desc(origin.aval)} is widened at step "
            f"{birth} but first consumed at step {used} "
            f"({gap} of {live.n_steps} steps later): the wide copy "
            f"({_fmt_bytes(wide)}/device) is live the whole gap where "
            f"the narrow one ({_fmt_bytes(narrow)}) would do — move "
            f"the cast next to its consumer and "
            f"{_fmt_bytes(wide - narrow)} of live range disappears",
            dedup_key=(str(origin),))


def _check_offload(ctx, live, state_leaves, leaf_label, out_origins,
                   th):
    if live.n_steps < th["offload_min_steps"]:
        return
    for i in sorted(state_leaves):
        if i >= len(live.invar_canon):
            continue
        cv = live.invar_canon[i]
        first = live.first_use.get(cv)
        if first is None:
            continue
        if first < th["offload_frac"] * live.n_steps:
            continue
        nbytes = live.var_bytes(cv)
        if nbytes < th["min_offload_bytes"]:
            continue
        # its own update must exist: an output deriving from this leaf
        # with the same shape/dtype (the rewritten state slot)
        key = (tuple(getattr(cv.aval, "shape", ()) or ()),
               str(getattr(cv.aval, "dtype", "?")))
        updated = any(
            i in origins and
            (tuple(getattr(ov.aval, "shape", ()) or ()),
             str(getattr(ov.aval, "dtype", "?"))) == key
            for ov, origins in out_origins)
        if not updated:
            continue
        ctx.add(
            "offload-candidate", "warning",
            f"state leaf {leaf_label(i)} ({_aval_desc(cv.aval)}, "
            f"{_fmt_bytes(nbytes)}/device) is step-carried but not "
            f"read until step {first}/{live.n_steps} — its own update "
            f"at the tail of the step: between steps the buffer is "
            f"dead weight in HBM, legal to park in host RAM and "
            f"prefetch before the update (device->host offload, the "
            f"storage tier ROADMAP item 3 names)",
            dedup_key=(i,))


# ----------------------------------------------------------------- entry


def analyze_memory_jaxpr(closed, *, name, donated=frozenset(),
                         state_leaves=frozenset(), in_vals=None,
                         axis_sizes=None, checks=None, leaf_label=None,
                         stats_out=None, priors=None, thresholds=None):
    """Run the memory-liveness checks over a traced ``ClosedJaxpr``.

    ``donated``: flat invar indices with a donate_argnums slot.
    ``state_leaves``: flat invar indices that are step-carried state
    (the offload check's scope — empty disables it, there is no way to
    know which inputs persist across steps without the caller saying
    so). ``leaf_label``: flat index -> human label for messages.
    ``priors``: calibration ratio for this program (see
    :func:`prior_for`); threads into ``stats_out`` as
    ``calibrated_peak_hbm_bytes``. Returns a list of
    :class:`~.findings.Finding`."""
    run = _validate_checks(checks)
    th = dict(DEFAULT_THRESHOLDS)
    for k, v in (thresholds or {}).items():
        if k not in DEFAULT_THRESHOLDS:
            raise ValueError(
                f"unknown memory threshold {k!r}; valid: "
                f"{sorted(DEFAULT_THRESHOLDS)}")
        th[k] = v
    ctx = _Ctx(name, f"<jaxpr:{name}>", checks=run)
    label = leaf_label or (lambda j: f"input #{j}")

    live = compute_liveness(closed, list(in_vals or []),
                            donated=frozenset(donated),
                            axis_sizes=axis_sizes)

    # provenance ride-along: one unified-interpreter pass ties every
    # output back to the input leaves it derives from (across
    # call/scan/shard_map boundaries the linearized walk keeps opaque)
    n_in = len(closed.jaxpr.invars)
    mem_in = [MemVal(origins=frozenset({j})) for j in range(n_in)]
    (mem_outs,) = interp.interpret_lattices(
        closed, [interp.LatticeRun(MEMORY_LATTICE, mem_in)],
        axis_sizes=axis_sizes or {})
    out_origins = tuple(
        (ov, mem_outs[k].origins if k < len(mem_outs)
         and mem_outs[k] is not None else frozenset())
        for k, ov in enumerate(closed.jaxpr.outvars)
        if interp.is_var(ov))

    if "missed-donation" in run:
        _check_missed_donation(ctx, live, frozenset(donated), label, th)
    if "remat-opportunity" in run:
        _check_remat(ctx, live, th)
    if "peak-spike" in run:
        _check_peak_spike(ctx, live, th)
    if "live-range-upcast" in run:
        _check_upcast(ctx, live, th)
    if "offload-candidate" in run:
        _check_offload(ctx, live, frozenset(state_leaves), label,
                       out_origins, th)

    if stats_out is not None:
        stats_out.update({
            "peak_hbm_bytes": live.peak_hbm_bytes,
            "peak_step": live.peak_step,
            "n_steps": live.n_steps,
            "n_values": len(live.births),
            "donated": len(frozenset(donated)),
            "steady_bytes": live.steady_bytes(),
        })
        if priors is not None:
            ratio = prior_ratio_of(priors)
            stats_out["prior_ratio"] = ratio
            stats_out["calibrated_peak_hbm_bytes"] = int(
                round(live.peak_hbm_bytes * ratio))
    return ctx.findings


def analyze_memory(fn, *example_args, name=None, donate_argnums=(),
                   state_argnums=(), in_specs=None, axis_sizes=None,
                   checks=None, stats_out=None, priors=None,
                   thresholds=None):
    """Trace ``fn(*example_args)`` and run the memory-liveness checks.

    ``donate_argnums``: the argnums the REAL call site donates — the
    missed-donation check flags dead non-donated inputs relative to
    exactly this set. ``state_argnums``: argnums holding step-carried
    state (optimizer moments, scaler state); scopes the
    offload-candidate check. ``in_specs``: optional PartitionSpec
    pytree per arg (sharded byte pricing, as in ``analyze_sharding``).
    Returns a list of :class:`~.findings.Finding`."""
    import jax

    name = name or getattr(fn, "__name__", "fn")

    flat_ranges = []
    labels = []
    start = 0
    for a, arg in enumerate(example_args):
        flat, _ = jax.tree_util.tree_flatten_with_path(arg)
        flat_ranges.append((start, start + len(flat)))
        for kp, _leaf in flat:
            suffix = jax.tree_util.keystr(kp)
            labels.append(f"arg {a}{suffix}" if suffix else f"arg {a}")
        start += len(flat)

    def leaf_range(argnums, what):
        out = set()
        for a in argnums:
            if not 0 <= a < len(flat_ranges):
                raise ValueError(
                    f"{what} {a} out of range for "
                    f"{len(flat_ranges)} args")
            out.update(range(*flat_ranges[a]))
        return frozenset(out)

    donated = leaf_range(donate_argnums, "donate_argnums")
    state_leaves = leaf_range(state_argnums, "state_argnums")

    closed = jax.make_jaxpr(fn)(*example_args)

    in_vals = None
    if in_specs is not None:
        from jax.sharding import PartitionSpec

        flat_specs = jax.tree_util.tree_flatten(
            in_specs, is_leaf=lambda s: s is None
            or isinstance(s, PartitionSpec))[0]
        if len(flat_specs) != len(closed.jaxpr.invars):
            raise ValueError(
                f"analyze_memory({name}): in_specs has "
                f"{len(flat_specs)} leaves, the traced program has "
                f"{len(closed.jaxpr.invars)} inputs")
        in_vals = [
            None if spec is None else ShardVal(spec=normalize_spec(
                spec, len(getattr(var.aval, 'shape', ()) or ())))
            for spec, var in zip(flat_specs, closed.jaxpr.invars)]

    def leaf_label(j):
        return labels[j] if j < len(labels) else f"input #{j}"

    return analyze_memory_jaxpr(
        closed, name=name, donated=donated, state_leaves=state_leaves,
        in_vals=in_vals, axis_sizes=axis_sizes, checks=checks,
        leaf_label=leaf_label, stats_out=stats_out, priors=priors,
        thresholds=thresholds)


def _validate_checks(checks):
    run = set(checks or MEMORY_CHECKS)
    unknown = run - set(MEMORY_CHECKS)
    if unknown:
        raise ValueError(
            f"unknown memory check(s) {sorted(unknown)}; valid: "
            f"{list(MEMORY_CHECKS)}")
    return run


def report_to_registry(results, registry=None):
    """Publish memory findings + per-target peak stats as the
    ``analysis/memory_*`` metric family.

    ``results``: {target name: (findings list, stats dict)}. Counters:
    ``analysis/memory_findings{check=}`` — ZERO-FILLED: every check id
    is emitted every run (an explicit 0, not an absent series), so the
    binary ``--compare`` gate distinguishes "clean" from "never ran".
    Gauges: ``analysis/memory_findings_total``,
    ``analysis/memory_peak_hbm_bytes{target=}``. Returns
    {check: count}."""
    from apex_tpu.observability import get_registry

    reg = registry if registry is not None else get_registry()
    counts = {c: 0 for c in MEMORY_CHECKS}
    for target, (findings, stats) in sorted(results.items()):
        for f in findings:
            if f.check in counts:
                counts[f.check] += 1
        if stats:
            reg.gauge("analysis/memory_peak_hbm_bytes",
                      target=target).set(stats.get("peak_hbm_bytes", 0))
    for check, n in counts.items():
        reg.counter("analysis/memory_findings", check=check).inc(n)
    reg.gauge("analysis/memory_findings_total").set(
        sum(counts.values()))
    return counts
