"""Persistent per-device tuning cache.

One JSON file (default ``~/.cache/apex_tpu/tuning_cache.json``,
``APEX_TPU_TUNING_CACHE`` overrides — also how a repo-committed export is
activated) holding every tuned tile and race verdict, keyed by
``(device_kind, kernel, shape-bucket)``:

.. code-block:: json

    {"schema_version": 1, "kind": "apex_tpu.tuning",
     "entries": {"TPU v5 lite": {"flat_adam": {"n~536870912": {
         "params": {"block_rows": 256, "cols": 512},
         "pallas_ms": 11.2, "xla_ms": 14.8, "use_pallas": true,
         "source": "measured", "dims": {"n": 356515840}}}}}}

The schema version is rejected LOUDLY on mismatch (a silently-ignored
cache would pin stale tiles forever); ``source`` records whether the
entry came from a real on-device race (``measured``) or the CPU roofline
fallback (``roofline`` — deterministic, CI-testable, never applied to a
TPU device_kind because the key is the device the tuner ran on).

Dispatch consults this module through :mod:`apex_tpu.tuning.geometry`
(tile lookup, hit/miss counters) and through :func:`apply_verdicts`
(race verdicts flipped into ``pallas_config._KERNEL_AUTO`` with the
cache file as the provenance evidence artifact — ``tuning:<path>``).
"""

from __future__ import annotations

import json
import os
import tempfile

SCHEMA_VERSION = 1
KIND = "apex_tpu.tuning"

# process-level memo: resolved path -> parsed cache (invalidate with
# clear_memo after writes or in tests that repoint the env override)
_MEMO: dict = {}


def cache_path() -> str:
    """Resolved cache file location (env override wins)."""
    env = os.environ.get("APEX_TPU_TUNING_CACHE")
    if env:
        return os.path.abspath(os.path.expanduser(env))
    return os.path.join(os.path.expanduser("~"), ".cache", "apex_tpu",
                        "tuning_cache.json")


def empty() -> dict:
    return {"schema_version": SCHEMA_VERSION, "kind": KIND, "entries": {}}


def _validate(data, path):
    if not isinstance(data, dict) or data.get("kind") != KIND:
        raise ValueError(
            f"tuning cache {path} is not an {KIND} file (missing kind "
            f"header) — refusing to guess at its layout")
    version = data.get("schema_version")
    if version != SCHEMA_VERSION:
        raise ValueError(
            f"tuning cache {path} has schema_version {version}; this "
            f"reader knows [{SCHEMA_VERSION}] — re-tune (tools/tune.sh) "
            f"or delete the stale cache")
    if not isinstance(data.get("entries"), dict):
        raise ValueError(f"tuning cache {path} has no entries object")
    return data


def load(path=None) -> dict:
    """Parse the cache at ``path`` (default :func:`cache_path`); an
    absent file is an empty cache, a malformed or version-mismatched one
    raises ValueError."""
    path = path or cache_path()
    if not os.path.exists(path):
        return empty()
    with open(path) as f:
        try:
            data = json.load(f)
        except ValueError as e:
            raise ValueError(f"tuning cache {path} is not JSON: {e}")
    return _validate(data, path)


def save(cache: dict, path=None) -> str:
    """Atomically write ``cache`` (validated first — a writer bug must
    not corrupt the dispatch-time artifact) and invalidate the memo."""
    path = path or cache_path()
    _validate(cache, "<in-memory cache>")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".tuning_cache.")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(cache, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    clear_memo()
    return path


def clear_memo() -> None:
    _MEMO.clear()


def _loaded(path=None) -> dict:
    path = path or cache_path()
    if path not in _MEMO:
        _MEMO[path] = load(path)
    return _MEMO[path]


def current_device_kind() -> str:
    """Cache key for the running backend: device_kind on TPU, the
    platform name elsewhere (CPU roofline entries key as 'cpu')."""
    import jax

    dev = jax.devices()[0]
    return dev.device_kind if dev.platform == "tpu" else dev.platform


def lookup(kernel: str, bucket: str, device_kind=None, path=None):
    """The tuned entry for ``(device_kind, kernel, bucket)`` or None.
    Ticks the ``tuning/cache_hit`` / ``tuning/cache_miss`` counter so
    every bench run records how much of its dispatch was tuned."""
    if device_kind is None:
        device_kind = current_device_kind()
    entry = (_loaded(path).get("entries", {})
             .get(device_kind, {}).get(kernel, {}).get(bucket))
    try:
        from apex_tpu.observability import get_registry

        get_registry().counter(
            "tuning/cache_hit" if entry is not None
            else "tuning/cache_miss", kernel=kernel).inc()
    except Exception:  # noqa: BLE001 — telemetry must never gate dispatch
        pass
    return entry


def put(cache: dict, device_kind: str, kernel: str, bucket: str,
        entry: dict) -> dict:
    """Insert/replace one entry in an in-memory cache dict."""
    cache.setdefault("entries", {}).setdefault(
        device_kind, {}).setdefault(kernel, {})[bucket] = entry
    return cache


def merge(dst: dict, src: dict) -> dict:
    """Fold every entry of ``src`` into ``dst`` (src wins per bucket).
    The tuner's write path merges into the on-disk cache rather than
    replacing it — a CPU roofline run must never destroy another
    device's measured entries (they are provenance evidence for
    _KERNEL_AUTO pins)."""
    for device_kind, kernels in src.get("entries", {}).items():
        for kernel, buckets in kernels.items():
            for bucket, entry in buckets.items():
                put(dst, device_kind, kernel, bucket, entry)
    return dst


def entries_for(device_kind=None, path=None) -> dict:
    """All tuned entries for one device kind (the bench JSON-line's
    'active tuning-cache entries' payload)."""
    if device_kind is None:
        device_kind = current_device_kind()
    return dict(_loaded(path).get("entries", {}).get(device_kind, {}))


# ------------------------------------------------- dispatch verdict flip

# search-space kernel -> pallas_config.KNOWN_KERNELS dispatch name.
# flash fwd/bwd share one dispatch gate: Pallas only when every tuned
# pass won its race (a fwd win that taxes the bwd is not a win).
_VERDICT_KERNEL = {
    "flat_adam": "flat_adam",
    "layer_norm": "layer_norm",
    "rms_norm": "rms_norm",
    "fused_softmax": "fused_softmax",
    "flash_attention_fwd": "flash_attention",
    "flash_attention_bwd": "flash_attention",
}


def verdicts_for(device_kind=None, path=None) -> dict:
    """dispatch-kernel -> bool race verdicts derived from the cache
    entries of ``device_kind`` (AND over buckets and over flash passes:
    a kernel must win everywhere it was measured to keep the default)."""
    out: dict = {}
    for kernel, buckets in entries_for(device_kind, path).items():
        name = _VERDICT_KERNEL.get(kernel)
        if name is None:
            continue
        for entry in buckets.values():
            won = entry.get("use_pallas")
            if not isinstance(won, bool):
                continue
            out[name] = out.get(name, True) and won
    return out


def apply_verdicts(path=None, device_kind=None) -> dict:
    """Flip ``pallas_config._KERNEL_AUTO`` from the cache's race
    verdicts, with ``tuning:<path>`` as the evidence artifact (the
    provenance check validates that the named cache exists and parses).
    Explicit ``env:`` pins (the deployment knob) are never overridden.
    Returns the verdicts actually applied."""
    from apex_tpu.ops import pallas_config

    path = path or cache_path()
    verdicts = verdicts_for(device_kind, path)
    current_ev = pallas_config.kernel_auto_evidence()
    applied = {
        k: v for k, v in verdicts.items()
        if not current_ev.get(k, "").startswith("env:")}
    if applied:
        pallas_config.set_kernel_auto(evidence=f"tuning:{path}",
                                      **applied)
    return applied
