"""Candidate measurement: on-device races on TPU, roofline model on CPU.

On a TPU backend each candidate runs through the REAL dispatch path
(``tuning.geometry.override`` pins the tile, ``pallas_config.force``
selects Pallas vs the XLA fallback) and is timed with the corrected-sync
scan-slope timer (:func:`apex_tpu.runtime.timing.time_scanned` — the
per-dispatch tunnel floor is ~0.7 ms, bigger than most of these
kernels, so host-loop timing would measure the tunnel, not the tile).

Off-TPU the roofline model from ``docs/kernel_cost_study.md`` is the
sanctioned fallback: ``t = max(flops/peak, bytes/bw) + grid_overhead``,
pure arithmetic, no RNG and no device — tuning stays deterministic and
testable in CI, and the ranking it produces is stable across runs by
construction. Roofline entries are recorded with ``source='roofline'``
and keyed to the CPU device kind, so they can never masquerade as
on-silicon evidence.
"""

from __future__ import annotations

from apex_tpu.tuning import geometry, search_space

# v5e roofline constants (docs/kernel_cost_study.md): peak bf16 compute
# and HBM bandwidth. Only RATIOS between candidates matter for ranking,
# so one generation's constants are fine as the portable CPU fallback.
PEAK_FLOPS = 197e12
HBM_BW = 819e9
# fixed cost per grid step (pipeline bubble + bookkeeping): what makes a
# 44k-step tiny-block sweep lose to a 700-step one on equal bytes, small
# enough that a well-blocked kernel's byte advantage still dominates
# (calibrated so the roofline reproduces every decision in the
# kernel-cost-study table: Pallas wins flash/norms/softmax, ties-then-
# loses flat_adam).
GRID_OVERHEAD_S = 2e-7

_ISZ = 2  # bf16 storage at the bench shapes; fp32 state modeled below


def backend_is_tpu() -> bool:
    import jax

    return jax.default_backend() == "tpu"


def _ceil_div(a, b):
    return -(-a // b)


# ------------------------------------------------------ roofline models


def _roofline_flat_adam(params, dims):
    n = dims["n"]
    br, cols = params["block_rows"], params["cols"]
    rows = _ceil_div(n, cols)
    padded = _ceil_div(rows, br) * br * cols
    steps = padded // (br * cols)
    bytes_ = padded * 4 * 7  # g/p/m/v in + delta/m/v out, fp32 state
    return bytes_ / HBM_BW + steps * GRID_OVERHEAD_S


def _roofline_flat_adam_xla(dims):
    # XLA's fused elementwise chain reads/writes exactly the unpadded
    # buffer — no fusion left to beat (cost-study flat_adam row).
    return dims["n"] * 4 * 7 / HBM_BW


def _flash_dims(dims):
    return (dims.get("bh", 64), dims["sq"], dims["sk"], dims["d"],
            dims.get("causal", True))


def _roofline_flash(kind, params, dims):
    bh, sq, sk, d, causal = _flash_dims(dims)
    bq, bk = params["block_q"], params["block_kv"]
    nq, nk = _ceil_div(sq, bq), _ceil_div(sk, bk)
    frac = 0.5 if causal else 1.0
    flops = 4 * bh * sq * sk * d * frac
    # q/o ride once; k+v re-stream once per q block (the tile knob)
    io = bh * _ISZ * (2 * sq * d + nq * 2 * sk * d)
    steps = bh * nq * nk
    if kind == "bwd":
        flops *= 2.5  # dq + dkv kernels: 5 matmuls vs the fwd's 2
        io += bh * _ISZ * (3 * sq * d + nk * 2 * sq * d + 4 * sk * d)
        steps *= 2
    return max(flops / PEAK_FLOPS, io / HBM_BW) \
        + steps * frac * GRID_OVERHEAD_S


def _roofline_flash_xla(kind, dims):
    bh, sq, sk, d, causal = _flash_dims(dims)
    frac = 0.5 if causal else 1.0
    flops = 4 * bh * sq * sk * d * frac * (2.5 if kind == "bwd" else 1.0)
    # the fallback materializes the [sq, sk] score tensor and streams it
    # through 4 (fwd) / 8 (bwd) reduction fusions (cost-study flash rows)
    passes = 8 if kind == "bwd" else 4
    io = bh * _ISZ * ((4 if kind == "bwd" else 3) * (sq + sk) * d
                      + passes * sq * sk * frac)
    return max(flops / PEAK_FLOPS, io / HBM_BW)


def _roofline_norm(params, dims):
    rows, h = dims["rows"], dims["h"]
    block = params["block_rows"]
    padded = _ceil_div(rows, block) * block
    bytes_ = padded * h * _ISZ * 2 + padded * 4 * 2  # x in, y out, stats
    return bytes_ / HBM_BW + (padded // block) * GRID_OVERHEAD_S


def _roofline_norm_xla(dims):
    # measured-fusion column: the proxy compiler runs LN fwd as ~3
    # h-sized passes (1.5x the single-pass kernel's traffic)
    return dims["rows"] * dims["h"] * _ISZ * 3 / HBM_BW


def _roofline_softmax(params, dims):
    sk = dims["sk"]
    rows = dims.get("rows", 1024)
    bk = params["block_k"]
    # two-pass blocked kernel: x streams twice, y written once; the row
    # block shrinks as bk grows (fused_softmax sizes it off the same
    # ~2 MiB VMEM row budget), which is the bk tradeoff being swept
    bq = max(search_space._SUBLANE, (2 << 20) // (4 * bk))
    bytes_ = rows * sk * _ISZ * 3
    steps = _ceil_div(rows, bq) * _ceil_div(sk, bk) * 2
    return bytes_ / HBM_BW + steps * GRID_OVERHEAD_S


def _roofline_softmax_xla(dims):
    rows = dims.get("rows", 1024)
    return rows * dims["sk"] * _ISZ * 4 / HBM_BW


def _roofline_fp8_cast(params, dims):
    n = dims["n"]
    br, cols = params["block_rows"], params["cols"]
    rows = _ceil_div(n, cols)
    padded = _ceil_div(rows, br) * br * cols
    bytes_ = padded * (4 + 1)  # fp32 in, fp8 out; scale/amax are noise
    return bytes_ / HBM_BW + (padded // (br * cols)) * GRID_OVERHEAD_S


def _roofline_fp8_cast_xla(dims):
    # XLA runs the quantize (scale+clip+cast) and the amax reduction as
    # two fusions over the unpadded buffer: the input streams twice
    # (cost-study reduction-fusion stance) — the one-read fusion is the
    # kernel's whole advantage
    return dims["n"] * (2 * 4 + 1) / HBM_BW


def roofline(kernel, params, dims) -> float:
    """Modeled seconds for the Pallas kernel at ``params``."""
    if kernel == "flat_adam":
        return _roofline_flat_adam(params, dims)
    if kernel == "flash_attention_fwd":
        return _roofline_flash("fwd", params, dims)
    if kernel == "flash_attention_bwd":
        return _roofline_flash("bwd", params, dims)
    if kernel in ("layer_norm", "rms_norm"):
        return _roofline_norm(params, dims)
    if kernel == "fused_softmax":
        return _roofline_softmax(params, dims)
    if kernel == "fp8_cast":
        return _roofline_fp8_cast(params, dims)
    raise ValueError(f"unknown kernel {kernel!r}")


def roofline_xla(kernel, dims) -> float:
    """Modeled seconds for the XLA fallback path."""
    if kernel == "flat_adam":
        return _roofline_flat_adam_xla(dims)
    if kernel == "flash_attention_fwd":
        return _roofline_flash_xla("fwd", dims)
    if kernel == "flash_attention_bwd":
        return _roofline_flash_xla("bwd", dims)
    if kernel in ("layer_norm", "rms_norm"):
        return _roofline_norm_xla(dims)
    if kernel == "fused_softmax":
        return _roofline_softmax_xla(dims)
    if kernel == "fp8_cast":
        return _roofline_fp8_cast_xla(dims)
    raise ValueError(f"unknown kernel {kernel!r}")


# ---------------------------------------------------- live measurement


def _live_runner(kernel, dims):
    """(make_fn, carry, chain, k) for time_scanned — the same on-device
    scan-slope construction bench_kernels uses, per kernel."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    if kernel == "flat_adam":
        n = dims["n"]
        g = jax.random.normal(key, (n,), jnp.float32) * 1e-3
        p = jax.random.normal(jax.random.fold_in(key, 1), (n,),
                              jnp.float32)
        m = jnp.zeros((n,), jnp.float32)
        v = jnp.zeros((n,), jnp.float32)

        def make_fn():
            from apex_tpu.optimizers import _math
            from apex_tpu.ops import pallas_config
            from apex_tpu.ops.fused_adam_kernel import adam_flat_pallas

            def step(g, p, m, v):
                if pallas_config.use_pallas("flat_adam"):
                    # adam_flat_pallas resolves the active override into
                    # the inner jit's STATIC key per call — each
                    # candidate races its own compiled tile, never the
                    # first trace's
                    d, mo, vo = adam_flat_pallas(
                        g, p, m, v, jnp.float32(1e-3), jnp.float32(2.0),
                        b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01,
                        adam_w_mode=True, bias_correction=True,
                        interpret=pallas_config.interpret())
                else:
                    d, mo, vo = _math.adam_step(
                        g, p, m, v, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                        weight_decay=0.01, adam_w_mode=True, step=2.0,
                        bias_correction=True)
                return g, p + d, mo, vo

            return step

        return make_fn, (g, p, m, v), (lambda c, step: step(*c)), 8

    if kernel in ("flash_attention_fwd", "flash_attention_bwd"):
        bh, sq, sk, d, causal = _flash_dims(dims)
        b, h = max(bh // 16, 1), min(bh, 16)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, sq, h, d), jnp.bfloat16)
        kk_ = jax.random.normal(kk, (b, sk, h, d), jnp.bfloat16)
        vv = jax.random.normal(kv, (b, sk, h, d), jnp.bfloat16)

        def make_fwd():
            from apex_tpu.ops.flash_attention import flash_attention

            return lambda q, k, v: flash_attention(q, k, v,
                                                   causal=causal)

        def make_bwd():
            from apex_tpu.ops.flash_attention import flash_attention

            return jax.grad(
                lambda q, k, v: jnp.sum(
                    flash_attention(q, k, v, causal=causal)
                    .astype(jnp.float32)), argnums=(0, 1, 2))

        if kernel.endswith("fwd"):
            chain = lambda c, step: (step(*c), c[1], c[2])  # noqa: E731
            return make_fwd, (q, kk_, vv), chain, 8
        return make_bwd, (q, kk_, vv), (lambda c, step: step(*c)), 8

    if kernel in ("layer_norm", "rms_norm"):
        rows, h = dims["rows"], dims["h"]
        x = jax.random.normal(key, (rows, h), jnp.bfloat16)
        w = jnp.ones((h,), jnp.float32)
        b = jnp.zeros((h,), jnp.float32)

        def make_fn():
            from apex_tpu.ops.layer_norm import layer_norm, rms_norm

            if kernel == "layer_norm":
                return lambda x: layer_norm(x, w, b, (h,))
            return lambda x: rms_norm(x, w, (h,))

        return make_fn, x, (lambda c, step: step(c)), 32

    if kernel == "fused_softmax":
        rows, sk = dims.get("rows", 256), dims["sk"]
        x = jax.random.normal(key, (8, rows, sk), jnp.bfloat16)

        def make_fn():
            from apex_tpu.transformer.functional.fused_softmax import (
                scaled_upper_triang_masked_softmax,
            )

            return lambda x: scaled_upper_triang_masked_softmax(
                x, None, 1.0)

        return make_fn, x, (lambda c, step: step(c)), 16

    if kernel == "fp8_cast":
        n = dims["n"]
        x = jax.random.normal(key, (n,), jnp.float32)

        def make_fn():
            from apex_tpu.ops import precision

            def step(x):
                # dequantize back to the fp32 carry so the scan threads
                # the kernel's output (idempotent after iteration 1 —
                # fine for timing, the bytes still stream); the
                # sign(amax+1) factor is 1 but keeps the fused amax
                # output live against DCE
                y, amax = precision.quantize_fp8_stats(
                    x, jnp.float32(1.0))
                return y.astype(jnp.float32) * jnp.sign(amax + 1.0)

            return step

        return make_fn, x, (lambda c, step: step(c)), 16

    raise ValueError(f"unknown kernel {kernel!r}")


def live_runner(kernel, dims):
    """Build the measurement inputs ONCE per (kernel, dims) and reuse
    across the whole sweep — the flat_adam carry alone is ~5.7 GB of
    freshly-drawn arrays, which must not be regenerated per candidate
    inside a scarce live-TPU window."""
    return _live_runner(kernel, dims)


def measure_live(kernel, params, dims, runner=None) -> float:
    """Seconds per iteration of the Pallas path at ``params`` on the
    current (TPU) backend."""
    from apex_tpu.ops import pallas_config
    from apex_tpu.runtime import timing

    make_fn, carry, chain, k = runner or _live_runner(kernel, dims)
    with geometry.override(kernel, params):
        with pallas_config.force("on"):
            return timing.time_scanned(make_fn, carry, chain, k=k)


def measure_live_xla(kernel, dims, runner=None) -> float:
    """Seconds per iteration of the XLA fallback on the current
    backend."""
    from apex_tpu.ops import pallas_config
    from apex_tpu.runtime import timing

    make_fn, carry, chain, k = runner or _live_runner(kernel, dims)
    with pallas_config.force("off"):
        return timing.time_scanned(make_fn, carry, chain, k=k)


def measure(kernel, params, dims, live=None, runner=None) -> float:
    """Pallas-candidate seconds: live race on TPU, roofline elsewhere."""
    if live is None:
        live = backend_is_tpu()
    if live:
        return measure_live(kernel, params, dims, runner=runner)
    return roofline(kernel, params, dims)


def measure_xla(kernel, dims, live=None, runner=None) -> float:
    """XLA-fallback seconds under the same live/roofline policy."""
    if live is None:
        live = backend_is_tpu()
    if live:
        return measure_live_xla(kernel, dims, runner=runner)
    return roofline_xla(kernel, dims)
