"""Per-kernel tiling search spaces + the untuned default geometries.

This module is the single place tile/block *numbers* are allowed to live
outside ``ops/pallas_config.py`` (the ``hardcoded-tile-size`` AST lint
enforces exactly that): every Pallas kernel's candidate tilings are
declared here, generated within the analyzer's VMEM-lint budget
(:func:`apex_tpu.ops.pallas_config.device_vmem_bytes`) so no candidate
the tuner sweeps can be a VMEM-overflow compile bomb, and every kernel's
*untuned* fallback geometry is a function here too — the same tables
serve dispatch defaults, the tuner sweep, and the interpret-mode parity
tests (which must cover every candidate the sweep can emit).

Shape buckets: tuning results are keyed by a coarse shape bucket, not the
exact shape — ceil-power-of-2 on the data-volume dims (a 300M and a 350M
flat buffer share a tile) and exact on the dims tiles directly depend on
(head_dim, hidden). :func:`shape_bucket` is the one implementation.
"""

from __future__ import annotations

import math

from apex_tpu.ops import pallas_config

# Every kernel the tuner knows. flash fwd/bwd are separate search
# problems (different VMEM residency, different best tiles — the shipped
# defaults were 512 vs 256); both map onto the single 'flash_attention'
# dispatch verdict in pallas_config.KNOWN_KERNELS. fp8_cast is the O4
# fused cast-and-scale pass (ops/fp8_cast_kernel.py).
KERNELS = ("flat_adam", "flash_attention_fwd", "flash_attention_bwd",
           "layer_norm", "rms_norm", "fused_softmax", "fp8_cast")

# TPU min-tile geometry (pallas_guide.md tiling table): lane dim is
# always 128; fp32 sublane multiple is 8. Candidates below never go
# under these.
_LANE = 128
_SUBLANE = 8

# Fraction of the per-core VMEM budget a kernel's resident blocks may
# use: double-buffered pipelining needs ~2x the block residency, plus
# headroom for Mosaic's own scratch — same planning stance as the
# pallas-block VMEM check in apex_tpu.analysis.
_VMEM_FRACTION = 0.5


def _vmem_budget(device_kind=None) -> int:
    return int(pallas_config.device_vmem_bytes(device_kind)
               * _VMEM_FRACTION)


def _ceil_pow2(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


def shape_bucket(kernel: str, **dims) -> str:
    """Deterministic cache-key bucket for ``kernel`` at ``dims``.

    flat_adam buckets by ceil-pow2 buffer size; flash by ceil-pow2
    (sq, sk) with exact d; norms and fused_softmax by ceil-pow2 rows
    with exact h / sk. A tuned tile is reused for every shape landing in
    the same bucket.
    """
    if kernel in ("flat_adam", "fp8_cast"):
        return f"n~{_ceil_pow2(dims['n'])}"
    if kernel in ("flash_attention_fwd", "flash_attention_bwd"):
        return (f"sq~{_ceil_pow2(dims['sq'])},"
                f"sk~{_ceil_pow2(dims['sk'])},d={dims['d']}")
    if kernel in ("layer_norm", "rms_norm"):
        return f"rows~{_ceil_pow2(dims['rows'])},h={dims['h']}"
    if kernel == "fused_softmax":
        return f"sk~{_ceil_pow2(dims['sk'])}"
    raise ValueError(f"unknown kernel {kernel!r}; valid: {list(KERNELS)}")


# --------------------------------------------------------- candidate sets


def _flat_adam_vmem(block_rows: int, cols: int) -> int:
    # 5 input blocks (scalars negligible) + 3 output blocks, fp32-sized
    # (p may be bf16 — bound with fp32), double-buffered by the caller's
    # _VMEM_FRACTION.
    return block_rows * cols * 4 * 8


def flat_adam_candidates(n: int, device_kind=None) -> list:
    """(block_rows, cols) sweep for the flat Adam slab at buffer size
    ``n``. The 1024-column width is itself swept (the fixed (rows, 1024)
    slab is the prime suspect for the measured 3.2x TPU inversion);
    multi-row grid steps (block_rows > 8) are in the sweep. Candidates
    whose whole slab would pad to more than ~2x the buffer are dropped —
    padding waste is HBM traffic the kernel pays and XLA does not."""
    budget = _vmem_budget(device_kind)
    out = []
    for cols in (128, 256, 512, 1024, 2048):
        rows = -(-n // cols)
        for block_rows in (8, 16, 32, 64, 128, 256, 512, 1024):
            if _flat_adam_vmem(block_rows, cols) > budget:
                continue
            padded = -(-rows // block_rows) * block_rows * cols
            if padded > max(2 * n, _SUBLANE * _LANE * 8):
                continue
            out.append({"block_rows": block_rows, "cols": cols})
    return out or [{"block_rows": _SUBLANE, "cols": _LANE}]


def _flash_fwd_vmem(bq: int, bk: int, d: int) -> int:
    # q + o tiles [bq, d], k + v tiles [bk, d], fp32 score block
    # [bq, bk], m/l/acc scratch ([bq, 1] x2 + [bq, d]) — all fp32.
    return 4 * (2 * bq * d + 2 * bk * d + bq * bk + 2 * bq + bq * d)


def _flash_bwd_vmem(bq: int, bk: int, d: int) -> int:
    # worst of the dq / dkv kernels: q/k/v/do tiles + p/dp/ds blocks +
    # two [bk, d] accumulators, fp32.
    return 4 * (4 * bq * d + 2 * bk * d + 3 * bq * bk + 2 * bk * d
                + 2 * bq)


def flash_candidates(kind: str, sq: int, sk: int, d: int,
                     device_kind=None) -> list:
    """(block_q, block_kv) sweep for the flash ``kind`` pass. The kernel
    clamps any tile to a divisor of the sequence (``_pick_block``), so a
    candidate can never produce a non-dividing block at runtime; the
    VMEM filter here keeps the sweep compile-safe."""
    if kind not in ("fwd", "bwd"):
        raise ValueError(f"flash kind must be fwd/bwd, got {kind!r}")
    vmem = _flash_fwd_vmem if kind == "fwd" else _flash_bwd_vmem
    budget = _vmem_budget(device_kind)
    out = []
    for bq in (128, 256, 512, 1024):
        for bk in (128, 256, 512, 1024):
            if bq > max(sq, _LANE) or bk > max(sk, _LANE):
                continue
            if vmem(bq, bk, d) > budget:
                continue
            out.append({"block_q": bq, "block_kv": bk})
    return out or [{"block_q": _LANE, "block_kv": _LANE}]


def _fp8_cast_vmem(block_rows: int, cols: int) -> int:
    # x block fp32 in + fp8 out + the fp32 compute copy live at once;
    # the (1, 1) scale/amax blocks are noise. 2x headroom rides the
    # caller's _VMEM_FRACTION like every other kernel here.
    return block_rows * cols * (4 + 1 + 4)


def fp8_cast_candidates(n: int, device_kind=None) -> list:
    """(block_rows, cols) sweep for the fused fp8 cast-and-scale slab
    over an ``n``-element buffer (ops/fp8_cast_kernel.py). Same slab
    rules as flat_adam — padding capped at ~2x the buffer — except the
    row floor is 32: the fp8 OUTPUT's min tile is (32, 128)
    (pallas_guide.md dtype table), so an 8-row block that fp32 would
    accept is a Mosaic reject for an f8 store."""
    budget = _vmem_budget(device_kind)
    out = []
    for cols in (128, 256, 512, 1024, 2048):
        rows = -(-n // cols)
        for block_rows in (32, 64, 128, 256, 512, 1024):
            if _fp8_cast_vmem(block_rows, cols) > budget:
                continue
            padded = -(-rows // block_rows) * block_rows * cols
            if padded > max(2 * n, 32 * _LANE * 8):
                continue
            out.append({"block_rows": block_rows, "cols": cols})
    return out or [{"block_rows": 32, "cols": _LANE}]


def norm_candidates(kernel: str, rows: int, h: int,
                    device_kind=None) -> list:
    """Row-block sweep for layer_norm / rms_norm. The backward holds ~5
    fp32 block x h temps live (measured; see ops/layer_norm.py) — bound
    candidates by that so one tuned block serves fwd and bwd."""
    del kernel
    budget = _vmem_budget(device_kind)
    out = []
    for block in (8, 16, 32, 64, 128, 256, 512):
        if block * h * 4 * 5 > budget:
            continue
        if block > max(rows, _SUBLANE):
            continue
        out.append({"block_rows": block})
    return out or [{"block_rows": _SUBLANE}]


def softmax_candidates(sk: int, device_kind=None) -> list:
    """k-block sweep for the two-pass blocked fused softmax (long rows).
    x streams through VMEM twice; the resident block is [1, rows, bk]
    fp32 with rows >= 8."""
    budget = _vmem_budget(device_kind)
    out = []
    for bk in (512, 1024, 2048, 4096):
        if bk > max(sk, _LANE) or bk * _SUBLANE * 4 * 3 > budget:
            continue
        out.append({"block_k": bk})
    return out or [{"block_k": 512}]


def candidates(kernel: str, device_kind=None, **dims) -> list:
    """The full candidate list for ``kernel`` at ``dims`` — the one
    enumeration the tuner sweeps and the parity tests replay."""
    if kernel == "flat_adam":
        return flat_adam_candidates(dims["n"], device_kind)
    if kernel == "flash_attention_fwd":
        return flash_candidates("fwd", dims["sq"], dims["sk"], dims["d"],
                                device_kind)
    if kernel == "flash_attention_bwd":
        return flash_candidates("bwd", dims["sq"], dims["sk"], dims["d"],
                                device_kind)
    if kernel in ("layer_norm", "rms_norm"):
        return norm_candidates(kernel, dims["rows"], dims["h"],
                               device_kind)
    if kernel == "fused_softmax":
        return softmax_candidates(dims["sk"], device_kind)
    if kernel == "fp8_cast":
        return fp8_cast_candidates(dims["n"], device_kind)
    raise ValueError(f"unknown kernel {kernel!r}; valid: {list(KERNELS)}")


# ------------------------------------------------------ untuned defaults


def default_flat_adam_geometry(n: int) -> tuple:
    """(block_rows, cols) when no tuned entry exists. Unlike the old
    module constants (a fixed (512, 1024) slab, 8-row pad for anything
    smaller — a scalar bias padded to 8x1024 fp32 x4 buffers), the pad
    block follows the actual leaf size: cols shrinks to the smallest
    lane multiple that keeps the slab near-square-ish, and block_rows
    caps padding waste at ~25% + one block."""
    n = max(int(n), 1)
    cols = _LANE
    while cols < 1024 and n >= cols * _SUBLANE * 2:
        cols *= 2
    rows = -(-n // cols)
    block_rows = _SUBLANE
    for cand in (1024, 512, 256, 128, 64, 32, 16, _SUBLANE):
        if cand > rows and cand > _SUBLANE:
            continue
        if _flat_adam_vmem(cand, cols) > _vmem_budget():
            continue
        padded = -(-rows // cand) * cand
        if padded - rows <= max(_SUBLANE, rows // 4):
            block_rows = cand
            break
    return block_rows, cols


def default_norm_row_block(rows: int, h: int, f32_temps: int) -> int:
    """Largest ladder block whose fp32 scratch fits the scoped budget —
    the pre-tuner heuristic from ops/layer_norm.py, now living in the
    search-space tables. 0 = even the smallest block busts VMEM (caller
    takes the jnp path)."""
    budget = _vmem_budget() * 3 // 2  # ~12 MiB of the 16 MiB figure
    cap = budget // (max(h, 1) * 4 * max(f32_temps, 1))
    if cap < _SUBLANE:
        return 0
    best = _SUBLANE
    for cand in (256, 128, 64, 32, 16, _SUBLANE):
        if cand > cap:
            continue
        if rows % cand == 0:
            return cand
        best = max(best, cand)
    return best


def default_softmax_block_k() -> int:
    """k-block for the long-row two-pass fused softmax (the old
    fused_softmax._BLOCKED_BK module constant, routed here)."""
    return 2048


def default_fp8_cast_geometry(n: int) -> tuple:
    """(block_rows, cols) for the fp8 cast-and-scale slab when no tuned
    entry exists: the flat_adam sizing ladder with the row floor raised
    to the fp8 (32, 128) min tile, padding waste bounded the same way."""
    n = max(int(n), 1)
    cols = _LANE
    while cols < 1024 and n >= cols * 32 * 2:
        cols *= 2
    rows = -(-n // cols)
    block_rows = 32
    for cand in (1024, 512, 256, 128, 64, 32):
        if cand > rows and cand > 32:
            continue
        if _fp8_cast_vmem(cand, cols) > _vmem_budget():
            continue
        padded = -(-rows // cand) * cand
        if padded - rows <= max(32, rows // 4):
            block_rows = cand
            break
    return block_rows, cols
