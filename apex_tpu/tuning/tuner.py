"""Tile-sweep tuner: race every candidate, persist winners + verdicts.

``tune_kernel`` sweeps one kernel's search space at one shape, races the
best Pallas candidate against the XLA fallback, and writes the result
into the persistent cache — tile AND dispatch verdict, so a tuned entry
is the evidence artifact that flips ``pallas_config._KERNEL_AUTO``.
``tune_all`` is the offline tune-everything entry point behind
``tools/tune.sh`` (and ``python -m apex_tpu.tuning``).

Telemetry: every race ticks ``tuning/race_won_pallas`` or
``tuning/race_won_xla`` (labeled by kernel) and sets
``tuning/best_pallas_ms`` / ``tuning/xla_ms`` gauges, so bench runs land
the tuning story in BENCH_METRICS.jsonl next to the perf numbers.
"""

from __future__ import annotations

import sys

from apex_tpu.tuning import cache, measure, search_space

# Default sweep shapes: the bench.py kernel-race shapes (the workloads
# whose dispatch the cache will actually serve). n is the GPT-2-345M
# flat-buffer size from bench.make_params.
DEFAULT_SHAPES = {
    "flat_adam": {"n": 356515840},
    "flash_attention_fwd": {"bh": 64, "sq": 2048, "sk": 2048, "d": 128,
                            "causal": True},
    "flash_attention_bwd": {"bh": 64, "sq": 2048, "sk": 2048, "d": 128,
                            "causal": True},
    "layer_norm": {"rows": 8192, "h": 4096},
    "rms_norm": {"rows": 8192, "h": 4096},
    "fused_softmax": {"rows": 256, "sk": 32768},
    # the llama lm_head activation at the bench shapes: (B*S, hidden) =
    # 8 * 2048 * 4096 — the biggest tensor the O4 tier quantizes per step
    "fp8_cast": {"n": 8 * 2048 * 4096},
}


def _registry(registry=None):
    if registry is not None:
        return registry
    from apex_tpu.observability import get_registry

    return get_registry()


def tune_kernel(kernel, dims=None, *, live=None, cache_dict=None,
                write=True, apply=True, registry=None, log=None):
    """Sweep ``kernel`` at ``dims``; returns the result record.

    ``live=None`` auto-detects (real race on TPU, roofline off-TPU).
    ``cache_dict`` accumulates results across calls (tune_all); with
    ``write`` the cache file is saved and — when ``apply`` — the race
    verdict is flipped into pallas_config with the cache file as its
    evidence artifact.
    """
    if kernel not in search_space.KERNELS:
        raise ValueError(f"unknown kernel {kernel!r}; valid: "
                         f"{list(search_space.KERNELS)}")
    dims = dict(DEFAULT_SHAPES[kernel] if dims is None else dims)
    if live is None:
        live = measure.backend_is_tpu()
    reg = _registry(registry)
    log = log or (lambda msg: print(msg, file=sys.stderr))

    # one set of measurement inputs for the whole sweep (the flat_adam
    # carry is ~5.7 GB — regenerating it per candidate would burn the
    # relay window on RNG, not races)
    runner = measure.live_runner(kernel, dims) if live else None
    ranked = []
    for params in search_space.candidates(kernel, **dims):
        try:
            t = measure.measure(kernel, params, dims, live=live,
                                runner=runner)
        except Exception as e:  # noqa: BLE001 — one Mosaic-rejected
            # candidate must not kill the sweep; it just can't win
            log(f"tune {kernel} {params}: FAILED {repr(e)[:120]}")
            reg.counter("tuning/candidate_error", kernel=kernel).inc()
            continue
        ranked.append((t, sorted(params.items())))
        log(f"tune {kernel} {params}: {t * 1e3:.3f} ms")
    if not ranked:
        raise RuntimeError(f"every {kernel} candidate failed to measure")
    ranked.sort()  # (time, params) — deterministic tie-break on params
    best_t, best_params = ranked[0][0], dict(ranked[0][1])
    xla_t = measure.measure_xla(kernel, dims, live=live, runner=runner)

    won = best_t <= xla_t
    reg.counter("tuning/race_won_pallas" if won else "tuning/race_won_xla",
                kernel=kernel).inc()
    bucket = search_space.shape_bucket(kernel, **{
        k: v for k, v in dims.items() if k not in ("bh", "causal")})
    reg.gauge("tuning/best_pallas_ms", kernel=kernel,
              bucket=bucket).set(round(best_t * 1e3, 4))
    reg.gauge("tuning/xla_ms", kernel=kernel,
              bucket=bucket).set(round(xla_t * 1e3, 4))
    entry = {
        "params": best_params,
        "pallas_ms": round(best_t * 1e3, 4),
        "xla_ms": round(xla_t * 1e3, 4),
        "use_pallas": bool(won),
        "source": "measured" if live else "roofline",
        "dims": dims,
    }
    device_kind = cache.current_device_kind()
    reg.event("tuning_result", kernel=kernel, bucket=bucket,
              device_kind=device_kind, **{
                  k: v for k, v in entry.items() if k != "dims"})
    log(f"tune {kernel}: best {best_params} "
        f"pallas {best_t * 1e3:.3f} ms vs xla {xla_t * 1e3:.3f} ms "
        f"-> {'pallas' if won else 'xla'} [{entry['source']}]")

    result = {"kernel": kernel, "bucket": bucket,
              "device_kind": device_kind, "entry": entry,
              "ranking": [(round(t * 1e3, 4), dict(p))
                          for t, p in ranked]}
    if cache_dict is not None:
        cache.put(cache_dict, device_kind, kernel, bucket, entry)
    if write:
        # always merge into the CURRENT on-disk cache: saving a bare
        # accumulator would destroy every entry another device (or an
        # earlier run) already measured
        target = cache.load()
        if cache_dict is not None:
            cache.merge(target, cache_dict)
        else:
            cache.put(target, device_kind, kernel, bucket, entry)
        path = cache.save(target)
        result["cache_path"] = path
        if apply:
            result["applied_verdicts"] = cache.apply_verdicts(path)
    return result


def tune_all(shapes=None, *, kernels=None, live=None, write=True,
             apply=True, registry=None, log=None):
    """Sweep every registered kernel — or just ``kernels`` — with
    ``shapes`` overriding per-kernel dims, and persist one merged cache
    write at the end. Returns the list of per-kernel results; a kernel
    whose whole sweep fails is recorded, not fatal — an offline tune
    run must report every kernel it could."""
    shapes = shapes or {}
    acc = cache.load()
    results = []
    for kernel in (kernels or search_space.KERNELS):
        try:
            results.append(tune_kernel(
                kernel, shapes.get(kernel), live=live, cache_dict=acc,
                write=False, registry=registry, log=log))
        except Exception as e:  # noqa: BLE001
            results.append({"kernel": kernel, "error": repr(e)[:200]})
    if write:
        path = cache.save(cache.merge(cache.load(), acc))
        for r in results:
            r["cache_path"] = path
        if apply:
            applied = cache.apply_verdicts(path)
            for r in results:
                r.setdefault("applied_verdicts", applied)
    return results
