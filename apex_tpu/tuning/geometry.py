"""Dispatch-time tile resolution: override > tuned cache > default.

Every Pallas kernel asks these helpers for its block geometry instead of
reading module constants (the ``hardcoded-tile-size`` lint enforces it).
Resolution order:

1. an active :func:`override` context — how the tuner's measurement
   harness pins one candidate at a time without touching the cache;
2. the persistent tuning cache (:mod:`apex_tpu.tuning.cache`), keyed by
   ``(device_kind, kernel, shape_bucket)``;
3. the untuned default from :mod:`apex_tpu.tuning.search_space`.

All of this runs at TRACE time (the helpers are called while building
the pallas_call, never inside a kernel body), so the file read behind
the cache happens once per process and the per-call cost is dict
lookups.
"""

from __future__ import annotations

import contextlib

from apex_tpu.tuning import cache, search_space

# kernel -> params dict pinned by the innermost active override()
_OVERRIDES: dict = {}


@contextlib.contextmanager
def override(kernel: str, params: dict):
    """Pin ``kernel``'s geometry to ``params`` within the context — the
    measurement harness races candidates through exactly the dispatch
    path production uses (so a candidate that only wins with a special
    code path can't win the sweep)."""
    if kernel not in search_space.KERNELS:
        raise ValueError(f"unknown kernel {kernel!r}; valid: "
                         f"{list(search_space.KERNELS)}")
    prev = _OVERRIDES.get(kernel)
    _OVERRIDES[kernel] = dict(params)
    try:
        yield
    finally:
        if prev is None:
            _OVERRIDES.pop(kernel, None)
        else:
            _OVERRIDES[kernel] = prev


def _resolve(kernel: str, **dims):
    """(params, source) for ``kernel`` at ``dims`` — params may be None
    when neither an override nor a tuned entry exists."""
    ov = _OVERRIDES.get(kernel)
    if ov is not None:
        return ov, "override"
    entry = cache.lookup(kernel, search_space.shape_bucket(kernel, **dims))
    if entry is not None and isinstance(entry.get("params"), dict):
        return entry["params"], "tuned"
    return None, "default"


def flat_adam_geometry(n: int) -> tuple:
    """(block_rows, cols) for the flat Adam slab over an ``n``-element
    buffer. Tuned cols are clamped down for buffers too small for them
    (a tile tuned at 350M elements must not pad a 100-element leaf to
    its slab — the pad block follows the actual leaf size)."""
    params, _ = _resolve("flat_adam", n=n)
    if params is None:
        return search_space.default_flat_adam_geometry(n)
    block_rows = int(params["block_rows"])
    cols = int(params["cols"])
    d_rows, d_cols = search_space.default_flat_adam_geometry(n)
    if block_rows * cols > max(2 * n, d_rows * d_cols):
        return d_rows, d_cols
    return block_rows, cols


def flash_tiles(kind: str, sq: int, sk: int, d: int):
    """Tuned (block_q, block_kv) for the flash ``kind`` pass, or None
    when no override/tuned entry exists (pallas_config then applies its
    per-shape heuristic). The kernel still clamps to sequence divisors."""
    params, source = _resolve(f"flash_attention_{kind}",
                              sq=sq, sk=sk, d=d)
    if params is None or source == "default":
        return None
    return int(params["block_q"]), int(params["block_kv"])


def norm_row_block(kernel: str, rows: int, h: int, f32_temps: int) -> int:
    """Row block for layer_norm / rms_norm at (rows, h); 0 = take the
    jnp fallback. A tuned block still respects the f32_temps VMEM bound
    (the backward holds more live temps than the forward the tuner may
    have raced)."""
    params, _ = _resolve(kernel, rows=rows, h=h)
    if params is None:
        return search_space.default_norm_row_block(rows, h, f32_temps)
    block = int(params["block_rows"])
    floor = search_space.default_norm_row_block(rows, h, f32_temps)
    if floor == 0:
        return 0
    while block > floor and block * h * 4 * f32_temps > \
            search_space._vmem_budget() * 3 // 2:
        block //= 2
    return max(block, search_space._SUBLANE)


def softmax_block_k(sk: int) -> int:
    """k-block for the two-pass blocked fused softmax."""
    params, _ = _resolve("fused_softmax", sk=sk)
    if params is None:
        return search_space.default_softmax_block_k()
    return int(params["block_k"])


def fp8_cast_geometry(n: int) -> tuple:
    """(block_rows, cols) for the fused fp8 cast-and-scale slab over an
    ``n``-element buffer — same clamp rule as flat_adam: a tile tuned
    on a big activation must not over-pad a small one."""
    params, _ = _resolve("fp8_cast", n=n)
    if params is None:
        return search_space.default_fp8_cast_geometry(n)
    block_rows = int(params["block_rows"])
    cols = int(params["cols"])
    d_rows, d_cols = search_space.default_fp8_cast_geometry(n)
    if block_rows * cols > max(2 * n, d_rows * d_cols):
        return d_rows, d_cols
    return block_rows, cols
