"""``python -m apex_tpu.tuning`` — one-shot offline tune-all.

    python -m apex_tpu.tuning                 # sweep every kernel,
                                              # write + print the cache
    python -m apex_tpu.tuning --kernel flat_adam
    python -m apex_tpu.tuning --export TUNING_CACHE.json  # repo-
                                              # committable copy too
    python -m apex_tpu.tuning --json          # machine-readable report

Runs on whatever backend the environment provides: real corrected-sync
races on TPU (the relay hunter runs this opportunistically on a live
window), the deterministic roofline fallback elsewhere. Exit 0 when
every requested kernel tuned, 1 when any sweep failed.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys

from apex_tpu.tuning import cache, search_space, tuner


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.tuning",
        description="apex_tpu Pallas kernel autotuner (offline tune-all)")
    ap.add_argument("--kernel", action="append", default=[],
                    choices=list(search_space.KERNELS),
                    help="tune only these kernels (repeatable; "
                         "default: all)")
    ap.add_argument("--export", default=None, metavar="PATH",
                    help="also copy the written cache to PATH (a "
                         "repo-committable evidence artifact)")
    ap.add_argument("--no-write", dest="write", action="store_false",
                    help="sweep and report without touching the cache")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    results = tuner.tune_all(kernels=args.kernel or None,
                             write=args.write)

    path = cache.cache_path()
    if args.export and args.write:
        shutil.copyfile(path, args.export)
        print(f"exported tuning cache to {args.export}", file=sys.stderr)

    failed = [r for r in results if "error" in r]
    if args.json:
        print(json.dumps({"cache_path": path if args.write else None,
                          "results": results}, indent=1))
    else:
        for r in results:
            if "error" in r:
                print(f"{r['kernel']}: ERROR {r['error']}")
            else:
                e = r["entry"]
                print(f"{r['kernel']:22s} {r['bucket']:28s} "
                      f"{json.dumps(e['params'])} "
                      f"pallas {e['pallas_ms']} ms / xla {e['xla_ms']} ms"
                      f" -> {'pallas' if e['use_pallas'] else 'xla'}"
                      f" [{e['source']}]")
        if args.write:
            print(f"cache: {path}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
