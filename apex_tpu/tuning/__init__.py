"""apex_tpu.tuning — Pallas kernel autotuner (ISSUE 6 / ROADMAP item 3).

Every Pallas kernel *earns* its tiling and its dispatch verdict per
device: search spaces are declared (VMEM-bounded) in
:mod:`~apex_tpu.tuning.search_space`, candidates are raced against the
XLA fallback by :mod:`~apex_tpu.tuning.measure` (real corrected-sync
races on TPU, the kernel-cost-study roofline model as the deterministic
CPU fallback), and winners persist in a schema-versioned JSON cache
(:mod:`~apex_tpu.tuning.cache`) keyed by ``(device_kind, kernel,
shape-bucket)``. Dispatch (``pallas_config.flash_blocks`` /
``use_pallas`` and the kernels' geometry lookups in
:mod:`~apex_tpu.tuning.geometry`) consults the cache, so a tuned entry
both picks the tile and flips the ``_KERNEL_AUTO`` verdict — with the
cache file as the provenance evidence artifact.

Offline tune-everything: ``python -m apex_tpu.tuning`` / tools/tune.sh.
"""

from apex_tpu.tuning.cache import (  # noqa: F401
    SCHEMA_VERSION,
    apply_verdicts,
    cache_path,
    entries_for,
)
from apex_tpu.tuning.cache import load as load_cache  # noqa: F401
from apex_tpu.tuning.cache import save as save_cache  # noqa: F401
from apex_tpu.tuning.geometry import (  # noqa: F401
    flash_tiles,
    flat_adam_geometry,
    fp8_cast_geometry,
    norm_row_block,
    override,
    softmax_block_k,
)
from apex_tpu.tuning.search_space import (  # noqa: F401
    KERNELS,
    candidates,
    shape_bucket,
)
from apex_tpu.tuning.tuner import (  # noqa: F401
    DEFAULT_SHAPES,
    tune_all,
    tune_kernel,
)
