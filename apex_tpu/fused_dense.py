"""Fused dense layers (TPU re-design of ``apex.fused_dense``;
ref apex/fused_dense/fused_dense.py, csrc/fused_dense_cuda.cu).

The CUDA path fuses gemm+bias (and gemm+bias+gelu+gemm+bias) via cublasLt
epilogues. XLA performs the same fusion on TPU from plain jnp expressions,
so these are thin, numerically-defined entry points with the reference's
API; ``fused_dense_gelu_dense_function`` uses a custom_vjp that saves
``gelu_in`` and ``output1`` exactly like the reference's backward
(ref fused_dense.py:34-46) instead of rematerializing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.ops.precision import (
    einsum_fp32acc,
    matmul_amp,
    matmul_fp32acc as _mm_acc,
)

_wgrad = functools.partial(einsum_fp32acc, "...i,...o->io")

# forward gemms route through the amp-aware hook (O4 fp8 upgrades the
# "fused_dense" sites); the hand-written custom_vjp backward below keeps
# the fp32-accum epilogue — cotangent math stays at full precision,
# matching the E5M2-only-where-AD-flows contract in docs/amp.md
_mm = functools.partial(matmul_amp, name="fused_dense")


def fused_dense_function(input, weight, bias):
    """gemm + bias; weight is (in, out) (ref FusedDenseFunc)."""
    return _mm(input, weight) + bias


def dense_no_bias_function(input, weight):
    return _mm(input, weight)


@jax.custom_vjp
def _fdgd_vjp(input, weight1, bias1, weight2, bias2):
    gelu_in = _mm(input, weight1) + bias1
    output1 = jax.nn.gelu(gelu_in, approximate=False)
    return _mm(output1, weight2) + bias2


def fused_dense_gelu_dense_function(input, weight1, bias1, weight2, bias2):
    """dense → gelu → dense (ref FusedDenseGeluDenseFunc).

    Under the O4 fp8 context the saved-activation ``custom_vjp`` steps
    aside (its hand-written backward cannot see the context's amax
    probes) and AD flows through ``matmul_fp8``'s vjp — the quantized
    residuals replace ``gelu_in``/``output1`` as the saved state."""
    from apex_tpu.amp.scaler import current_fp8

    if current_fp8() is not None:
        gelu_in = _mm(input, weight1) + bias1
        output1 = jax.nn.gelu(gelu_in, approximate=False)
        return _mm(output1, weight2) + bias2
    return _fdgd_vjp(input, weight1, bias1, weight2, bias2)


def _fdgd_fwd(input, weight1, bias1, weight2, bias2):
    gelu_in = _mm(input, weight1) + bias1
    output1 = jax.nn.gelu(gelu_in, approximate=False)
    output2 = _mm(output1, weight2) + bias2
    return output2, (input, weight1, weight2, gelu_in, output1)


def _fdgd_bwd(res, g):
    input, weight1, weight2, gelu_in, output1 = res
    # second gemm
    d_output1 = _mm_acc(g, weight2.T)
    d_weight2 = _wgrad(output1, g)
    d_bias2 = jnp.sum(g, axis=tuple(range(g.ndim - 1)))
    # gelu (exact erf form) backward
    _, gelu_vjp = jax.vjp(lambda t: jax.nn.gelu(t, approximate=False), gelu_in)
    d_gelu_in = gelu_vjp(d_output1)[0]
    # first gemm
    d_input = _mm_acc(d_gelu_in, weight1.T)
    d_weight1 = _wgrad(input, d_gelu_in)
    d_bias1 = jnp.sum(d_gelu_in, axis=tuple(range(d_gelu_in.ndim - 1)))
    return d_input, d_weight1, d_bias1, d_weight2, d_bias2


_fdgd_vjp.defvjp(_fdgd_fwd, _fdgd_bwd)

# O1 boundary casts: gemm(+gelu) chains are MXU work → compute dtype
from apex_tpu.amp.amp import half_function as _half_function  # noqa: E402

fused_dense_function = _half_function(fused_dense_function)
dense_no_bias_function = _half_function(dense_no_bias_function)
fused_dense_gelu_dense_function = _half_function(fused_dense_gelu_dense_function)


class FusedDense:
    """apex-shaped module (ref fused_dense.py:66 FusedDense). Weights are
    stored (in, out); ``.params`` is the optimizer-ready pytree."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 seed: int = 0, dtype=jnp.float32):
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        k = jax.random.PRNGKey(seed)
        kw, kb = jax.random.split(k)
        bound = 1.0 / in_features ** 0.5
        self.params = {"weight": jax.random.uniform(
            kw, (in_features, out_features), dtype, -bound, bound)}
        if bias:
            self.params["bias"] = jax.random.uniform(
                kb, (out_features,), dtype, -bound, bound)

    def __call__(self, x, params=None):
        p = params if params is not None else self.params
        if self.use_bias:
            return fused_dense_function(x, p["weight"], p["bias"])
        return dense_no_bias_function(x, p["weight"])


class FusedDenseGeluDense:
    """ref fused_dense.py:84 FusedDenseGeluDense."""

    def __init__(self, in_features: int, intermediate_features: int,
                 out_features: int, bias: bool = True, seed: int = 0,
                 dtype=jnp.float32):
        if not bias:
            raise ValueError(
                "FusedDenseGeluDense requires bias=True (ref fused_dense.py:88)")
        k = jax.random.PRNGKey(seed)
        k1, k2, k3, k4 = jax.random.split(k, 4)
        b1 = 1.0 / in_features ** 0.5
        b2 = 1.0 / intermediate_features ** 0.5
        self.params = {
            "weight1": jax.random.uniform(
                k1, (in_features, intermediate_features), dtype, -b1, b1),
            "bias1": jax.random.uniform(
                k2, (intermediate_features,), dtype, -b1, b1),
            "weight2": jax.random.uniform(
                k3, (intermediate_features, out_features), dtype, -b2, b2),
            "bias2": jax.random.uniform(
                k4, (out_features,), dtype, -b2, b2),
        }

    def __call__(self, x, params=None):
        p = params if params is not None else self.params
        return fused_dense_gelu_dense_function(
            x, p["weight1"], p["bias1"], p["weight2"], p["bias2"])
