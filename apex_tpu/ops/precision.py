"""fp32-accumulator contraction helpers.

One home for the "storage dtype unchanged, MXU accumulator pinned at
>= fp32" contract every half-precision contraction in the tree follows
(enforced by the ``apex_tpu.analysis`` ``lowprec-accum`` precision
check): the result dtype stays the operands' promotion (so callers'
dtype contracts are untouched), while ``preferred_element_type`` keeps
the partial sums in at least fp32 on the MXU. For fp32/fp64 operands
both helpers are exact no-ops relative to a plain call.

Used by ``mlp``, ``fused_dense``, ``transformer.tensor_parallel.layers``
and ``transformer.moe`` — fix accumulation policy here, not per-site.
"""

from __future__ import annotations

import jax.numpy as jnp


def _acc_dtype(out_dtype):
    if not jnp.issubdtype(out_dtype, jnp.floating):
        return out_dtype  # integer/bool contraction: leave untouched
    return jnp.promote_types(out_dtype, jnp.float32)


def matmul_fp32acc(a, b, *, keep_acc=False):
    """``jnp.matmul`` with the accumulator pinned at >= fp32; output
    dtype identical to ``jnp.matmul(a, b)``.

    ``keep_acc=True`` returns the accumulator-dtype result instead of
    downcasting — for callers that fuse more fp32 epilogue work (bias,
    activation) before settling to the storage dtype. They own the final
    downcast; leaving the epilogue in the narrow dtype would push its
    *backward* reductions (e.g. the bias-grad sum) into bf16, which the
    lowprec-accum check rightly flags.
    """
    out = jnp.result_type(a, b)
    y = jnp.matmul(a, b, preferred_element_type=_acc_dtype(out))
    return y if keep_acc else y.astype(out)


def einsum_fp32acc(subscripts, a, b):
    """``jnp.einsum`` (two operands) with the accumulator pinned at
    >= fp32; output dtype identical to ``jnp.einsum(subscripts, a, b)``."""
    out = jnp.result_type(a, b)
    return jnp.einsum(
        subscripts, a, b,
        preferred_element_type=_acc_dtype(out)).astype(out)
