"""fp32-accumulator and fp8 contraction helpers.

One home for the "storage dtype unchanged, MXU accumulator pinned at
>= fp32" contract every half-precision contraction in the tree follows
(enforced by the ``apex_tpu.analysis`` ``lowprec-accum`` precision
check): the result dtype stays the operands' promotion (so callers'
dtype contracts are untouched), while ``preferred_element_type`` keeps
the partial sums in at least fp32 on the MXU. For fp32/fp64 operands
both helpers are exact no-ops relative to a plain call.

The O4 tier (ISSUE 13) adds the fp8 epilogues next to them:
:func:`matmul_fp8` / :func:`einsum_fp8` run scale-in → saturating
E4M3 cast → dot with an fp32 ``preferred_element_type`` → scale-out,
with a ``custom_vjp`` that quantizes the backward cotangent to E5M2
under its own delayed scale ("FP8 Formats for Deep Learning",
Micikevicius et al. 2022). :func:`matmul_amp` is the routing hook the
library's contraction call sites use: identical to
:func:`matmul_fp32acc` until a step enters the amp fp8 context
(``apex_tpu.amp.scaler.Fp8DelayedScaler.step`` — the O4 opt level), at
which point registered sites upgrade to the fp8 path. Raw
``astype(float8_*)`` casts anywhere else in the tree are rejected by
the ``raw-fp8-cast`` AST lint — quantization happens HERE, behind the
scales, or not at all.

Used by ``mlp``, ``fused_dense``, ``transformer.tensor_parallel.layers``
and ``transformer.moe`` — fix accumulation policy here, not per-site.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

#: the two MXU fp8 formats (jax's float8 dtypes — bit-exact CPU
#: emulation off-TPU, which is what bench.py's fp8 race and every CI
#: test run on). E4M3: forward operands; E5M2: backward cotangents.
F8_E4M3 = jnp.float8_e4m3fn
F8_E5M2 = jnp.float8_e5m2

#: largest representable magnitudes (saturation bounds — E4M3 has no
#: inf encoding, so an unsaturated overflow would round to NaN). Kept
#: numerically identical to observability.numerics.history.F8_*_MAX,
#: which the delayed-scale computation uses.
F8_E4M3_MAX = 448.0
F8_E5M2_MAX = 57344.0

_F8_MAX = {jnp.dtype(F8_E4M3): F8_E4M3_MAX,
           jnp.dtype(F8_E5M2): F8_E5M2_MAX}


def _acc_dtype(out_dtype):
    if not jnp.issubdtype(out_dtype, jnp.floating):
        return out_dtype  # integer/bool contraction: leave untouched
    return jnp.promote_types(out_dtype, jnp.float32)


def matmul_fp32acc(a, b, *, keep_acc=False):
    """``jnp.matmul`` with the accumulator pinned at >= fp32; output
    dtype identical to ``jnp.matmul(a, b)``.

    ``keep_acc=True`` returns the accumulator-dtype result instead of
    downcasting — for callers that fuse more fp32 epilogue work (bias,
    activation) before settling to the storage dtype. They own the final
    downcast; leaving the epilogue in the narrow dtype would push its
    *backward* reductions (e.g. the bias-grad sum) into bf16, which the
    lowprec-accum check rightly flags.
    """
    out = jnp.result_type(a, b)
    y = jnp.matmul(a, b, preferred_element_type=_acc_dtype(out))
    return y if keep_acc else y.astype(out)


def einsum_fp32acc(subscripts, a, b):
    """``jnp.einsum`` (two operands) with the accumulator pinned at
    >= fp32; output dtype identical to ``jnp.einsum(subscripts, a, b)``."""
    out = jnp.result_type(a, b)
    return jnp.einsum(
        subscripts, a, b,
        preferred_element_type=_acc_dtype(out)).astype(out)


# ------------------------------------------------------------- fp8 (O4)


def fp8_amax(x):
    """``max(|x|)`` as an fp32 scalar — the delayed-scaling observation
    fed into the AmaxHistory rings."""
    return jnp.max(jnp.abs(x.astype(jnp.float32)))


def quantize_fp8(x, scale, dtype=F8_E4M3):
    """Scale-in + saturating cast: ``sat(x * scale) -> dtype``. The one
    sanctioned fp8 quantization in the tree (the ``raw-fp8-cast`` lint
    rejects bare ``astype(float8_*)`` elsewhere); routed through the
    fused Pallas cast-and-scale kernel when ``use_pallas('fp8_cast')``.
    """
    from apex_tpu.ops import fp8_cast_kernel

    fmax = _F8_MAX[jnp.dtype(dtype)]
    y, _ = fp8_cast_kernel.cast_and_scale_stats(x, scale, dtype, fmax)
    return y


def quantize_fp8_stats(x, scale, dtype=F8_E4M3):
    """``(quantize_fp8(x, scale, dtype), fp8_amax(x))`` in one fused
    pass (one read of ``x`` under the Pallas kernel)."""
    from apex_tpu.ops import fp8_cast_kernel

    fmax = _F8_MAX[jnp.dtype(dtype)]
    return fp8_cast_kernel.cast_and_scale_stats(x, scale, dtype, fmax)


# The grad-ring observation problem: the cotangent's amax is only
# available while the BACKWARD is being traced, and a value collected
# there may not escape the grad transform (UnexpectedTracerError).
# Solution: every fp8 matmul takes a zero-valued ``grad_probe`` scalar
# whose custom_vjp cotangent is DEFINED as ``fp8_amax(g)`` — the
# observation flows out of ``value_and_grad`` as the probe's gradient,
# a plain functional output. ``Fp8DelayedScaler``'s context threads the
# probes and harvests the gradients; standalone callers may pass
# ``grad_probe=None`` (observation discarded).


# _matmul_fp8 always returns (y, amax_a, amax_b): the fused
# cast-and-scale pass computes the operand amaxes anyway (one read),
# and the amp context needs them as its E4M3 ring observations —
# recomputing them outside would stream every operand from HBM twice.
# Callers that drop the amaxes (plain matmul_fp8) leave them dead at
# the trace level, so the jnp fallback path pays nothing.


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _matmul_fp8(out_dtype, a_dtype, b_dtype, a, b, sa, sb, gs, probe):
    ys, _ = _matmul_fp8_fwd(out_dtype, a_dtype, b_dtype, a, b, sa, sb,
                            gs, probe)
    return ys


def _matmul_fp8_fwd(out_dtype, a_dtype, b_dtype, a, b, sa, sb, gs,
                    probe):
    del a_dtype, b_dtype, probe
    a8, amax_a = quantize_fp8_stats(a, sa, F8_E4M3)
    b8, amax_b = quantize_fp8_stats(b, sb, F8_E4M3)
    acc = jnp.matmul(a8, b8, preferred_element_type=jnp.float32)
    y = (acc * (1.0 / (sa * sb))).astype(out_dtype)
    # the fp8 residency IS the memory win: the backward reuses the
    # quantized operands instead of re-saving bf16 activations
    return (y, amax_a, amax_b), (a8, b8, sa, sb, gs)


def _matmul_fp8_bwd(out_dtype, a_dtype, b_dtype, res, ct):
    del out_dtype
    a8, b8, sa, sb, gs = res
    g = ct[0]  # the amax outputs' cotangents are meaningless — drop
    g8 = quantize_fp8(g, gs, F8_E5M2)
    da = jnp.matmul(g8, b8.T, preferred_element_type=jnp.float32) \
        * (1.0 / (gs * sb))
    a2 = a8.reshape((-1, a8.shape[-1]))
    g2 = g8.reshape((-1, g8.shape[-1]))
    db = jnp.matmul(a2.T, g2, preferred_element_type=jnp.float32) \
        * (1.0 / (gs * sa))
    return (da.astype(a_dtype), db.astype(b_dtype),
            jnp.zeros_like(sa), jnp.zeros_like(sb), jnp.zeros_like(gs),
            fp8_amax(g))  # the probe cotangent IS the E5M2 observation


_matmul_fp8.defvjp(_matmul_fp8_fwd, _matmul_fp8_bwd)


def matmul_fp8(a, b, scale_a, scale_b, *, grad_scale=None,
               out_dtype=None, grad_probe=None):
    """fp8 matmul epilogue: scale-in → saturating E4M3 cast → dot with
    fp32 ``preferred_element_type`` → scale-out to ``out_dtype``
    (default: the operands' promotion, so callers' storage-dtype
    contracts are untouched).

    ``b`` must be a 2-D ``(k, n)`` weight (``a`` may carry leading
    batch dims). Scales are this tensor's *delayed* factors — computed
    from an amax-history ring BEFORE this step, which is what keeps the
    whole cast on device (``apex_tpu.amp.scaler.Fp8DelayedScaler``
    owns them; the ``fp8-stale-amax`` analysis check rejects scales
    with any other provenance). The backward quantizes the incoming
    cotangent to E5M2 under ``grad_scale`` and contracts it against
    the saved fp8 operands; scale cotangents are zero (scales are
    state, not parameters). ``grad_probe``: a zero fp32 scalar whose
    gradient is defined as the cotangent's pre-scale amax — the grad
    ring observation, harvested by ``Fp8DelayedScaler``'s
    ``ctx.value_and_grad`` (None: observation discarded).
    """
    y, _, _ = matmul_fp8_stats(a, b, scale_a, scale_b,
                               grad_scale=grad_scale,
                               out_dtype=out_dtype,
                               grad_probe=grad_probe)
    return y


def matmul_fp8_stats(a, b, scale_a, scale_b, *, grad_scale=None,
                     out_dtype=None, grad_probe=None):
    """:func:`matmul_fp8` that also returns the operands' pre-scale
    amaxes: ``(y, amax_a, amax_b)``. The amaxes come out of the SAME
    fused cast-and-scale pass that quantizes (one read per operand) —
    this is the form the amp fp8 context consumes for its E4M3 ring
    observations."""
    if b.ndim != 2:
        raise ValueError(
            f"matmul_fp8 expects a 2-D (k, n) weight for b, got shape "
            f"{b.shape} — reshape leading dims into a, or use einsum_fp8")
    out_dtype = jnp.dtype(out_dtype) if out_dtype is not None \
        else jnp.promote_types(a.dtype, b.dtype)
    gs = jnp.ones([], jnp.float32) if grad_scale is None \
        else jnp.asarray(grad_scale, jnp.float32)
    probe = jnp.zeros([], jnp.float32) if grad_probe is None \
        else grad_probe
    return _matmul_fp8(str(out_dtype), str(a.dtype), str(b.dtype), a, b,
                       jnp.asarray(scale_a, jnp.float32),
                       jnp.asarray(scale_b, jnp.float32), gs, probe)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _einsum_fp8(subscripts, out_dtype, a_dtype, b_dtype, a, b, sa, sb,
                gs, probe):
    y, _ = _einsum_fp8_fwd(subscripts, out_dtype, a_dtype, b_dtype,
                           a, b, sa, sb, gs, probe)
    return y


def _einsum_fp8_fwd(subscripts, out_dtype, a_dtype, b_dtype, a, b, sa,
                    sb, gs, probe):
    del a_dtype, b_dtype, probe
    a8 = quantize_fp8(a, sa, F8_E4M3)
    b8 = quantize_fp8(b, sb, F8_E4M3)
    acc = jnp.einsum(subscripts, a8, b8,
                     preferred_element_type=jnp.float32)
    y = (acc * (1.0 / (sa * sb))).astype(out_dtype)
    return y, (a8, b8, sa, sb, gs)


def _einsum_fp8_bwd(subscripts, out_dtype, a_dtype, b_dtype, res, g):
    del out_dtype
    a8, b8, sa, sb, gs = res
    g8 = quantize_fp8(g, gs, F8_E5M2)
    # transpose the einsum via vjp at the saved quantized operands; all
    # three ride upcast to fp32 (bit-identical values — f8 is a strict
    # fp32 subset) because jax refuses implicit f8/f32 promotion in the
    # transposed contraction
    _, vjp = jax.vjp(
        lambda x, y: jnp.einsum(subscripts, x, y,
                                preferred_element_type=jnp.float32),
        a8.astype(jnp.float32), b8.astype(jnp.float32))
    da, db = vjp(g8.astype(jnp.float32))
    inv = 1.0 / gs
    return ((da * (inv / sb)).astype(a_dtype),
            (db * (inv / sa)).astype(b_dtype),
            jnp.zeros_like(sa), jnp.zeros_like(sb), jnp.zeros_like(gs),
            fp8_amax(g))


_einsum_fp8.defvjp(_einsum_fp8_fwd, _einsum_fp8_bwd)


def einsum_fp8(subscripts, a, b, scale_a, scale_b, *, grad_scale=None,
               out_dtype=None, grad_probe=None):
    """Two-operand einsum variant of :func:`matmul_fp8` (same scale-in /
    E4M3 / fp32-accumulate / scale-out recipe; backward cotangent
    E5M2-quantized, transposed through the einsum's own vjp)."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype is not None \
        else jnp.promote_types(a.dtype, b.dtype)
    gs = jnp.ones([], jnp.float32) if grad_scale is None \
        else jnp.asarray(grad_scale, jnp.float32)
    probe = jnp.zeros([], jnp.float32) if grad_probe is None \
        else grad_probe
    return _einsum_fp8(subscripts, str(out_dtype), str(a.dtype),
                       str(b.dtype), a, b,
                       jnp.asarray(scale_a, jnp.float32),
                       jnp.asarray(scale_b, jnp.float32), gs, probe)


def matmul_amp(a, b, *, name="matmul", keep_acc=False):
    """The amp-aware contraction the library call sites route through
    (``mlp``, ``fused_dense``, TP layers, the llama lm_head).

    Identical to :func:`matmul_fp32acc` — same output dtype, fp32 MXU
    accumulator — until a step enters the O4 fp8 context
    (``Fp8DelayedScaler.step``): then sites the scaler was built with
    run :func:`matmul_fp8` under their delayed scales (and register
    this step's amax observations), while unregistered sites keep the
    fp32-accum path. ``name`` identifies the site (trace-order ordinals
    disambiguate reuse); ``keep_acc`` returns the fp32-accumulator
    dtype for callers fusing more epilogue work, exactly like
    :func:`matmul_fp32acc`.
    """
    from apex_tpu.amp.scaler import current_fp8

    ctx = current_fp8()
    if ctx is not None and b.ndim == 2 \
            and jnp.issubdtype(a.dtype, jnp.floating) \
            and jnp.issubdtype(b.dtype, jnp.floating):
        out = jnp.result_type(a, b)
        return ctx.matmul(a, b, name=name,
                          out_dtype=_acc_dtype(out) if keep_acc else out)
    return matmul_fp32acc(a, b, keep_acc=keep_acc)
