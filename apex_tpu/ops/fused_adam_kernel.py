"""Pallas TPU kernel for the flat-buffer fused Adam update.

The reference's ``csrc/multi_tensor_adam.cu`` is ONE kernel over chunked
tensor lists; the TPU flat path packs the whole model into a 1-D buffer
per dtype, and this kernel is the single fused elementwise pass over it
(SURVEY §1 kernel layer: "fused adam/lamb on flat buffers"). XLA's own
fusion of the jnp chain is the fallback and the baseline ``bench.py``
races this kernel against — elementwise chains are XLA's home turf, so
the kernel must EARN its default (``use_kernel=None`` defers to the
pallas gate; the bench reports both).

Layout: the 1-D buffer pads to a fp32-tileable ``(rows, cols)`` slab and
the grid walks ``block_rows``-row blocks; traced scalars (lr_t and the
bias-correction denominators — step-dependent) ride a (1, 4) block,
static hyperparams close over the kernel. The slab geometry is
TUNER-SUPPLIED (apex_tpu.tuning): callers either pass ``(block_rows,
cols)`` explicitly (the sweep does) or leave them None and get the
tuned/default pick for the actual buffer size — the fixed (rows, 1024)
slab with a constant 512-row block was the prime suspect for the
measured 3.2x TPU inversion (BENCH_r05_live.json), and the old
small-tensor path padded a scalar bias to 8x1024 fp32 x4 buffers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops import pallas_config


def _adam_kernel(b1, b2, eps, weight_decay, adam_w_mode, bias_correction,
                 sc_ref, g_ref, p_ref, m_ref, v_ref,
                 d_ref, mo_ref, vo_ref):
    lr_t = sc_ref[0, 0]
    c1 = sc_ref[0, 1]
    c2 = sc_ref[0, 2]
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    m = m_ref[...]
    v = v_ref[...]
    if not adam_w_mode and weight_decay:
        g = g + weight_decay * p
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * jnp.square(g)
    if bias_correction:
        m_hat = m / c1
        v_hat = v / c2
    else:
        m_hat, v_hat = m, v
    update = m_hat / (jnp.sqrt(v_hat) + eps)
    if adam_w_mode and weight_decay:
        update = update + weight_decay * p
    d_ref[...] = (-lr_t * update).astype(d_ref.dtype)
    mo_ref[...] = m
    vo_ref[...] = v


def _pad_to_slab(x, block_rows, cols):
    n = x.size
    rows = -(-n // cols)
    rows = -(-rows // block_rows) * block_rows
    pad = rows * cols - n
    if pad:
        x = jnp.pad(x.ravel(), (0, pad))
    return x.reshape(rows, cols), n


def slab_geometry(n: int, block_rows=None, cols=None) -> tuple:
    """Resolve the (block_rows, cols) slab for an ``n``-element buffer:
    explicit values win (the tuner's sweep passes candidates through
    here), otherwise the tuned/default pick from apex_tpu.tuning — which
    sizes the pad block from the ACTUAL buffer, so tiny leaves no longer
    over-pad."""
    if block_rows is not None and cols is not None:
        return int(block_rows), int(cols)
    from apex_tpu.tuning import flat_adam_geometry

    t_rows, t_cols = flat_adam_geometry(n)
    return (int(block_rows) if block_rows is not None else t_rows,
            int(cols) if cols is not None else t_cols)


def adam_flat_pallas(g, p, m, v, lr_t, step, *, b1, b2, eps, weight_decay,
                     adam_w_mode, bias_correction, block_rows=None,
                     cols=None, interpret=False):
    """One fused Adam pass over 1-D buffers.

    ``g``/``m``/``v`` fp32, ``p`` any float dtype; ``lr_t``/``step``
    traced scalars. Returns ``(delta, m', v')`` with delta in p's dtype.
    ``block_rows``/``cols`` pin the slab geometry; None defers to the
    tuning cache / per-size default. Resolution happens HERE, outside
    the jit, so the resolved geometry is part of the inner jit's static
    key — a fresh tune (or a sweep override) changes the key and forces
    a retrace instead of silently reusing the first-traced tile.
    """
    block_rows, cols = slab_geometry(g.size, block_rows, cols)
    return _adam_flat_pallas(g, p, m, v, lr_t, step, b1=b1, b2=b2,
                             eps=eps, weight_decay=weight_decay,
                             adam_w_mode=adam_w_mode,
                             bias_correction=bias_correction,
                             block_rows=block_rows, cols=cols,
                             interpret=interpret)


@functools.partial(jax.jit, static_argnames=(
    "b1", "b2", "eps", "weight_decay", "adam_w_mode", "bias_correction",
    "block_rows", "cols", "interpret"))
def _adam_flat_pallas(g, p, m, v, lr_t, step, *, b1, b2, eps,
                      weight_decay, adam_w_mode, bias_correction,
                      block_rows, cols, interpret=False):
    g2, n = _pad_to_slab(g.astype(jnp.float32), block_rows, cols)
    p2, _ = _pad_to_slab(p, block_rows, cols)
    m2, _ = _pad_to_slab(m, block_rows, cols)
    v2, _ = _pad_to_slab(v, block_rows, cols)
    rows = g2.shape[0]
    step = step.astype(jnp.float32)
    scalars = jnp.stack([
        jnp.asarray(lr_t, jnp.float32),
        1.0 - b1 ** step if bias_correction else jnp.float32(1.0),
        1.0 - b2 ** step if bias_correction else jnp.float32(1.0),
        jnp.float32(0.0),
    ]).reshape(1, 4)

    row_spec = pl.BlockSpec((block_rows, cols), lambda i: (i, 0))
    sc_spec = pl.BlockSpec((1, 4), lambda i: (0, 0))
    d2, mo2, vo2 = pl.pallas_call(
        functools.partial(_adam_kernel, b1, b2, eps, weight_decay,
                          adam_w_mode, bias_correction),
        grid=(rows // block_rows,),
        in_specs=[sc_spec, row_spec, row_spec, row_spec, row_spec],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[
            pallas_config.out_struct((rows, cols), p.dtype, g, p, m, v),
            pallas_config.out_struct((rows, cols), jnp.float32, g, p, m, v),
            pallas_config.out_struct((rows, cols), jnp.float32, g, p, m, v),
        ],
        interpret=interpret,
    )(scalars, g2, p2, m2, v2)
    return (d2.ravel()[:n], mo2.ravel()[:n], vo2.ravel()[:n])
