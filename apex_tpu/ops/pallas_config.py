"""Shared Pallas dispatch control for all apex_tpu kernels.

Every fused op in the tree (layer_norm, flash_attention, fused_softmax, ...)
asks :func:`use_pallas` whether to take its Pallas path and passes
:func:`interpret` to ``pl.pallas_call``. The default ('auto') compiles
Pallas on TPU and takes the jnp fallback elsewhere; tests use
``force('interpret')`` to execute the actual kernel bodies on the CPU mesh
through the Pallas interpreter, so kernel logic is exercised in CI rather
than only on real hardware (round-1 gap: VERDICT.md weak #2).
"""

from __future__ import annotations

import contextlib
import json
import os

import jax

_MODE = "auto"  # auto | off | on | interpret

# Flash-attention tile sizes, keyed by pass. ``None`` = per-shape auto
# pick (see :func:`flash_blocks`). Tunable because the best tile depends
# on head_dim / seq / VMEM of the device generation (VERDICT r2 weak:
# 512/256 were hardcoded at flash_attention.py:389,405).
_FLASH_BLOCKS = {"fwd": None, "bwd": None}
_FLASH_DEFAULTS = {"fwd": (512, 512), "bwd": (256, 256)}

# Per-kernel verdicts for 'auto' mode, set from the bench.py kernel race
# on real hardware (VERDICT r2 item 2 / r4 next-step 2: a kernel slower
# than its XLA fallback must lose its default). ``True``/``False`` pin
# the auto decision on TPU; ``None`` keeps the backend heuristic
# (Pallas iff TPU). ``force('on'/'off'/'interpret')`` still overrides,
# so tests and the bench race reach both paths regardless.
_KERNEL_AUTO = {
    # measured on TPU v5 lite (docs/kernel_cost_study.md): the XLA-fused
    # chain beats the Pallas flat-buffer kernel, keep the XLA default
    "flat_adam": False,
}

# Provenance: every pinned verdict above MUST name the evidence artifact
# that justified it (a repo path for source pins; env/runtime pins are
# tagged automatically by set_kernel_auto). The apex_tpu.analysis
# self-check and tests/run_analysis enforce this — an unevidenced pin is
# exactly how a stale race result outlives the hardware it was measured
# on.
_KERNEL_AUTO_EVIDENCE = {
    "flat_adam": "docs/kernel_cost_study.md",
}

# every kernel that consults use_pallas(<name>); a verdict for anything
# else is a typo that would silently never be consulted
KNOWN_KERNELS = frozenset(
    {"flash_attention", "layer_norm", "rms_norm", "fused_softmax",
     "flat_adam", "fp8_cast"})


def _env_json(name: str, shape_hint: str):
    """Parse an env var as a JSON object, or None when unset."""
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        table = json.loads(raw)
    except ValueError as e:
        raise ValueError(f"{name} is not valid JSON: {raw!r}") from e
    if not isinstance(table, dict):
        raise ValueError(f"{name} must be a JSON object of {shape_hint}")
    return table


def _load_env_overrides():
    """APEX_TPU_KERNEL_AUTO='{"layer_norm": false}' pins per-kernel auto
    verdicts at import time — the deployment knob for applying a
    bench_kernels race result without editing source."""
    table = _env_json("APEX_TPU_KERNEL_AUTO", "kernel name -> bool|null")
    if table is not None:
        set_kernel_auto(evidence="env:APEX_TPU_KERNEL_AUTO", **table)


def _load_flash_tile_overrides():
    """APEX_TPU_FLASH_TILES='{"fwd": [512, 512], "bwd": [256, 128]}'
    pins flash-attention tiles at import — the deployment knob for the
    bench autotuner's measured winners ("auto" or null restores the
    per-shape picker). null maps to "auto" (set_flash_blocks treats
    None as keep-current, which is not what a JSON null means here)."""
    table = _env_json(
        "APEX_TPU_FLASH_TILES",
        "'fwd'/'bwd' -> [block_q, block_k] | \"auto\" | null")
    if table is None:
        return
    set_flash_blocks(**{k: ("auto" if v is None else v)
                        for k, v in table.items()})


# Lazy one-time application of the persistent tuning cache's race
# verdicts (apex_tpu.tuning.cache.apply_verdicts): a tuned entry for the
# current device kind flips _KERNEL_AUTO with `tuning:<cache-path>` as
# its evidence artifact. Lazy because dispatch must not pay a file read
# per call, and one-time because the cache is a process-stable artifact
# (refresh_tuning() rearms after an in-process tune/write).
_TUNING_APPLIED = False


def _ensure_tuning_applied():
    global _TUNING_APPLIED
    if _TUNING_APPLIED:
        return
    from apex_tpu.tuning import cache as tuning_cache

    if os.path.exists(tuning_cache.cache_path()):
        # a malformed/mismatched cache raises here — loudly, by design:
        # silently ignoring it would pin stale tiles forever. The flag
        # flips only on SUCCESS, so a caller that swallowed one error
        # doesn't convert every later dispatch into a silent skip — the
        # bad cache keeps raising until fixed or removed.
        tuning_cache.apply_verdicts()
    _TUNING_APPLIED = True


def refresh_tuning() -> None:
    """Re-arm the lazy tuning-cache consultation (after tools/tune.sh
    wrote new entries in-process, or a test repointed
    APEX_TPU_TUNING_CACHE)."""
    global _TUNING_APPLIED
    from apex_tpu.tuning import cache as tuning_cache

    tuning_cache.clear_memo()
    _TUNING_APPLIED = False


def use_pallas(kernel: str | None = None) -> bool:
    """Should fused ops take their Pallas path right now?

    ``kernel`` (optional) names the caller ('layer_norm', 'rms_norm',
    'flash_attention', 'fused_softmax', 'flat_adam') so measured
    per-kernel verdicts from :data:`_KERNEL_AUTO` apply under 'auto' —
    including verdicts the persistent tuning cache supplies for the
    current device generation (see :func:`_ensure_tuning_applied`).
    """
    if _MODE == "off":
        return False
    if _MODE in ("on", "interpret"):
        return True
    if kernel is not None:
        _ensure_tuning_applied()
    on_tpu = jax.default_backend() == "tpu"
    verdict = _KERNEL_AUTO.get(kernel) if kernel is not None else None
    if verdict is not None:
        return verdict and on_tpu
    return on_tpu


def set_kernel_auto(*, evidence: "str | None" = None, **verdicts) -> None:
    """Pin per-kernel auto decisions (True/False) or restore the backend
    heuristic (None). Used to apply measured race results.

    Strict on both axes: a typo'd kernel name would be stored but never
    consulted, and a stringly value ("false" via yaml/k8s templating)
    would bool() to the OPPOSITE of the intent — both raise instead.

    ``evidence`` names the artifact that justifies the pin (repo path of
    a measurement doc, or a deployment tag like the env loader's
    ``env:APEX_TPU_KERNEL_AUTO``); unevidenced runtime pins are tagged
    ``runtime:set_kernel_auto`` so :func:`validate_kernel_auto_provenance`
    can tell them from an unevidenced SOURCE pin, which is an error."""
    unknown = set(verdicts) - KNOWN_KERNELS
    if unknown:
        raise ValueError(f"unknown kernel name(s) {sorted(unknown)}; "
                         f"valid: {sorted(KNOWN_KERNELS)}")
    for kernel, v in verdicts.items():
        if v is not None and not isinstance(v, bool):
            raise ValueError(
                f"verdict for {kernel!r} must be true/false/null, "
                f"got {v!r}")
        if v is None:
            _KERNEL_AUTO.pop(kernel, None)
            _KERNEL_AUTO_EVIDENCE.pop(kernel, None)
        else:
            _KERNEL_AUTO[kernel] = v
            _KERNEL_AUTO_EVIDENCE[kernel] = (
                evidence if evidence else "runtime:set_kernel_auto")


def kernel_auto() -> dict:
    return dict(_KERNEL_AUTO)


def kernel_auto_evidence() -> dict:
    """Pinned-verdict provenance: kernel name -> evidence artifact."""
    return dict(_KERNEL_AUTO_EVIDENCE)


def validate_kernel_auto_provenance(repo_root: "str | None" = None) -> list:
    """Problems with the pinned-verdict provenance, [] when clean.

    Every key of :data:`_KERNEL_AUTO` must have an evidence entry, and
    path-like evidence (no ``tag:`` prefix) must exist relative to
    ``repo_root`` (default: the checkout containing this file). A
    ``tuning:<path>`` prefix names a persistent tuning-cache file
    (apex_tpu.tuning) as the measurement record: the file must exist
    (absolute, ~-expanded, or repo-relative) AND parse with the schema
    this build knows — a vanished or version-drifted cache is exactly a
    stale race result outliving its hardware. Run by the
    ``kernel-auto-provenance`` check in ``apex_tpu.analysis`` and by
    tests/run_analysis, so a new pin cannot land without naming the
    measurement that justified it."""
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    problems = []
    for kernel in sorted(_KERNEL_AUTO):
        ev = _KERNEL_AUTO_EVIDENCE.get(kernel)
        if not ev:
            problems.append(
                f"pinned verdict for {kernel!r} has no evidence artifact")
        elif ev.split(":", 1)[0] in ("env", "runtime"):
            pass  # deployment tags, set by the loaders themselves
        elif ev.split(":", 1)[0] == "tuning":
            problems.extend(
                f"evidence for {kernel!r}: {p}"
                for p in _validate_tuning_evidence(ev.split(":", 1)[1],
                                                   repo_root))
        elif not os.path.exists(os.path.join(repo_root, ev)):
            problems.append(
                f"evidence for {kernel!r} names a missing artifact: {ev}")
    for kernel in sorted(set(_KERNEL_AUTO_EVIDENCE) - set(_KERNEL_AUTO)):
        problems.append(
            f"evidence entry for {kernel!r} has no pinned verdict")
    return problems


def _validate_tuning_evidence(path: str, repo_root: str) -> list:
    """Problems with a ``tuning:<path>`` evidence artifact ([] = valid):
    the named cache file must exist and load with the schema version
    this build's apex_tpu.tuning knows."""
    from apex_tpu.tuning import cache as tuning_cache

    resolved = os.path.expanduser(path)
    if not os.path.isabs(resolved):
        resolved = os.path.join(repo_root, resolved)
    if not os.path.exists(resolved):
        return [f"tuning cache is a missing artifact: {path}"]
    try:
        tuning_cache.load(resolved)
    except ValueError as e:
        return [f"tuning cache is not a valid evidence artifact: {e}"]
    return []


# Per-core VMEM by device generation, matched by substring against
# jax.devices()[0].device_kind (same scheme as bench._PEAK_FLOPS). The
# Pallas guide's planning figure is ~16 MiB/core across current
# generations; entries here override when a generation differs. Used by
# the pallas-block VMEM-budget check in apex_tpu.analysis and available
# to kernels for tile planning.
_VMEM_BYTES_DEFAULT = 16 << 20
_VMEM_BYTES = (
    ("v6", 32 << 20), ("trillium", 32 << 20),
)


def device_vmem_bytes(kind: "str | None" = None) -> int:
    """Per-core VMEM budget in bytes for ``kind`` (a device_kind string;
    default: the current backend's first device, or the conservative
    16 MiB planning figure off-TPU)."""
    if kind is None:
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return _VMEM_BYTES_DEFAULT
        kind = dev.device_kind
    kind = kind.lower()
    for key, nbytes in _VMEM_BYTES:
        if key in kind:
            return nbytes
    return _VMEM_BYTES_DEFAULT


# Per-device HBM by generation, same substring scheme as _VMEM_BYTES.
# Used by the hbm-budget sharding check in apex_tpu.analysis as the
# default live-set budget; APEX_TPU_HBM_BYTES overrides for odd
# topologies (e.g. a budget held back for XLA scratch).
_HBM_BYTES_DEFAULT = 16 << 30
# Per jax DEVICE, which on v2/v3 is one TensorCore (half the chip's
# HBM); v4+ expose one megacore device per chip.
_HBM_BYTES = (
    ("v5p", 95 << 30), ("v5 lite", 16 << 30), ("v5e", 16 << 30),
    ("v6", 32 << 30), ("trillium", 32 << 30), ("v4", 32 << 30),
    ("v3", 16 << 30), ("v2", 8 << 30),
)


def device_hbm_bytes(kind: "str | None" = None) -> int:
    """Per-device HBM budget in bytes for ``kind`` (a device_kind
    string; default: the current backend's first device, or the
    conservative 16 GiB planning figure off-TPU). The
    ``APEX_TPU_HBM_BYTES`` env var overrides everything — the knob the
    hbm-budget analysis check documents in docs/runtime.md.

    ISSUE 15 satellite: when no ``kind`` is asked for and the live
    device is a real TPU whose PJRT allocator reports a
    ``bytes_limit``, that measured limit wins over the static
    per-generation table — the hbm-budget check and the planner's
    pruning then use what the attached chip actually has (which the
    table can only approximate: a slice of HBM is held back for system
    use). Precedence: env override > live ``bytes_limit`` > static
    table. A malformed live value is a loud error, not a silent
    fallback — a bad limit would mis-prune every candidate layout."""
    env = os.environ.get("APEX_TPU_HBM_BYTES")
    if env:
        try:
            return int(env)
        except ValueError:
            raise ValueError(
                f"APEX_TPU_HBM_BYTES must be an integer byte count, "
                f"got {env!r}")
    if kind is None:
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return _HBM_BYTES_DEFAULT
        limit = _live_hbm_limit(dev)
        if limit is not None:
            return limit
        kind = dev.device_kind
    kind = kind.lower()
    for key, nbytes in _HBM_BYTES:
        if key in kind:
            return nbytes
    return _HBM_BYTES_DEFAULT


def _live_hbm_limit(dev) -> "int | None":
    """``dev.memory_stats()["bytes_limit"]`` as a validated int, or
    None when the backend doesn't report one (stats are an optional
    PJRT surface). Malformed values raise — see device_hbm_bytes."""
    try:
        stats = dev.memory_stats()
    except Exception:  # noqa: BLE001 — optional PJRT surface
        return None
    if not stats or "bytes_limit" not in stats:
        return None
    limit = stats["bytes_limit"]
    try:
        limit = int(limit)
    except (TypeError, ValueError):
        raise ValueError(
            f"device.memory_stats()['bytes_limit'] is not an integer "
            f"byte count: {limit!r} — refusing to guess an HBM budget "
            f"(set APEX_TPU_HBM_BYTES to override)")
    if limit <= 0:
        raise ValueError(
            f"device.memory_stats()['bytes_limit'] is non-positive "
            f"({limit}) — refusing to use it as the HBM budget "
            f"(set APEX_TPU_HBM_BYTES to override)")
    return limit


def out_struct(shape, dtype, *like):
    """``jax.ShapeDtypeStruct`` for a ``pallas_call`` out_shape that works
    inside ``shard_map``: with jax's check_vma on, pallas outputs must
    declare which mesh axes they vary over — the union of the inputs'
    vma (``like``) is the right answer for every elementwise/blockwise
    kernel here. Outside shard_map (or on older jax) this reduces to a
    plain ShapeDtypeStruct."""
    vma: frozenset = frozenset()
    for x in like:
        try:
            vma = vma | jax.typeof(x).vma
        except (AttributeError, TypeError):
            pass
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:  # jax without the vma kwarg
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def mode() -> str:
    return _MODE


def flash_blocks(kind: str, sq: int, sk: int, d: int) -> tuple:
    """(block_q, block_k) for the flash-attention ``kind`` pass at shape
    (sq, sk, d). Explicit override via :func:`set_flash_blocks` wins;
    then a tuned entry from the persistent tuning cache (the tuner's
    sweep-time pin rides the same consult); otherwise a per-shape pick
    that keeps the kernel's VMEM residency (q/k/v/acc tiles + the
    [bq, bk] fp32 score block) around ~4 MiB so double-buffered
    pipelining still fits a ~16 MiB VMEM."""
    override = _FLASH_BLOCKS.get(kind)
    if override is not None:
        return override
    from apex_tpu.tuning import geometry as tuning_geometry

    tuned = tuning_geometry.flash_tiles(kind, sq, sk, d)
    if tuned is not None:
        return tuned
    bq, bk = _FLASH_DEFAULTS[kind]
    # score block bq*bk*4B dominates at d=128; wide heads add bq*d + 2*bk*d
    # tile bytes, so shrink until the whole residency fits ~2 MiB
    while d >= 256 and (bq * bk + (bq + 2 * bk) * d) * 4 >= 2 ** 21 \
            and bq > 128:
        bq //= 2
        bk //= 2
    return min(bq, max(sq, 1)), min(bk, max(sk, 1))


def set_flash_blocks(fwd=None, bwd=None, **bad) -> None:
    """Override flash-attention tiles globally. ``None`` keeps the current
    setting; pass a (block_q, block_k) pair to pin, or 'auto' to restore
    per-shape auto picking. Strictly validated — a yaml/k8s templating
    slip like ``[true, 512]`` must error, not pin block_q=1."""
    if bad:
        raise ValueError(f"unknown flash tile kind(s) {sorted(bad)}; "
                         "valid: ['bwd', 'fwd']")
    for kind, val in (("fwd", fwd), ("bwd", bwd)):
        if val is None:
            continue
        if val == "auto":
            _FLASH_BLOCKS[kind] = None
            continue
        ok = (isinstance(val, (list, tuple)) and len(val) == 2
              and all(isinstance(v, int) and not isinstance(v, bool)
                      and v > 0 for v in val))
        if not ok:
            raise ValueError(
                f"flash tile {kind!r} must be a 2-int list/tuple of "
                f"positive sizes, 'auto', or None; got {val!r}")
        _FLASH_BLOCKS[kind] = (val[0], val[1])


@contextlib.contextmanager
def flash_block_override(fwd=None, bwd=None):
    """Temporarily pin flash tiles (used by the autotuner in bench.py)."""
    prev = dict(_FLASH_BLOCKS)
    try:
        set_flash_blocks(fwd=fwd, bwd=bwd)
        yield
    finally:
        _FLASH_BLOCKS.update(prev)




def interpret() -> bool:
    """Value to pass as ``pl.pallas_call(..., interpret=...)``."""
    return _MODE == "interpret"


@contextlib.contextmanager
def force(new_mode: str):
    """Force kernel dispatch within the context.

    'off' → jnp fallbacks; 'on' → compiled Pallas (TPU only);
    'interpret' → Pallas interpreter (runs kernel bodies on any backend);
    'auto' → Pallas iff the default backend is TPU.
    """
    global _MODE
    if new_mode not in ("auto", "off", "on", "interpret"):
        raise ValueError(f"unknown pallas mode {new_mode!r}")
    prev = _MODE
    _MODE = new_mode
    try:
        yield
    finally:
        _MODE = prev


_load_env_overrides()
_load_flash_tile_overrides()
