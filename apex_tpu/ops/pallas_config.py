"""Shared Pallas dispatch control for all apex_tpu kernels.

Every fused op in the tree (layer_norm, flash_attention, fused_softmax, ...)
asks :func:`use_pallas` whether to take its Pallas path and passes
:func:`interpret` to ``pl.pallas_call``. The default ('auto') compiles
Pallas on TPU and takes the jnp fallback elsewhere; tests use
``force('interpret')`` to execute the actual kernel bodies on the CPU mesh
through the Pallas interpreter, so kernel logic is exercised in CI rather
than only on real hardware (round-1 gap: VERDICT.md weak #2).
"""

from __future__ import annotations

import contextlib

import jax

_MODE = "auto"  # auto | off | on | interpret


def out_struct(shape, dtype, *like):
    """``jax.ShapeDtypeStruct`` for a ``pallas_call`` out_shape that works
    inside ``shard_map``: with jax's check_vma on, pallas outputs must
    declare which mesh axes they vary over — the union of the inputs'
    vma (``like``) is the right answer for every elementwise/blockwise
    kernel here. Outside shard_map (or on older jax) this reduces to a
    plain ShapeDtypeStruct."""
    vma: frozenset = frozenset()
    for x in like:
        try:
            vma = vma | jax.typeof(x).vma
        except (AttributeError, TypeError):
            pass
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:  # jax without the vma kwarg
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def mode() -> str:
    return _MODE


def use_pallas() -> bool:
    """Should fused ops take their Pallas path right now?"""
    if _MODE == "off":
        return False
    if _MODE in ("on", "interpret"):
        return True
    return jax.default_backend() == "tpu"


def interpret() -> bool:
    """Value to pass as ``pl.pallas_call(..., interpret=...)``."""
    return _MODE == "interpret"


@contextlib.contextmanager
def force(new_mode: str):
    """Force kernel dispatch within the context.

    'off' → jnp fallbacks; 'on' → compiled Pallas (TPU only);
    'interpret' → Pallas interpreter (runs kernel bodies on any backend);
    'auto' → Pallas iff the default backend is TPU.
    """
    global _MODE
    if new_mode not in ("auto", "off", "on", "interpret"):
        raise ValueError(f"unknown pallas mode {new_mode!r}")
    prev = _MODE
    _MODE = new_mode
    try:
        yield
    finally:
        _MODE = prev
