"""Pallas TPU kernels for fused LayerNorm / RMSNorm.

TPU re-design of the reference CUDA kernels
(ref csrc/layer_norm_cuda_kernel.cu via apex/normalization/fused_layer_norm.py).

Design: one single-pass kernel per row-block computes the statistics and the
normalized output in VMEM (fp32 math regardless of storage dtype — same
policy as the CUDA kernel's float accumulators). The backward is ALSO a
single-pass Pallas kernel (dx per row-block + dw/db accumulated across the
sequential grid into one (1, h) output — the TPU analog of the reference's
dedicated bwd kernels, csrc/layer_norm_cuda_kernel.cu cuComputeGradInput +
cuComputePartGradGammaBeta); saved activations are just (mu, rstd). A
closed-form jnp backward remains as the non-TPU fallback and as the
baseline bench.py races the kernel against.

On non-TPU backends (tests run on a CPU mesh) the forward falls back to an
equivalent jnp implementation — same math, same vjp.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import pallas_config


def _use_pallas(kernel: str = "layer_norm") -> bool:
    return pallas_config.use_pallas(kernel)


# ---------------------------------------------------------------- kernels


def _ln_fwd_kernel(eps, affine, x_ref, w_ref, b_ref, y_ref, mu_ref, rstd_ref):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(jnp.square(xc), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    if affine:
        y = xhat * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    else:
        y = xhat
    y_ref[:] = y.astype(y_ref.dtype)
    mu_ref[:] = mu
    rstd_ref[:] = rstd


def _rms_fwd_kernel(eps, affine, x_ref, w_ref, y_ref, rstd_ref):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = x * rstd
    if affine:
        y = xhat * w_ref[:].astype(jnp.float32)
    else:
        y = xhat
    y_ref[:] = y.astype(y_ref.dtype)
    rstd_ref[:] = rstd


# Row-block selection is TUNER-SUPPLIED (apex_tpu.tuning): a tuned cache
# entry for (device_kind, kernel, shape-bucket) wins, otherwise the
# search-space default ladder — the same VMEM-scoped heuristic that used
# to live here as module constants (Mosaic's stack limit is 16MB,
# validated on a v5e: the bwd kernel at block=256, h=4096 was rejected
# at 20.23M). `f32_temps` is the number of block×h fp32 intermediates
# the kernel holds live (measured ~5 for bwd, ~3 for fwd); the tuner
# clamps a tuned block back down when the bwd's temps would bust VMEM.


def _row_block(n_rows: int, h: int, f32_temps: int,
               kernel: str = "layer_norm") -> int:
    from apex_tpu.tuning import norm_row_block

    return norm_row_block(kernel, n_rows, h, f32_temps)


def _pad_rows(x2, block):
    n = x2.shape[0]
    pad = (-n) % block
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2, n


def _ln_fwd_pallas(x2, w, b, eps):
    affine = w is not None
    block = _row_block(x2.shape[0], x2.shape[1], 3)
    if not block:
        return _ln_fwd_jnp(x2, w, b, eps)
    x2p, n = _pad_rows(x2, block)
    rows, h = x2p.shape
    grid = (rows // block,)
    row_spec = pl.BlockSpec((block, h), lambda i: (i, 0), memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((block, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)
    in_specs = [row_spec] + ([vec_spec, vec_spec] if affine else [])
    args = (x2p,) + ((w.reshape(1, h), b.reshape(1, h)) if affine else ())
    kernel = functools.partial(_ln_fwd_kernel, eps, affine)
    if not affine:
        kernel = functools.partial(
            lambda eps_, x_ref, y_ref, mu_ref, rstd_ref: _ln_fwd_kernel(
                eps_, False, x_ref, None, None, y_ref, mu_ref, rstd_ref), eps)
    y, mu, rstd = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[
            pallas_config.out_struct((rows, h), x2.dtype, *args),
            pallas_config.out_struct((rows, 1), jnp.float32, *args),
            pallas_config.out_struct((rows, 1), jnp.float32, *args),
        ],
        interpret=pallas_config.interpret(),
    )(*args)
    return y[:n], mu[:n], rstd[:n]


def _rms_fwd_pallas(x2, w, eps):
    affine = w is not None
    block = _row_block(x2.shape[0], x2.shape[1], 3, kernel="rms_norm")
    if not block:
        return _rms_fwd_jnp(x2, w, eps)
    x2p, n = _pad_rows(x2, block)
    rows, h = x2p.shape
    grid = (rows // block,)
    row_spec = pl.BlockSpec((block, h), lambda i: (i, 0), memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((block, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)
    in_specs = [row_spec] + ([vec_spec] if affine else [])
    args = (x2p,) + ((w.reshape(1, h),) if affine else ())
    if affine:
        kernel = functools.partial(_rms_fwd_kernel, eps, True)
    else:
        kernel = functools.partial(
            lambda eps_, x_ref, y_ref, rstd_ref: _rms_fwd_kernel(
                eps_, False, x_ref, None, y_ref, rstd_ref), eps)
    y, rstd = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[row_spec, stat_spec],
        out_shape=[
            pallas_config.out_struct((rows, h), x2.dtype, *args),
            pallas_config.out_struct((rows, 1), jnp.float32, *args),
        ],
        interpret=pallas_config.interpret(),
    )(*args)
    return y[:n], rstd[:n]


# ------------------------------------------------------- backward kernels


def _ln_bwd_kernel(affine, x_ref, dy_ref, mu_ref, rstd_ref, *refs):
    """dx for one row block; dw/db accumulate across the (sequential) grid
    into a shared (1, h) block — no [grid, h] partials in HBM."""
    i = pl.program_id(0)
    if affine:
        w_ref, dx_ref, dw_ref, db_ref = refs
    else:
        dx_ref, = refs
    x = x_ref[:].astype(jnp.float32)
    g = dy_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = (x - mu_ref[:]) * rstd
    gw = g * w_ref[:].astype(jnp.float32) if affine else g
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (rstd * (gw - m1 - xhat * m2)).astype(dx_ref.dtype)
    if affine:
        @pl.when(i == 0)
        def _init():
            dw_ref[:] = jnp.zeros_like(dw_ref)
            db_ref[:] = jnp.zeros_like(db_ref)

        dw_ref[:] += jnp.sum(g * xhat, axis=0, keepdims=True)
        db_ref[:] += jnp.sum(g, axis=0, keepdims=True)


def _rms_bwd_kernel(affine, x_ref, dy_ref, rstd_ref, *refs):
    i = pl.program_id(0)
    if affine:
        w_ref, dx_ref, dw_ref = refs
    else:
        dx_ref, = refs
    x = x_ref[:].astype(jnp.float32)
    g = dy_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = x * rstd
    gw = g * w_ref[:].astype(jnp.float32) if affine else g
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (rstd * (gw - xhat * m2)).astype(dx_ref.dtype)
    if affine:
        @pl.when(i == 0)
        def _init():
            dw_ref[:] = jnp.zeros_like(dw_ref)

        dw_ref[:] += jnp.sum(g * xhat, axis=0, keepdims=True)


def _ln_bwd_jnp(x2, w, mu, rstd, dy):
    """Closed-form jnp backward (fallback + non-TPU path)."""
    x = x2.astype(jnp.float32)
    g = dy.astype(jnp.float32)
    xhat = (x - mu) * rstd
    gw = g * w.astype(jnp.float32).reshape(1, -1) if w is not None else g
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = (rstd * (gw - m1 - xhat * m2)).astype(x2.dtype)
    if w is None:
        return dx
    dw = jnp.sum(g * xhat, axis=0).astype(w.dtype)
    db = jnp.sum(g, axis=0).astype(w.dtype)
    return dx, dw, db


def _rms_bwd_jnp(x2, w, rstd, dy):
    x = x2.astype(jnp.float32)
    g = dy.astype(jnp.float32)
    xhat = x * rstd
    gw = g * w.astype(jnp.float32).reshape(1, -1) if w is not None else g
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = (rstd * (gw - xhat * m2)).astype(x2.dtype)
    if w is None:
        return dx
    dw = jnp.sum(g * xhat, axis=0).astype(w.dtype)
    return dx, dw


def _ln_bwd_pallas(x2, w, mu, rstd, dy):
    affine = w is not None
    block = _row_block(x2.shape[0], x2.shape[1], 5)
    if not block:
        return _ln_bwd_jnp(x2, w, mu, rstd, dy)
    x2p, n = _pad_rows(x2, block)
    dyp, _ = _pad_rows(dy, block)
    mup, _ = _pad_rows(mu, block)
    rstdp, _ = _pad_rows(rstd, block)
    rows, h = x2p.shape
    grid = (rows // block,)
    row_spec = pl.BlockSpec((block, h), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((block, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, h), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [row_spec, row_spec, stat_spec, stat_spec]
    args = (x2p, dyp, mup, rstdp)
    out_specs = [row_spec]
    out_shape = [pallas_config.out_struct((rows, h), x2.dtype, *args)]
    if affine:
        in_specs.append(vec_spec)
        args = args + (w.reshape(1, h),)
        out_specs += [vec_spec, vec_spec]
        out_shape += [
            pallas_config.out_struct((1, h), jnp.float32, *args),
            pallas_config.out_struct((1, h), jnp.float32, *args),
        ]
    outs = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, affine),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=pallas_config.interpret(),
    )(*args)
    if affine:
        dx, dw, db = outs
        return dx[:n], dw[0].astype(w.dtype), db[0].astype(w.dtype)
    return outs[0][:n]


def _rms_bwd_pallas(x2, w, rstd, dy):
    affine = w is not None
    block = _row_block(x2.shape[0], x2.shape[1], 5, kernel="rms_norm")
    if not block:
        return _rms_bwd_jnp(x2, w, rstd, dy)
    x2p, n = _pad_rows(x2, block)
    dyp, _ = _pad_rows(dy, block)
    rstdp, _ = _pad_rows(rstd, block)
    rows, h = x2p.shape
    grid = (rows // block,)
    row_spec = pl.BlockSpec((block, h), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((block, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, h), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [row_spec, row_spec, stat_spec]
    args = (x2p, dyp, rstdp)
    out_specs = [row_spec]
    out_shape = [pallas_config.out_struct((rows, h), x2.dtype, *args)]
    if affine:
        in_specs.append(vec_spec)
        args = args + (w.reshape(1, h),)
        out_specs.append(vec_spec)
        out_shape.append(
            pallas_config.out_struct((1, h), jnp.float32, *args))
    outs = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, affine),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=pallas_config.interpret(),
    )(*args)
    if affine:
        dx, dw = outs
        return dx[:n], dw[0].astype(w.dtype)
    return outs[0][:n]


# ------------------------------------------------------- fallbacks (jnp)


def _ln_fwd_jnp(x2, w, b, eps):
    x = x2.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(jnp.square(xc), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd
    if w is not None:
        y = y * w.astype(jnp.float32).reshape(1, -1) + b.astype(jnp.float32).reshape(1, -1)
    return y.astype(x2.dtype), mu, rstd


def _rms_fwd_jnp(x2, w, eps):
    x = x2.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y = x * rstd
    if w is not None:
        y = y * w.astype(jnp.float32).reshape(1, -1)
    return y.astype(x2.dtype), rstd


# ------------------------------------------------ custom_vjp entry points


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_affine(x2, w, b, eps):
    fwd = _ln_fwd_pallas if _use_pallas() else _ln_fwd_jnp
    return fwd(x2, w, b, eps)[0]


def _layer_norm_affine_fwd(x2, w, b, eps):
    fwd = _ln_fwd_pallas if _use_pallas() else _ln_fwd_jnp
    y, mu, rstd = fwd(x2, w, b, eps)
    return y, (x2, w, mu, rstd)


def _layer_norm_affine_bwd(eps, res, dy):
    x2, w, mu, rstd = res
    if _use_pallas():
        return _ln_bwd_pallas(x2, w, mu, rstd, dy)
    return _ln_bwd_jnp(x2, w, mu, rstd, dy)


_layer_norm_affine.defvjp(_layer_norm_affine_fwd, _layer_norm_affine_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _layer_norm_plain(x2, eps):
    fwd = _ln_fwd_pallas if _use_pallas() else _ln_fwd_jnp
    return fwd(x2, None, None, eps)[0]


def _layer_norm_plain_fwd(x2, eps):
    fwd = _ln_fwd_pallas if _use_pallas() else _ln_fwd_jnp
    y, mu, rstd = fwd(x2, None, None, eps)
    return y, (x2, mu, rstd)


def _layer_norm_plain_bwd(eps, res, dy):
    x2, mu, rstd = res
    if _use_pallas():
        return (_ln_bwd_pallas(x2, None, mu, rstd, dy),)
    return (_ln_bwd_jnp(x2, None, mu, rstd, dy),)


_layer_norm_plain.defvjp(_layer_norm_plain_fwd, _layer_norm_plain_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm_affine(x2, w, eps):
    fwd = _rms_fwd_pallas if _use_pallas("rms_norm") else _rms_fwd_jnp
    return fwd(x2, w, eps)[0]


def _rms_norm_affine_fwd(x2, w, eps):
    fwd = _rms_fwd_pallas if _use_pallas("rms_norm") else _rms_fwd_jnp
    y, rstd = fwd(x2, w, eps)
    return y, (x2, w, rstd)


def _rms_norm_affine_bwd(eps, res, dy):
    x2, w, rstd = res
    if _use_pallas("rms_norm"):
        return _rms_bwd_pallas(x2, w, rstd, dy)
    return _rms_bwd_jnp(x2, w, rstd, dy)


_rms_norm_affine.defvjp(_rms_norm_affine_fwd, _rms_norm_affine_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _rms_norm_plain(x2, eps):
    fwd = _rms_fwd_pallas if _use_pallas("rms_norm") else _rms_fwd_jnp
    return fwd(x2, None, eps)[0]


def _rms_norm_plain_fwd(x2, eps):
    fwd = _rms_fwd_pallas if _use_pallas("rms_norm") else _rms_fwd_jnp
    y, rstd = fwd(x2, None, eps)
    return y, (x2, rstd)


def _rms_norm_plain_bwd(eps, res, dy):
    x2, rstd = res
    if _use_pallas("rms_norm"):
        return (_rms_bwd_pallas(x2, None, rstd, dy),)
    return (_rms_bwd_jnp(x2, None, rstd, dy),)


_rms_norm_plain.defvjp(_rms_norm_plain_fwd, _rms_norm_plain_bwd)


# ------------------------------------------------------------- public API


def _to_2d(x, normalized_shape):
    import numpy as np
    h = int(np.prod(normalized_shape))
    lead = x.shape[: x.ndim - len(normalized_shape)]
    if tuple(x.shape[x.ndim - len(normalized_shape):]) != tuple(normalized_shape):
        raise ValueError(
            f"input trailing dims {x.shape} do not match normalized_shape "
            f"{normalized_shape}")
    return x.reshape(-1, h), lead


def layer_norm(x, weight: Optional[jax.Array], bias: Optional[jax.Array],
               normalized_shape, eps: float = 1e-5):
    """Fused LayerNorm over trailing ``normalized_shape`` dims."""
    normalized_shape = (normalized_shape,) if isinstance(normalized_shape, int) else tuple(normalized_shape)
    x2, lead = _to_2d(x, normalized_shape)
    if weight is not None:
        y = _layer_norm_affine(x2, weight.reshape(-1), bias.reshape(-1), eps)
    else:
        y = _layer_norm_plain(x2, eps)
    return y.reshape(*lead, *normalized_shape)


def rms_norm(x, weight: Optional[jax.Array], normalized_shape, eps: float = 1e-5):
    """Fused RMSNorm over trailing ``normalized_shape`` dims."""
    normalized_shape = (normalized_shape,) if isinstance(normalized_shape, int) else tuple(normalized_shape)
    x2, lead = _to_2d(x, normalized_shape)
    if weight is not None:
        y = _rms_norm_affine(x2, weight.reshape(-1), eps)
    else:
        y = _rms_norm_plain(x2, eps)
    return y.reshape(*lead, *normalized_shape)
