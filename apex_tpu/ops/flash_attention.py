"""Pallas TPU flash attention (the kernel behind ``apex_tpu.contrib.fmha``;
ref apex/contrib/fmha/fmha.py + csrc/fmha cutlass kernels).

Design (TPU-first, not a CUDA port):
- grid = (batch*heads, q_blocks, k_blocks), k innermost so the online
  softmax state (m, l, acc) lives in VMEM scratch across the k sweep.
- one q tile is [BLOCK_Q, d] in VMEM; each step streams one [BLOCK_K, d]
  k/v tile through the MXU (q @ k^T then p @ v), fp32 accumulation.
- causal masking is positional (iota compare) — no mask tensor ever
  materializes in HBM (the reference's kernels read a cu_seqlens array;
  fixed-shape batched input is the TPU-friendly layout).

Backward runs the standard recompute-based VJP expressed in jnp (XLA fuses
it well at these sizes); the Pallas forward is the memory win: no [sq, sk]
attention matrix is ever written to HBM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _fwd_kernel(causal, scale, block_q, block_k, sq, sk,
                q_ref, k_ref, v_ref, o_ref, m_sc, l_sc, acc_sc):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    run = True
    if causal:
        # whole block above the diagonal ⇒ nothing to do
        run = (ki * block_k) <= (qi * block_q + block_q - 1)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
        k = k_ref[0].astype(jnp.float32)                  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        if causal:
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        # mask key padding (sk not multiple of block_k)
        if sk % block_k:
            s = jnp.where(k_pos < sk, s, _NEG_INF)

        m_prev = m_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        # rows with nothing allowed yet: keep p exact zero
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_sc[:, 0] = l_sc[:, 0] * alpha + jnp.sum(p, axis=-1)
        acc_sc[:] = acc_sc[:] * alpha[:, None] + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_sc[:, 0] = m_new

    @pl.when(ki == nk - 1)
    def _finish():
        o_ref[0] = (acc_sc[:] /
                    jnp.maximum(l_sc[:, 0], 1e-30)[:, None]
                    ).astype(o_ref.dtype)


def _pick_block(s, target):
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k"))
def _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k):
    """q [bh, sq, d], k/v [bh_kv, sk, d] → o [bh, sq, d].

    GQA: when bh_kv < bh, ``rep = bh // bh_kv`` query heads read the SAME
    k/v block via the BlockSpec index map — no repeated copy in HBM.
    Layout requirement: q heads grouped kv-major (head g*rep+r shares kv
    head g), which :func:`flash_attention` arranges.
    """
    bh, sq, d = q.shape
    bh_kv, sk, _ = k.shape
    rep = bh // bh_kv
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    grid = (bh, pl.cdiv(sq, bq), pl.cdiv(sk, bk))

    kernel = functools.partial(_fwd_kernel, causal, scale, bq, bk, sq, sk)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b // rep, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b // rep, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
    )(q, k, v)


def _reference_attention(q, k, v, causal, scale):
    """jnp reference — also the VJP path (rematerialized). GQA-aware:
    q [bh, sq, d] with k/v [bh_kv, sk, d]; grouped einsum, no kv copy."""
    bh, sq, d = q.shape
    bh_kv, sk, _ = k.shape
    rep = bh // bh_kv
    qg = q.reshape(bh_kv, rep, sq, d).astype(jnp.float32)
    s = jnp.einsum("grqd,gkd->grqk", qg, k.astype(jnp.float32)) * scale
    if causal:
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(sk)[None, :]
        s = jnp.where(kpos <= qpos, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("grqk,gkd->grqd", p, v.astype(jnp.float32))
    return o.reshape(bh, sq, d).astype(q.dtype)


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    if _use_pallas():
        return _flash_fwd_pallas(q, k, v, causal, scale, 512, 512)
    return _reference_attention(q, k, v, causal, scale)


def _flash_fwd(q, k, v, causal, scale):
    return _flash(q, k, v, causal, scale), (q, k, v)


def _flash_bwd(causal, scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _reference_attention(q, k, v, causal, scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Fused attention on [b, s, h, d] (heads may differ for k/v — GQA).

    Returns [b, sq, h, d]; fp32 softmax internally, output in q's dtype.
    """
    b, sq, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {h_kv}")
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / d ** 0.5

    # heads-major flatten; q head g*rep+r shares kv head g (standard GQA
    # head order), matching the kernel's b//rep kv indexing
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
    o = _flash(qt, kt, vt, causal, float(scale))
    return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
