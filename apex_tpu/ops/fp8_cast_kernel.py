"""Pallas TPU kernel for the fused fp8 cast-and-scale pass (O4 tier).

Delayed-scaling fp8 ("FP8 Formats for Deep Learning", Micikevicius et
al. 2022) quantizes every matmul operand as ``sat_cast(x * scale)`` and
wants the NEXT step's amax observation of the same tensor — two
elementwise passes XLA runs separately. This kernel fuses them: one
stream over the buffer emits the saturating-cast fp8 values AND the
pre-scale ``max(|x|)`` (accumulated across the sequential grid into a
(1, 1) output, the same pattern as the layer_norm backward's dw/db
accumulation), so the quantize pays one read instead of two.

Layout mirrors the flat-Adam slab: the buffer pads to a fp32-tileable
``(rows, cols)`` slab and the grid walks ``block_rows``-row blocks. The
geometry is TUNER-SUPPLIED (``apex_tpu.tuning.fp8_cast_geometry`` —
candidates declared VMEM-bounded in ``tuning/search_space.py``); the
jnp fallback (same math, fused by XLA) runs on non-TPU backends and is
the baseline the autotuner races the kernel against.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops import pallas_config


def _cast_scale_kernel(fmax, x_ref, s_ref, y_ref, amax_ref):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)

    @pl.when(i == 0)
    def _init():
        amax_ref[...] = jnp.zeros_like(amax_ref)

    # pre-scale amax of the REAL values; padding rows are zeros and
    # amax is >= 0, so they never vote
    amax_ref[0, 0] = jnp.maximum(amax_ref[0, 0], jnp.max(jnp.abs(x)))
    y = jnp.clip(x * s_ref[0, 0], -fmax, fmax)  # saturate, never inf/nan
    y_ref[...] = y.astype(y_ref.dtype)


def _pad_to_slab(x, block_rows, cols):
    n = x.size
    rows = -(-n // cols)
    rows = -(-rows // block_rows) * block_rows
    pad = rows * cols - n
    flat = x.ravel()
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, cols), n


@functools.partial(jax.jit, static_argnames=(
    "dtype", "fmax", "block_rows", "cols", "interpret"))
def _cast_and_scale_pallas(x, scale, *, dtype, fmax, block_rows, cols,
                           interpret=False):
    x2, n = _pad_to_slab(x.astype(jnp.float32), block_rows, cols)
    rows = x2.shape[0]
    sc = jnp.reshape(jnp.asarray(scale, jnp.float32), (1, 1))
    row_spec = pl.BlockSpec((block_rows, cols), lambda i: (i, 0))
    sc_spec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    y2, amax = pl.pallas_call(
        functools.partial(_cast_scale_kernel, fmax),
        grid=(rows // block_rows,),
        in_specs=[row_spec, sc_spec],
        out_specs=[row_spec, sc_spec],
        out_shape=[
            pallas_config.out_struct((rows, cols), dtype, x, scale),
            pallas_config.out_struct((1, 1), jnp.float32, x, scale),
        ],
        interpret=interpret,
    )(x2, sc)
    return y2.ravel()[:n].reshape(x.shape), amax[0, 0]


def _cast_and_scale_jnp(x, scale, dtype, fmax):
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32))
    scale = jnp.asarray(scale, jnp.float32)
    y = jnp.clip(x32 * scale, -fmax, fmax).astype(dtype)
    return y, amax


def cast_and_scale_stats(x, scale, dtype, fmax):
    """``(sat_cast(x * scale) -> dtype, max(|x|))`` in one fused pass —
    Pallas on TPU (``use_pallas('fp8_cast')``), jnp elsewhere. ``fmax``
    is the target format's largest magnitude (saturation bound: an fp8
    overflow must clamp to the edge, not round to inf/NaN — E4M3 has no
    inf encoding at all)."""
    if x.ndim == 0 or x.size == 0 or \
            not pallas_config.use_pallas("fp8_cast"):
        return _cast_and_scale_jnp(x, scale, dtype, fmax)
    from apex_tpu.tuning import fp8_cast_geometry

    block_rows, cols = fp8_cast_geometry(x.size)
    return _cast_and_scale_pallas(
        x, scale, dtype=jnp.dtype(dtype), fmax=float(fmax),
        block_rows=block_rows, cols=cols,
        interpret=pallas_config.interpret())
