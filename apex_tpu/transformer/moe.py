"""Mixture-of-Experts with expert parallelism over an 'ep' mesh axis.

No reference-file analog (SURVEY.md §1 lists 'ep' among the comms-layer
mesh axes the TPU design must serve; the CUDA reference predates MoE).
The design is the GShard/Switch formulation, which is TPU-first by
construction — everything is static-shaped einsums the MXU eats directly:

- router: softmax over experts, top-1 (Switch) or top-2 (GShard) gating
  with the standard load-balancing auxiliary loss;
- dispatch/combine: one-hot [tokens, experts, capacity] masks — no
  sorting, no dynamic shapes; tokens beyond an expert's capacity are
  dropped (scaled by capacity_factor);
- expert parallelism: experts shard over 'ep'; inside ``shard_map`` a pair
  of ``all_to_all`` collectives swaps the token dimension for the expert
  dimension and back, so each rank runs only its local experts (the NCCL
  analog would be torch all_to_all; here XLA schedules it on ICI).

Layout summary (per ep rank, T = local tokens, E = global experts,
C = per-expert capacity):

    x [T, h] --dispatch--> [E, C, h] --all_to_all--> [E_local, n*C, h]
      --expert mlp--> [E_local, n*C, h] --all_to_all--> [E, C, h]
      --combine--> [T, h]
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

# dispatch/combine/expert einsums contract over the (large) token and
# capacity axes — bf16 partial sums there lose real gate mass, so the
# accumulator is pinned >= fp32 (apex_tpu.analysis lowprec-accum)
from apex_tpu.ops.precision import einsum_fp32acc as _ein_fp32acc
from apex_tpu.transformer.tensor_parallel.mappings import _axis_bound

EXPERT_AXIS = "ep"


class MoEConfig(NamedTuple):
    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    top_k: int = 2                 # 1 = Switch, 2 = GShard
    capacity_factor: float = 1.25
    router_jitter: float = 0.0     # optional exploration noise (training)
    aux_loss_coef: float = 1e-2
    # router z-loss (ST-MoE §4, arXiv:2202.08906): penalizes large router
    # logits, which destabilize bf16 training; 0 disables (default)
    z_loss_coef: float = 0.0


def init_moe_params(key, cfg: MoEConfig, dtype=jnp.float32):
    """router [h, E] + per-expert MLP weights stacked on dim 0.

    Shard for ep with ``P('ep', ...)`` on the expert-stacked weights;
    the router replicates.
    """
    kr, k1, k2 = jax.random.split(key, 3)
    h, f, e = cfg.hidden_size, cfg.ffn_hidden_size, cfg.num_experts
    lim1 = (6.0 / (h + f)) ** 0.5
    return {
        "router": (jax.random.normal(kr, (h, e)) * 0.02).astype(dtype),
        "wi": jax.random.uniform(k1, (e, h, f), dtype, -lim1, lim1),
        "wo": jax.random.uniform(k2, (e, f, h), dtype, -lim1, lim1),
    }


def moe_param_specs(cfg: MoEConfig, ep_axis: str = EXPERT_AXIS):
    from jax.sharding import PartitionSpec as P

    return {"router": P(), "wi": P(ep_axis, None, None),
            "wo": P(ep_axis, None, None)}


def _capacity(tokens: int, cfg: MoEConfig) -> int:
    cap = int(tokens * cfg.top_k * cfg.capacity_factor / cfg.num_experts)
    return max(cap, cfg.top_k)


def router_gates(logits, cfg: MoEConfig, with_stats: bool = False):
    """Top-k gating with position-in-expert assignment (GShard algo).

    logits [T, E] -> (combine [T, E, C], dispatch [T, E, C], aux_loss).
    All shapes static; tokens past an expert's capacity get zero gates
    (dropped — the residual stream carries them unchanged).

    ``aux_loss`` is the scalar TOTAL auxiliary loss (load-balance +
    optional z-loss) so callers can add it straight to the task loss.
    ``with_stats=True`` appends a telemetry dict
    ``{"dropped_frac", "balance_loss", "z_loss"}`` — dropped_frac is the
    fraction of the T·k routing assignments that fell past an expert's
    capacity (the production drop-rate signal a capacity_factor is tuned
    against).
    """
    t, e = logits.shape
    c = _capacity(t, cfg)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]

    combine = jnp.zeros((t, e, c), jnp.float32)
    remaining = probs
    # cumulative per-expert fill across the k choices
    fill = jnp.zeros((e,), jnp.int32)
    gates_sum = jnp.zeros((t,), jnp.float32)
    pieces = []
    for _ in range(cfg.top_k):
        idx = jnp.argmax(remaining, axis=-1)                     # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)       # [T, E]
        gate = jnp.sum(probs * onehot, axis=-1)                  # [T]
        # position of each token within its chosen expert's queue:
        # running count of earlier tokens (any k-th choice) + earlier
        # choices' fill
        pos = (jnp.cumsum(onehot, axis=0) - onehot) + fill[None, :]
        pos_t = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [T]
        keep = pos_t < c
        gate = gate * keep.astype(jnp.float32)
        pieces.append((onehot, gate, pos_t, keep))
        fill = fill + jnp.sum(onehot, axis=0).astype(jnp.int32)
        gates_sum = gates_sum + gate
        remaining = remaining * (1.0 - onehot)

    # top-k>1: normalize the kept gates to sum to 1 per token (GShard /
    # Mixtral combine). top-1 keeps the RAW probability (Switch eq. 2):
    # normalizing would make the gate a constant 1 and kill the router's
    # task-loss gradient — it would learn from the balance loss only.
    if cfg.top_k == 1:
        denom = jnp.ones_like(gates_sum)
    else:
        denom = jnp.maximum(gates_sum, 1e-9)
    for onehot, gate, pos_t, keep in pieces:
        slot = jax.nn.one_hot(pos_t, c, dtype=jnp.float32)       # [T, C]
        contrib = (gate / denom)[:, None, None] * onehot[:, :, None] \
            * slot[:, None, :]
        combine = combine + jnp.where(keep[:, None, None], contrib, 0.0)

    dispatch = combine > 0.0

    # load-balancing aux loss (Switch eq. 4): E * mean_frac . mean_prob
    first_onehot = pieces[0][0]
    frac = jnp.mean(first_onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    balance = cfg.aux_loss_coef * e * jnp.sum(frac * mean_prob)

    # router z-loss (ST-MoE eq. 5): mean (logsumexp of the fp32 logits)^2.
    # cfg.z_loss_coef is a static float: skip the logsumexp (+ backward)
    # entirely at the 0.0 default — 0*z is not DCE-safe for XLA
    if cfg.z_loss_coef:
        z_loss = cfg.z_loss_coef * jnp.mean(jax.scipy.special.logsumexp(
            logits.astype(jnp.float32), axis=-1) ** 2)
    else:
        z_loss = jnp.zeros((), jnp.float32)
    aux = balance + z_loss
    if not with_stats:
        return combine, dispatch, aux

    kept = sum(jnp.sum(keep.astype(jnp.float32))
               for _, _, _, keep in pieces)
    stats = {
        "dropped_frac": 1.0 - kept / (t * cfg.top_k),
        "balance_loss": balance,
        "z_loss": z_loss,
    }
    return combine, dispatch, aux, stats


def expert_parallel_apply(expert_fn, expert_params, x, router,
                          cfg: MoEConfig,
                          ep_axis: Optional[str] = EXPERT_AXIS,
                          router_key=None, with_stats: bool = False):
    """Route tokens through per-expert functions; returns (y, aux_loss).

    ``expert_fn(expert_params, tokens)`` maps [E_local, C', h] ->
    [E_local, C', h] with the LOCAL experts' stacked params (any
    structure — a dict of stacked weights works). Inside ``shard_map``
    with ``ep_axis`` bound the dispatch swaps the expert dim for the
    token dim with a pair of tiled all_to_all collectives so each rank
    runs only its experts; without the axis everything runs locally
    (identical math). This is the layer other modules build on — e.g.
    the Llama Mixtral-style SwiGLU experts — while :func:`moe_mlp` is
    the plain two-matmul MLP instance.

    ``with_stats=True`` returns ``(y, aux_loss, stats)`` (see
    :func:`router_gates`); inside ``shard_map`` the stats are per-rank —
    ``pmean`` them over the dp/ep axes for global telemetry.
    """
    lead = x.shape[:-1]
    h = x.shape[-1]
    xt = x.reshape(-1, h)

    logits = jnp.matmul(xt.astype(jnp.float32), router.astype(jnp.float32))
    if router_key is not None and cfg.router_jitter > 0.0:
        logits = logits * jax.random.uniform(
            router_key, logits.shape, jnp.float32,
            1.0 - cfg.router_jitter, 1.0 + cfg.router_jitter)
    gated = router_gates(logits, cfg, with_stats=with_stats)
    combine, dispatch, aux = gated[:3]

    expert_in = _ein_fp32acc("tec,th->ech", dispatch.astype(xt.dtype), xt)

    if _axis_bound(ep_axis):
        # [E, C, h] -> [E/n, n*C, h]: send expert-chunk j to rank j, gather
        # every rank's C-token slab for my local experts along capacity.
        # tiled=True is load-bearing: untiled all_to_all STACKS a new rank
        # axis instead of concatenating tiles, which silently broadcasts
        # against the local expert dim whenever E/n == 1
        expert_in = jax.lax.all_to_all(
            expert_in, ep_axis, split_axis=0, concat_axis=1, tiled=True)

    y = expert_fn(expert_params, expert_in)

    if _axis_bound(ep_axis):
        # inverse: [E/n, n*C, h] -> [E, C, h]; capacity slab j returns to
        # rank j, expert chunks re-concatenate in global expert order
        y = jax.lax.all_to_all(y, ep_axis, split_axis=1, concat_axis=0,
                               tiled=True)

    out = _ein_fp32acc("tec,ech->th", combine.astype(xt.dtype), y)
    out = out.reshape(*lead, h).astype(x.dtype)
    if with_stats:
        return out, aux.astype(jnp.float32), gated[3]
    return out, aux.astype(jnp.float32)


def moe_mlp(params, x, cfg: MoEConfig, ep_axis: Optional[str] = EXPERT_AXIS,
            activation=jax.nn.gelu, router_key=None,
            with_stats: bool = False):
    """MoE feed-forward on [..., h]; returns (y, aux_loss).

    Inside ``shard_map`` with ``ep_axis`` bound, experts run
    expert-parallel: params['wi']/'wo' hold only the LOCAL experts
    ([E/n, ...], sharded with :func:`moe_param_specs`) while the router
    and dispatch math see all E experts. Without a bound axis it runs all
    experts locally (single-device semantics, same math).
    """

    def expert_fn(p, tokens):
        y = _ein_fp32acc("ech,ehf->ecf", tokens,
                         p["wi"].astype(tokens.dtype))
        y = activation(y)
        return _ein_fp32acc("ecf,efh->ech", y,
                            p["wo"].astype(tokens.dtype))

    return expert_parallel_apply(
        expert_fn, {"wi": params["wi"], "wo": params["wo"]}, x,
        params["router"], cfg, ep_axis=ep_axis, router_key=router_key,
        with_stats=with_stats)
