"""apex_tpu.transformer (being built — see SURVEY.md §2)."""
