"""Gradient scaler with model-parallel inf check
(ref apex/transformer/amp/grad_scaler.py GradScaler).

The reference subclasses ``torch.cuda.amp.GradScaler`` and all-reduces
``found_inf`` (MAX) over the model-parallel group before deciding to step
or back off — a rank seeing a local overflow must make EVERY tp/pp rank
skip, or the replicas diverge. The TPU form subclasses the in-graph
:class:`apex_tpu.amp.LossScaler`: :meth:`unscale` ORs the overflow flag
across the model-parallel mesh axes with ``pmax`` inside the jitted step.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler


class GradScaler(LossScaler):
    """ref grad_scaler.py:21. ``model_parallel_axes`` are the mesh axes the
    overflow decision must agree across (tp and pp by default); axes not
    bound in the current shard_map are skipped, so the same scaler works
    under any mesh subset."""

    def __init__(self, init_scale=2.0 ** 16, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000, enabled=True,
                 model_parallel_axes: Sequence[str] = ("tp", "pp")):
        super().__init__(
            loss_scale="dynamic", init_scale=init_scale,
            scale_factor=growth_factor, scale_window=growth_interval,
            enabled=enabled)
        if backoff_factor != 1.0 / growth_factor:
            # LossScaler uses one symmetric factor (apex default semantics:
            # backoff = 1/growth); asymmetric factors are not represented
            self.backoff_factor = backoff_factor
        self.model_parallel_axes = tuple(model_parallel_axes)

    def unscale(self, grads, state):
        unscaled, overflow = super().unscale(grads, state)
        # sync the decision across model-parallel ranks (ref
        # _maybe_opt_step's MAX allreduce over get_model_parallel_group())
        flag = overflow.astype(jnp.int32)
        for axis in self.model_parallel_axes:
            try:
                flag = jax.lax.pmax(flag, axis)
            except NameError:
                continue  # axis not bound here
        return unscaled, flag > 0
