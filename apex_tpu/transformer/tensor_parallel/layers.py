"""Tensor-parallel layers (ref apex/transformer/tensor_parallel/layers.py).

Two complementary forms, both TPU-native:

1. **GSPMD flax modules** (primary): ``ColumnParallelLinear`` /
   ``RowParallelLinear`` / ``VocabParallelEmbedding`` hold *logical full-size*
   parameters annotated with ``flax.linen.with_partitioning`` over the tp
   mesh axis. The forward is plain math; under ``jit`` over a Mesh, XLA's
   SPMD partitioner shards the gemms and inserts the allreduce the
   reference's ``_ReduceFromModelParallelRegion`` does by hand — including
   overlapping the dgrad allreduce with wgrad compute, which is what the
   reference's ``async_grad_allreduce`` (ref layers.py:259-316) exists to
   do manually. Use :func:`param_partition_specs` to shard the params.

2. **Explicit per-shard functions** (for ``shard_map`` code and exact
   reference-shaped control): :func:`column_parallel_linear`,
   :func:`row_parallel_linear`, :func:`vocab_parallel_embedding`,
   :func:`linear_with_grad_accumulation_and_async_allreduce` take *local
   shards* and use the mappings-module collectives.

Weights follow the JAX ``(in, out)`` kernel convention rather than torch's
``(out, in)`` — this is a re-design, not a checkpoint-compatible port.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

import functools

from apex_tpu.ops.precision import matmul_amp, matmul_fp32acc as _mm_fp32acc

# forward gemms route through the amp-aware hook: identical fp32-accum
# behavior everywhere except under the O4 fp8 context, where registered
# "column_parallel"/"row_parallel"/"tp_linear" sites take the
# E4M3/E5M2 delayed-scaling epilogue (AD flows straight through these
# call sites, so the E5M2 grad recipe applies in full)
_mm_col = functools.partial(matmul_amp, name="column_parallel")
_mm_row = functools.partial(matmul_amp, name="row_parallel")
_mm_tp = functools.partial(matmul_amp, name="tp_linear")
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import mappings
from apex_tpu.transformer.tensor_parallel.mappings import _axis_bound
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility
from apex_tpu.transformer.utils import divide

Dtype = Any
TP = parallel_state.TENSOR_AXIS


def _default_init():
    # Megatron default is xavier-normal (ref layers.py:97 init_method).
    return nn.initializers.xavier_normal()


def param_partition_specs(variables):
    """PartitionSpecs for a variable tree built from these modules
    (wrapper over ``nn.get_partition_spec``)."""
    return nn.get_partition_spec(variables)


def _constrain(x, *spec):
    """Best-effort activation sharding hint; no-op without an ambient mesh."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or mesh.empty:
            return x
        names = set(mesh.axis_names)
        if any(s is not None and s not in names for s in spec):
            return x
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.PartitionSpec(*spec)
        )
    except Exception:
        return x


def set_tensor_model_parallel_attributes(tensor, is_parallel, dim, stride):
    """API-parity no-op: partitioning metadata lives on ``nn.Partitioned``
    boxes, not tensor attributes (ref layers.py:69)."""
    del tensor, is_parallel, dim, stride


def param_is_not_tensor_parallel_duplicate(param) -> bool:
    """True when the param is sharded over tp (ref layers.py:63). With
    ``nn.Partitioned`` metadata this is just: does any dim name == 'tp'."""
    names = getattr(param, "names", None)
    return bool(names) and TP in tuple(names)


def set_defaults_if_not_set_tensor_model_parallel_attributes(tensor):
    """API-parity no-op (ref layers.py:79): jax arrays carry partition
    metadata in ``nn.Partitioned`` boxes / PartitionSpecs, not as
    settable attributes, and the default (replicated) needs no marker."""
    del tensor


def copy_tensor_model_parallel_attributes(destination_tensor,
                                          source_tensor):
    """API-parity no-op (ref layers.py:88): partition metadata travels
    with the ``nn.Partitioned`` box itself when a tree is mapped, so
    there is nothing to copy onto a raw array."""
    del destination_tensor, source_tensor


class ColumnParallelLinear(nn.Module):
    """Y = X·A with A split column-wise over tp (ref layers.py:377).

    Returns ``(output, output_bias)`` like the reference: ``output_bias`` is
    the (unapplied) bias when ``skip_bias_add`` else ``None``.
    """

    output_size: int
    input_size: Optional[int] = None  # inferred from input when None
    use_bias: bool = True
    gather_output: bool = True
    init_method: Optional[Callable] = None
    stride: int = 1  # accepted for parity; XLA owns layout
    keep_master_weight_for_test: bool = False
    skip_bias_add: bool = False
    params_dtype: Dtype = jnp.float32
    compute_dtype: Optional[Dtype] = None
    sequence_parallel_enabled: bool = False

    @nn.compact
    def __call__(self, x) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        in_features = self.input_size or x.shape[-1]
        init = self.init_method or _default_init()
        kernel = self.param(
            "kernel",
            nn.with_partitioning(init, (None, TP)),
            (in_features, self.output_size),
            self.params_dtype,
        )
        bias = (
            self.param(
                "bias",
                nn.with_partitioning(nn.initializers.zeros_init(), (TP,)),
                (self.output_size,),
                self.params_dtype,
            )
            if self.use_bias
            else None
        )
        dtype = self.compute_dtype or x.dtype
        if self.sequence_parallel_enabled:
            # Input arrives sequence-sharded over tp; the gemm needs the
            # full sequence — constrain to replicated and let XLA gather.
            x = _constrain(x, *([None] * x.ndim))
        y = _mm_col(x.astype(dtype), kernel.astype(dtype))
        if bias is not None and not self.skip_bias_add:
            y = y + bias.astype(dtype)
        if self.gather_output:
            y = _constrain(y, *([None] * y.ndim))
        else:
            y = _constrain(y, *([None] * (y.ndim - 1)), TP)
        out_bias = bias.astype(dtype) if (self.skip_bias_add and bias is not None) else None
        return y, out_bias


class RowParallelLinear(nn.Module):
    """Y = X·A with A split row-wise over tp; output allreduced
    (ref layers.py:541)."""

    output_size: int
    input_size: Optional[int] = None
    use_bias: bool = True
    input_is_parallel: bool = False
    init_method: Optional[Callable] = None
    stride: int = 1
    keep_master_weight_for_test: bool = False
    skip_bias_add: bool = False
    params_dtype: Dtype = jnp.float32
    compute_dtype: Optional[Dtype] = None
    sequence_parallel_enabled: bool = False

    @nn.compact
    def __call__(self, x) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        in_features = self.input_size or x.shape[-1]
        init = self.init_method or _default_init()
        kernel = self.param(
            "kernel",
            nn.with_partitioning(init, (TP, None)),
            (in_features, self.output_size),
            self.params_dtype,
        )
        # Bias is added after the reduction; replicated (ref layers.py:596).
        bias = (
            self.param(
                "bias", nn.initializers.zeros_init(), (self.output_size,),
                self.params_dtype,
            )
            if self.use_bias
            else None
        )
        dtype = self.compute_dtype or x.dtype
        if not self.input_is_parallel:
            x = _constrain(x, *([None] * (x.ndim - 1)), TP)
        y = _mm_row(x.astype(dtype), kernel.astype(dtype))
        if self.sequence_parallel_enabled:
            # reduce_scatter over the sequence dim instead of full allreduce.
            y = _constrain(y, TP, *([None] * (y.ndim - 1)))
        else:
            y = _constrain(y, *([None] * y.ndim))
        out_bias = None
        if bias is not None:
            if self.skip_bias_add:
                out_bias = bias.astype(dtype)
            else:
                y = y + bias.astype(dtype)
        return y, out_bias


class VocabParallelEmbedding(nn.Module):
    """Embedding table split over the vocab dim (ref layers.py:154).

    Plain ``take`` forward: XLA's SPMD partitioner lowers a gather from a
    dim-0-sharded table to the reference's mask-local-lookup + allreduce
    pattern automatically.
    """

    num_embeddings: int
    embedding_dim: int
    init_method: Optional[Callable] = None
    params_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, ids) -> jnp.ndarray:
        init = self.init_method or nn.initializers.normal(stddev=1.0)
        table = self.param(
            "embedding",
            nn.with_partitioning(init, (TP, None)),
            (self.num_embeddings, self.embedding_dim),
            self.params_dtype,
        )
        y = jnp.take(jnp.asarray(table), ids, axis=0)
        return _constrain(y, *([None] * (ids.ndim + 1)))


# ------------------------------------------------------------------
# Explicit per-shard functional forms (shard_map path).
# ------------------------------------------------------------------


@jax.custom_vjp
def _matmul_fp32_wgrad(x, weight):
    """bf16 gemm with fp32 weight gradients — the TPU form of the
    reference's gradient-accumulation fusion (ref tensor_parallel/
    layers.py:264-298 + csrc/megatron/fused_weight_gradient_dense*).

    The CUDA kernel writes wgrad straight into an fp32 ``main_grad`` buffer
    attached to the half-precision weight. Functionally that is: keep the
    stored weight fp32 (the master), run the forward gemm in the
    activation's (bf16) dtype on the MXU, and compute the weight cotangent
    with fp32 MXU accumulation, returned AS fp32 — so microbatch
    grad-accumulation loops carry fp32 main grads with no cast or extra
    buffer per microbatch.
    """
    return _mm_fp32acc(x, weight.astype(x.dtype))


def _matmul_fp32_wgrad_fwd(x, weight):
    return _mm_fp32acc(x, weight.astype(x.dtype)), (x, weight)


def _matmul_fp32_wgrad_bwd(res, g):
    x, weight = res
    dx = _mm_fp32acc(g, weight.astype(g.dtype).swapaxes(-1, -2))
    # fp32 accumulation on the MXU; cotangent dtype = stored weight dtype
    dw = jnp.einsum("...i,...o->io", x, g,
                    preferred_element_type=jnp.float32)
    return dx.astype(x.dtype), dw.astype(weight.dtype)


_matmul_fp32_wgrad.defvjp(_matmul_fp32_wgrad_fwd, _matmul_fp32_wgrad_bwd)


def linear_with_grad_accumulation_and_async_allreduce(
    input,
    weight,
    bias=None,
    gradient_accumulation_fusion: bool = False,
    async_grad_allreduce: bool = True,
    sequence_parallel_enabled: bool = False,
    axis_name: Optional[str] = None,
    seq_dim: int = 0,
):
    """Local gemm whose input-grad allreduce overlaps wgrad (ref layers.py:308).

    Under XLA the overlap is automatic: the dgrad ``psum`` generated by
    transposing :func:`mappings.copy_to_tensor_model_parallel_region` is
    scheduled concurrently with the independent wgrad gemm
    (``async_grad_allreduce`` is therefore accepted as a no-op). ``weight``
    is the local ``(in, out_local)`` shard.

    ``gradient_accumulation_fusion`` engages :func:`_matmul_fp32_wgrad`:
    store the weight fp32, run the forward gemm in the activation dtype,
    and get fp32 weight grads with fp32 MXU accumulation — the reference's
    fp32 main-grad wgrad fusion.
    """
    del async_grad_allreduce
    axis = axis_name if axis_name is not None else TP
    if sequence_parallel_enabled:
        x = mappings.gather_from_sequence_parallel_region(input, axis,
                                                          seq_dim=seq_dim)
    else:
        x = mappings.copy_to_tensor_model_parallel_region(input, axis)
    if gradient_accumulation_fusion:
        y = _matmul_fp32_wgrad(x, weight)
    else:
        y = _mm_tp(x, weight)
    if bias is not None:
        y = y + bias
    return y


def column_parallel_linear(
    x,
    kernel,
    bias=None,
    gather_output: bool = True,
    sequence_parallel_enabled: bool = False,
    axis_name: Optional[str] = None,
    seq_dim: int = 0,
):
    """Per-shard column-parallel linear: kernel is ``(in, out/tp)``."""
    axis = axis_name if axis_name is not None else TP
    y = linear_with_grad_accumulation_and_async_allreduce(
        x, kernel, bias, sequence_parallel_enabled=sequence_parallel_enabled,
        axis_name=axis, seq_dim=seq_dim,
    )
    if gather_output:
        y = mappings.gather_from_tensor_model_parallel_region(y, axis)
    return y


def row_parallel_linear(
    x,
    kernel,
    bias=None,
    input_is_parallel: bool = True,
    sequence_parallel_enabled: bool = False,
    axis_name: Optional[str] = None,
    seq_dim: int = 0,
):
    """Per-shard row-parallel linear: kernel is ``(in/tp, out)``; the partial
    products are psum'd (or reduce-scattered in sequence-parallel mode)."""
    axis = axis_name if axis_name is not None else TP
    if not input_is_parallel:
        x = mappings.scatter_to_tensor_model_parallel_region(x, axis)
    y = _mm_tp(x, kernel)
    if sequence_parallel_enabled:
        y = mappings.reduce_scatter_to_sequence_parallel_region(y, axis,
                                                                seq_dim=seq_dim)
    else:
        y = mappings.reduce_from_tensor_model_parallel_region(y, axis)
    if bias is not None:
        y = y + bias
    return y


def vocab_parallel_embedding(ids, table, axis_name: Optional[str] = None):
    """Per-shard vocab-parallel lookup: ``table`` is ``(vocab/tp, hidden)``.

    Reference algorithm (layers.py:154-257): mask ids outside this rank's
    range, lookup locally, zero masked rows, psum.
    """
    axis = axis_name if axis_name is not None else TP
    if not _axis_bound(axis):
        return jnp.take(table, ids, axis=0)
    n = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    start, _ = VocabUtility.vocab_range_from_per_partition_vocab_size(
        table.shape[0], rank, n
    )
    local = ids - start
    in_range = (local >= 0) & (local < table.shape[0])
    safe = jnp.where(in_range, local, 0)
    out = jnp.take(table, safe, axis=0)
    out = jnp.where(in_range[..., None], out, 0.0)
    return jax.lax.psum(out, axis)
