"""Tensor-parallel collective regions (ref apex/transformer/tensor_parallel/mappings.py).

The reference wraps four NCCL patterns in autograd Functions:

    copy    — identity fwd,  allreduce bwd   (entering a column-parallel gemm)
    reduce  — allreduce fwd, identity bwd    (leaving a row-parallel gemm)
    scatter — split fwd,     all-gather bwd
    gather  — all-gather fwd, split bwd

On TPU none of these need a hand-written backward: JAX's collective
primitives already transpose to the right duals under ``shard_map``
(``pcast``-to-varying ⇄ ``psum``; tiled ``all_gather`` ⇄ ``psum_scatter``),
so each region is just the forward collective and autodiff produces the
reference's backward — with ``gather``'s transpose being the *more* correct
``psum_scatter`` (the reference's plain split silently assumes replicated
cotangents, ref mappings.py:127-145).

All functions must run inside ``shard_map`` with the tensor-parallel axis
bound; with tp=1 (axis absent) they are identity, so model code is
parallelism-agnostic.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.observability import span
from apex_tpu.transformer import parallel_state


def _axis(axis_name: Optional[str]) -> str:
    """``None`` means the DEFAULT tp axis name, not "no parallelism" —
    like the reference's ``group=None`` → default NCCL group. To run
    tensor-parallel code unpartitioned on a mesh that has a bound 'tp'
    axis, use a different axis name for that mesh dimension; when 'tp' is
    simply unbound these regions are identity."""
    return (
        axis_name
        if axis_name is not None
        else parallel_state.TENSOR_AXIS
    )


def _axis_bound(axis: str) -> bool:
    """True when ``axis`` is a manual (shard_map) axis in the current trace."""
    try:
        jax.lax.axis_size(axis)
        return True
    except (NameError, ValueError, KeyError, TypeError):
        return False


def make_varying(x, axis: str):
    """Mark a replicated value as device-varying over a shard_map axis
    (transpose: psum). Idempotent: values already varying over ``axis``
    pass through. Public — model code, examples, and other subsystems
    need it whenever fresh values must match the vma of computed ones."""
    return _to_varying(x, axis)


def tree_vma(*trees) -> set:
    """Union of the mesh axes any leaf of the given pytrees varies over.

    The standard companion to :func:`make_varying`: fresh zeros for scan
    carries / cond branches must be marked varying over exactly these
    axes to type-match values computed from the real inputs."""
    axes: set = set()
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            try:
                axes |= set(jax.typeof(leaf).vma)
            except (AttributeError, TypeError):
                pass
    return axes


def _to_varying(x, axis: str):
    """Mark a replicated value as device-varying (transpose: psum).
    Idempotent: values already varying over ``axis`` pass through."""
    try:
        if axis in jax.typeof(x).vma:
            return x
    except (AttributeError, TypeError):
        pass
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis, to="varying")
    return jax.lax.pvary(x, (axis,))


def _to_invariant(x, axis: str):
    """Make a numerically-replicated value vma-invariant over ``axis``
    (e.g. an all_gather output, identical on every rank). jax has no claim
    primitive, so this divides by the axis size and psums — psum is the
    variant→invariant collective. XLA folds the scale into the reduce."""
    try:
        if axis not in jax.typeof(x).vma:
            return x
    except (AttributeError, TypeError):
        return x
    n = jax.lax.axis_size(axis)
    return jax.lax.psum(x / n, axis)


def copy_to_tensor_model_parallel_region(x, axis_name: Optional[str] = None):
    """Identity forward; gradients allreduce over tp (ref mappings.py:148)."""
    axis = _axis(axis_name)
    if not _axis_bound(axis):
        return x
    with span("tp/copy"):
        return _to_varying(x, axis)


def reduce_from_tensor_model_parallel_region(x, axis_name: Optional[str] = None):
    """Allreduce forward; identity gradient (ref mappings.py:152)."""
    axis = _axis(axis_name)
    if not _axis_bound(axis):
        return x
    with span("tp/allreduce"):
        return jax.lax.psum(x, axis)


def scatter_to_tensor_model_parallel_region(x, axis_name: Optional[str] = None):
    """Keep this rank's last-dim chunk (ref mappings.py:156)."""
    axis = _axis(axis_name)
    if not _axis_bound(axis):
        return x
    n = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    chunk = x.shape[-1] // n
    with span("tp/scatter"):
        x = _to_varying(x, axis)
        return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk,
                                            axis=x.ndim - 1)


def gather_from_tensor_model_parallel_region(x, axis_name: Optional[str] = None):
    """All-gather last-dim chunks into the full tensor (ref mappings.py:160)."""
    axis = _axis(axis_name)
    if not _axis_bound(axis):
        return x
    with span("tp/all_gather"):
        return jax.lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)


def reduce_scatter_to_tensor_model_parallel_region(x, axis_name: Optional[str] = None):
    """psum_scatter over the LAST dim: the fused form of ``reduce_from``
    followed by ``scatter_to``. A full allreduce whose result is then
    sliced back to this rank's chunk moves ~2x the bytes and throws
    (n-1)/n of them away — the pattern the ``psum-scatter`` analysis
    check flags; this is the one-call fix it points at."""
    axis = _axis(axis_name)
    if not _axis_bound(axis):
        return x
    with span("tp/reduce_scatter"):
        return jax.lax.psum_scatter(x, axis,
                                    scatter_dimension=x.ndim - 1,
                                    tiled=True)


# --------------------------------------------------- sequence-parallel duals
# (ref: Megatron-LM sequence parallelism; the apex snapshot gates these behind
# sequence_parallel_enabled on the layers.)


def scatter_to_sequence_parallel_region(x, axis_name: Optional[str] = None,
                                        seq_dim: int = 0):
    """Split the *sequence* dim across tp ranks (Megatron layout puts it
    leading; our [b, s, h] model families pass ``seq_dim=1``)."""
    axis = _axis(axis_name)
    if not _axis_bound(axis):
        return x
    n = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    chunk = x.shape[seq_dim] // n
    with span("sp/scatter"):
        x = _to_varying(x, axis)
        return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk,
                                            axis=seq_dim)


def gather_from_sequence_parallel_region(x, axis_name: Optional[str] = None,
                                         seq_dim: int = 0):
    axis = _axis(axis_name)
    if not _axis_bound(axis):
        return x
    with span("sp/all_gather"):
        return jax.lax.all_gather(x, axis, axis=seq_dim, tiled=True)


def reduce_scatter_to_sequence_parallel_region(x, axis_name: Optional[str] = None,
                                               seq_dim: int = 0):
    """psum_scatter over the sequence dim (row-parallel output in SP mode)."""
    axis = _axis(axis_name)
    if not _axis_bound(axis):
        return x
    with span("sp/reduce_scatter"):
        return jax.lax.psum_scatter(x, axis, scatter_dimension=seq_dim,
                                    tiled=True)
