"""Tensor parallelism over the 'tp' mesh axis
(ref apex/transformer/tensor_parallel/__init__.py export surface)."""

from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    column_parallel_linear,
    linear_with_grad_accumulation_and_async_allreduce,
    copy_tensor_model_parallel_attributes,
    param_is_not_tensor_parallel_duplicate,
    set_defaults_if_not_set_tensor_model_parallel_attributes,
    param_partition_specs,
    row_parallel_linear,
    set_tensor_model_parallel_attributes,
    vocab_parallel_embedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    reduce_scatter_to_tensor_model_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.memory import (
    MemoryBuffer,
    RingMemBuffer,
    allocate_mem_buff,
    get_mem_buff,
)
from apex_tpu.transformer.tensor_parallel.random import (
    CudaRNGStatesTracker,
    RNGStatesTracker,
    checkpoint,
    get_cuda_rng_tracker,
    get_rng_tracker,
    model_parallel_cuda_manual_seed,
    model_parallel_rng_seed,
    tp_rank_key,
)
from apex_tpu.transformer.tensor_parallel.utils import (
    VocabUtility,
    split_tensor_along_last_dim,
)

__all__ = [
    "vocab_parallel_cross_entropy",
    "broadcast_data",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "column_parallel_linear",
    "row_parallel_linear",
    "vocab_parallel_embedding",
    "linear_with_grad_accumulation_and_async_allreduce",
    "copy_tensor_model_parallel_attributes",
    "param_is_not_tensor_parallel_duplicate",
    "set_defaults_if_not_set_tensor_model_parallel_attributes",
    "param_partition_specs",
    "set_tensor_model_parallel_attributes",
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "reduce_scatter_to_tensor_model_parallel_region",
    "MemoryBuffer",
    "RingMemBuffer",
    "allocate_mem_buff",
    "get_mem_buff",
    "RNGStatesTracker",
    "CudaRNGStatesTracker",
    "checkpoint",
    "get_rng_tracker",
    "get_cuda_rng_tracker",
    "model_parallel_rng_seed",
    "model_parallel_cuda_manual_seed",
    "tp_rank_key",
    "VocabUtility",
    "split_tensor_along_last_dim",
]
