"""Context (sequence) parallelism — first-class long-context support.

No reference-file analog (the CUDA reference scales sequence length with
megatron context parallelism + flash attention at the framework level; see
SURVEY.md §2 #53): sequences are sharded over the 'cp' mesh axis and
attention runs as **ring attention** — each step computes one K/V block's
contribution with an online-softmax accumulator (flash-attention algebra in
fp32) and ``ppermute``s the K/V block around the ring, so peak memory is
O(s_local²/P) and the ICI transfer overlaps the block matmul. Backward is
autodiff through the scan: the transposed ppermutes run the ring in reverse.

Alternative: :func:`ulysses_attention` (DeepSpeed-Ulysses-style) swaps
sequence↔head sharding with two ``all_to_all``s and runs plain attention
locally — cheaper at moderate sequence lengths when heads ≥ cp.

All functions run inside ``shard_map`` with 'cp' bound; layouts are
``[batch, seq_local, heads, head_dim]``.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state

_NEG_INF = -1e30


def _axis(axis_name: Optional[str]) -> str:
    return axis_name if axis_name is not None else parallel_state.CONTEXT_AXIS


def ring_attention(
    q,
    k,
    v,
    axis_name: Optional[str] = None,
    causal: bool = True,
    scale: Optional[float] = None,
    remat: bool = True,
):
    """Exact attention over a cp-sharded sequence.

    q/k/v: [b, s_local, h, d] — this rank's sequence shard. Returns the
    attention output for the local queries, identical (up to fp roundoff) to
    full attention over the gathered sequence.
    """
    axis = _axis(axis_name)
    n = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    b, s_local, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv:
        raise ValueError(f"query heads {h} not a multiple of kv heads {h_kv}")
    rep = h // h_kv  # GQA: k/v ride the ring at h_kv heads, never repeated
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    q32 = q.astype(jnp.float32) * scale
    if rep > 1:
        q32 = q32.reshape(b, s_local, h_kv, rep, d)
    row_pos = rank * s_local + jnp.arange(s_local)  # global query positions

    def block(carry_kv, src_rank):
        """One K/V block's contribution given its originating rank."""
        k_blk, v_blk = carry_kv
        k32 = k_blk.astype(jnp.float32)
        if rep > 1:
            s = jnp.einsum("bqgrd,bkgd->bgrqk", q32, k32)
            s = s.reshape(b, h, s_local, -1)
        else:
            s = jnp.einsum("bqhd,bkhd->bhqk", q32, k32)
        if causal:
            col_pos = src_rank * s_local + jnp.arange(s_local)
            allowed = col_pos[None, :] <= row_pos[:, None]  # [q, k]
            s = jnp.where(allowed[None, None], s, _NEG_INF)
        return s

    def step(carry, i):
        k_blk, v_blk, m, l, o = carry
        src = (rank - i) % n
        s = block((k_blk, v_blk), src)  # [b, h, q, k]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # fully-masked rows have s == m_new == _NEG_INF; exp(0)=1 would leak
        # weight onto masked keys, so zero them explicitly
        p = jnp.where(
            s <= _NEG_INF * 0.5, 0.0, jnp.exp(s - m_new[..., None])
        )
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        v32 = v_blk.astype(jnp.float32)
        if rep > 1:
            pv = jnp.einsum(
                "bgrqk,bkgd->bgrqd",
                p.reshape(b, h_kv, rep, s_local, -1), v32
            ).reshape(b, h, s_local, d)
        else:
            pv = jnp.einsum("bhqk,bkhd->bhqd", p, v32)
        o = o * alpha[..., None] + pv
        # rotate K/V around the ring (rank r's block moves to r+1)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        return (k_blk, v_blk, m_new, l, o), None

    from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

    step_fn = jax.checkpoint(step) if remat else step
    # accumulators become device-varying inside the loop; start them that way
    m0 = _to_varying(jnp.full((b, h, s_local), _NEG_INF, jnp.float32), axis)
    l0 = _to_varying(jnp.zeros((b, h, s_local), jnp.float32), axis)
    o0 = _to_varying(jnp.zeros((b, h, s_local, d), jnp.float32), axis)
    (_, _, m, l, o), _ = jax.lax.scan(
        step_fn, (k, v, m0, l0, o0), jnp.arange(n)
    )
    out = o / jnp.maximum(l, 1e-20)[..., None]  # [b, h, q, d]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ulysses_attention(
    q,
    k,
    v,
    attn_fn: Optional[Callable] = None,
    axis_name: Optional[str] = None,
    causal: bool = True,
    scale: Optional[float] = None,
):
    """All-to-all sequence parallelism: trade seq sharding for head sharding,
    attend locally over the FULL sequence, swap back.

    Requires heads % cp == 0. ``attn_fn(q, k, v)`` (full-sequence layouts)
    defaults to plain softmax attention with the usual 1/√d scale.
    """
    axis = _axis(axis_name)
    n = jax.lax.axis_size(axis)

    def seq_to_heads(x):
        # [b, s_local, h, d] -> [b, s_full, h/n, d]
        x = jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                               tiled=True)
        return x

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    qf, kf, vf = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)

    if attn_fn is None:
        d = q.shape[-1]
        sc = scale if scale is not None else 1.0 / (d ** 0.5)

        def attn_fn(q, k, v):
            s = jnp.einsum(
                "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
            ) * sc
            if causal:
                sq, sk = s.shape[-2], s.shape[-1]
                rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
                cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
                s = jnp.where((cols > rows)[None, None], _NEG_INF, s)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
            return o.astype(q.dtype)

    of = attn_fn(qf, kf, vf)
    return heads_to_seq(of)


def split_sequence(x, axis_name: Optional[str] = None, seq_dim: int = 1):
    """Take this rank's sequence chunk (delegates to the tensor_parallel
    mapping; the cp default axis and [b, s, ...] seq_dim=1 differ)."""
    from apex_tpu.transformer.tensor_parallel import mappings

    return mappings.scatter_to_sequence_parallel_region(
        x, _axis(axis_name), seq_dim=seq_dim)


def gather_sequence(x, axis_name: Optional[str] = None, seq_dim: int = 1):
    """Inverse of :func:`split_sequence`."""
    from apex_tpu.transformer.tensor_parallel import mappings

    return mappings.gather_from_sequence_parallel_region(
        x, _axis(axis_name), seq_dim=seq_dim)


def context_parallel_positions(s_local: int, axis_name: Optional[str] = None):
    """Global position ids for this rank's shard (feed to RoPE)."""
    axis = _axis(axis_name)
    rank = jax.lax.axis_index(axis)
    return rank * s_local + jnp.arange(s_local)
