"""Global singletons for the test harness
(ref apex/transformer/testing/global_vars.py).

``set_global_variables`` parses args once and builds the num-microbatches
calculator; ``get_args``/``get_num_microbatches``/``get_timers`` read the
singletons with the reference's initialized/not-initialized assertions.
Timers block on device work (``block_until_ready``) the way the
reference's timers ``cuda.synchronize`` (ref global_vars.py:191).
"""

from __future__ import annotations

import time
from typing import Optional

import jax

from apex_tpu.transformer.microbatches import (
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.testing.arguments import parse_args

_GLOBAL_ARGS = None
_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TIMERS = None


def _ensure_initialized(var, name):
    assert var is not None, f"{name} is not initialized."
    return var


def _ensure_not_initialized(var, name):
    assert var is None, f"{name} is already initialized."


def get_args():
    """Return arguments (ref global_vars.py:34)."""
    return _ensure_initialized(_GLOBAL_ARGS, "args")


def get_num_microbatches() -> int:
    return _ensure_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    ).get()


def get_current_global_batch_size() -> int:
    return _ensure_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    ).get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, *,
                            consistency_check: bool = True) -> None:
    _ensure_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    ).update(consumed_samples, consistency_check)


def get_timers():
    return _ensure_initialized(_GLOBAL_TIMERS, "timers")


def set_global_variables(extra_args_provider=None, args_defaults=None,
                         ignore_unknown_args: bool = True,
                         data_parallel_size: Optional[int] = None,
                         args=None):
    """Parse args and set every singleton (ref global_vars.py:87)."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR, _GLOBAL_TIMERS
    _ensure_not_initialized(_GLOBAL_ARGS, "args")
    parsed = parse_args(extra_args_provider, args_defaults,
                        ignore_unknown_args, args=args)
    _GLOBAL_ARGS = parsed
    dp = data_parallel_size if data_parallel_size is not None else 1
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank=0,
        rampup_batch_size=parsed.rampup_batch_size,
        global_batch_size=parsed.global_batch_size,
        micro_batch_size=parsed.micro_batch_size,
        data_parallel_size=dp,
    )
    _GLOBAL_TIMERS = Timers()
    return parsed


def destroy_global_vars():
    """Reset for the next test (the reference leaks these across tests)."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR, _GLOBAL_TIMERS
    _GLOBAL_ARGS = None
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
    _GLOBAL_TIMERS = None


class _Timer:
    """ref global_vars.py:191 — start/stop/elapsed with device sync."""

    def __init__(self, name):
        self.name = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = None

    def start(self):
        assert not self.started_, "timer has already been started"
        (jax.device_put(0.0)).block_until_ready()  # drain pending work
        self.start_time = time.time()
        self.started_ = True

    def stop(self):
        assert self.started_, "timer is not started"
        (jax.device_put(0.0)).block_until_ready()
        self.elapsed_ += time.time() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset=True):
        started = self.started_
        if started:
            self.stop()
        e = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return e


class Timers:
    """ref global_vars.py:236 — named timer registry."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        strings = [
            f"{name}: {self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer:.2f}"
            for name in names if name in self.timers
        ]
        print("time (ms) | " + " | ".join(strings), flush=True)
