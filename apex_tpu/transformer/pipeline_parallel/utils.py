"""Pipeline training utilities (ref apex/transformer/pipeline_parallel/utils.py)."""

from __future__ import annotations

from typing import List, Optional, Union

import jax

from apex_tpu.transformer.pipeline_parallel._timers import (  # noqa: F401
    Timers,
    _Timer,
)
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.microbatches import build_num_microbatches_calculator

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TIMERS = None
_GLOBAL_AUTORESUME = None


def _ensure_var_is_initialized(var, name):
    if var is None:
        raise RuntimeError(f"{name} is not initialized")


def _ensure_var_is_not_initialized(var, name):
    if var is not None:
        raise RuntimeError(f"{name} is already initialized")


def listify_model(model) -> List:
    """ref utils.py:42."""
    return model if isinstance(model, list) else [model]


def setup_microbatch_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> None:
    """ref utils.py:58."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _ensure_var_is_not_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    )
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size,
    )


def _reconfigure_microbatch_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> None:
    """ref utils.py:72 (test/eval hook — replaces unconditionally)."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size,
    )


def destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def get_micro_batch_size() -> int:
    """ref utils.py:88."""
    _ensure_var_is_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    )
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.micro_batch_size


def get_num_microbatches() -> int:
    """ref utils.py:92."""
    _ensure_var_is_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    )
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size() -> int:
    """ref utils.py:96."""
    _ensure_var_is_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    )
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples, consistency_check=True) -> None:
    """ref utils.py:100."""
    _ensure_var_is_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    )
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(
        consumed_samples, consistency_check
    )


def split_batch_into_microbatches(batch, micro_batch_size: int):
    """Reshape [B, ...] leaves to [M, mb, ...] for the schedules
    (ref utils.py:105 ``_split_batch_into_microbatch``)."""
    def split(x):
        b = x.shape[0]
        if b % micro_batch_size:
            raise ValueError(
                f"batch {b} not divisible by micro batch {micro_batch_size}"
            )
        return x.reshape((b // micro_batch_size, micro_batch_size)
                         + x.shape[1:])

    return jax.tree_util.tree_map(split, batch)


def get_kth_microbatch(batch, k: int):
    """ref utils.py:122."""
    return jax.tree_util.tree_map(lambda x: x[k], batch)


def average_losses_across_data_parallel_group(losses):
    """ref utils.py:242 — pmean over 'dp' (inside shard_map)."""
    stacked = jnp.stack([jnp.reshape(l, ()) for l in losses])
    return jax.lax.pmean(stacked, parallel_state.DATA_AXIS)


def param_is_not_shared(param) -> bool:
    """ref utils.py:181 — no shared-parameter aliasing in functional trees."""
    del param
    return True


def unwrap_model(model, module_instances=None):
    """ref utils.py:185 — unwrap DDP-style wrappers."""
    return_list = True
    if not isinstance(model, list):
        model = [model]
        return_list = False
    unwrapped = []
    for m in model:
        while hasattr(m, "module") and m.module is not None and (
            module_instances is None or isinstance(m, module_instances)
        ):
            inner = m.module
            if inner is m:
                break
            m = inner
        unwrapped.append(m)
    return unwrapped if return_list else unwrapped[0]


def calc_params_l2_norm(params, bf16: bool = True):
    """Global param L2 norm across model-parallel ranks (ref utils.py:213).
    Outside shard_map this is just the tree norm."""
    del bf16
    leaves = jax.tree_util.tree_leaves(params)
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(sq)


def get_ltor_masks_and_position_ids(
    data,
    eod_token: Optional[int] = None,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
):
    """Left-to-right masks + position ids (ref utils.py:303). Static-shape
    version: per-document resets use cumulative counts of EOD tokens rather
    than Python loops over found positions."""
    b, s = data.shape
    attention_mask = jnp.tril(jnp.ones((s, s), dtype=bool))[None]
    loss_mask = jnp.ones((b, s), dtype=jnp.float32)
    if eod_mask_loss and eod_token is not None:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)
    position_ids = jnp.broadcast_to(jnp.arange(s), (b, s))
    if (reset_position_ids or reset_attention_mask) and eod_token is not None:
        # document id = number of EODs strictly before each position
        is_eod = (data == eod_token).astype(jnp.int32)
        doc_id = jnp.cumsum(is_eod, axis=1) - is_eod
        if reset_position_ids:
            # position restarts right after each EOD: running max of
            # (index of the token following the latest EOD) per row
            seg_start = jax.lax.associative_scan(
                jnp.maximum,
                jnp.where(
                    jnp.roll(is_eod, 1, axis=1).at[:, 0].set(0) == 1,
                    jnp.broadcast_to(jnp.arange(s), (b, s)),
                    0,
                ),
                axis=1,
            )
            position_ids = jnp.arange(s)[None] - seg_start
        if reset_attention_mask:
            same_doc = doc_id[:, :, None] == doc_id[:, None, :]
            attention_mask = attention_mask & same_doc
    return attention_mask, loss_mask, position_ids


# ------------------------------------------------------------------- timers


# _Timer/Timers live in _timers.py (the single implementation: device
# sync via block_until_ready, profiler TraceAnnotations, tensorboard
# write) — re-exported here for the reference's utils-level access path.


def _set_timers():
    global _GLOBAL_TIMERS
    _ensure_var_is_not_initialized(_GLOBAL_TIMERS, "timers")
    _GLOBAL_TIMERS = Timers()


def get_timers():
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _GLOBAL_TIMERS = Timers()
    return _GLOBAL_TIMERS


def print_rank_0(message: str) -> None:
    """ref utils.py:159."""
    if jax.process_index() == 0:
        print(message, flush=True)


def is_last_rank() -> bool:
    return jax.process_index() == jax.process_count() - 1


def print_rank_last(message):
    if is_last_rank():
        print(message, flush=True)


def report_memory(name: str) -> str:
    """ref pipeline_parallel/utils.py report_memory — print device memory
    stats. CUDA's allocated/cached split maps onto the PJRT
    ``memory_stats`` of the local device: bytes in use, peak, and limit
    (absent on backends that don't report, e.g. the CPU mesh). Read
    through the memory observability tier (ISSUE 15) — the raw PJRT
    surface belongs to apex_tpu.observability.memory."""
    import jax

    from apex_tpu.observability.memory import device_memory_stats

    dev = jax.local_devices()[0]
    stats = device_memory_stats(dev)
    giga = 1024.0 ** 3
    parts = [f"[{name}] memory on {dev.platform}:{dev.id}"]
    for key, label in (("bytes_in_use", "in use"),
                       ("peak_bytes_in_use", "peak"),
                       ("bytes_limit", "limit")):
        if key in stats:
            parts.append(f"{label} {stats[key] / giga:.3f} GiB")
    line = " | ".join(parts)
    print(line, flush=True)
    return line


def print_params_min_max_norm(optimizer, iteration: int) -> None:
    """ref pipeline_parallel/utils.py print_params_min_max_norm — per-param
    (iteration, rank, index, min, max, norm) lines. Accepts a
    FusedOptimizer-shaped object (``.params``) or a bare params tree."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.transformer import parallel_state

    import flax.linen as nn

    from apex_tpu.transformer.tensor_parallel.layers import (
        param_is_not_tensor_parallel_duplicate)

    params = getattr(optimizer, "params", optimizer)
    try:
        rank = parallel_state.get_tensor_model_parallel_rank()
    except Exception:  # outside an initialized mesh
        rank = 0
    index = 0
    # stop at Partitioned boxes: flattening through them would strip the
    # .names metadata the model-parallel flag reads
    flat = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=lambda x: isinstance(x, nn.Partitioned))[0]
    for path, leaf in flat:
        index += 1
        mp = int(param_is_not_tensor_parallel_duplicate(leaf))
        if isinstance(leaf, nn.Partitioned):
            leaf = leaf.value
        x = leaf.astype(jnp.float32)
        print(f"iteration, rank, index, model-parallel, min, max, norm: "
              f"{iteration} {rank} {index} {mp} "
              f"{float(x.min()):.6e} {float(x.max()):.6e} "
              f"{float(jnp.linalg.norm(x.ravel())):.6e}  {jax.tree_util.keystr(path)}",
              flush=True)
