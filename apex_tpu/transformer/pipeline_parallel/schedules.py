"""Pipeline-parallel schedules (ref apex/transformer/pipeline_parallel/schedules/*).

The reference drives 1F1B with a Python loop of NCCL send/recvs and manual
``backward_step`` calls (ref fwd_bwd_pipelining_without_interleaving.py:156).
The TPU re-design is *collective*: every stage runs the SAME jitted program —
a ``lax.scan`` over time steps where each step computes this stage's
microbatch and ``ppermute``s activations downstream. Differentiating through
the scan + ppermute yields the reverse pipeline automatically (transpose of
a +1 ppermute is a −1 ppermute), so the backward schedule the reference
hand-codes is produced by AD, and XLA overlaps the collectives with compute.
Per-microbatch ``jax.checkpoint`` on the stage body gives the 1F1B memory
profile (activations of at most "in-flight" microbatches are live).

Everything here must run inside ``shard_map`` with the 'pp' axis bound
(or via :func:`get_forward_backward_func`, which wraps the stage code).

Conventions:
- ``stage_fn(stage_params, x) -> y`` applies THIS stage's slice of the model;
  activation shapes must match across stages (y.shape == x.shape).
- ``stage_params`` is the per-stage parameter pytree (shard a stacked tree
  with ``in_specs=P('pp', ...)``).
- microbatched tensors carry a leading microbatch dim ``[M, mb, ...]``;
  inputs are consumed by stage 0, outputs produced on the last stage.
"""

from __future__ import annotations

import functools
import warnings
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from apex_tpu.observability import span
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import p2p


class ExperimentalWarning(Warning):
    """ref schedules/__init__.py:18."""


class InterleavedFallbackWarning(UserWarning):
    """The interleaved schedule silently has a different cost model when it
    falls back to chained GPipe (M % P != 0) — surfaced so users sizing
    microbatch counts see the switch (VERDICT r3 weak #4)."""


# ------------------------------------------------------------ no pipelining


def forward_backward_no_pipelining(
    loss_fn: Callable,
    params,
    microbatches,
    forward_only: bool = False,
    grad_scale=None,
):
    """Microbatched gradient accumulation without pipelining
    (ref fwd_bwd_no_pipelining.py:31).

    ``loss_fn(params, microbatch) -> scalar``; ``microbatches`` is a pytree
    with leading microbatch dim M. Returns ``(mean_loss, grads)`` — grads are
    the mean over microbatches (None when ``forward_only``).
    """
    m_count = jax.tree_util.tree_leaves(microbatches)[0].shape[0]

    if forward_only:
        def fwd_body(acc, mb):
            return acc + loss_fn(params, mb), None

        total, _ = jax.lax.scan(fwd_body, 0.0, microbatches)
        return total / m_count, None

    vg = jax.value_and_grad(loss_fn)

    def body(carry, mb):
        loss_acc, grad_acc = carry
        loss, grads = vg(params, mb)
        grad_acc = jax.tree_util.tree_map(jnp.add, grad_acc, grads)
        return (loss_acc + loss, grad_acc), None

    # accumulator avals must match the GRAD avals, not the param avals:
    # with grad-accumulation fusion the wgrads are fp32 over bf16-computed
    # layers, and the fp32 carry is where the fusion's accumulation lives
    first_mb = jax.tree_util.tree_map(lambda a: a[0], microbatches)
    grad_shapes = jax.eval_shape(lambda p, mb: vg(p, mb)[1], params, first_mb)
    zero_grads = jax.tree_util.tree_map(
        lambda sd: jnp.zeros(sd.shape, sd.dtype), grad_shapes
    )
    with span("pp/grad_accum"):
        (loss_sum, grad_sum), _ = jax.lax.scan(body, (0.0, zero_grads),
                                               microbatches)
    scale = 1.0 / m_count if grad_scale is None else grad_scale / m_count
    grads = jax.tree_util.tree_map(lambda g: g * scale, grad_sum)
    return loss_sum / m_count, grads


# ------------------------------------------------------ collective pipeline



def _maybe_remat(stage_fn, remat):
    """remat: False = none; True = full recompute; "dots" = keep matmul
    outputs, recompute VPU chains (jax.checkpoint_policies
    .dots_with_no_batch_dims_saveable) — same contract as
    apex_tpu.models.llama.run_layers."""
    if not remat:
        return stage_fn
    policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
              if remat == "dots" else None)
    return jax.checkpoint(stage_fn, policy=policy)

def pipelined_forward(
    stage_fn: Callable,
    stage_params,
    inputs,
    axis_name: Optional[str] = None,
    remat: bool = True,
):
    """GPipe/1F1B collective forward: scan over M+P−1 time steps with a +1
    ppermute each step (the TPU analog of the warmup/steady/cooldown loops in
    ref fwd_bwd_pipelining_without_interleaving.py:156).

    ``inputs``: [M, mb, ...] — read by stage 0 (other stages ignore it).
    Returns [M, mb, ...] activations — meaningful on the LAST stage.
    """
    axis = axis_name if axis_name is not None else parallel_state.PIPELINE_AXIS
    n_stage = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    m_count = inputs.shape[0]
    steps = m_count + n_stage - 1

    body_fn = _maybe_remat(stage_fn, remat)

    def step(carry, t):
        incoming, outputs = carry
        mb_idx = jnp.clip(t, 0, m_count - 1)
        feed = jax.lax.dynamic_index_in_dim(inputs, mb_idx, 0, keepdims=False)
        x = jnp.where(rank == 0, feed, incoming)
        with span("pp/stage_compute"):
            y = body_fn(stage_params, x)
        out_idx = jnp.clip(t - (n_stage - 1), 0, m_count - 1)
        write = (t >= n_stage - 1)  # uniform across ranks
        prev = jax.lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                            keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, y, prev), out_idx, 0
        )
        with span("pp/send_recv"):
            incoming = p2p.send_forward_recv_forward(y, axis)
        return (incoming, outputs), None

    from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

    one = jax.lax.dynamic_index_in_dim(inputs, 0, 0, keepdims=False)
    # carries become device-varying inside the loop; start them that way
    init = (_to_varying(jnp.zeros_like(one), axis),
            _to_varying(jnp.zeros_like(inputs), axis))
    with span("pp/forward"):
        (_, outputs), _ = jax.lax.scan(step, init, jnp.arange(steps))
    return outputs


def _last_stage_mean_loss(loss_fn, outputs, targets, axis):
    """Per-microbatch loss on the last stage, psum'd to every stage."""
    n_stage = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    losses = jax.vmap(loss_fn)(outputs, targets)
    local = jnp.where(rank == n_stage - 1, jnp.mean(losses), 0.0)
    return jax.lax.psum(local, axis)


def forward_backward_pipelining_without_interleaving(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    inputs,
    targets,
    forward_only: bool = False,
    axis_name: Optional[str] = None,
    remat: bool = True,
):
    """1F1B equivalent (ref fwd_bwd_pipelining_without_interleaving.py:156):
    forward is :func:`pipelined_forward`; the backward pipeline (reverse
    ppermutes, per-stage wgrad) falls out of ``jax.value_and_grad``.

    ``loss_fn(one_output_mb, one_target_mb) -> scalar``. Returns
    ``(mean_loss, stage_grads)``; every stage gets the loss (psum) and the
    grads of ITS OWN stage_params.
    """
    axis = axis_name if axis_name is not None else parallel_state.PIPELINE_AXIS

    def total_loss(stage_params):
        outs = pipelined_forward(stage_fn, stage_params, inputs, axis, remat)
        with span("pp/loss"):
            return _last_stage_mean_loss(loss_fn, outs, targets, axis)

    if forward_only:
        return total_loss(stage_params), None
    with span("pp/forward_backward"):
        return jax.value_and_grad(total_loss)(stage_params)


def interleaved_num_steps(m_count: int, p: int, v: int) -> int:
    """Scan length of the interleaved schedule: fill once, then stream all
    V·M chunk-computations — vs ``v * (m_count + p - 1)`` for V chained
    GPipe passes. The saving, ``(v-1)·(p-1)`` steps, is the interleaving
    bubble reduction (ref fwd_bwd_pipelining_with_interleaving.py's point:
    bubble ∝ (p-1)/v because each virtual stage is 1/v of the model)."""
    return v * m_count + p - 1


def pipelined_forward_chained(
    stage_fn: Callable,
    stage_params_chunks,
    inputs,
    axis_name: Optional[str] = None,
    remat: bool = True,
):
    """V chained GPipe passes with a cyclic last→first ppermute between
    chunks — the fallback when M is not a multiple of P (the true
    interleaved order needs whole microbatch groups of size P)."""
    axis = axis_name if axis_name is not None else parallel_state.PIPELINE_AXIS
    v_size = jax.tree_util.tree_leaves(stage_params_chunks)[0].shape[0]
    outs = inputs
    for v in range(v_size):
        params_v = jax.tree_util.tree_map(
            lambda x: x[v], stage_params_chunks
        )
        outs = pipelined_forward(stage_fn, params_v, outs, axis, remat)
        if v < v_size - 1:
            # last stage hands chunk output back to stage 0 over the ring
            outs = p2p._shift_cyclic(outs, +1, axis)
    return outs


def pipelined_forward_interleaved(
    stage_fn: Callable,
    stage_params_chunks,
    inputs,
    axis_name: Optional[str] = None,
    remat: bool = True,
    strict: bool = False,
):
    """Interleaved virtual-pipeline forward
    (ref fwd_bwd_pipelining_with_interleaving.py:26).

    ``stage_params_chunks`` carries a leading virtual-chunk dim V: device r
    owns virtual stages (r, r+P, ..., r+(V-1)·P) of a V·P-stage model —
    the reference's model-chunk assignment.

    Collective re-design of the interleaved 1F1B order: one ``lax.scan`` of
    ``V·M + P − 1`` steps (vs ``V·(M + P − 1)`` for chained GPipe). Device
    ``r`` at local step ``u = t − r`` runs unit ``(chunk c, microbatch m)``
    with ``g = u // (V·P)``, ``c = (u // P) % V``, ``i = u % P``,
    ``m = g·P + i`` — microbatches in groups of P, cycling chunks per group,
    exactly Megatron's interleaved order. Under this ordering EVERY
    dependency (same-chunk previous stage, and the last→first chunk
    handoff) is "my ring-neighbour produced it one step ago", so stage
    transfer is a single cyclic ppermute per step and the reference's
    hand-scheduled warmup/steady/cooldown phases collapse into index
    arithmetic. The backward (reverse ring, per-chunk wgrad scatter-add)
    falls out of AD. Requires ``M % P == 0`` (whole microbatch groups —
    the reference asserts the same,
    ref fwd_bwd_pipelining_with_interleaving.py:26); other sizes fall back
    to :func:`pipelined_forward_chained` with an
    :class:`InterleavedFallbackWarning` (the fallback costs
    ``V·(M+P−1)`` scan steps instead of ``V·M+P−1`` — a different bubble
    model), or raise when ``strict=True``.
    """
    axis = axis_name if axis_name is not None else parallel_state.PIPELINE_AXIS
    p = jax.lax.axis_size(axis)
    m_count = inputs.shape[0]
    v = jax.tree_util.tree_leaves(stage_params_chunks)[0].shape[0]
    if m_count % p:
        msg = (
            f"interleaved schedule needs whole microbatch groups: "
            f"num_microbatches={m_count} is not a multiple of "
            f"pipeline_size={p}; falling back to chained GPipe "
            f"({v}·({m_count}+{p}−1) = {v * (m_count + p - 1)} scan steps "
            f"instead of {interleaved_num_steps(m_count, p, v)} — a "
            f"different bubble cost model). Pad the microbatch count or "
            f"pass strict=True to fail instead.")
        if strict:
            raise ValueError(msg)
        warnings.warn(msg, InterleavedFallbackWarning, stacklevel=2)
        return pipelined_forward_chained(
            stage_fn, stage_params_chunks, inputs, axis, remat)
    rank = jax.lax.axis_index(axis)
    units = v * m_count
    steps = interleaved_num_steps(m_count, p, v)

    body_fn = _maybe_remat(stage_fn, remat)

    from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

    inputs_v = _to_varying(inputs, axis)

    def step(carry, t):
        incoming, outputs = carry
        u = t - rank
        valid = (u >= 0) & (u < units)
        uc = jnp.clip(u, 0, units - 1)
        c = (uc // p) % v                       # which of my V chunks
        m = (uc // (v * p)) * p + uc % p        # microbatch g·P + i
        params_c = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
            stage_params_chunks)
        feed = jax.lax.dynamic_index_in_dim(inputs_v, m, 0, keepdims=False)
        # virtual stage 0 = (device 0, chunk 0) reads external input
        x = jnp.where((rank == 0) & (c == 0), feed, incoming)
        with span("pp/stage_compute"):
            y = body_fn(params_c, x)
        # virtual stage V·P−1 = (device P−1, chunk V−1) emits the output
        is_out = (rank == p - 1) & (c == v - 1) & valid
        prev = jax.lax.dynamic_index_in_dim(outputs, m, 0, keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(is_out, y, prev), m, 0)
        with span("pp/send_recv"):
            incoming = p2p._shift_cyclic(y, +1, axis)
        return (incoming, outputs), None

    one = jax.lax.dynamic_index_in_dim(inputs, 0, 0, keepdims=False)
    init = (_to_varying(jnp.zeros_like(one), axis),
            _to_varying(jnp.zeros_like(inputs), axis))
    with span("pp/forward_interleaved"):
        (_, outputs), _ = jax.lax.scan(step, init, jnp.arange(steps))
    return outputs


def _forward_backward_pipelining_with_interleaving(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params_chunks,
    inputs,
    targets,
    forward_only: bool = False,
    axis_name: Optional[str] = None,
    remat: bool = True,
    strict: bool = False,
):
    """Interleaved-schedule entry (ref fwd_bwd_pipelining_with_interleaving.py:26).
    True interleaved order when ``M % P == 0``; chained-GPipe fallback
    otherwise with an :class:`InterleavedFallbackWarning`, or raise when
    ``strict=True`` (see :func:`pipelined_forward_interleaved`)."""
    axis = axis_name if axis_name is not None else parallel_state.PIPELINE_AXIS

    def total_loss(chunks):
        outs = pipelined_forward_interleaved(stage_fn, chunks, inputs, axis,
                                             remat, strict=strict)
        with span("pp/loss"):
            return _last_stage_mean_loss(loss_fn, outs, targets, axis)

    if forward_only:
        return total_loss(stage_params_chunks), None
    with span("pp/forward_backward"):
        return jax.value_and_grad(total_loss)(stage_params_chunks)


forward_backward_pipelining_with_interleaving = (
    _forward_backward_pipelining_with_interleaving
)


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_size: Optional[int] = None,
):
    """Pick the schedule (ref schedules/__init__.py:22)."""
    if pipeline_model_parallel_size is None:
        pipeline_model_parallel_size = (
            parallel_state.get_pipeline_model_parallel_world_size()
        )
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            warnings.warn(
                "interleaved collective schedule (chained fallback when "
                "num_microbatches % pp != 0)",
                ExperimentalWarning,
            )
            return _forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


# ---------------------------------------------------------------- build_model


def build_model(
    model_provider_func: Callable,
    wrap_with_ddp: bool = True,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    model_type=None,
    **kwargs,
) -> List:
    """Instantiate one model (chunk) per virtual pipeline rank
    (ref schedules/common.py:29). ``model_provider_func(pre_process,
    post_process, **kwargs)`` returns a flax module; pre/post flags tell the
    provider whether this chunk holds the embedding / the head."""
    del model_type
    pp_world = parallel_state.get_pipeline_model_parallel_world_size()
    pp_rank = parallel_state.get_pipeline_model_parallel_rank()
    v = virtual_pipeline_model_parallel_size
    models = []
    n_chunks = v if v is not None else 1
    for chunk in range(n_chunks):
        stage_id = (
            pp_rank + chunk * pp_world if v is not None else pp_rank
        )
        total = pp_world * n_chunks
        model = model_provider_func(
            pre_process=(stage_id == 0),
            post_process=(stage_id == total - 1),
            **kwargs,
        )
        if wrap_with_ddp:
            from apex_tpu.parallel import DistributedDataParallel

            model = DistributedDataParallel(model)
        models.append(model)
    return models


def get_params_for_weight_decay_optimization(params) -> dict:
    """Weight-decay mask pytree: True for rank≥2 kernels, False for biases
    and norm scales (ref schedules/common.py:161
    ``_get_params_for_weight_decay_optimization``). Use with
    ``optax.masked``."""
    return jax.tree_util.tree_map(lambda p: jnp.ndim(p) >= 2, params)
