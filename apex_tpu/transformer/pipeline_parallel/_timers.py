"""Named phase timers (ref apex/transformer/pipeline_parallel/_timers.py).

The reference's ``_Timer`` calls ``torch.cuda.synchronize()`` around each
start/stop so wall-clock brackets the device work. The TPU analog has no
global sync primitive — async dispatch means a bare ``time.time()`` pair
measures dispatch, not execution — so :meth:`_Timer.stop` accepts the
step's output and calls ``jax.block_until_ready`` on it, and each running
timer opens a ``jax.profiler.TraceAnnotation`` so the phases also show up
named in a profiler trace (the nvtx analog the reference pairs with
pyprof).

Usage (identical shape to the reference):

    timers = Timers()
    timers("forward").start()
    out = step(batch)
    timers("forward").stop(out)        # blocks on out, records elapsed
    timers.log(["forward"], normalizer=n_iters)
"""

from __future__ import annotations

import time
from typing import Optional

import jax


class _Timer:
    """One named timer (ref _timers.py:6)."""

    def __init__(self, name: str):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = time.time()
        self._annotation = None

    def start(self):
        if self.started_:
            raise RuntimeError("timer has already been started")
        self._annotation = jax.profiler.TraceAnnotation(
            f"timer/{self.name_}")
        self._annotation.__enter__()
        self.start_time = time.time()
        self.started_ = True

    def stop(self, block_on=None):
        """``block_on``: pytree of device values produced by the timed
        region — synced so the elapsed time covers device execution
        (the reference's cuda.synchronize analog). Omit for host-only
        regions. Host-fetch sync rather than block_until_ready: the
        latter is a no-op over the axon tunnel (the r5 MFU=330 bug),
        which would turn every phase timing into dispatch time."""
        if not self.started_:
            raise RuntimeError("timer is not started")
        overhead = 0.0
        if block_on is not None:
            from apex_tpu.runtime import timing
            timing.sync(block_on)
            now = time.time()
            # the sync's own host-fetch RTT (~79 ms over the tunnel)
            # must not count as phase time; the constant is measured
            # once per process and subtracted
            overhead = timing.cached_fetch_cost(block_on)
        else:
            now = time.time()
        self.elapsed_ += max(now - self.start_time - overhead, 0.0)
        self.started_ = False
        if self._annotation is not None:
            self._annotation.__exit__(None, None, None)
            self._annotation = None

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False
        if self._annotation is not None:
            # a running timer's profiler range must close or the trace
            # nesting stays unbalanced for the rest of the process
            self._annotation.__exit__(None, None, None)
            self._annotation = None

    def elapsed(self, reset: bool = True) -> float:
        started = self.started_
        if started:
            self.stop()
        elapsed = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return elapsed


class Timers:
    """Group of named timers (ref _timers.py:51 _Timers)."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def write(self, names, writer, iteration, normalizer: float = 1.0,
              reset: bool = False):
        """Write timings to a tensorboard-style ``writer`` (anything with
        ``add_scalar(tag, value, step)``)."""
        assert normalizer > 0.0
        for name in names:
            if name not in self.timers:
                continue  # same contract as log(): unstarted phases skip
            value = self.timers[name].elapsed(reset=reset) / normalizer
            writer.add_scalar(f"{name}-time", value, iteration)

    def log(self, names, normalizer: float = 1.0, reset: bool = True,
            printer: Optional[callable] = None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name not in self.timers:
                continue  # never-started phases just don't report
            elapsed_time = (self.timers[name].elapsed(reset=reset)
                            * 1000.0 / normalizer)
            string += f" | {name}: {elapsed_time:.2f}"
        if printer is not None:
            printer(string)
        else:
            # flushed: timing lines must survive a watchdog os._exit
            print(string, flush=True)
