"""Named phase timers (ref apex/transformer/pipeline_parallel/_timers.py).

Since ISSUE 2 this is a thin adapter over the shared telemetry layer:
the actual timing lives in :class:`apex_tpu.observability.Timer`
(corrected host-fetch sync via ``runtime.timing`` — the reference's
``torch.cuda.synchronize`` analog, minus the tunnel-no-op
``block_until_ready`` trap — plus a ``timer/<name>`` trace scope, the
nvtx analog the reference pairs with pyprof). What remains here is the
reference-shaped ``Timers.write/log`` API, and the timers register in
the process :class:`~apex_tpu.observability.MetricRegistry` so pipeline
phase times ride the same JSONL export as every other metric.

Usage (identical shape to the reference):

    timers = Timers()
    timers("forward").start()
    out = step(batch)
    timers("forward").stop(out)        # syncs out, records elapsed
    timers.log(["forward"], normalizer=n_iters)
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.observability import MetricRegistry, Timer, get_registry


class _Timer:
    """One named timer (ref _timers.py:6) — adapter over
    ``observability.Timer`` preserving the reference's accumulate /
    elapsed(reset) contract.

    Start/stop/accumulate state is PER INSTANCE (a private Timer, like
    the reference's per-``Timers``-group ``_Timer`` objects — two groups
    must never see each other's running flag), while every recorded
    interval is also observed into the shared registry metric
    ``pp_phase/<name>`` so phase times ride the process JSONL export.
    """

    def __init__(self, name: str, registry: Optional[MetricRegistry] = None):
        self.name_ = name
        reg = registry if registry is not None else get_registry()
        self._timer = Timer(f"pp_phase/{name}", {})   # private state
        self._sink = reg.timer(f"pp_phase/{name}")    # shared metric

    @property
    def started_(self) -> bool:
        return self._timer.running

    @property
    def elapsed_(self) -> float:
        return self._timer.total_elapsed

    def start(self):
        if self._timer.running:
            raise RuntimeError("timer has already been started")
        self._timer.start()

    def stop(self, block_on=None):
        """``block_on``: pytree of device values produced by the timed
        region — synced (host fetch, fetch-constant subtracted) so the
        elapsed time covers device execution. Omit for host-only
        regions."""
        if not self._timer.running:
            raise RuntimeError("timer is not started")
        self._sink.observe(self._timer.stop(block_on))

    def reset(self):
        if self._timer.running:
            # a running timer's profiler scope must close or the trace
            # nesting stays unbalanced for the rest of the process
            self._timer.cancel()
        self._timer.reset_total()

    def elapsed(self, reset: bool = True) -> float:
        started = self._timer.running
        if started:
            # split the PRIVATE accumulator only: a poll (write/log on a
            # running timer, reference semantics) is not a completed
            # phase, so the shared pp_phase histogram must not record
            # the fragment — only real stop() calls feed the sink
            self._timer.stop()
        elapsed = self._timer.total_elapsed
        if reset:
            self._timer.reset_total()
        if started:
            self.start()
        return elapsed


class Timers:
    """Group of named timers (ref _timers.py:51 _Timers)."""

    def __init__(self, registry: Optional[MetricRegistry] = None):
        self.timers = {}
        self._registry = registry

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name, self._registry)
        return self.timers[name]

    def write(self, names, writer, iteration, normalizer: float = 1.0,
              reset: bool = False):
        """Write timings to a tensorboard-style ``writer`` (anything with
        ``add_scalar(tag, value, step)``)."""
        assert normalizer > 0.0
        for name in names:
            if name not in self.timers:
                continue  # same contract as log(): unstarted phases skip
            value = self.timers[name].elapsed(reset=reset) / normalizer
            writer.add_scalar(f"{name}-time", value, iteration)

    def log(self, names, normalizer: float = 1.0, reset: bool = True,
            printer: Optional[callable] = None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name not in self.timers:
                continue  # never-started phases just don't report
            elapsed_time = (self.timers[name].elapsed(reset=reset)
                            * 1000.0 / normalizer)
            string += f" | {name}: {elapsed_time:.2f}"
        if printer is not None:
            printer(string)
        else:
            # flushed: timing lines must survive a watchdog os._exit
            print(string, flush=True)
