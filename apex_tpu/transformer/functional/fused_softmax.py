"""Fused scale+mask+softmax (ref apex/transformer/functional/fused_softmax.py
+ csrc/megatron/scaled_{masked,upper_triang_masked}_softmax*.cu).

The CUDA kernels fuse scale→mask→softmax to avoid three HBM round-trips. On
TPU, XLA already fuses the elementwise chain into the surrounding ops, so the
pure-jnp path is close to optimal; the Pallas kernels here add the two wins
XLA can't express:

- the **causal** variant never materializes the [sq, sk] mask in HBM — it is
  generated from ``iota`` inside the kernel (the reference's
  upper-triang kernel hardcodes the triangle the same way);
- softmax statistics are computed in fp32 in VMEM regardless of the bf16
  storage dtype (same accumulator policy as the CUDA kernels).

Backward is left to autodiff: softmax's vjp is a row reduction XLA fuses.
Non-TPU backends (the CPU test mesh) use the identical-math jnp fallback.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import pallas_config
from apex_tpu.transformer.enums import AttnMaskType

_MASK_FILL = -10000.0


def _use_pallas() -> bool:
    return pallas_config.use_pallas("fused_softmax")


# ------------------------------------------------------------- jnp reference


def _softmax_fp32(x, dtype):
    return jax.nn.softmax(x.astype(jnp.float32), axis=-1).astype(dtype)


def _causal_mask(sq: int, sk: int, dtype):
    # True above the diagonal = masked (matches the reference's triangle).
    q = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return k > q + (sk - sq)


# ---------------------------------------------------------------- Pallas fwd

# Keep one fp32 row-block comfortably inside VMEM (~16 MiB/core): budget
# ~2 MiB for x plus the same for y.
_VMEM_ROW_BUDGET = 2 * 1024 * 1024
# Rows up to this many keys use the single-pass whole-row kernel; longer
# rows switch to the two-pass k-blocked kernels (no upper limit).
_WHOLE_ROW_MAX_SK = 16384
# Test/debug override for the blocked kernels' k-block; None defers to
# the tuner (apex_tpu.tuning.softmax_block_k: tuned cache entry for the
# device, else the search-space default — the 2048 that used to live
# here as a hardcoded tile).
_BLOCKED_BK = None


def _blocked_bk(sk: int) -> int:
    if _BLOCKED_BK is not None:
        return _BLOCKED_BK
    from apex_tpu.tuning import softmax_block_k

    return softmax_block_k(sk)


def _largest_divisor(s: int, target: int) -> int:
    b = min(s, target)
    while s % b:
        b -= 1
    return b


def _pick_block_rows(sq: int, sk: int) -> int:
    # largest divisor of sq whose fp32 row block fits the VMEM budget
    return _largest_divisor(sq, max(8, _VMEM_ROW_BUDGET // (4 * sk)))


def _pallas_ok(sq: int, sk: int) -> bool:
    del sq  # k-blocking removed the sk cap (VERDICT weak #9)
    if sk > _WHOLE_ROW_MAX_SK:
        # only long rows consult the tuner for their k-block: the
        # whole-row path never uses it, and must not pay a cache lookup
        # (or inherit a cache error) per dispatch
        bk = _blocked_bk(sk)
        if _largest_divisor(sk, bk) < min(128, bk):
            # awkward sk (e.g. prime): the blocked kernel would
            # degenerate to lane-dim blocks far below a TPU tile —
            # jnp/XLA is faster there (min() keeps tests that shrink
            # _BLOCKED_BK on the blocked path)
            return False
    return _use_pallas()


def _causal_kernel(scale, block_rows, sq, sk, x_ref, y_ref):
    j = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32) * scale  # [1, block_rows, sk]
    row = (
        jax.lax.broadcasted_iota(jnp.int32, (block_rows, sk), 0)
        + j * block_rows
    )
    col = jax.lax.broadcasted_iota(jnp.int32, (block_rows, sk), 1)
    masked = jnp.where((col > row + (sk - sq))[None], _MASK_FILL, x)
    m = jnp.max(masked, axis=-1, keepdims=True)
    e = jnp.exp(masked - m)
    y = e / jnp.sum(e, axis=-1, keepdims=True)
    y_ref[:] = y.astype(y_ref.dtype)


def _masked_kernel(scale, x_ref, mask_ref, y_ref):
    x = x_ref[:].astype(jnp.float32) * scale
    masked = jnp.where(mask_ref[:], _MASK_FILL, x)
    m = jnp.max(masked, axis=-1, keepdims=True)
    e = jnp.exp(masked - m)
    y = e / jnp.sum(e, axis=-1, keepdims=True)
    y_ref[:] = y.astype(y_ref.dtype)


def _pallas_causal(x, scale):
    b, sq, sk = x.shape
    if sk > _WHOLE_ROW_MAX_SK:
        return _pallas_causal_blocked(x, scale)
    rows = _pick_block_rows(sq, sk)
    blk = (1, rows, sk)
    idx = lambda i, j: (i, j, 0)
    return pl.pallas_call(
        functools.partial(_causal_kernel, scale, rows, sq, sk),
        out_shape=pallas_config.out_struct(x.shape, x.dtype, x),
        grid=(b, sq // rows),
        in_specs=[pl.BlockSpec(blk, idx)],
        out_specs=pl.BlockSpec(blk, idx),
        interpret=pallas_config.interpret(),
    )(x)


# --------------------------------------------- k-blocked two-pass kernels
# Long-context rows (sk > _WHOLE_ROW_MAX_SK) never fit a whole fp32 row in
# VMEM, which is where fusion matters most (ref csrc/megatron/
# scaled_masked_softmax.h caps at 16k the same way and falls back to
# unfused torch). Two blocked passes: (1) online (max, sumexp) row stats
# over the k sweep, (2) normalize blockwise. x streams through VMEM twice;
# nothing of size [sq, sk] is ever resident.


def _causal_pos(bq, bk, qi, ki, off):
    row = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    col = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return col > row + off


def _stats_kernel(scale, bq, bk, off, causal, x_ref, mask_ref, m_ref, l_ref,
                  m_sc, l_sc):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        # -inf, not _MASK_FILL: a row whose true max is below the fill
        # value must still normalize (exp(-inf - m_new) == 0 is fine;
        # seeding with the fill value would zero the sum and divide by 0).
        m_sc[:] = jnp.full_like(m_sc, -jnp.inf)
        l_sc[:] = jnp.zeros_like(l_sc)

    xb = x_ref[0].astype(jnp.float32) * scale
    if causal:
        xb = jnp.where(_causal_pos(bq, bk, qi, ki, off), _MASK_FILL, xb)
    if mask_ref is not None:
        xb = jnp.where(mask_ref[0], _MASK_FILL, xb)
    m_prev = m_sc[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(xb, axis=-1))
    # m_new can be -inf while every element seen so far is -inf (additive
    # -inf masks reach this kernel); exp(-inf - -inf) = NaN, so shift by a
    # finite stand-in — all exps are exactly 0 then and l stays 0.
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    l_sc[:, 0] = (l_sc[:, 0] * jnp.exp(m_prev - m_safe)
                  + jnp.sum(jnp.exp(xb - m_safe[:, None]), axis=-1))
    m_sc[:, 0] = m_new

    @pl.when(ki == nk - 1)
    def _finish():
        m_ref[0] = m_sc[:, 0]
        l_ref[0] = l_sc[:, 0]


def _apply_kernel(scale, bq, bk, off, causal, x_ref, mask_ref, m_ref, l_ref,
                  y_ref):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    xb = x_ref[0].astype(jnp.float32) * scale
    if causal:
        xb = jnp.where(_causal_pos(bq, bk, qi, ki, off), _MASK_FILL, xb)
    if mask_ref is not None:
        xb = jnp.where(mask_ref[0], _MASK_FILL, xb)
    y = jnp.exp(xb - m_ref[0][:, None]) / l_ref[0][:, None]
    y_ref[0] = y.astype(y_ref.dtype)


def _pallas_blocked(x, mask, scale, causal):
    """Shared two-pass driver; ``mask`` broadcast to x's shape or None."""
    b, sq, sk = x.shape
    bk_target = _blocked_bk(sk)
    bq = _largest_divisor(sq, max(8, _VMEM_ROW_BUDGET // (4 * bk_target)))
    bk = _largest_divisor(sk, bk_target)
    off = sk - sq
    grid = (b, sq // bq, sk // bk)
    xspec = pl.BlockSpec((1, bq, bk), lambda i, j, k: (i, j, k))
    rowspec = pl.BlockSpec((1, bq), lambda i, j, k: (i, j))
    in_specs = [xspec]
    args = (x,)
    if mask is not None:
        in_specs.append(xspec)
        args = (x, mask)

    def with_mask(kernel):
        if mask is not None:
            return kernel
        return lambda x_ref, *rest: kernel(x_ref, None, *rest)

    m, l = pl.pallas_call(
        with_mask(functools.partial(_stats_kernel, scale, bq, bk, off,
                                    causal)),
        grid=grid,
        in_specs=in_specs,
        out_specs=[rowspec, rowspec],
        out_shape=[pallas_config.out_struct((b, sq), jnp.float32, *args)] * 2,
        scratch_shapes=[pltpu.VMEM((bq, 1), jnp.float32)] * 2,
        interpret=pallas_config.interpret(),
    )(*args)
    return pl.pallas_call(
        with_mask(functools.partial(_apply_kernel, scale, bq, bk, off,
                                    causal)),
        grid=grid,
        in_specs=in_specs + [rowspec, rowspec],
        out_specs=xspec,
        out_shape=pallas_config.out_struct(x.shape, x.dtype, *args, m, l),
        interpret=pallas_config.interpret(),
    )(*args, m, l)


def _pallas_causal_blocked(x, scale):
    return _pallas_blocked(x, None, scale, causal=True)


def _pallas_masked(x, mask, scale):
    mask = jnp.broadcast_to(mask, x.shape)
    lead = x.shape[:-2]
    sq, sk = x.shape[-2:]
    x3 = x.reshape((-1, sq, sk))
    mask3 = mask.reshape((-1, sq, sk))
    if sk > _WHOLE_ROW_MAX_SK:
        out = _pallas_blocked(x3, mask3, scale, causal=False)
        return out.reshape(lead + (sq, sk))
    rows = _pick_block_rows(sq, sk)
    blk = (1, rows, sk)
    idx = lambda i, j: (i, j, 0)
    out = pl.pallas_call(
        functools.partial(_masked_kernel, scale),
        out_shape=pallas_config.out_struct(x3.shape, x.dtype, x3, mask3),
        grid=(x3.shape[0], sq // rows),
        in_specs=[pl.BlockSpec(blk, idx), pl.BlockSpec(blk, idx)],
        out_specs=pl.BlockSpec(blk, idx),
        interpret=pallas_config.interpret(),
    )(x3, mask3)
    return out.reshape(lead + (sq, sk))


# -------------------------------------------------------------- custom vjp
# Pallas kernels are forward-only; the backward is the standard softmax vjp
# dx = scale · y · (g − Σ g·y), a row reduction XLA fuses. Saving only ``y``
# (not the masked pre-softmax logits) matches the CUDA kernels' backward
# (ref csrc/megatron/scaled_masked_softmax.h bwd reads softmax output).


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _causal_softmax(x, scale):
    if _pallas_ok(x.shape[-2], x.shape[-1]):
        return _pallas_causal(x, scale)
    xs = x.astype(jnp.float32) * scale
    mask = _causal_mask(xs.shape[-2], xs.shape[-1], xs.dtype)
    return _softmax_fp32(jnp.where(mask, _MASK_FILL, xs), x.dtype)


def _causal_softmax_fwd(x, scale):
    y = _causal_softmax(x, scale)
    return y, y


def _softmax_bwd_math(scale, y, g):
    y32 = y.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    inner = jnp.sum(g32 * y32, axis=-1, keepdims=True)
    return (scale * y32 * (g32 - inner)).astype(y.dtype)


def _causal_softmax_bwd(scale, y, g):
    return (_softmax_bwd_math(scale, y, g),)


_causal_softmax.defvjp(_causal_softmax_fwd, _causal_softmax_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _masked_softmax(x, mask, scale):
    if _pallas_ok(x.shape[-2], x.shape[-1]):
        return _pallas_masked(x, mask, scale)
    xs = x.astype(jnp.float32) * scale
    return _softmax_fp32(jnp.where(mask, _MASK_FILL, xs), x.dtype)


def _masked_softmax_fwd(x, mask, scale):
    y = _masked_softmax(x, mask, scale)
    return y, y


def _masked_softmax_bwd(scale, y, g):
    return (_softmax_bwd_math(scale, y, g), None)


_masked_softmax.defvjp(_masked_softmax_fwd, _masked_softmax_bwd)


# ------------------------------------------------------------------- public


def scaled_upper_triang_masked_softmax(inputs, _, scale: float = 1.0):
    """Causal scale+softmax on [attn_batches, sq, sk]
    (ref fused_softmax.py:53)."""
    return _causal_softmax(inputs, float(scale))


def scaled_masked_softmax(inputs, mask, scale: float = 1.0):
    """Mask-fill + scale + softmax on [b, np, sq, sk]; ``mask`` is boolean
    with True = masked (ref fused_softmax.py:94). ``mask=None`` is plain
    scaled softmax (ref ScaledSoftmax path)."""
    if mask is None:
        x = inputs.astype(jnp.float32) * scale
        return _softmax_fp32(x, inputs.dtype)
    return _masked_softmax(inputs, mask, float(scale))


class FusedScaleMaskSoftmax:
    """Dispatch wrapper (ref fused_softmax.py:101 FusedScaleMaskSoftmax).

    fusion flags are kept for parity; on TPU the fused path is always
    numerically identical to the unfused one, so the only dispatch that
    matters is causal (maskless kernel) vs padding (explicit mask).
    """

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = True,
        attn_mask_type: AttnMaskType = AttnMaskType.causal,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if input_in_fp16 and input_in_bf16:
            raise ValueError("both fp16 and bf16 flags are set")
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        if self.scale is not None and not self.softmax_in_fp32:
            raise ValueError("softmax should be in fp32 when scaled")

    def __call__(self, input, mask=None):
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            b, np_, sq, sk = input.shape
            if mask is None:
                out = scaled_upper_triang_masked_softmax(
                    input.reshape(b * np_, sq, sk), None, scale
                )
                return out.reshape(b, np_, sq, sk)
            # causal + padding: the triangle always applies (the reference's
            # causal kernel path never sees a mask; combining keeps both).
            mask = jnp.broadcast_to(mask, input.shape) | _causal_mask(
                sq, sk, input.dtype
            )
        if mask is not None and self.mask_func is not None:
            x = self.mask_func(input.astype(jnp.float32) * scale, mask)
            return _softmax_fp32(x, input.dtype)
        return scaled_masked_softmax(input, mask, scale)

    # parity helper (ref fused_softmax.py is_kernel_available)
    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        del mask, b, np_
        return _pallas_ok(sq, sk)

    @staticmethod
    def get_batch_per_block(sq, sk, b, np_):
        """ref fused_softmax.py get_batch_per_block — rows of the
        (b*np, sq, sk) batch one CUDA thread block handles. The Pallas
        analog is rows per kernel block: the grid tiles (rows, sq) and
        each program consumes a whole sk row, so the answer is the row
        tile — useful only for parity asserts, the TPU grid is chosen
        inside the kernels."""
        del sk, b, np_
        return max(1, min(128, sq))

    def forward_fused_softmax(self, input, mask=None):
        """ref fused_softmax.py:181 — force the fused (Pallas) path,
        like the reference forces its CUDA kernel; requires a TPU (or
        ``pallas_config.force('interpret')`` above this call in tests)."""
        from apex_tpu.ops import pallas_config

        mode = "interpret" if pallas_config.mode() == "interpret" else "on"
        with pallas_config.force(mode):
            return self(input, mask)

    def forward_torch_softmax(self, input, mask=None):
        """ref fused_softmax.py:186 — the unfused reference path (jnp
        fallback, named for parity with the torch implementation)."""
        from apex_tpu.ops import pallas_config

        with pallas_config.force("off"):
            return self(input, mask)
