"""KV-cache autoregressive decoding for the llama family.

No reference analog (apex is a training toolkit); provided because the
HF checkpoint import (models/convert.py) makes the model zoo hold real
weights, and the natural smoke test of real weights is sampling. The
design is decode-native rather than a re-run of the training forward:

- static shapes throughout: the cache is ``[L, b, max_len, nkv, d]``
  and a position mask (``idx <= pos``) replaces dynamic slicing, so the
  whole generation loop is ONE ``lax.scan`` under jit;
- prefill is a single full-sequence pass (flash attention) that also
  emits every layer's rotated k / v — the prompt costs one step, not
  one step per token;
- decode attends one query token against the cache with a plain fp32
  softmax (a [b, nq, max_len] score row — no S×S anything).

Greedy (``temperature=0``) or temperature sampling. Works on any
backend; sharded serving is out of scope (single-host batch decode).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models import llama as _llama
from apex_tpu.transformer.functional.rope import apply_rotary_qk

__all__ = ["greedy_generate", "generate", "gpt2_generate"]


def _split_heads(x, n, d):
    b, s, _ = x.shape
    return x.reshape(b, s, n, d)


def _layer_qkv(x, lp, cfg, positions):
    """Projections + rope for one (unstacked) layer on [b, s, h]."""
    d = cfg.head_dim
    q = _split_heads(jnp.matmul(x, lp["wq"].astype(x.dtype)),
                     cfg.num_heads, d)
    k = _split_heads(jnp.matmul(x, lp["wk"].astype(x.dtype)),
                     cfg.num_kv_heads, d)
    v = _split_heads(jnp.matmul(x, lp["wv"].astype(x.dtype)),
                     cfg.num_kv_heads, d)
    q, k = apply_rotary_qk(q, k, positions=positions, base=cfg.rope_theta)
    return q, k, v


def _decode_attention(q, k_cache, v_cache, pos):
    """q [b, 1, nq, d] vs cache [b, max_len, nkv, d], valid idx <= pos.

    GQA contracts grouped: q reshapes to [b, nkv, rep, d] (query head
    n = kv * rep + r) and both einsums run against the nkv-head cache
    directly, so the rep× cache copy a ``jnp.repeat`` to nq heads would
    materialize every decode step never exists. ``pos`` is a scalar for
    the batch-uniform generate()/gpt2 loops, or any shape broadcastable
    against [b, nq, max_len] (e.g. [b, 1, 1] per-row positions for the
    serving scheduler's packed batches).
    """
    b, _, nq, d = q.shape
    nkv = k_cache.shape[2]
    rep = nq // nkv
    qg = q.astype(jnp.float32).reshape(b, nkv, rep, d)
    scores = jnp.einsum("bkrd,btkd->bkrt", qg,
                        k_cache.astype(jnp.float32)) * (d ** -0.5)
    scores = scores.reshape(b, nq, -1)            # [b, nq, T]
    idx = jnp.arange(k_cache.shape[1])
    scores = jnp.where(idx[None, None, :] <= pos, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bkrt,btkd->bkrd", probs.reshape(b, nkv, rep, -1),
                   v_cache.astype(jnp.float32))
    return o.reshape(b, 1, nq * d)


def _moe_router_weights(xt, lp, cfg):
    """Top-k combine weights on [T, h] tokens, matching the training
    router's selection and normalization (transformer/moe.py
    router_gates) — minus the capacity drop, which is a training
    throughput artifact inference should never apply."""
    logits = jnp.matmul(xt.astype(jnp.float32),
                        lp["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    gate, idx = jax.lax.top_k(probs, cfg.moe_top_k)        # [T, k]
    if cfg.moe_top_k > 1:  # GShard/Mixtral renorm; top-1 keeps raw prob
        gate = gate / jnp.maximum(
            jnp.sum(gate, axis=-1, keepdims=True), 1e-9)
    return gate, idx


def _moe_decode_ffn(hm, lp, cfg):
    """Routed SwiGLU for ONE decode token per batch row ([b, 1, h]):
    gather the top-k experts' weights per token and run only those —
    at decode batch sizes the k weight gathers beat the training path's
    dispatch/combine einsums, and no token is ever capacity-dropped.
    Closes the MoE hole in generation (VERDICT r4 missing #3)."""
    b, _, h = hm.shape
    xt = hm.reshape(b, h)
    gate, idx = _moe_router_weights(xt, lp, cfg)
    wg = jnp.take(lp["wg"], idx, axis=0).astype(xt.dtype)  # [b, k, h, f]
    wu = jnp.take(lp["wu"], idx, axis=0).astype(xt.dtype)
    wd = jnp.take(lp["wd"], idx, axis=0).astype(xt.dtype)  # [b, k, f, h]
    g = jnp.einsum("bh,bkhf->bkf", xt, wg)
    u = jnp.einsum("bh,bkhf->bkf", xt, wu)
    y = jnp.einsum("bkf,bkfh->bkh", jax.nn.silu(g) * u, wd)
    out = jnp.einsum("bk,bkh->bh", gate.astype(xt.dtype), y)
    return out.reshape(b, 1, h)


def _moe_prefill_ffn(hm, lp, cfg):
    """Routed SwiGLU on the full prompt [b, s, h]: run EVERY expert on
    every token and mask with the combine weights. Exact (no capacity
    drops), static-shaped, MXU-friendly; compute-inflated by E/k vs the
    training dispatch — acceptable for a one-shot prefill pass."""
    b, s, h = hm.shape
    xt = hm.reshape(-1, h)
    gate, idx = _moe_router_weights(xt, lp, cfg)
    w = jnp.sum(jax.nn.one_hot(idx, cfg.num_experts, dtype=jnp.float32)
                * gate[..., None], axis=1)                 # [T, E]
    wg, wu = lp["wg"].astype(xt.dtype), lp["wu"].astype(xt.dtype)
    g = jnp.einsum("th,ehf->tef", xt, wg)
    u = jnp.einsum("th,ehf->tef", xt, wu)
    y = jnp.einsum("tef,efh->teh", jax.nn.silu(g) * u,
                   lp["wd"].astype(xt.dtype))
    out = jnp.einsum("te,teh->th", w.astype(xt.dtype), y)
    return out.reshape(b, s, h)


def _dense_ffn(hm, lp, dtype):
    g = jnp.matmul(hm, lp["wg"].astype(dtype))
    u = jnp.matmul(hm, lp["wu"].astype(dtype))
    return jnp.matmul(jax.nn.silu(g) * u, lp["wd"].astype(dtype))


def _decode_layer(x, lp, cfg, k_cache, v_cache, pos):
    """One decode step through one layer; returns (x, new_k, new_v)."""
    h = _llama._rmsnorm(x, lp["attn_norm"], cfg.rms_eps)
    q, k, v = _layer_qkv(h, lp, cfg,
                         positions=jnp.full((x.shape[0], 1), pos,
                                            jnp.int32))
    k_cache = jax.lax.dynamic_update_slice_in_dim(
        k_cache, k.astype(k_cache.dtype), pos, axis=1)
    v_cache = jax.lax.dynamic_update_slice_in_dim(
        v_cache, v.astype(v_cache.dtype), pos, axis=1)
    o = _decode_attention(q, k_cache, v_cache, pos).astype(x.dtype)
    x = x + jnp.matmul(o, lp["wo"].astype(x.dtype))
    hm = _llama._rmsnorm(x, lp["mlp_norm"], cfg.rms_eps)
    if cfg.moe:
        return x + _moe_decode_ffn(hm, lp, cfg), k_cache, v_cache
    return x + _dense_ffn(hm, lp, x.dtype), k_cache, v_cache


def _prefill_layer(x, lp, cfg, positions):
    """Full-sequence layer pass that also returns rotated k / v."""
    from apex_tpu.ops.flash_attention import flash_attention

    h = _llama._rmsnorm(x, lp["attn_norm"], cfg.rms_eps)
    q, k, v = _layer_qkv(h, lp, cfg, positions)
    o = flash_attention(q, k, v, causal=True, scale=cfg.head_dim ** -0.5)
    b, s = x.shape[:2]
    x = x + jnp.matmul(o.reshape(b, s, -1), lp["wo"].astype(x.dtype))
    hm = _llama._rmsnorm(x, lp["mlp_norm"], cfg.rms_eps)
    if cfg.moe:
        return x + _moe_prefill_ffn(hm, lp, cfg), k, v
    return x + _dense_ffn(hm, lp, x.dtype), k, v


def _logits(params, x, cfg):
    x = _llama._rmsnorm(x, params["final_norm"], cfg.rms_eps)
    w = _llama.lm_head_weight(params, cfg)
    return jnp.matmul(x, w.astype(x.dtype)).astype(jnp.float32)


def _sample(logits, temperature, key):
    if temperature:
        return jax.random.categorical(key, logits / temperature)
    return jnp.argmax(logits, axis=-1)


def _autoregress(embed_step, decode_layer_fn, logits_fn, layers,
                 k_cache, v_cache, logits0, prompt_tokens,
                 max_new_tokens, temperature, key):
    """The shared decode loop: max_new-1 scan steps, each consuming the
    previous token and emitting the next (the final token needs no
    decode pass)."""
    key, key0 = jax.random.split(key)
    first = _sample(logits0, temperature, key0)[:, None]

    def step(carry, key_t):
        token, kc, vc, pos = carry
        x = embed_step(token, pos)

        def body(h, layer):
            lp, k1, v1 = layer
            h, k1, v1 = decode_layer_fn(h, lp, k1, v1, pos)
            return h, (k1, v1)

        x, (kc, vc) = jax.lax.scan(body, x, (layers, kc, vc))
        nxt = _sample(logits_fn(x)[:, 0], temperature, key_t)
        return (nxt[:, None], kc, vc, pos + 1), nxt

    p = prompt_tokens.shape[1]
    keys = jax.random.split(key, max_new_tokens - 1)
    _, toks = jax.lax.scan(
        step, (first, k_cache, v_cache, jnp.int32(p)), keys)
    new = jnp.concatenate([first, toks.T], axis=1)  # [b, max_new]
    return jnp.concatenate([prompt_tokens, new], axis=1)


def _check_sampling_args(temperature, key):
    if temperature and key is None:
        raise ValueError("temperature sampling needs a PRNG key")
    return key if key is not None else jax.random.PRNGKey(0)


def generate(params, prompt_tokens, cfg, max_new_tokens: int,
             temperature: float = 0.0,
             key: Optional[jax.Array] = None):
    """Llama autoregressive decode: prompt [b, p] → tokens [b, p + new].

    Greedy at ``temperature=0`` (default); otherwise softmax sampling
    with ``key``. The prompt must be dense (no padding); cache length is
    ``p + max_new_tokens``. MoE configs route every token through its
    top-k experts with NO capacity drop (the training path's drops are a
    throughput artifact, not an inference semantic).
    """
    b, p = prompt_tokens.shape
    key = _check_sampling_args(temperature, key)

    # ---- prefill: one full pass, caches for every layer
    positions = jnp.broadcast_to(jnp.arange(p), (b, p))
    x = _llama.embed(params, prompt_tokens, cfg, tp_axis=None)

    def pre_body(h, lp):
        h, k, v = _prefill_layer(h, lp, cfg, positions)
        return h, (k, v)

    x, (ks, vs) = jax.lax.scan(pre_body, x, params["layers"])
    pad = [(0, 0), (0, 0), (0, max_new_tokens), (0, 0), (0, 0)]
    k_cache = jnp.pad(ks.astype(cfg.dtype), pad)  # [L, b, max_len, ...]
    v_cache = jnp.pad(vs.astype(cfg.dtype), pad)
    logits0 = _logits(params, x[:, -1:], cfg)[:, 0]

    return _autoregress(
        lambda token, pos: _llama.embed(params, token, cfg, tp_axis=None),
        lambda h, lp, kc, vc, pos: _decode_layer(h, lp, cfg, kc, vc, pos),
        lambda x: _logits(params, x, cfg),
        params["layers"], k_cache, v_cache, logits0, prompt_tokens,
        max_new_tokens, temperature, key)


def greedy_generate(params, prompt_tokens, cfg, max_new_tokens: int):
    return generate(params, prompt_tokens, cfg, max_new_tokens,
                    temperature=0.0)


# ------------------------------------------------------------------- gpt2


def _gpt2_qkv(x, lp, cfg):
    from apex_tpu.models import gpt2 as _gpt2

    b, s, h = x.shape
    n, d = cfg.num_heads, cfg.head_dim
    qkv = (jnp.matmul(x, lp["wqkv"].reshape(h, -1).astype(x.dtype))
           + lp["bqkv"].reshape(-1))
    q, k, v = jnp.split(qkv, 3, axis=-1)
    return (q.reshape(b, s, n, d), k.reshape(b, s, n, d),
            v.reshape(b, s, n, d))


def _gpt2_mlp(x, lp):
    y = jnp.matmul(x, lp["wfc"].astype(x.dtype)) + lp["bfc"]
    y = jax.nn.gelu(y, approximate=True)
    return jnp.matmul(y, lp["wproj"].astype(x.dtype)) + lp["bproj"]


def _gpt2_prefill_layer(x, lp, cfg):
    from apex_tpu.models._common import layer_norm as _ln
    from apex_tpu.ops.flash_attention import flash_attention

    b, s = x.shape[:2]
    h = _ln(x, lp["ln1_w"], lp["ln1_b"], cfg.ln_eps)
    q, k, v = _gpt2_qkv(h, lp, cfg)
    o = flash_attention(q, k, v, causal=True, scale=cfg.head_dim ** -0.5)
    x = x + (jnp.matmul(o.reshape(b, s, -1), lp["wo"].astype(x.dtype))
             + lp["bo"])
    h = _ln(x, lp["ln2_w"], lp["ln2_b"], cfg.ln_eps)
    return x + _gpt2_mlp(h, lp), k, v


def _gpt2_decode_layer(x, lp, cfg, k_cache, v_cache, pos):
    from apex_tpu.models._common import layer_norm as _ln

    h = _ln(x, lp["ln1_w"], lp["ln1_b"], cfg.ln_eps)
    q, k, v = _gpt2_qkv(h, lp, cfg)
    k_cache = jax.lax.dynamic_update_slice_in_dim(
        k_cache, k.astype(k_cache.dtype), pos, axis=1)
    v_cache = jax.lax.dynamic_update_slice_in_dim(
        v_cache, v.astype(v_cache.dtype), pos, axis=1)
    o = _decode_attention(q, k_cache, v_cache, pos).astype(x.dtype)
    x = x + jnp.matmul(o, lp["wo"].astype(x.dtype)) + lp["bo"]
    h = _ln(x, lp["ln2_w"], lp["ln2_b"], cfg.ln_eps)
    return x + _gpt2_mlp(h, lp), k_cache, v_cache


def gpt2_generate(params, prompt_tokens, cfg, max_new_tokens: int,
                  temperature: float = 0.0,
                  key: Optional[jax.Array] = None):
    """GPT-2 decode (learned positions, packed qkv, tied head)."""
    from apex_tpu.models._common import layer_norm as _ln

    b, p = prompt_tokens.shape
    max_len = p + max_new_tokens
    if max_len > cfg.max_seq_len:
        raise ValueError(f"prompt + new tokens ({max_len}) exceeds "
                         f"max_seq_len {cfg.max_seq_len}")
    key = _check_sampling_args(temperature, key)

    def embed(tokens, pos0):
        x = jnp.take(params["embed"], tokens, axis=0)
        s = tokens.shape[1]
        wpe = jax.lax.dynamic_slice_in_dim(params["pos_embed"], pos0, s)
        return (x + wpe[None]).astype(cfg.dtype)

    def logits_fn(x):
        x = _ln(x, params["lnf_w"], params["lnf_b"], cfg.ln_eps)
        return jnp.matmul(
            x, params["embed"].T.astype(x.dtype)).astype(jnp.float32)

    x = embed(prompt_tokens, 0)

    def pre_body(h, lp):
        h, k, v = _gpt2_prefill_layer(h, lp, cfg)
        return h, (k, v)

    x, (ks, vs) = jax.lax.scan(pre_body, x, params["layers"])
    pad = [(0, 0), (0, 0), (0, max_new_tokens), (0, 0), (0, 0)]
    k_cache = jnp.pad(ks.astype(cfg.dtype), pad)
    v_cache = jnp.pad(vs.astype(cfg.dtype), pad)
    logits0 = logits_fn(x[:, -1:])[:, 0]

    return _autoregress(
        lambda token, pos: embed(token, pos),
        lambda h, lp, kc, vc, pos: _gpt2_decode_layer(h, lp, cfg, kc, vc,
                                                      pos),
        logits_fn, params["layers"], k_cache, v_cache, logits0,
        prompt_tokens, max_new_tokens, temperature, key)
