"""Shared model-zoo scaffolding: init helpers and the BatchNorm switch."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


def fan_in_normal(key, *shape, fan_in=None, dtype=jnp.float32):
    """N(0, 1/fan_in) init (fan_in defaults to the second-to-last dim)."""
    scale = (fan_in if fan_in is not None else shape[-2]) ** -0.5
    return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)


class BatchNorm(nn.Module):
    """Plain flax BatchNorm or cross-replica :class:`SyncBatchNorm`.

    ``momentum`` uses the flax convention (fraction of the running stat
    KEPT each step); SyncBatchNorm follows the torch convention (fraction
    REPLACED, ref apex/parallel/sync_batchnorm.py), so it gets ``1 - m`` —
    the same inversion ``convert_syncbn_model`` applies.
    """

    sync: bool = False
    axis_name: Optional[str] = "data"
    momentum: float = 0.9
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool):
        if self.sync:
            return SyncBatchNorm(momentum=1.0 - self.momentum, eps=self.eps,
                                 axis_name=self.axis_name)(
                x, use_running_average=not train)
        return nn.BatchNorm(use_running_average=not train,
                            momentum=self.momentum, epsilon=self.eps,
                            dtype=x.dtype)(x)
