"""Llama model family (flagship) — TP/SP/CP/PP-composable functional model.

Role in the framework: the reference (NVIDIA Apex) ships no model zoo, but
its headline benchmarks run Megatron-style transformers built from its
primitives (ColumnParallelLinear/RowParallelLinear, FusedRMSNorm, fused
softmax/RoPE — ref apex/transformer/tensor_parallel/layers.py,
apex/normalization/fused_layer_norm.py, apex/transformer/functional/).
This module is the TPU-native assembly of those same primitives into the
Llama-3 architecture (RMSNorm pre-norm, SwiGLU, GQA, RoPE).

Design: pure-functional param pytrees with stacked per-layer weights
([L, ...] leading dim, consumed by ``lax.scan``) so the whole depth compiles
as one rolled loop (fast compile, remat-friendly). Every collective degrades
to a no-op when its mesh axis is unbound, so the SAME code runs single-chip,
under tp-only shard_map, and as one pipeline stage:

- tp:   column/row-parallel projections, vocab-parallel embedding + CE
- sp:   ``sequence_parallel=True`` switches tp collectives to
        reduce_scatter/all_gather over the sequence dim
- cp:   ring attention over the 'cp' axis; RoPE uses global positions
- pp:   :func:`stage_fn` applies a contiguous slice of layers — feed it to
        ``pipeline_parallel.schedules``
- ep:   ``num_experts > 0`` swaps the dense SwiGLU MLP for Mixtral-style
        top-k routed experts (apex_tpu.transformer.moe); experts shard
        over the 'ep' axis, the router replicates. The load-balancing aux
        loss is returned by :func:`loss_fn`; the pipeline ``stage_fn``
        path drops it (documented — activations are the only pp payload).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models._common import fan_in_normal

from apex_tpu.normalization.fused_layer_norm import fused_rms_norm_affine
from apex_tpu.transformer.context_parallel import (
    context_parallel_positions,
    ring_attention,
)
from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.transformer.functional.rope import apply_rotary_qk
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    _axis_bound,
    gather_from_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    tie_embeddings: bool = False
    # Mixtral-style MoE: 0 = dense SwiGLU; >0 routes tokens through that
    # many SwiGLU experts (top-k, capacity-dropped) over the 'ep' axis
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25

    @property
    def moe(self) -> bool:
        return self.num_experts > 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def llama3_8b(**over) -> LlamaConfig:
    return LlamaConfig(**over)


def flagship_0p9b(**over) -> LlamaConfig:
    """The single-chip benchmark config (bench.py's Llama MFU model and
    tools/tpu_profile.py's traced model — one definition so the profile
    always explains the bench number)."""
    kw = dict(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
              num_layers=8, num_heads=16, num_kv_heads=8, max_seq_len=2048,
              dtype=jnp.bfloat16)
    kw.update(over)
    return LlamaConfig(**kw)


def tiny(**over) -> LlamaConfig:
    """Test-scale config (tp/cp-divisible heads)."""
    kw = dict(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128, dtype=jnp.float32,
    )
    kw.update(over)
    return LlamaConfig(**kw)


def init_params(key, cfg: LlamaConfig):
    """Full (unsharded) parameter pytree; layer weights stacked on dim 0.

    Shard for tp with ``P(None, 'tp')`` on column kernels (wq/wk/wv/wg/wu),
    ``P(None, 'tp', None)`` on row kernels' input dim (wo/wd), ``P('tp',)``
    on the embedding's vocab dim and the lm head's output dim.
    """
    h, i, d = cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
    nq, nkv, L = cfg.num_heads, cfg.num_kv_heads, cfg.num_layers
    dt = cfg.dtype

    ks = jax.random.split(key, 10)

    def norm(k, *shape, fan_in=None):
        return fan_in_normal(k, *shape, fan_in=fan_in, dtype=dt)

    layers = {
        "attn_norm": jnp.ones((L, h), dt),
        "wq": norm(ks[1], L, h, nq * d),
        "wk": norm(ks[2], L, h, nkv * d),
        "wv": norm(ks[3], L, h, nkv * d),
        "wo": norm(ks[4], L, nq * d, h),
        "mlp_norm": jnp.ones((L, h), dt),
    }
    if cfg.moe:
        E = cfg.num_experts
        layers.update({
            "router": (jax.random.normal(ks[9], (L, h, E)) * 0.02
                       ).astype(dt),
            "wg": norm(ks[5], L, E, h, i),
            "wu": norm(ks[6], L, E, h, i),
            "wd": norm(ks[7], L, E, i, h),
        })
    else:
        layers.update({
            "wg": norm(ks[5], L, h, i),
            "wu": norm(ks[6], L, h, i),
            "wd": norm(ks[7], L, i, h),
        })
    params = {
        "embed": norm(ks[0], cfg.vocab_size, h, fan_in=h),
        "layers": layers,
        "final_norm": jnp.ones((h,), dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(ks[8], h, cfg.vocab_size, fan_in=h)
    return params


def _rmsnorm(x, w, eps):
    return fused_rms_norm_affine(x, w, (x.shape[-1],), eps=eps)


def _attention(x, lp, cfg: LlamaConfig, positions, tp_axis, cp_axis,
               sequence_parallel):
    """GQA attention on [b, s_local, h]; q/k/v heads tp-sharded, sequence
    cp-sharded (ring attention when 'cp' is bound)."""
    b = x.shape[0]
    d = cfg.head_dim
    tp = jax.lax.axis_size(tp_axis) if _axis_bound(tp_axis) else 1
    if cfg.num_heads % tp or cfg.num_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_heads={cfg.num_heads} and "
            f"num_kv_heads={cfg.num_kv_heads}")
    nq, nkv = cfg.num_heads // tp, cfg.num_kv_heads // tp

    # x arrives sequence-FULL (decoder_layer gathers once in sp mode), so
    # the qkv projections never re-gather.
    q = column_parallel_linear(x, lp["wq"], gather_output=False,
                               axis_name=tp_axis)
    k = column_parallel_linear(x, lp["wk"], gather_output=False,
                               axis_name=tp_axis)
    v = column_parallel_linear(x, lp["wv"], gather_output=False,
                               axis_name=tp_axis)
    s_full = q.shape[1]
    q = q.reshape(b, s_full, nq, d)
    k = k.reshape(b, s_full, nkv, d)
    v = v.reshape(b, s_full, nkv, d)

    q, k = apply_rotary_qk(q, k, positions=positions, base=cfg.rope_theta)

    if _axis_bound(cp_axis):
        # ring_attention is GQA-aware: k/v circulate at nkv heads
        o = ring_attention(q, k, v, axis_name=cp_axis, causal=True)
    else:
        # GQA-aware flash attention: online softmax, no [s, s] matrix in
        # HBM fwd or bwd (jnp fallback off-TPU is the same math)
        o = flash_attention(q, k, v, causal=True, scale=d ** -0.5)

    o = o.reshape(b, s_full, nq * d)
    return row_parallel_linear(o, lp["wo"], input_is_parallel=True,
                               sequence_parallel_enabled=sequence_parallel,
                               axis_name=tp_axis, seq_dim=1)


def _mlp(x, lp, tp_axis, sequence_parallel):
    # x arrives sequence-full (see decoder_layer); no per-gemm gather.
    g = column_parallel_linear(x, lp["wg"], gather_output=False,
                               axis_name=tp_axis)
    u = column_parallel_linear(x, lp["wu"], gather_output=False,
                               axis_name=tp_axis)
    return row_parallel_linear(jax.nn.silu(g) * u, lp["wd"],
                               input_is_parallel=True,
                               sequence_parallel_enabled=sequence_parallel,
                               axis_name=tp_axis, seq_dim=1)


def _moe_cfg(cfg: LlamaConfig):
    from apex_tpu.transformer.moe import MoEConfig

    return MoEConfig(hidden_size=cfg.hidden_size,
                     ffn_hidden_size=cfg.intermediate_size,
                     num_experts=cfg.num_experts, top_k=cfg.moe_top_k,
                     capacity_factor=cfg.moe_capacity_factor)


def _moe_mlp(x, lp, cfg: LlamaConfig, ep_axis, tp_axis, sequence_parallel):
    """Mixtral-style routed SwiGLU experts in place of the dense MLP.

    x arrives sequence-full and tp-replicated (every tp rank computes the
    same routing — experts shard over 'ep', orthogonal to tp; grads of the
    expert weights are therefore tp-identical). Returns (y, aux); in sp
    mode y is scattered back to the sequence-sharded stream.
    """
    from apex_tpu.transformer.moe import expert_parallel_apply

    def expert_fn(p, tokens):  # [E_local, C', h] -> [E_local, C', h]
        g = jnp.einsum("ech,ehf->ecf", tokens,
                       p["wg"].astype(tokens.dtype))
        u = jnp.einsum("ech,ehf->ecf", tokens,
                       p["wu"].astype(tokens.dtype))
        return jnp.einsum("ecf,efh->ech", jax.nn.silu(g) * u,
                          p["wd"].astype(tokens.dtype))

    y, aux = expert_parallel_apply(
        expert_fn, {"wg": lp["wg"], "wu": lp["wu"], "wd": lp["wd"]}, x,
        lp["router"], _moe_cfg(cfg), ep_axis=ep_axis)
    if sequence_parallel:
        y = scatter_to_sequence_parallel_region(y, tp_axis, seq_dim=1)
    return y, aux


def decoder_layer(x, lp, cfg: LlamaConfig, positions,
                  tp_axis: Optional[str] = "tp",
                  cp_axis: Optional[str] = "cp",
                  sequence_parallel: bool = False,
                  ep_axis: Optional[str] = "ep"):
    """One pre-norm block on a single layer's (unstacked) params ``lp``.
    Returns ``(x, aux)`` — aux is the MoE load-balancing loss (0 dense).

    In sp mode the residual stream (and the norms) stay sequence-sharded;
    each half-block all-gathers the normed input ONCE for its column gemms
    and reduce-scatters the row-gemm output (Megatron sequence-parallel
    comm pattern: 2 gathers + 2 scatters per layer, not one per gemm).
    """

    def to_full(h):
        if sequence_parallel:
            return gather_from_sequence_parallel_region(h, tp_axis, seq_dim=1)
        return h

    h = to_full(_rmsnorm(x, lp["attn_norm"], cfg.rms_eps))
    x = x + _attention(h, lp, cfg, positions, tp_axis, cp_axis,
                       sequence_parallel)
    h = to_full(_rmsnorm(x, lp["mlp_norm"], cfg.rms_eps))
    if cfg.moe:
        y, aux = _moe_mlp(h, lp, cfg, ep_axis, tp_axis, sequence_parallel)
    else:
        y, aux = _mlp(h, lp, tp_axis, sequence_parallel), jnp.zeros(
            (), jnp.float32)
    return x + y, aux


def _positions(b, s_local, cp_axis):
    if _axis_bound(cp_axis):
        pos = context_parallel_positions(s_local, cp_axis)
    else:
        pos = jnp.arange(s_local)
    return jnp.broadcast_to(pos[None, :], (b, s_local))


def run_layers(x, stacked, cfg: LlamaConfig, positions,
               tp_axis="tp", cp_axis="cp", sequence_parallel=False,
               remat=True, ep_axis: Optional[str] = "ep"):
    """Scan a stacked [L, ...] layer pytree over the residual stream.
    Returns ``(x, aux)`` — aux sums the per-layer MoE balance losses.

    ``remat``: False = save all activations; True = full per-layer
    recompute; ``"dots"`` = recompute only elementwise/norm chains while
    keeping matmul outputs resident
    (``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``) — the
    usual best memory/MFU trade on TPU, where the recompute that hurts is
    the MXU work, not the VPU chains."""

    def body(h, lp):
        # aux rides the scan's stacked outputs, not the carry — a fresh
        # zero carry would need its vma hand-matched under shard_map
        return decoder_layer(h, lp, cfg, positions, tp_axis, cp_axis,
                             sequence_parallel, ep_axis)

    if cfg.moe and _axis_bound(ep_axis):
        # the MoE all_to_all makes the stream ep-varying; the carry must
        # start that way or the scan's vma check trips
        from apex_tpu.transformer.tensor_parallel.mappings import (
            _to_varying,
        )

        x = _to_varying(x, ep_axis)
    if remat:
        policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                  if remat == "dots" else None)
        body = jax.checkpoint(body, policy=policy)
    x, auxs = jax.lax.scan(body, x, stacked)
    return x, jnp.sum(auxs)


def embed(params, tokens, cfg: LlamaConfig, tp_axis="tp",
          sequence_parallel=False):
    x = vocab_parallel_embedding(tokens, params["embed"], axis_name=tp_axis)
    x = x.astype(cfg.dtype)
    if sequence_parallel:
        x = scatter_to_sequence_parallel_region(x, tp_axis, seq_dim=1)
    return x


def lm_head_weight(params, cfg: LlamaConfig):
    """The [h, vocab] classifier kernel (embed.T when tied)."""
    return params["embed"].T if cfg.tie_embeddings else params["lm_head"]


def lm_head(params, x, cfg: LlamaConfig, tp_axis="tp",
            sequence_parallel=False):
    """Final norm + vocab-sharded logits [b, s, vocab/tp] (fp32)."""
    if sequence_parallel:
        x = gather_from_sequence_parallel_region(x, tp_axis, seq_dim=1)
    x = _rmsnorm(x, params["final_norm"], cfg.rms_eps)
    w = lm_head_weight(params, cfg)
    # vocab-sharded output: plain local gemm, no gather (CE is
    # vocab-parallel). Routed through the amp-aware hook: under the O4
    # fp8 context the registered "lm_head" site runs the E4M3/E5M2
    # delayed-scaling epilogue (the biggest single matmul in the step);
    # everywhere else this is the same fp32-accum gemm as before.
    from apex_tpu.ops.precision import matmul_amp

    return matmul_amp(x, w.astype(x.dtype),
                      name="lm_head").astype(jnp.float32)


def hidden_states(params, tokens, cfg: LlamaConfig,
                  tp_axis: Optional[str] = "tp",
                  cp_axis: Optional[str] = "cp",
                  sequence_parallel: bool = False, remat: bool = True,
                  ep_axis: Optional[str] = "ep"):
    """The shared model trunk: embed + all decoder layers (pre-final-norm).
    tokens [b, s_local] → (hidden [b, s_local, h], moe aux loss). Both
    loss paths (lm_head logits, chunked CE) consume this, so model
    changes land in each exactly once."""
    b, s = tokens.shape
    positions = _positions(b, s, cp_axis)
    x = embed(params, tokens, cfg, tp_axis, sequence_parallel)
    return run_layers(x, params["layers"], cfg, positions, tp_axis,
                      cp_axis, sequence_parallel, remat, ep_axis)


def forward_with_aux(params, tokens, cfg: LlamaConfig,
                     tp_axis: Optional[str] = "tp",
                     cp_axis: Optional[str] = "cp",
                     sequence_parallel: bool = False, remat: bool = True,
                     ep_axis: Optional[str] = "ep"):
    """tokens [b, s_local] → (vocab-sharded logits, moe aux loss)."""
    x, aux = hidden_states(params, tokens, cfg, tp_axis, cp_axis,
                           sequence_parallel, remat, ep_axis)
    return lm_head(params, x, cfg, tp_axis, sequence_parallel), aux


def forward(params, tokens, cfg: LlamaConfig,
            tp_axis: Optional[str] = "tp", cp_axis: Optional[str] = "cp",
            sequence_parallel: bool = False, remat: bool = True,
            ep_axis: Optional[str] = "ep"):
    """tokens [b, s_local] → vocab-sharded logits [b, s_local, v_local]."""
    return forward_with_aux(params, tokens, cfg, tp_axis, cp_axis,
                            sequence_parallel, remat, ep_axis)[0]


def loss_fn(params, batch, cfg: LlamaConfig,
            tp_axis: Optional[str] = "tp", cp_axis: Optional[str] = "cp",
            sequence_parallel: bool = False, remat: bool = True,
            ep_axis: Optional[str] = "ep",
            vocab_chunks: Optional[int] = None):
    """Next-token CE (+ MoE balance aux when cfg.moe);
    ``batch = (tokens, targets)`` both [b, s_local].

    ``vocab_chunks``: stream the lm-head + CE in that many vocab slices
    so the fp32 ``[b·s, vocab]`` logits — the largest live buffer of an
    LLM step — are never materialized (functional/chunked_ce.py). With a
    bound ``tp_axis`` the per-rank streams merge vocab-parallel."""
    tokens, targets = batch
    if vocab_chunks:
        from apex_tpu.transformer.functional.chunked_ce import (
            chunked_lm_cross_entropy,
        )

        x, aux = hidden_states(params, tokens, cfg, tp_axis, cp_axis,
                               sequence_parallel, remat, ep_axis)
        if sequence_parallel:
            x = gather_from_sequence_parallel_region(x, tp_axis, seq_dim=1)
        x = _rmsnorm(x, params["final_norm"], cfg.rms_eps)
        losses = chunked_lm_cross_entropy(
            x.reshape(-1, x.shape[-1]), lm_head_weight(params, cfg),
            targets.reshape(-1), vocab_chunks,
            tp_axis=tp_axis if _axis_bound(tp_axis) else None)
        return jnp.mean(losses) + aux
    logits, aux = forward_with_aux(params, tokens, cfg, tp_axis, cp_axis,
                                   sequence_parallel, remat, ep_axis)
    losses = vocab_parallel_cross_entropy(logits, targets, axis_name=tp_axis)
    return jnp.mean(losses) + aux


def param_specs(cfg: LlamaConfig, tp_axis: str = "tp",
                ep_axis: str = "ep"):
    """PartitionSpec pytree matching :func:`init_params` (tp sharding):
    column kernels split the output dim, row kernels the input dim, the
    embedding/head split the vocab dim, norms replicate."""
    from jax.sharding import PartitionSpec as P

    t = tp_axis
    layer_specs = {
        "attn_norm": P(), "mlp_norm": P(),
        "wq": P(None, None, t), "wk": P(None, None, t),
        "wv": P(None, None, t), "wo": P(None, t, None),
    }
    if cfg.moe:
        # experts shard over ep_axis (orthogonal to tp); router replicates
        e = ep_axis
        layer_specs.update({
            "router": P(),
            "wg": P(None, e, None, None),
            "wu": P(None, e, None, None),
            "wd": P(None, e, None, None),
        })
    else:
        layer_specs.update({
            "wg": P(None, None, t), "wu": P(None, None, t),
            "wd": P(None, t, None),
        })
    specs = {
        "embed": P(t, None),
        "layers": layer_specs,
        "final_norm": P(),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, t)
    return specs


# ------------------------------------------------------------- pipeline view


def stage_fn(stage_params, x, cfg: LlamaConfig, positions,
             tp_axis="tp", cp_axis=None, sequence_parallel=False,
             ep_axis: Optional[str] = "ep"):
    """Apply one pipeline stage's stacked layer slice to the residual
    stream — plug into ``pipeline_parallel.schedules`` (embedding/head live
    outside via :func:`embed`/:func:`lm_head` on the first/last stage).
    The MoE aux loss is dropped here: the pipeline transports activations
    only — train MoE stages with the aux folded in via :func:`loss_fn`
    style accounting outside pp, or accept routing without the balance
    regularizer under pp."""
    x, _ = run_layers(x, stage_params, cfg, positions, tp_axis, cp_axis,
                      sequence_parallel, remat=False, ep_axis=ep_axis)
    return x


def split_stages(params, n_stages: int):
    """Reshape stacked [L, ...] layers into [n_stages, L/n_stages, ...] for
    ``shard_map`` with ``in_specs=P('pp', ...)``."""
    def r(x):
        return x.reshape(n_stages, x.shape[0] // n_stages, *x.shape[1:])

    return jax.tree_util.tree_map(r, params["layers"])
