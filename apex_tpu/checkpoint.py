"""Checkpoint/resume (SURVEY.md §5): orbax-backed save/restore of
params + optimizer state + amp/loss-scaler state + RNG.

The reference has no checkpoint layer of its own (torch.save in examples,
plus ``amp.state_dict()`` — ref apex/amp/frontend.py state_dict); here the
whole training state round-trips through one API, sharding-aware via orbax
(restores land on the same Mesh/PartitionSpec layout they were saved from).

Async saves (``AsyncCheckpointWriter`` / ``CheckpointManager(
async_save=True)``) copy device arrays to host, then write in a
background thread while the TPU keeps training — on a chip whose step
time is milliseconds, a blocking multi-GB write is the difference
between checkpointing every 15 minutes and every minute.

Durability protocol (ISSUE 5): every save is *atomic* — data lands in
``step_XXXXXXXX.tmp``, a commit marker (``_APEX_COMMIT.json``: a file
manifest with sizes + crc32 checksums) is written inside, and the tmp
dir is renamed to its final name. A process killed mid-write leaves only
a ``.tmp`` dir, which :func:`latest_valid_step` ignores and
:func:`gc_partial_checkpoints` removes — ``restore`` can never pick up a
torn write. :mod:`apex_tpu.resilience` injects simulated write failures
through the module-level ``_FAULT_HOOK`` so the failure paths are
testable on CPU.

Manifest format 2 (ISSUE 18): saves additionally record a *semantic*
``state_schema`` block — treedef, per-leaf path/shape/dtype/
PartitionSpec, and a fingerprint over the lot
(:func:`state_schema_of`) — so the static state-flow engine
(:mod:`apex_tpu.analysis.state_checks`) can prove, without opening the
arrays, that the code about to restore this checkpoint agrees with
what was saved (``ckpt-schema-drift``). Format-1 markers (no schema)
remain fully valid: :func:`validate_step_dir`, restore and GC never
look at the block, and format-2 markers are read fine by format-1-era
code because validation only consumes ``files``.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import zlib
from typing import Any, Optional

import jax

#: Name of the commit marker written inside every committed step dir.
COMMIT_MARKER = "_APEX_COMMIT.json"

#: Suffix of in-flight (uncommitted) step dirs.
TMP_SUFFIX = ".tmp"

# Fault-injection hook (set by apex_tpu.resilience.faults injectors):
# called as hook(stage, step, path) at "pre_write" (before any data is
# written — the ENOSPC point) and "pre_commit" (after the data, before
# the marker + rename — the torn-write point). Raising aborts the save
# exactly where a real kill/disk-full would.
_FAULT_HOOK = None


def _fault_point(stage: str, step, path: str) -> None:
    hook = _FAULT_HOOK
    if hook is not None:
        hook(stage, step, path)


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def _step_dirname(step: int) -> str:
    return f"step_{step:08d}"


# --------------------------------------------------------------- manifest

def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def build_manifest(dirpath: str) -> dict:
    """File manifest of a checkpoint dir: relpath -> {size, crc32}.
    The commit marker itself is excluded (it is written after)."""
    files = {}
    for root, _dirs, names in os.walk(dirpath):
        for name in sorted(names):
            full = os.path.join(root, name)
            rel = os.path.relpath(full, dirpath)
            if rel == COMMIT_MARKER:
                continue
            files[rel] = {"size": os.path.getsize(full),
                          "crc32": _file_crc32(full)}
    return {"files": files}


def encode_spec(spec) -> Optional[list]:
    """JSON-native encoding of a PartitionSpec: one entry per dim,
    each ``None`` (replicated), an axis name, or a list of axis names.
    ``None`` in = ``None`` out (spec unknown, not replicated-everywhere
    — the state engine treats unknown as unshardable-on-dim-0)."""
    if spec is None:
        return None
    out = []
    for dim in tuple(spec):
        if dim is None:
            out.append(None)
        elif isinstance(dim, (tuple, list)):
            out.append([str(a) for a in dim])
        else:
            out.append(str(dim))
    return out


def schema_fingerprint(body: dict) -> str:
    """sha1 over the canonical JSON of the schema's treedef + leaves —
    one string two manifests (or a manifest and the code-derived
    schema) can compare without walking the leaf list."""
    import hashlib

    canon = json.dumps({"treedef": body.get("treedef"),
                        "leaves": body.get("leaves")},
                       sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(canon.encode()).hexdigest()


def state_schema_of(state: Any, specs: Optional[Any] = None) -> dict:
    """Semantic schema of a state pytree, as stored in the format-2
    commit marker: ``{"treedef", "leaves": [{path, shape, dtype, spec,
    kind}], "fingerprint"}``.

    ``specs``: optional PartitionSpec pytree matching ``state``; when
    absent each leaf's own ``.sharding.spec`` is used where available.
    ``kind`` tags leaves of the registered state constructors
    (``Zero1AdamState.mu`` etc.) via the state engine's constructor
    registry — best-effort, None when the analysis package is absent.
    """
    import numpy as np

    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    spec_flat = None
    if specs is not None:
        from jax.sharding import PartitionSpec

        spec_flat = jax.tree_util.tree_flatten(
            specs, is_leaf=lambda s: s is None
            or isinstance(s, PartitionSpec))[0]
        if len(spec_flat) != len(flat):
            raise ValueError(
                f"state_schema_of: specs pytree has {len(spec_flat)} "
                f"leaves, state has {len(flat)} — the trees diverged")
    try:
        from apex_tpu.analysis.state_checks import leaf_kinds

        kinds = leaf_kinds(state)
    except Exception:  # noqa: BLE001 — tags are optional decoration
        kinds = (None,) * len(flat)
    leaves = []
    for i, (kp, leaf) in enumerate(flat):
        if spec_flat is not None:
            spec = encode_spec(spec_flat[i])
        else:
            try:
                spec = encode_spec(leaf.sharding.spec)
            except Exception:  # noqa: BLE001 — host arrays, scalars
                spec = None
        dt = getattr(leaf, "dtype", None)
        dtype = np.dtype(dt if dt is not None
                         else np.asarray(leaf).dtype).name
        leaves.append({
            "path": jax.tree_util.keystr(kp),
            "shape": [int(d) for d in getattr(leaf, "shape", ())],
            "dtype": dtype,
            "spec": spec,
            "kind": kinds[i] if i < len(kinds) else None,
        })
    body = {"treedef": str(treedef), "leaves": leaves}
    body["fingerprint"] = schema_fingerprint(body)
    return body


def write_commit_marker(dirpath: str, step: Optional[int] = None,
                        state_schema: Optional[dict] = None) -> str:
    """Write the manifest/commit marker into ``dirpath`` (atomically
    within the dir: marker.part + rename). The marker is the LAST write
    of a checkpoint — its presence asserts every listed file landed.

    ``state_schema`` (a :func:`state_schema_of` dict) upgrades the
    marker to format 2; without it the format-1 payload is written
    byte-compatible with every earlier release."""
    payload = {"format": 1, "step": step, **build_manifest(dirpath)}
    if state_schema is not None:
        payload["format"] = 2
        payload["state_schema"] = state_schema
    marker = os.path.join(dirpath, COMMIT_MARKER)
    part = marker + ".part"
    with open(part, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(part, marker)
    return marker


def read_manifest(dirpath: str) -> Optional[dict]:
    """The commit-marker payload of ``dirpath``, or None when the dir
    has no (parseable) marker. Format-agnostic: returns whatever the
    marker holds."""
    marker = os.path.join(dirpath, COMMIT_MARKER)
    try:
        with open(marker) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def manifest_state_schema(dirpath: str) -> Optional[dict]:
    """The ``state_schema`` block of a step dir's commit marker, or
    None for format-1 (pre-schema) checkpoints and unmarked dirs — the
    state engine treats None as "nothing to compare", never as drift."""
    payload = read_manifest(dirpath)
    if payload is None:
        return None
    schema = payload.get("state_schema")
    return schema if isinstance(schema, dict) else None


def validate_step_dir(dirpath: str, deep: bool = False) -> bool:
    """Is ``dirpath`` a committed, intact checkpoint?

    Requires the commit marker, and every manifest file present with its
    recorded size; ``deep=True`` additionally re-checksums the files
    (crc32) — use for paranoid resume, skip for fast polling.
    """
    marker = os.path.join(dirpath, COMMIT_MARKER)
    try:
        with open(marker) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return False
    files = payload.get("files")
    if not isinstance(files, dict):
        return False
    for rel, meta in files.items():
        full = os.path.join(dirpath, rel)
        try:
            if os.path.getsize(full) != meta.get("size"):
                return False
            if deep and _file_crc32(full) != meta.get("crc32"):
                return False
        except OSError:
            return False
    return True


# ---------------------------------------------------------- dir scanning

def _committed_steps(path: str) -> dict:
    """{step: dirname} of committed (non-``.tmp``) step dirs."""
    steps = {}
    if not os.path.isdir(path):
        return steps
    for d in os.listdir(path):
        if not d.startswith("step_"):
            continue
        try:
            steps[int(d[5:])] = d
        except ValueError:
            # .tmp dirs, orbax in-flight temp dirs, anything non-numeric
            continue
    return steps


def latest_step(path: str) -> Optional[int]:
    """Largest committed ``step_*`` subdirectory, or None. Makes no
    validity claim — prefer :func:`latest_valid_step` for resume."""
    steps = _committed_steps(path)
    return max(steps) if steps else None


def valid_steps(path: str, deep: bool = False) -> list:
    """Ascending list of committed steps whose dirs validate."""
    return sorted(s for s, d in _committed_steps(path).items()
                  if validate_step_dir(os.path.join(path, d), deep=deep))


def latest_valid_step(path: str, deep: bool = False) -> Optional[int]:
    """Largest committed step with an intact commit marker/manifest, or
    None — the step auto-resume is allowed to trust."""
    steps = valid_steps(path, deep=deep)
    return steps[-1] if steps else None


def gc_partial_checkpoints(path: str, keep=()) -> list:
    """Remove torn-write leftovers under ``path``: ``step_*.tmp`` dirs,
    orbax in-flight temp dirs, and committed step dirs whose commit
    marker exists but no longer validates (corrupted/truncated data).

    Marker-less committed dirs are left alone — they may be checkpoints
    from a pre-marker writer, and deleting data this module did not
    provably write is not this function's call. ``keep``: path PREFIXES
    to spare — an in-flight async write, including orbax's own
    ``<path>.orbax-checkpoint-tmp-*`` staging dirs for it. Returns the
    removed paths.
    """
    removed = []
    if not os.path.isdir(path):
        return removed
    keep = tuple(os.path.abspath(k) for k in keep)
    for d in sorted(os.listdir(path)):
        if not d.startswith("step_"):
            continue
        full = os.path.abspath(os.path.join(path, d))
        if any(full.startswith(k) for k in keep) or not os.path.isdir(full):
            continue
        is_tmp = d.endswith(TMP_SUFFIX) or ".orbax-checkpoint-tmp" in d
        has_marker = os.path.exists(os.path.join(full, COMMIT_MARKER))
        if is_tmp or (has_marker and not validate_step_dir(full)):
            shutil.rmtree(full, ignore_errors=True)
            removed.append(full)
    return removed


# ------------------------------------------------------------ save/restore

def _check_overwrite(final: str, overwrite: bool) -> None:
    """Fail BEFORE any data is written, and with a non-retryable class
    (ValueError, matching the pre-atomic orbax behavior): an existing
    checkpoint is a permanent condition, not I/O weather — it must not
    look transiently retryable to a retry.Policy's OSError rule."""
    if not overwrite and os.path.isdir(final):
        raise ValueError(
            f"checkpoint already exists at {final} and overwrite=False")


def _commit(tmp: str, final: str, step, overwrite: bool,
            state_schema: Optional[dict] = None) -> str:
    """Marker + rename: the atomic tail of every save path."""
    _fault_point("pre_commit", step, tmp)
    write_commit_marker(tmp, step=step, state_schema=state_schema)
    if os.path.isdir(final):
        _check_overwrite(final, overwrite)  # lost the entry-check race
        shutil.rmtree(final)
    os.rename(tmp, final)
    return final


def save_checkpoint(path: str, state: Any, step: Optional[int] = None,
                    overwrite: bool = True):
    """Save a pytree (params / opt state / amp state / rng — anything).

    ``step`` appends a step subdirectory (``path/step_000010``). The
    write is atomic: data lands in ``<dir>.tmp``, the commit marker is
    written, then the dir is renamed — a crash at any point leaves
    either the previous checkpoint or an ignorable ``.tmp`` dir.
    """
    ocp = _ocp()
    if step is not None:
        path = os.path.join(path, _step_dirname(step))
    final = os.path.abspath(path)
    _check_overwrite(final, overwrite)
    tmp = final + TMP_SUFFIX
    if os.path.isdir(tmp):  # stale torn write from a previous crash
        shutil.rmtree(tmp, ignore_errors=True)
    _fault_point("pre_write", step, tmp)
    schema = _schema_or_none(state)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(tmp, state, force=True)
    return _commit(tmp, final, step, overwrite, state_schema=schema)


def _schema_or_none(state: Any) -> Optional[dict]:
    """Best-effort format-2 schema: a state tree the encoder cannot
    describe (exotic leaves) degrades the marker to format 1 rather
    than failing the save — durability beats observability here."""
    try:
        return state_schema_of(state)
    except Exception:  # noqa: BLE001 — schema is advisory metadata
        return None


def restore_checkpoint(path: str, target: Optional[Any] = None,
                       step: Optional[int] = None):
    """Restore; ``target`` (a matching pytree of arrays/ShapeDtypeStructs)
    pins structure, dtypes and shardings.

    ``step=None`` resumes from the newest *valid* (committed + intact
    manifest) step; when no step carries a marker at all (a dir written
    by a pre-marker writer) it falls back to the newest step dir.
    """
    ocp = _ocp()
    if step is None:
        # resume semantics: a stepped checkpoint dir restores its newest
        # VALID step — an uncommitted/torn dir must never win
        step = latest_valid_step(path)
        if step is None:
            step = latest_step(path)
    if step is not None:
        path = os.path.join(path, _step_dirname(step))
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    if target is None:
        return ckptr.restore(path)
    return ckptr.restore(path, item=target)


class AsyncCheckpointWriter:
    """Background checkpoint writer over ``ocp.AsyncCheckpointer``.

    ``save`` returns as soon as device arrays are snapshotted to host;
    the serialization/write runs concurrently with subsequent training
    steps. A second ``save`` (or ``wait``) blocks until the previous
    write lands — at most one write is ever in flight.

    Writes follow the atomic protocol: the background write targets
    ``<dir>.tmp``; ``wait()`` (or the fence inside the next ``save``)
    finalizes it — commit marker, then rename. A process killed while a
    write is in flight leaves only the ``.tmp`` dir.
    """

    def __init__(self):
        ocp = _ocp()
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        self._pending = None  # (tmp, final, step, overwrite, schema)
        # save/wait/close all fence-and-commit through _pending; two
        # threads interleaving (a trainer saving while an eval thread
        # waits) would double-commit one write or drop another's
        # commit entirely. RLock: save()'s fence re-enters wait().
        self._lock = threading.RLock()

    @property
    def in_flight_tmp(self) -> Optional[str]:
        """Abs path of the uncommitted ``.tmp`` dir, if a write is in
        flight — GC must spare it."""
        return self._pending[0] if self._pending else None

    def save(self, path: str, state: Any, step: Optional[int] = None,
             overwrite: bool = True) -> str:
        if step is not None:
            path = os.path.join(path, _step_dirname(step))
        final = os.path.abspath(path)
        _check_overwrite(final, overwrite)
        tmp = final + TMP_SUFFIX
        with self._lock:
            # fence + finalize the PREVIOUS write before issuing a new
            # one — keeps the single-write-in-flight contract and
            # commits in order. Holding the lock across the stale-tmp
            # sweep and the async submit IS the point here: this lock
            # exists to serialize whole save/wait transactions, not to
            # guard a hot path.
            self.wait()
            if os.path.isdir(tmp):
                shutil.rmtree(tmp, ignore_errors=True)  # apex-lint: disable=blocking-call-under-lock
            _fault_point("pre_write", step, tmp)
            # schema is derived from the live tree BEFORE the async
            # write snapshots it — the marker must describe what was
            # handed to the writer, not whatever the tree mutated into
            schema = _schema_or_none(state)
            self._ckptr.save(tmp, state, force=True)
            self._pending = (tmp, final, step, overwrite, schema)
        return final

    def wait(self):
        """Block until the in-flight write (if any) is durable AND
        committed (marker + rename)."""
        with self._lock:
            self._ckptr.wait_until_finished()
            if self._pending is not None:
                tmp, final, step, overwrite, schema = self._pending
                # clear first: a failed commit leaves a torn .tmp
                # behind (as a real crash would) rather than wedging
                # every later save
                self._pending = None
                _commit(tmp, final, step, overwrite,
                        state_schema=schema)

    def close(self):
        with self._lock:
            self.wait()
            self._ckptr.close()


class CheckpointManager:
    """Thin rotation/bookkeeping wrapper (orbax CheckpointManager analog
    with the apex-era torch.save ergonomics).

    Async mode (``async_save=True``): each ``save`` fences and commits
    the previous write before issuing the new one, so retention always
    runs over committed dirs only; the in-flight ``.tmp`` dir is never
    GC'd. Call :meth:`wait_until_finished` at the end of the training
    loop: it flushes + commits the last write and applies final
    retention; a caller that skips it leaves the last write as an
    uncommitted ``.tmp`` dir (recovered as "previous step" semantics —
    exactly what a kill at that moment would have produced).

    Retention never deletes the newest *valid* checkpoint, even when it
    has aged out of the ``max_to_keep`` window — a run whose recent
    saves were all torn/corrupted must still have something to resume
    from."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = False):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)
        self._writer = AsyncCheckpointWriter() if async_save else None

    def save(self, step: int, state: Any):
        if self._writer is not None:
            p = self._writer.save(self.directory, state, step=step)
            self._gc()
            return p
        p = save_checkpoint(self.directory, state, step=step)
        self._gc()
        return p

    def wait_until_finished(self):
        """Async mode: block until pending writes land and commit, then
        apply retention. No-op in blocking mode."""
        if self._writer is not None:
            self._writer.wait()
            self._gc()

    def restore(self, target: Optional[Any] = None,
                step: Optional[int] = None):
        if step is None:
            step = self.latest_valid_step()
            if step is None:
                step = latest_step(self.directory)
        if step is None:
            return None
        return restore_checkpoint(self.directory, target, step=step)

    def latest_step(self) -> Optional[int]:
        return latest_step(self.directory)

    def latest_valid_step(self, deep: bool = False) -> Optional[int]:
        return latest_valid_step(self.directory, deep=deep)

    def _gc(self):
        in_flight = self._writer.in_flight_tmp if self._writer else None
        # torn-write leftovers first (never the in-flight tmp dir)
        gc_partial_checkpoints(
            self.directory, keep=(in_flight,) if in_flight else ())
        steps = _committed_steps(self.directory)
        if not steps or self.max_to_keep <= 0:
            # max_to_keep<=0 keeps everything (the pre-atomic slicing
            # semantics: [:-0] deleted nothing); tmp cleanup already ran
            return
        keep = set(sorted(steps)[-self.max_to_keep:])
        valid = [s for s in sorted(steps)
                 if validate_step_dir(os.path.join(self.directory,
                                                   steps[s]))]
        if valid and not any(s in keep for s in valid):
            # every survivor would be invalid/legacy: spare the newest
            # valid checkpoint — never delete the only resumable state
            keep.add(valid[-1])
        for s, d in steps.items():
            if s not in keep:
                shutil.rmtree(os.path.join(self.directory, d),
                              ignore_errors=True)
