"""Checkpoint/resume (SURVEY.md §5): orbax-backed save/restore of
params + optimizer state + amp/loss-scaler state + RNG.

The reference has no checkpoint layer of its own (torch.save in examples,
plus ``amp.state_dict()`` — ref apex/amp/frontend.py state_dict); here the
whole training state round-trips through one API, sharding-aware via orbax
(restores land on the same Mesh/PartitionSpec layout they were saved from).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def save_checkpoint(path: str, state: Any, step: Optional[int] = None,
                    overwrite: bool = True):
    """Save a pytree (params / opt state / amp state / rng — anything).

    ``step`` appends a step subdirectory (``path/step_000010``).
    """
    ocp = _ocp()
    if step is not None:
        path = os.path.join(path, f"step_{step:08d}")
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, state, force=overwrite)
    return path


def restore_checkpoint(path: str, target: Optional[Any] = None,
                       step: Optional[int] = None):
    """Restore; ``target`` (a matching pytree of arrays/ShapeDtypeStructs)
    pins structure, dtypes and shardings."""
    ocp = _ocp()
    if step is None:
        # resume semantics: a stepped checkpoint dir restores its newest step
        step = latest_step(path)
    if step is not None:
        path = os.path.join(path, f"step_{step:08d}")
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    if target is None:
        return ckptr.restore(path)
    return ckptr.restore(path, item=target)


def latest_step(path: str) -> Optional[int]:
    """Largest ``step_*`` subdirectory, or None."""
    if not os.path.isdir(path):
        return None
    steps = []
    for d in os.listdir(path):
        if d.startswith("step_"):
            try:
                steps.append(int(d[5:]))
            except ValueError:
                pass
    return max(steps) if steps else None


class CheckpointManager:
    """Thin rotation/bookkeeping wrapper (orbax CheckpointManager analog
    with the apex-era torch.save ergonomics)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)

    def save(self, step: int, state: Any):
        p = save_checkpoint(self.directory, state, step=step)
        self._gc()
        return p

    def restore(self, target: Optional[Any] = None,
                step: Optional[int] = None):
        step = step if step is not None else latest_step(self.directory)
        if step is None:
            return None
        return restore_checkpoint(self.directory, target, step=step)

    def latest_step(self) -> Optional[int]:
        return latest_step(self.directory)

    def _gc(self):
        import shutil

        steps = sorted(
            int(d[5:]) for d in os.listdir(self.directory)
            if d.startswith("step_"))
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, f"step_{s:08d}"),
                          ignore_errors=True)
