"""Native host runtime bindings (SURVEY.md §2 #50).

ctypes loader for ``csrc/libapex_tpu_host.so`` plus pure-Python fallbacks
so the package works before ``make -C csrc`` has run. ``timing`` holds
the corrected-sync device timing helpers shared by bench.py and tools/.
"""

from apex_tpu.runtime import timing
from apex_tpu.runtime.host import (
    HostRuntime,
    PrefetchLoader,
    bucket_offsets,
    flatten_into,
    plan_buckets,
    runtime_available,
    unflatten_from,
)

__all__ = [
    "HostRuntime", "PrefetchLoader", "bucket_offsets", "flatten_into",
    "plan_buckets", "runtime_available", "timing", "unflatten_from",
]
