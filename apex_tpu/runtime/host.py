"""ctypes bindings for the C++ host runtime (csrc/host_runtime.cpp).

- bucket planning (ref apex/parallel/distributed.py bucket assignment —
  reverse-order greedy capped at bucket_cap bytes)
- threaded flat pack/unpack of numpy host buffers (ref
  csrc/flatten_unflatten.cpp)
- threaded prefetch ring driving a Python fill callback (the host input
  pipeline the reference delegates to torch DataLoader workers)

Pure-numpy fallbacks keep everything working when the .so is absent.
"""

from __future__ import annotations

import atexit
import ctypes
import os
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

_LIB = None
# _load() is lazy and may SPAWN A BUILD (make -C csrc): two threads
# hitting the first call unlocked would race duplicate makes and one
# could CDLL a half-written .so. Double-checked: the fast path stays
# lock-free (module attribute read is atomic), only first-load
# serializes.
_LOAD_LOCK = threading.Lock()
_FILL_FN = ctypes.CFUNCTYPE(ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p,
                            ctypes.c_int64, ctypes.c_void_p)


def _load():
    if _LIB is not None:
        return _LIB or None  # False = cached failure -> numpy fallback
    with _LOAD_LOCK:
        # blocking (make + CDLL) under the lock IS the point: this lock
        # exists solely to serialize the one-time build, there is no
        # hot path contending on it
        return _load_locked()  # apex-lint: disable=blocking-call-under-lock


def _load_locked():
    global _LIB
    if _LIB is not None:
        return _LIB or None
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # installed layout first (setup.py drops the lib inside the package),
    # then the source checkout's csrc/
    candidates = [
        os.path.join(pkg_dir, "_lib", "libapex_tpu_host.so"),
        os.path.join(os.path.dirname(pkg_dir), "csrc",
                     "libapex_tpu_host.so"),
    ]
    so = next((c for c in candidates if os.path.exists(c)), None)
    if so is None:
        # the binary is not version-controlled (platform-specific); build it
        # on first use when a toolchain is around, else numpy fallback
        import subprocess
        so = candidates[-1]
        try:
            subprocess.run(["make", "-C", os.path.dirname(so)],
                           capture_output=True, timeout=120, check=True)
        except Exception:
            _LIB = False  # cache the failure: no make re-spawn per call
            return None
    if not os.path.exists(so):
        _LIB = False
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        # .so present but not loadable on this OS/arch — use numpy fallback
        _LIB = False
        return None
    lib.apex_plan_buckets.restype = ctypes.c_int64
    lib.apex_plan_buckets.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.apex_bucket_offsets.restype = None
    lib.apex_bucket_offsets.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64)]
    lib.apex_flatten.restype = None
    lib.apex_flatten.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int]
    lib.apex_unflatten.restype = None
    lib.apex_unflatten.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int]
    lib.apex_prefetch_create.restype = ctypes.c_void_p
    lib.apex_prefetch_create.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
        _FILL_FN, ctypes.c_void_p]
    lib.apex_prefetch_next.restype = ctypes.c_int64
    lib.apex_prefetch_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_int64]
    lib.apex_prefetch_destroy.restype = None
    lib.apex_prefetch_destroy.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


def runtime_available() -> bool:
    return _load() is not None


def _as_i64(seq) -> "ctypes.Array":
    arr = (ctypes.c_int64 * len(seq))(*seq)
    return arr


def plan_buckets(sizes: Sequence[int], bucket_bytes: int) -> List[int]:
    """Greedy reverse-order bucket ids (grad-ready order ≈ reverse param
    order, ref apex/parallel/distributed.py)."""
    lib = _load()
    n = len(sizes)
    if n == 0:
        return []
    if lib is None:
        out = [0] * n
        bucket, used = 0, 0
        for i in range(n - 1, -1, -1):
            if used > 0 and used + sizes[i] > bucket_bytes:
                bucket += 1
                used = 0
            out[i] = bucket
            used += sizes[i]
        return out
    out = (ctypes.c_int64 * n)()
    lib.apex_plan_buckets(_as_i64(sizes), n, bucket_bytes, out)
    return list(out)


def bucket_offsets(sizes: Sequence[int], bucket_ids: Sequence[int]):
    """(per-tensor offset within its bucket, per-bucket total size)."""
    lib = _load()
    n = len(sizes)
    n_buckets = (max(bucket_ids) + 1) if bucket_ids else 0
    if lib is None:
        used = [0] * n_buckets
        offs = [0] * n
        for i in range(n):
            offs[i] = used[bucket_ids[i]]
            used[bucket_ids[i]] += sizes[i]
        return offs, used
    offs = (ctypes.c_int64 * n)()
    bsz = (ctypes.c_int64 * max(n_buckets, 1))()
    lib.apex_bucket_offsets(_as_i64(sizes), _as_i64(bucket_ids), n,
                            n_buckets, offs, bsz)
    return list(offs), list(bsz)[:n_buckets]


def flatten_into(arrays: Sequence[np.ndarray], flat: np.ndarray,
                 offsets: Optional[Sequence[int]] = None,
                 threads: int = 4) -> np.ndarray:
    """Pack host arrays into the preallocated ``flat`` byte-wise."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = [a.nbytes for a in arrays]
    if offsets is None:
        offsets = list(np.cumsum([0] + sizes[:-1]))
    lib = _load()
    if lib is None:
        fv = flat.view(np.uint8)
        for a, off in zip(arrays, offsets):
            fv[off:off + a.nbytes] = a.view(np.uint8).ravel()
        return flat
    srcs = (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
    lib.apex_flatten(srcs, _as_i64(sizes), _as_i64(offsets), len(arrays),
                     flat.ctypes.data_as(ctypes.c_void_p), threads)
    return flat


def unflatten_from(flat: np.ndarray, outs: Sequence[np.ndarray],
                   offsets: Optional[Sequence[int]] = None,
                   threads: int = 4) -> Sequence[np.ndarray]:
    """Scatter the flat byte buffer back into the preallocated ``outs``."""
    sizes = [a.nbytes for a in outs]
    if offsets is None:
        offsets = list(np.cumsum([0] + sizes[:-1]))
    lib = _load()
    if lib is None:
        fv = flat.view(np.uint8)
        for a, off in zip(outs, offsets):
            a.view(np.uint8).ravel()[:] = fv[off:off + a.nbytes]
        return outs
    dsts = (ctypes.c_void_p * len(outs))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in outs])
    lib.apex_unflatten(flat.ctypes.data_as(ctypes.c_void_p), _as_i64(sizes),
                       _as_i64(offsets), len(outs), dsts, threads)
    return outs


class HostRuntime:
    """Namespace-style facade mirroring the C ABI."""

    plan_buckets = staticmethod(plan_buckets)
    bucket_offsets = staticmethod(bucket_offsets)
    flatten = staticmethod(flatten_into)
    unflatten = staticmethod(unflatten_from)
    available = staticmethod(runtime_available)


# Live native prefetch rings: handle -> (lib, keep-alive callback).
# apex_prefetch_destroy stops + JOINS the C++ workers before freeing
# the slot buffers, so destroying through this registry is the one
# safe teardown. The atexit sweep covers iterators that were abandoned
# without being GC'd: without it, C++ worker threads could still be
# calling the Python fill callback while the interpreter tears itself
# down — a write into freed interpreter state.
_RINGS_LOCK = threading.Lock()
_ACTIVE_RINGS: dict = {}


def _register_ring(handle, lib, cb) -> None:
    with _RINGS_LOCK:
        _ACTIVE_RINGS[handle] = (lib, cb)


def _destroy_ring(handle) -> None:
    """Idempotent stop+join+free of one ring (no-op if already gone)."""
    with _RINGS_LOCK:
        entry = _ACTIVE_RINGS.pop(handle, None)
    if entry is not None:
        lib, _cb = entry
        # ctypes releases the GIL for the call, so workers blocked on
        # the GIL for an in-flight fill can finish before the join
        lib.apex_prefetch_destroy(handle)


@atexit.register
def _shutdown_rings() -> None:
    for handle in list(_ACTIVE_RINGS):
        _destroy_ring(handle)


class PrefetchLoader:
    """Threaded prefetch over a Python ``fill(batch_idx, out_array)``
    callback, backed by the C++ ring (falls back to a Python thread pool).

    Iterating yields numpy arrays of shape ``batch_shape``/dtype in batch
    order while up to ``n_slots`` future batches fill in the background —
    the input-pipeline overlap the reference gets from DataLoader workers.

    Shutdown contract (both backends): closing or abandoning the
    iterator stops and JOINS the fill workers before their buffers can
    be freed; a fill callback still running at interpreter exit is
    joined by the atexit sweep. A worker never wedges on a full queue
    after the consumer walks away, and a fill exception surfaces as
    ``RuntimeError`` on the consuming thread instead of hanging it.
    """

    def __init__(self, fill: Callable[[int, np.ndarray], None],
                 total_batches: int, batch_shape, dtype=np.float32,
                 n_slots: int = 4, n_workers: int = 2):
        self.fill = fill
        self.total = total_batches
        self.shape = tuple(batch_shape)
        self.dtype = np.dtype(dtype)
        self.nbytes = int(np.prod(self.shape)) * self.dtype.itemsize
        self.n_slots = n_slots
        self.n_workers = n_workers
        self._lib = _load()
        self._ring = None
        self._cb = None

    def __iter__(self):
        if self._lib is not None:
            return self._iter_native()
        return self._iter_python()

    def _iter_native(self):
        lib = self._lib

        def c_fill(batch_idx, buf_ptr, buf_bytes, ctx):
            try:
                arr = np.ctypeslib.as_array(
                    ctypes.cast(buf_ptr, ctypes.POINTER(ctypes.c_uint8)),
                    shape=(buf_bytes,))
                view = arr[:self.nbytes].view(self.dtype).reshape(self.shape)
                self.fill(int(batch_idx), view)
                return 0
            except Exception:
                return 1

        cb = _FILL_FN(c_fill)  # keep alive for the ring's lifetime
        ring = lib.apex_prefetch_create(self.n_slots, self.nbytes,
                                        self.total, self.n_workers, cb,
                                        None)
        _register_ring(ring, lib, cb)
        try:
            out = np.empty(self.nbytes, np.uint8)
            for _ in range(self.total):
                rc = lib.apex_prefetch_next(
                    ring, out.ctypes.data_as(ctypes.c_void_p), self.nbytes)
                if rc == -1:
                    raise RuntimeError("prefetch fill callback failed")
                if rc == -2:
                    return
                yield out[:self.nbytes].view(self.dtype).reshape(
                    self.shape).copy()
        finally:
            # stop + join workers BEFORE the callback can be released:
            # a fill in flight completes into still-owned slot memory,
            # then the workers exit, then cb may die
            _destroy_ring(ring)
            del cb

    def _iter_python(self):
        import queue

        q: "queue.Queue" = queue.Queue(maxsize=self.n_slots)
        stop = threading.Event()
        error = object()  # sentinel: fill raised on the worker thread

        def put(item) -> bool:
            # bounded put that can never wedge the worker: a consumer
            # that abandoned the iterator stops draining, and a plain
            # q.put would block this thread forever — stop.set() alone
            # cannot unblock a blocked put
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for b in range(self.total):
                    if stop.is_set():
                        return
                    arr = np.empty(self.shape, self.dtype)
                    self.fill(b, arr)
                    if not put((b, arr)):
                        return
                put((None, None))
            except BaseException as e:  # noqa: BLE001 — a dead
                # producer must surface on the consumer, which would
                # otherwise block on q.get() forever
                put((error, e))

        t = threading.Thread(target=worker, daemon=True,
                             name="apex-prefetch-fill")
        t.start()
        try:
            while True:
                b, arr = q.get()
                if b is error:
                    raise RuntimeError(
                        "prefetch fill callback failed") from arr
                if b is None:
                    return
                yield arr
        finally:
            stop.set()
            # drain so a put-blocked worker observes stop promptly,
            # then join — the iterator owns the thread's lifetime; a
            # missed join here is a thread leaked per abandoned epoch
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=10.0)
