"""Corrected-sync device timing — the shared helper every timed region
must go through.

``jax.block_until_ready`` is a NO-OP over the axon remote backend
(measured r5: a 1.1-TFLOP matmul "completed" in 0.04 ms under
block_until_ready vs 5.6 ms true device time) — every r1-r4 timing that
trusted it on TPU was dispatch time, not device time, and the r5 bench
published an impossible MFU=330 because of it. A host fetch of a single
element is the only sync that provably waits, and because the TPU
executes enqueued programs in order, syncing the LAST output of a
sequence syncs the whole sequence.

This module is the one place that knowledge lives. bench.py,
tools/tpu_profile.py and tools/tpu_validate.py all import from here, and
the ``sync-timing`` check in ``apex_tpu.analysis`` flags any new code
that times around a bare ``block_until_ready`` instead.

jax is imported lazily inside each function: bench.py's launcher half
must stay importable without touching the backend.
"""

from __future__ import annotations

import time

__all__ = [
    "sync", "fetch_cost", "time_fn", "time_train_step", "time_chained",
    "time_scanned",
]


def sync(out):
    """Force completion of ``out``'s producing computation by fetching one
    element of its last leaf to the host.

    Index (not ravel) one element: ravel() would dispatch a full-array
    reshape — on a sharded 16 GiB output that's a device-filling copy.
    The last leaf is fetched on the assumption that ``out`` came from one
    program (or that its leaves were enqueued in pytree order, as a
    ``(*state, loss)`` step output is): in-order device execution then
    makes one fetch sync everything. Pass the final output explicitly
    when timing a multi-dispatch region."""
    import jax
    import numpy as np
    leaves = jax.tree_util.tree_leaves(out)
    if not leaves:
        return None
    # belt: block_until_ready waits on EVERY leaf on backends that honor
    # it (local CPU/GPU/TPU pods — covers leaves from independent
    # dispatch queues); braces: the host fetch below is the only wait
    # the axon tunnel honors, and in-order execution makes one fetch of
    # the last-enqueued output cover the whole queue.
    jax.block_until_ready(leaves)
    leaf = leaves[-1]
    return np.asarray(leaf if getattr(leaf, "ndim", 0) == 0
                      else leaf[(0,) * leaf.ndim])


def fetch_cost(out):
    """Measured cost of one :func:`sync` on an already-ready array — ~79 ms
    through the tunnel (RTT + tiny-gather dispatch), ~0 locally. Timed
    loops subtract it so the fetch doesn't masquerade as device time."""
    sync(out)
    costs = []
    for _ in range(3):
        t0 = time.perf_counter()
        sync(out)
        costs.append(time.perf_counter() - t0)
    return min(costs)


_FETCH_COST = None


def cached_fetch_cost(sample) -> float:
    """:func:`fetch_cost` measured once per process (the tunnel constant
    is stable) — for one-shot timed regions like the pipeline phase
    timers, where re-measuring per stop would cost more than the fetch
    it corrects for. ``sample`` must already be synced."""
    global _FETCH_COST
    if _FETCH_COST is None:
        _FETCH_COST = fetch_cost(sample)
    return _FETCH_COST


def time_fn(fn, *args, iters=20, warmup=3, max_time_s=None):
    """Warmup then time ``iters`` independent calls + ONE final sync
    (in-order device execution ⇒ last-completion = all-complete), minus
    the measured fetch constant. ``max_time_s`` caps the TIMED loop's
    wall clock: the last warmup call (synced) estimates the per-step cost
    and ``iters`` shrinks to fit — the dispatch-bound baselines can take
    tens of seconds per step through a remote device tunnel, and one pass
    of a 2k-dispatch loop is a statistically fine sample."""
    for _ in range(max(warmup, 1) - 1):
        out = fn(*args)
    t0 = time.perf_counter()
    out = fn(*args)
    sync(out)
    per_step = time.perf_counter() - t0
    fetch = fetch_cost(out)
    if max_time_s is not None:
        iters = max(1, min(iters, int(max_time_s / max(per_step, 1e-9))))
    # sync every ~2s of enqueued work: async dispatch with NO sync lets
    # the in-flight buffer queue grow until the device OOMs (observed r5:
    # the 2k-dispatch eager loop exhausted HBM that a synced loop never
    # touches), and deletion RPCs only flush at a sync point
    sync_every = max(1, int(2.0 / max(per_step, 1e-9)))
    n_syncs = 0
    t0 = time.perf_counter()
    for i in range(iters):
        out = fn(*args)
        if (i + 1) % sync_every == 0 and i + 1 < iters:
            sync(out)
            n_syncs += 1
    sync(out)
    n_syncs += 1
    return max((time.perf_counter() - t0 - fetch * n_syncs), 1e-9) / iters


def time_train_step(step, state, batch, iters=10):
    """Warm up once, then time ``iters`` chained calls of a jitted train
    step whose outputs are ``(*new_state, loss)`` and whose inputs are
    ``(*state, *batch)`` — the shared methodology for every model-level
    bench (donated state threads through). The final-step loss is fetched
    to the host: it depends on the whole chain, so one fetch syncs all
    ``iters`` steps; the fetch constant is subtracted."""
    out = step(*state, *batch)
    sync(out[-1])
    fetch = fetch_cost(out[-1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(*out[:-1], *batch)
    sync(out[-1])
    return max((time.perf_counter() - t0 - fetch), 1e-9) / iters


def time_chained(step, grads, state, params, iters=100):
    """Output-feeds-input timing: true serial device time per step."""
    p, s = step(grads, state, params)
    sync(p)
    fetch = fetch_cost(p)
    t0 = time.perf_counter()
    for _ in range(iters):
        p, s = step(grads, s, p)
    sync(p)
    return max((time.perf_counter() - t0 - fetch), 1e-9) / iters


def time_scanned(make_step, carry, chain, k=32, reps=3):
    """Per-iteration device time of a sub-millisecond kernel.

    Per-dispatch overhead through the tunnel is ~0.7 ms (measured r5), so
    a chained host loop can't resolve kernels faster than that. Instead
    run ``k`` iterations ON DEVICE under one ``lax.scan`` dispatch
    (``chain(carry, step) -> carry`` threads the output back in so
    nothing is dead-code-eliminated), time 1 rep and ``reps`` chained
    reps of the SAME jitted scan, and take the slope — the fetch constant
    and dispatch overhead cancel."""
    import jax

    step = make_step()

    @jax.jit
    def scan_k(c):
        return jax.lax.scan(lambda c, _: (chain(c, step), None), c, None,
                            length=k)[0]

    out = scan_k(carry)       # compile + settle
    sync(out)
    t0 = time.perf_counter()
    out = scan_k(out)
    sync(out)
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = scan_k(out)
    sync(out)
    t_many = time.perf_counter() - t0
    return max(t_many - t_one, 1e-9) / ((reps - 1) * k)
