"""RNN-T transducer joint + loss (ref apex/contrib/transducer/
{transducer.py} TransducerJoint / TransducerLoss, csrc transducer kernels).

TPU-first design notes:
- The joint is the broadcast add f[:, :, None] + g[:, None, :] with optional
  relu/dropout — one XLA fusion (the reference's "packed" path exists to
  skip padding on GPU; fixed shapes + masking is the TPU-friendly layout).
- The loss's alpha recursion is reformulated so the inner (label) dimension
  runs as a ``lax.associative_scan`` in the log semiring: each time-frame
  row is a first-order linear recurrence
      alpha[t, u] = logaddexp(alpha[t-1, u] + blank[t-1, u],
                              alpha[t, u-1] + emit[t, u-1])
  whose scan element is the affine map X -> E*X + A, composed associatively
  as (log_m, log_a) pairs. The outer time loop is a ``lax.scan``. That
  turns the classic O(T·U) sequential lattice into O(T) steps of O(log U)
  depth — the TPU answer to the reference's warp-parallel CUDA DP.
- Gradients fall out of AD through the scans (exact), so there is no
  hand-written backward kernel to keep in sync.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


# ------------------------------------------------------------------- joint


def transducer_joint(f, g, f_len=None, g_len=None, pack_output: bool = False,
                     relu: bool = False, dropout: float = 0.0,
                     dropout_rng=None):
    """h[b, t, u, :] = f[b, t, :] + g[b, u, :] (ref TransducerJoint.forward).

    ``pack_output`` is accepted for API parity and ignored: TPU kernels
    want fixed shapes; padding is masked in the loss instead.
    """
    del f_len, g_len, pack_output
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jax.nn.relu(h)
    if dropout > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout, h.shape)
        h = jnp.where(keep, h / (1.0 - dropout), 0.0)
    return h


class TransducerJoint:
    """ref transducer.py:10 TransducerJoint."""

    def __init__(self, pack_output=False, relu=False, dropout=False,
                 dropout_prob=0.0, probe=None):
        del probe
        self.pack_output = pack_output
        self.relu = relu
        self.dropout_prob = dropout_prob if dropout else 0.0

    def __call__(self, f, g, f_len=None, g_len=None, batch_offset=None,
                 packed_batch=0, dropout_rng=None):
        del batch_offset, packed_batch
        return transducer_joint(f, g, f_len, g_len, self.pack_output,
                                self.relu, self.dropout_prob, dropout_rng)


# -------------------------------------------------------------------- loss


def _row_recurrence(prev_term, emit_row):
    """Solve alpha_row[u] = logaddexp(prev_term[u], alpha_row[u-1] +
    emit_row[u-1]) for all u via associative_scan in the log semiring.

    Element = affine map X -> M*X + A with (log_m, log_a); composition
    (applied left-to-right) is (lm1+lm2, logaddexp(la1 + lm2, la2)).
    """
    u1 = prev_term.shape[-1]
    # shift emit right: multiplier entering position u is emit[u-1]
    log_m = jnp.concatenate(
        [jnp.full(emit_row.shape[:-1] + (1,), _NEG_INF), emit_row[..., :-1]],
        axis=-1)
    log_a = prev_term

    def combine(x, y):
        lm1, la1 = x
        lm2, la2 = y
        return lm1 + lm2, jnp.logaddexp(la1 + lm2, la2)

    _, alpha = jax.lax.associative_scan(combine, (log_m, log_a), axis=-1)
    return alpha


def transducer_loss(logits, targets, f_len, y_len, blank_idx: int = 0,
                    packed_input: bool = False):
    """Negative log-likelihood per batch element (ref TransducerLoss).

    logits: [B, T, U+1, V] joint outputs; targets [B, U] label ids;
    f_len [B] valid time frames; y_len [B] valid labels.
    """
    if packed_input:
        raise NotImplementedError(
            "packed input is a GPU memory optimization; pass padded "
            "[B, T, U+1, V] logits (mask via f_len/y_len)")
    B, T, U1, V = logits.shape
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    blank = lp[..., blank_idx]                       # [B, T, U+1]
    emit = jnp.take_along_axis(
        lp[:, :, :-1, :], targets[:, None, :, None], axis=-1)[..., 0]
    # emit[b, t, u] = lp[t, u, targets[u]]; pad back to U+1 with -inf
    emit = jnp.concatenate(
        [emit, jnp.full((B, T, 1), _NEG_INF)], axis=2)   # [B, T, U+1]
    # labels beyond y_len can never be emitted
    u_pos = jnp.arange(U1)[None, :]
    emit = jnp.where(u_pos[None] < y_len[:, None, None], emit, _NEG_INF)

    alpha0 = jnp.full((B, U1), _NEG_INF).at[:, 0].set(0.0)
    alpha0 = _row_recurrence(
        alpha0.at[:, 1:].set(_NEG_INF).at[:, 0].set(0.0), emit[:, 0])

    def step(alpha_prev, inputs):
        blank_prev, emit_row = inputs  # blank at t-1, emit at t
        prev_term = alpha_prev + blank_prev
        alpha = _row_recurrence(prev_term, emit_row)
        return alpha, alpha

    blanks_t = jnp.moveaxis(blank[:, :-1], 1, 0)    # [T-1, B, U+1]
    emits_t = jnp.moveaxis(emit[:, 1:], 1, 0)
    _, alphas = jax.lax.scan(step, alpha0, (blanks_t, emits_t))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, U+1]
    alphas = jnp.moveaxis(alphas, 0, 1)             # [B, T, U+1]

    # ll = alpha[f_len-1, y_len] + blank[f_len-1, y_len]
    t_idx = jnp.clip(f_len - 1, 0, T - 1)
    a_final = jnp.take_along_axis(
        alphas, t_idx[:, None, None].repeat(U1, axis=2), axis=1)[:, 0]
    b_final = jnp.take_along_axis(
        blank, t_idx[:, None, None].repeat(U1, axis=2), axis=1)[:, 0]
    ll = jnp.take_along_axis(a_final + b_final, y_len[:, None], axis=1)[:, 0]
    return -ll


class TransducerLoss:
    """ref transducer.py TransducerLoss (Function.apply shape)."""

    def __init__(self, fuse_softmax_backward=True, opt=1,
                 packed_input=False):
        del fuse_softmax_backward, opt
        self.packed_input = packed_input

    def __call__(self, x, label, f_len, y_len, blank_idx=0,
                 batch_offset=None, max_f_len=None, debug_list=None):
        del batch_offset, max_f_len, debug_list
        return transducer_loss(x, label, f_len, y_len, blank_idx,
                               self.packed_input)
