"""Halo-exchange strategy family (ref apex/contrib/bottleneck/
halo_exchangers.py — HaloExchanger{NoComm,AllGather,SendRecv,Peer}).

The reference offers four transports for the same edge exchange (NCCL
all_gather, NCCL send/recv pairs, CUDA peer-to-peer memory, and a
no-comm debug mode). On a TPU mesh the transport is XLA's choice — the
strategies collapse to two real programs (`ppermute` neighbor shifts vs
`all_gather` + slice) plus the no-comm identity, all with identical
semantics: each rank receives its left neighbor's right edge and its
right neighbor's left edge. Boundary ranks receive zeros (ppermute) /
their own wrapped edge is never used by the bottleneck consumer, which
only reads interior halos — same contract as the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "HaloExchanger", "HaloExchangerNoComm", "HaloExchangerAllGather",
    "HaloExchangerSendRecv", "HaloExchangerPeer",
    "left_right_halo_exchange",
]


def left_right_halo_exchange(left_output_halo, right_output_halo,
                             axis_name: str = "spatial"):
    """(left_input_halo, right_input_halo) — the neighbor shift every
    exchanger implements (ref halo_exchangers.py:24,38,74,95):

    - ``left_input_halo``  = LEFT  neighbor's ``right_output_halo``
    - ``right_input_halo`` = RIGHT neighbor's ``left_output_halo``

    Rank 0's left input and rank n-1's right input are zeros.
    Must run inside ``shard_map``/``pmap`` with ``axis_name`` bound.
    """
    n = jax.lax.axis_size(axis_name)
    to_right = [(i, i + 1) for i in range(n - 1)]
    to_left = [(i, i - 1) for i in range(1, n)]
    left_input = jax.lax.ppermute(right_output_halo, axis_name, to_right)
    right_input = jax.lax.ppermute(left_output_halo, axis_name, to_left)
    return left_input, right_input


class HaloExchanger:
    """Base (ref halo_exchangers.py:11): holds the mesh axis standing in
    for the reference's (spatial_group_size, rank) pair."""

    def __init__(self, spatial_group_size=None, rank=None,
                 axis_name: str = "spatial"):
        del spatial_group_size, rank  # mesh axis carries both on TPU
        self.axis_name = axis_name

    def left_right_halo_exchange(self, left_output_halo,
                                 right_output_halo):
        raise NotImplementedError


class HaloExchangerNoComm(HaloExchanger):
    """ref halo_exchangers.py:20 — no communication: each rank's own
    edges come straight back swapped (single-rank/debug mode)."""

    def __init__(self, world_size=None, spatial_group_size=None, rank=None,
                 comm=None, axis_name: str = "spatial"):
        super().__init__(spatial_group_size, rank, axis_name)
        del world_size, comm

    def left_right_halo_exchange(self, left_output_halo,
                                 right_output_halo):
        return right_output_halo, left_output_halo


class HaloExchangerAllGather(HaloExchanger):
    """ref halo_exchangers.py:31 — gather every rank's edges, pick the
    neighbors'. More traffic than the shift but one collective."""

    def __init__(self, world_size=None, spatial_group_size=None, rank=None,
                 comm=None, axis_name: str = "spatial"):
        super().__init__(spatial_group_size, rank, axis_name)
        del world_size, comm

    def left_right_halo_exchange(self, left_output_halo,
                                 right_output_halo):
        ax = self.axis_name
        n = jax.lax.axis_size(ax)
        rank = jax.lax.axis_index(ax)
        rights = jax.lax.all_gather(right_output_halo, ax)  # [n, ...]
        lefts = jax.lax.all_gather(left_output_halo, ax)
        # neighbor picks, with boundary ranks zeroed to match ppermute
        left_input = jnp.where(
            rank > 0,
            jax.lax.dynamic_index_in_dim(
                rights, jnp.maximum(rank - 1, 0), 0, keepdims=False),
            jnp.zeros_like(right_output_halo))
        right_input = jnp.where(
            rank < n - 1,
            jax.lax.dynamic_index_in_dim(
                lefts, jnp.minimum(rank + 1, n - 1), 0, keepdims=False),
            jnp.zeros_like(left_output_halo))
        return left_input, right_input


class HaloExchangerSendRecv(HaloExchanger):
    """ref halo_exchangers.py:64 — pairwise neighbor transfer; the
    ppermute shift IS send/recv on the ICI torus."""

    def __init__(self, world_size=None, spatial_group_size=None, rank=None,
                 comm=None, axis_name: str = "spatial"):
        super().__init__(spatial_group_size, rank, axis_name)
        del world_size, comm

    def left_right_halo_exchange(self, left_output_halo,
                                 right_output_halo):
        return left_right_halo_exchange(left_output_halo,
                                        right_output_halo, self.axis_name)


class HaloExchangerPeer(HaloExchangerSendRecv):
    """ref halo_exchangers.py:81 — CUDA peer-memory transport; on TPU the
    direct-neighbor ICI hop is exactly the ppermute shift, so this is
    SendRecv with the reference's extra knobs accepted."""

    def __init__(self, world_size=None, spatial_group_size=None, rank=None,
                 comm=None, peer_pool=None, explicit_nhwc=False, numSM=1,
                 axis_name: str = "spatial"):
        super().__init__(world_size, spatial_group_size, rank, comm,
                         axis_name=axis_name)
        del peer_pool, explicit_nhwc, numSM
