"""apex.contrib.optimizers parity (ref apex/contrib/optimizers/)."""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
    distributed_fused_adam,
)

__all__ = ["DistributedFusedAdam", "distributed_fused_adam"]
