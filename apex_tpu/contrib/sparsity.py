"""ASP — automatic 2:4 structured sparsity (ref apex/contrib/sparsity/
{asp.py,sparse_masklib.py}).

The reference computes N:M masks with CUDA permutation-search kernels and
hooks the optimizer to re-apply masks after each step. TPU design: the mask
computation is a vectorized jnp program (magnitude-based m4n2_1d — the
reference's default --whitelist pattern), masks live in the param pytree,
and masking is a pure function applied inside the jitted train step (and
wrapped around any optax transform via :func:`masked_update`).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax


def mn_1d_mask(w, m: int = 4, n: int = 2):
    """Keep the ``n`` largest-magnitude of every ``m`` consecutive weights
    along the last dim (ref sparse_masklib.py:49 m4n2_1d / mn_1d_best).

    Works on any shape with last dim divisible by m; returns a 0/1 mask of
    w's shape and dtype bool.
    """
    if w.shape[-1] % m:
        raise ValueError(f"last dim {w.shape[-1]} not divisible by m={m}")
    groups = w.reshape(*w.shape[:-1], w.shape[-1] // m, m)
    mag = jnp.abs(groups)
    # keep exactly n per group by magnitude rank (deterministic ties)
    order = jnp.argsort(jnp.argsort(-mag, axis=-1), axis=-1)  # rank, 0=largest
    keep = order < n
    return keep.reshape(w.shape)


def create_mask(w, pattern: str = "m4n2_1d"):
    """ref sparse_masklib.py create_mask entry."""
    if pattern == "m4n2_1d":
        return mn_1d_mask(w, 4, 2)
    if pattern == "m4n2_2d_best":
        # 2d pattern: apply 1d along both dims greedily (the reference's
        # exhaustive 2d search is a CUDA kernel; 1d x transpose-1d is the
        # documented greedy fallback, ref sparse_masklib.py:67)
        m_rows = mn_1d_mask(w, 4, 2)
        m_cols = jnp.swapaxes(
            mn_1d_mask(jnp.swapaxes(w, -1, -2), 4, 2), -1, -2)
        return m_rows & m_cols
    raise ValueError(f"unknown pattern {pattern}")


def apply_masks(params, masks):
    """w * mask over the tree (the reference's in-place hook, functional)."""
    return jax.tree_util.tree_map(
        lambda p, m: p * m.astype(p.dtype) if m is not None else p,
        params, masks, is_leaf=lambda x: x is None)


def masked_update(tx: optax.GradientTransformation, masks):
    """Wrap an optax transform so updates AND params stay masked — the
    analog of ASP hooking optimizer.step (ref asp.py:init_optimizer_for_pruning)."""

    def init(params):
        return tx.init(apply_masks(params, masks))

    def update(grads, state, params=None):
        grads = apply_masks(grads, masks)
        updates, state = tx.update(grads, state, params)
        updates = apply_masks(updates, masks)
        return updates, state

    return optax.GradientTransformation(init, update)


class ASP:
    """ref asp.py ASP static class; functional equivalents.

    Usage:
        masks = ASP.compute_sparse_masks(params)       # once, post-warmup
        params = ASP.apply(params, masks)
        tx = ASP.init_optimizer_for_pruning(tx, masks) # masked updates
    """

    @staticmethod
    def _eligible(path: str, leaf) -> bool:
        # ref asp.py whitelist: linear/conv weights, ndim>=2, dims % 4 == 0
        return (hasattr(leaf, "ndim") and leaf.ndim >= 2
                and leaf.shape[-1] % 4 == 0)

    @staticmethod
    def compute_sparse_masks(params, pattern: str = "m4n2_1d",
                             eligible: Optional[Callable] = None):
        elig = eligible or ASP._eligible

        def mk(path, leaf):
            name = jax.tree_util.keystr(path)
            if elig(name, leaf):
                return create_mask(leaf, pattern)
            return None

        return jax.tree_util.tree_map_with_path(mk, params)

    @staticmethod
    def apply(params, masks):
        return apply_masks(params, masks)

    @staticmethod
    def init_optimizer_for_pruning(tx, masks):
        return masked_update(tx, masks)

    @staticmethod
    def init_model_for_pruning(params, mask_calculator: str = "m4n2_1d",
                               **kw):
        """Returns (params, masks) — functional twist on ref asp.py:61."""
        masks = ASP.compute_sparse_masks(params, mask_calculator)
        return apply_masks(params, masks), masks
