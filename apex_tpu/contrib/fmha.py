"""Fused multi-head attention (ref apex/contrib/fmha/fmha.py FMHAFun +
csrc/fmha cutlass kernels) — backed by the Pallas TPU flash attention
kernel in :mod:`apex_tpu.ops.flash_attention`.

The reference consumes varlen packed sequences (qkv [total, 3, h, d] +
cu_seqlens). TPU-first design uses fixed-shape batches (dynamic shapes
defeat XLA); varlen batches are expressed with a padding mask or by packing
to a common length upstream.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention


def fmha(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """[b, s, h, d] fused attention (flash; no s×s HBM materialization)."""
    return flash_attention(q, k, v, causal=causal, scale=scale)


def _masked_dense_attention(q, k, v, seqlens, scale):
    """[b, s, h, d] attention where batch row i only attends to its first
    ``seqlens[i]`` keys (padded keys excluded; ref fmha varlen semantics).

    fp32 softmax (the repo-wide attention accumulator policy); GQA via the
    grouped einsum (no repeated K/V copy); the mask fill is finite so an
    all-masked (empty) sequence stays NaN-free in forward AND backward —
    its query rows are zeroed, which also zeroes their gradients.
    """
    b, s, hq, d = q.shape
    h_kv = k.shape[2]
    rep = hq // h_kv
    scale = scale if scale is not None else d ** -0.5
    q32 = q.astype(jnp.float32) * scale
    k32 = k.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    key_ok = jnp.arange(s)[None, :] < seqlens[:, None]  # [b, sk]
    neg = jnp.float32(-1e30)
    if rep > 1:
        qg = q32.reshape(b, s, h_kv, rep, d)
        scores = jnp.einsum("bqgrd,bkgd->bgrqk", qg, k32)
        scores = jnp.where(key_ok[:, None, None, None, :], scores, neg)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bgrqk,bkgd->bqgrd", probs, v32)
        out = out.reshape(b, s, hq, d)
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k32)
        scores = jnp.where(key_ok[:, None, None, :], scores, neg)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v32)
    # padded QUERY rows are meaningless; zero them like the reference's
    # varlen kernels (no garbage flows into downstream dense layers)
    out = jnp.where(key_ok[:, :, None, None], out, 0.0)
    return out.astype(q.dtype)


def fmha_packed_qkv(qkv, causal: bool = False,
                    scale: Optional[float] = None, seqlens=None):
    """qkv [b, s, 3, h, d] (the reference's packed layout, batched).

    ``seqlens`` [b] masks per-sequence padding (the reference's varlen
    cu_seqlens semantics on the padded-dense TPU layout).
    """
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if seqlens is not None:
        if causal:
            raise NotImplementedError(
                "causal + varlen: combine a causal attn_mask with the "
                "key-padding path in contrib.multihead_attn")
        return _masked_dense_attention(q, k, v, jnp.asarray(seqlens), scale)
    return flash_attention(q, k, v, causal=causal, scale=scale)


class FMHAFun:
    """ref fmha.py FMHAFun.apply shape (padded-dense qkv [b, s, 3, h, d]).

    ``cu_seqlens`` (cumulative, [b+1] — the reference's varlen boundary
    vector) or ``seqlens`` ([b]) mask out each sequence's padding; the
    reference's flat [total, 3, h, d] packing is a CUDA memory layout —
    on TPU batches stay padded-dense (static shapes) and the mask carries
    the varlen semantics.
    """

    @staticmethod
    def apply(qkv, cu_seqlens=None, seqlens=None, p_dropout=0.0,
              max_s=None, is_training=True, zero_tensors=False):
        del max_s, is_training, zero_tensors
        if p_dropout:
            raise NotImplementedError(
                "attention dropout: apply dropout to the output projection "
                "(TPU kernels keep the softmax deterministic)")
        if qkv.ndim != 5:
            raise ValueError(
                "apex_tpu FMHAFun takes padded-dense qkv [b, s, 3, h, d]; "
                "flat varlen packing is a CUDA layout — unpack with "
                "cu_seqlens upstream")
        if seqlens is None and cu_seqlens is not None:
            cu = jnp.asarray(cu_seqlens)
            seqlens = cu[1:] - cu[:-1]
        return fmha_packed_qkv(qkv, seqlens=seqlens)
