"""Fused multi-head attention (ref apex/contrib/fmha/fmha.py FMHAFun +
csrc/fmha cutlass kernels) — backed by the Pallas TPU flash attention
kernel in :mod:`apex_tpu.ops.flash_attention`.

The reference consumes varlen packed sequences (qkv [total, 3, h, d] +
cu_seqlens). TPU-first design uses fixed-shape batches (dynamic shapes
defeat XLA); varlen batches are expressed with a padding mask or by packing
to a common length upstream.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention


def fmha(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """[b, s, h, d] fused attention (flash; no s×s HBM materialization)."""
    return flash_attention(q, k, v, causal=causal, scale=scale)


def fmha_packed_qkv(qkv, causal: bool = False,
                    scale: Optional[float] = None, seqlens=None):
    """qkv [b, s, 3, h, d] (the reference's packed layout, batched).

    ``seqlens`` [b] masks per-sequence padding (the reference's varlen
    cu_seqlens semantics on the padded-dense TPU layout) — handled INSIDE
    the flash kernel, so varlen batches keep O(s·d) memory.
    """
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if seqlens is not None:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               kv_lens=jnp.asarray(seqlens))
    return flash_attention(q, k, v, causal=causal, scale=scale)


class FMHAFun:
    """ref fmha.py FMHAFun.apply shape (padded-dense qkv [b, s, 3, h, d]).

    ``cu_seqlens`` (cumulative, [b+1] — the reference's varlen boundary
    vector) or ``seqlens`` ([b]) mask out each sequence's padding; the
    reference's flat [total, 3, h, d] packing is a CUDA memory layout —
    on TPU batches stay padded-dense (static shapes) and the mask carries
    the varlen semantics.
    """

    @staticmethod
    def apply(qkv, cu_seqlens=None, seqlens=None, p_dropout=0.0,
              max_s=None, is_training=True, zero_tensors=False):
        del max_s, zero_tensors
        if p_dropout and is_training:
            raise NotImplementedError(
                "attention dropout: apply dropout to the output projection "
                "(TPU kernels keep the softmax deterministic); at eval "
                "(is_training=False) dropout is inactive and allowed")
        if qkv.ndim != 5:
            raise ValueError(
                "apex_tpu FMHAFun takes padded-dense qkv [b, s, 3, h, d]; "
                "flat varlen packing is a CUDA layout — unpack with "
                "cu_seqlens upstream")
        if seqlens is None and cu_seqlens is not None:
            cu = jnp.asarray(cu_seqlens)
            seqlens = cu[1:] - cu[:-1]
        return fmha_packed_qkv(qkv, seqlens=seqlens)
