"""Fused multi-head attention (ref apex/contrib/fmha/fmha.py FMHAFun +
csrc/fmha cutlass kernels) — backed by the Pallas TPU flash attention
kernel in :mod:`apex_tpu.ops.flash_attention`.

The reference consumes varlen packed sequences (qkv [total, 3, h, d] +
cu_seqlens). TPU-first design uses fixed-shape batches (dynamic shapes
defeat XLA); varlen batches are expressed with a padding mask or by packing
to a common length upstream.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention


def fmha(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """[b, s, h, d] fused attention (flash; no s×s HBM materialization)."""
    return flash_attention(q, k, v, causal=causal, scale=scale)


def fmha_packed_qkv(qkv, causal: bool = False,
                    scale: Optional[float] = None):
    """qkv [b, s, 3, h, d] (the reference's packed layout, batched)."""
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    return flash_attention(q, k, v, causal=causal, scale=scale)


class FMHAFun:
    """ref fmha.py FMHAFun.apply shape."""

    @staticmethod
    def apply(qkv, cu_seqlens=None, seqlens=None, p_dropout=0.0,
              max_s=None, is_training=True, zero_tensors=False):
        del cu_seqlens, seqlens, max_s, is_training, zero_tensors
        if p_dropout:
            raise NotImplementedError(
                "attention dropout: apply dropout to the output projection "
                "(TPU kernels keep the softmax deterministic)")
        return fmha_packed_qkv(qkv)
