"""Automatic mixed precision (TPU re-design of ``apex.amp``).

Ref: apex/amp/__init__.py. See frontend.py for the O0-O3 → TPU mapping.
"""

from apex_tpu.amp.frontend import (
    O0,
    O1,
    O2,
    O3,
    O4,
    Policy,
    Properties,
    initialize,
    opt_levels,
    state_dict,
    load_state_dict,
)
from apex_tpu.amp.handle import AmpHandle, NoOpHandle
from apex_tpu.amp._amp_state import master_params
from apex_tpu.amp.scaler import (
    Fp8DelayedScaler,
    Fp8ScalingState,
    Fp8SiteRecorder,
    LossScaler,
    LossScaleState,
    current_fp8,
    scaled_update,
)
from apex_tpu.amp import lists
from apex_tpu.amp.amp import (
    amp_call,
    casting,
    current_policy,
    float_function,
    half_function,
    promote_function,
    register_float_function,
    register_half_function,
    register_promote_function,
)

__all__ = [
    "Policy", "Properties", "initialize", "state_dict", "load_state_dict",
    "O0", "O1", "O2", "O3", "O4", "opt_levels",
    "AmpHandle", "NoOpHandle", "master_params",
    "LossScaler", "LossScaleState",
    "Fp8DelayedScaler", "Fp8ScalingState", "Fp8SiteRecorder",
    "current_fp8",
    "scaled_update", "lists",
    "amp_call", "casting", "current_policy", "half_function",
    "float_function", "promote_function", "register_half_function",
    "register_float_function", "register_promote_function",
]


def scale_loss(loss, optimizers=None):
    """Module-level ``amp.scale_loss`` parity (ref apex/amp/handle.py:40)."""
    from apex_tpu.amp._amp_state import _amp_state
    if _amp_state.handle is None:
        raise RuntimeError("amp.initialize must be called before amp.scale_loss")
    return _amp_state.handle.scale_loss(loss, optimizers)
