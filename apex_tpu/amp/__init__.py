"""Automatic mixed precision (TPU re-design of ``apex.amp``).

Ref: apex/amp/__init__.py. See frontend.py for the O0-O3 → TPU mapping.
"""

from apex_tpu.amp.frontend import (
    Policy,
    Properties,
    initialize,
    state_dict,
    load_state_dict,
)
from apex_tpu.amp.handle import AmpHandle
from apex_tpu.amp.scaler import LossScaler, LossScaleState, scaled_update
from apex_tpu.amp import lists

__all__ = [
    "Policy", "Properties", "initialize", "state_dict", "load_state_dict",
    "AmpHandle", "LossScaler", "LossScaleState", "scaled_update", "lists",
]


def scale_loss(loss, optimizers=None):
    """Module-level ``amp.scale_loss`` parity (ref apex/amp/handle.py:40)."""
    from apex_tpu.amp._amp_state import _amp_state
    if _amp_state.handle is None:
        raise RuntimeError("amp.initialize must be called before amp.scale_loss")
    return _amp_state.handle.scale_loss(loss, optimizers)
