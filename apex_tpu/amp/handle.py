"""AmpHandle + ``scale_loss`` — TPU re-design of ``apex.amp.handle``.

Ref: apex/amp/handle.py. The reference's ``with amp.scale_loss(loss, opt)``
multiplies the loss, then unscales grads and maybe skips ``opt.step()`` on
exit. JAX gradients are functional, so the handle exposes both:

- the **functional protocol** (use inside jit):
  ``scaled = handle.scale_loss(loss, sstate)`` →
  ``grads = jax.grad(...)`` →
  ``updates, opt_state, sstate, overflow = handle.scaled_update(tx, grads, ...)``
- a **stateful convenience** mirroring apex: a ``with handle.scale_loss(loss)
  as scaled:`` context (host-level loop only) whose scaler state lives on the
  handle, plus FusedOptimizer integration via :meth:`attach`.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.frontend import Policy, Properties
from apex_tpu.amp.scaler import LossScaler, scaled_update as _scaled_update


class AmpHandle:
    def __init__(self, props: Properties, min_loss_scale=None,
                 max_loss_scale=2.0 ** 24, half_dtype=jnp.bfloat16):
        self.props = props
        compute = half_dtype if props.opt_level in ("O1", "O2", "O3",
                                                    "O4") else jnp.float32
        param = props.cast_model_type or jnp.float32
        self.policy = Policy(
            param_dtype=param,
            compute_dtype=compute if props.enabled else jnp.float32,
            output_dtype=jnp.float32,
            keep_batchnorm_fp32=bool(props.keep_batchnorm_fp32)
            if props.keep_batchnorm_fp32 is not None else True,
        )
        self.scaler = LossScaler(
            loss_scale=props.loss_scale if props.enabled else 1.0,
            min_loss_scale=min_loss_scale,
            max_loss_scale=max_loss_scale,
            enabled=props.enabled and props.loss_scale != 1.0,
        )
        self.scaler_state = self.scaler.init()
        self._optimizers = []
        # O4 (ISSUE 13): the delayed-scaling automaton is bound lazily —
        # its site set depends on the step function, which the handle
        # cannot know at initialize() time. init_fp8() binds it; until
        # then state_dict() simply carries no "fp8" block.
        self.fp8_enabled = bool(getattr(props, "fp8", False))
        self.fp8_scaler = None
        self.fp8_state = None

    # ---- fp8 tier (O4) -----------------------------------------------------

    def init_fp8(self, sites, history: int = 16, margin: float = 0.0):
        """Bind the O4 delayed-scaling automaton to ``sites`` (matmul
        site names — see ``ops.precision.matmul_amp``) and initialize
        its state. Returns the :class:`~apex_tpu.amp.scaler.Fp8DelayedScaler`;
        the state lives on ``handle.fp8_state`` and rides
        ``state_dict()``/``load_state_dict()`` next to the loss-scale
        automaton."""
        from apex_tpu.amp.scaler import Fp8DelayedScaler

        if not self.fp8_enabled:
            raise RuntimeError(
                f"init_fp8 needs the O4 opt level (got "
                f"{self.props.opt_level}): only O4 enables the fp8 tier")
        self.fp8_scaler = Fp8DelayedScaler(sites, history=history,
                                           margin=margin)
        self.fp8_state = self.fp8_scaler.init()
        return self.fp8_scaler

    # ---- functional protocol ----------------------------------------------

    def scale(self, loss, scaler_state=None):
        return self.scaler.scale_loss(
            loss, scaler_state if scaler_state is not None else self.scaler_state)

    def scaled_update(self, tx, grads, opt_state, params, scaler_state,
                      overflow_reduce_axes=()):
        return _scaled_update(tx, self.scaler, grads, opt_state, params,
                              scaler_state,
                              overflow_reduce_axes=overflow_reduce_axes)

    # ---- stateful convenience (host-level loops) --------------------------

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer=None):
        """``with handle.scale_loss(loss) as scaled_loss:`` (ref handle.py:40).

        Yields the scaled loss; the matching unscale+skip runs inside the
        attached optimizer's ``step`` (see :meth:`attach`).
        """
        yield self.scale(loss)

    def attach(self, optimizers):
        """Patch FusedOptimizer.step to unscale, skip-on-overflow, advance the
        dynamic scale, and (O2) keep fp32 master weights — the
        ``_process_optimizer`` analog (ref apex/amp/_process_optimizer.py).

        The whole amp step is jitted ONCE per optimizer with the scaler state
        as a traced argument, so repeated ``step`` calls hit the compilation
        cache and the loss scale evolves on device.
        """
        if not isinstance(optimizers, (list, tuple)):
            optimizers = [optimizers]
        for opt in optimizers:
            if any(o is opt for o in self._optimizers):
                continue
            self._optimizers.append(opt)
            scaler = self.scaler
            tx = opt.tx
            use_master = bool(self.props.master_weights)
            if use_master:
                # fp32 master copy; the model params stay in their (half) dtype
                # and are re-materialized from the master each step
                # (ref _process_optimizer.py master param setup).
                opt.master_params = jax.tree_util.tree_map(
                    lambda p: p.astype(jnp.float32), opt.params)
                # moments must match the master tree's dtype/shape
                opt.state = tx.init(opt.master_params)

            import optax as _optax

            # NB: bind per-optimizer values as defaults — jit traces lazily at
            # the first step() call, which can happen after this loop has
            # moved on to the next optimizer.
            def amp_step(grads, state, params, master, scaler_state,
                         tx=tx, use_master=use_master, scaler=scaler):
                unscaled, overflow = scaler.unscale(grads, scaler_state)
                opt_params = master if use_master else params
                g32 = (jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), unscaled)
                    if use_master else unscaled)

                def do(_):
                    updates, new_state = tx.update(g32, state, opt_params)
                    return _optax.apply_updates(opt_params, updates), new_state

                new_opt_params, new_state = jax.lax.cond(
                    overflow, lambda _: (opt_params, state), do, None)
                if use_master:
                    new_params = jax.tree_util.tree_map(
                        lambda m, p: m.astype(p.dtype), new_opt_params, params)
                    new_master = new_opt_params
                else:
                    new_params, new_master = new_opt_params, master
                new_sstate = scaler.update(scaler_state, overflow)
                return new_params, new_master, new_state, new_sstate, overflow

            jitted = jax.jit(amp_step)
            handle = self

            def step(grads=None, closure=None, _opt=opt, _jitted=jitted,
                     _use_master=use_master):
                loss = closure() if closure is not None else None
                if grads is None:
                    raise ValueError("pass grads to step()")
                (_opt.params, master, _opt.state,
                 handle.scaler_state, _) = _jitted(
                    grads, _opt.state, _opt.params,
                    getattr(_opt, "master_params", _opt.params),
                    handle.scaler_state)
                if _use_master:
                    _opt.master_params = master
                return loss if loss is not None else _opt.params

            opt.step = step

    # ---- reference-parity surface (ref handle.py AmpHandle) ---------------

    @property
    def is_active(self) -> bool:
        """ref handle.py:179 — True while amp is enabled."""
        return bool(self.props.enabled)

    @property
    def verbose(self) -> bool:
        """ref handle.py verbose flag (initialize(verbosity=...))."""
        from apex_tpu.amp._amp_state import _amp_state
        return getattr(_amp_state, "verbosity", 1) > 1

    # The reference caches casted tensors to dodge repeated fp16 copies
    # (handle.py cache/has_cache/remove_cache). Under XLA the compilation
    # cache plays that role — casts are fused into the jitted program and
    # never re-materialized — so the cache is always empty here; the API
    # exists so reference-shaped training loops run unchanged.

    @property
    def cache(self) -> dict:
        return {}

    @property
    def has_cache(self) -> bool:
        return False

    def remove_cache(self) -> None:
        return None

    _clear_cache = remove_cache

    def wrap_optimizer(self, optimizer, num_loss=1):
        """ref handle.py:188 — attach amp's unscale/skip/regrow protocol
        to one optimizer and return it (ours patches ``step`` in place
        via :meth:`attach`; ``num_loss`` is accepted for parity — each
        loss shares the one in-graph scaler)."""
        del num_loss
        self.attach([optimizer])
        return optimizer

    @contextlib.contextmanager
    def disable_casts(self):
        """ref handle.py:164 — a region where mixed precision is off:
        the policy's compute/param dtype is fp32 inside the context, so
        ``cast_to_compute`` upcasts half inputs to fp32 instead of
        casting to the half dtype (apex semantics: with casts disabled,
        ops run at fp32). Only affects traces made INSIDE the region — a
        step already jitted against the old policy keeps its baked-in
        casts, exactly like a torch function captured before unpatching."""
        prev = self.policy
        self.policy = dataclasses.replace(
            prev, compute_dtype=jnp.float32, param_dtype=jnp.float32)
        try:
            yield
        finally:
            self.policy = prev

    # ---- checkpointing -----------------------------------------------------

    def state_dict(self) -> dict:
        """Loss-scale automaton (+ the O4 ``"fp8"`` block when bound).

        Round-trip contract (ISSUE 13 satellite): a legacy (pre-fp8)
        dict loads into an fp8-bearing handle with the fp8 state left
        at its fresh init, and an fp8-bearing dict loads into a legacy
        handle with the extra key ignored — state format drift never
        bricks a checkpoint in either direction."""
        d = self.scaler.state_dict(self.scaler_state)
        if self.fp8_scaler is not None and self.fp8_state is not None:
            d["fp8"] = self.fp8_scaler.state_dict(self.fp8_state)
        return d

    def load_state_dict(self, d: dict) -> None:
        self.scaler_state = self.scaler.load_state_dict(d)
        if self.fp8_scaler is not None and "fp8" in d:
            self.fp8_state = self.fp8_scaler.load_state_dict(d["fp8"])


class NoOpHandle:
    """ref handle.py:254 — the handle used when amp is disabled: every
    operation is the identity."""

    @property
    def is_active(self) -> bool:
        return False

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer=None):
        yield loss

    def scale(self, loss, scaler_state=None):
        return loss

    def wrap_optimizer(self, optimizer, num_loss=1):
        del num_loss
        return optimizer

    @contextlib.contextmanager
    def disable_casts(self):
        yield

    # same parity surface as AmpHandle — a loop handed either handle
    # must not AttributeError when amp is toggled off
    @property
    def verbose(self) -> bool:
        return False

    @property
    def cache(self) -> dict:
        return {}

    @property
    def has_cache(self) -> bool:
        return False

    def remove_cache(self) -> None:
        return None

    _clear_cache = remove_cache

    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, d: dict) -> None:
        del d
