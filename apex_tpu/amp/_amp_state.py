"""Process-level amp registry (ref apex/amp/_amp_state.py).

Holds the active :class:`~apex_tpu.amp.handle.AmpHandle` so module-level
``amp.state_dict()`` / ``amp.load_state_dict()`` work like the reference.
"""

from __future__ import annotations


class AmpState:
    def __init__(self):
        self.handle = None
        self.opt_properties = None
        self.verbosity = 1


_amp_state = AmpState()


def maybe_print(s: str, verbose: bool = False) -> None:
    if _amp_state.verbosity > (0 if verbose else 1) or (verbose and _amp_state.verbosity > 0):
        print(s)


def warn_or_err(msg: str) -> None:
    raise RuntimeError("\n".join(["", msg]))
