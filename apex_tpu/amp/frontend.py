"""amp frontend: opt levels O0–O3 and ``initialize`` — TPU re-design of
``apex.amp.frontend``.

Ref: apex/amp/frontend.py. The reference's opt levels configure (a) model
weight dtype, (b) torch-function patching, (c) master weights, (d) loss
scaling. The TPU mapping:

=====  ==================  =====================  ==============  ===========
level  param dtype         compute casting        master weights  loss scale
=====  ==================  =====================  ==============  ===========
O0     fp32                none                   no              1.0
O1     fp32                bf16 at op boundaries  no              dynamic
O2     bf16 (norms fp32)   bf16 params            fp32 (in opt)   dynamic
O3     bf16                pure bf16              no              1.0
O4     bf16 (norms fp32)   fp8 matmuls (E4M3/     fp32 (in opt)   dynamic
                           E5M2, delayed scaling)
=====  ==================  =====================  ==============  ===========

O4 (ISSUE 13) keeps O2's storage/master discipline and additionally
runs registered matmul sites in fp8 via
``apex_tpu.amp.scaler.Fp8DelayedScaler`` + ``ops.precision.matmul_fp8``
(see the fp8 table in lists.py and docs/amp.md — the delayed-scaling
state is separate, explicitly threaded through the train step).

bf16 replaces fp16 as the default "half" type (same MXU throughput, fp32
exponent range — the reason loss scaling is rarely *needed* on TPU, though
it is still fully supported; pass ``half_dtype=jnp.float16`` for strict
fp16 parity experiments). O1's torch-function monkeypatching has no XLA
analog — casting happens where ops are called, via :meth:`Policy.cast_to_compute`
and the fp32-internal fused kernels (see apex_tpu/amp/lists.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from apex_tpu.amp._amp_state import _amp_state, maybe_print, warn_or_err

_NORM_KEY_HINTS = ("batchnorm", "bn", "layernorm", "rmsnorm", "norm", "scale_bias")


@dataclasses.dataclass
class Properties:
    """Resolved amp options (ref apex/amp/frontend.py:7 Properties)."""

    enabled: bool = False
    opt_level: Optional[str] = None
    cast_model_type: Optional[Any] = None     # param dtype (None = leave)
    patch_jax_functions: bool = False          # O1-style boundary casting
    keep_batchnorm_fp32: Optional[bool] = None
    master_weights: Optional[bool] = None
    loss_scale: Union[float, str] = 1.0
    fp8: bool = False                          # O4: fp8 matmul epilogues


def _opt_level_props(opt_level: str, half) -> Properties:
    if opt_level not in opt_levels:
        raise ValueError(
            f"Unexpected optimization level {opt_level}. Options are 'O0', "
            "'O1', 'O2', 'O3', 'O4'. Note that in `O0`, `O1`, etc., the "
            "prefix O is the letter O, not the number zero.")
    return opt_levels[opt_level](Properties(), half)


class O0:
    """Pure fp32 training (ref frontend.py O0 descriptor)."""

    brief = "O0: pure FP32 training.\n"
    more = ("Params stay fp32, no boundary casting, no loss scaling — the "
            "ground-truth baseline every other level is compared against.\n")

    def __call__(self, properties, half=jnp.bfloat16):
        properties.enabled = True
        properties.opt_level = "O0"
        properties.cast_model_type = jnp.float32
        properties.patch_jax_functions = False
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O1:
    """Boundary casting, fp32 weights (ref frontend.py O1 descriptor)."""

    brief = "O1: insert automatic casts at op boundaries.\n"
    more = ("Weights stay fp32; MXU-friendly ops run in bf16 via the "
            "op-policy tables (apex_tpu/amp/lists.py) — the XLA analog of "
            "the reference's torch-function patching. The safest way to "
            "try mixed precision.\n")

    def __call__(self, properties, half=jnp.bfloat16):
        properties.enabled = True
        properties.opt_level = "O1"
        properties.cast_model_type = None
        properties.patch_jax_functions = True
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = None
        properties.loss_scale = "dynamic"
        return properties


class O2:
    """Half weights + fp32 master weights (ref frontend.py O2)."""

    brief = "O2: 'almost half' — half model, fp32 master weights.\n"
    more = ("Params are cast to the half dtype (norm params stay fp32), "
            "the optimizer keeps fp32 master weights, dynamic loss "
            "scaling guards the update.\n")

    def __call__(self, properties, half=jnp.bfloat16):
        properties.enabled = True
        properties.opt_level = "O2"
        properties.cast_model_type = half
        properties.patch_jax_functions = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        properties.loss_scale = "dynamic"
        return properties


class O3:
    """Pure half training (ref frontend.py O3)."""

    brief = "O3: pure half-precision training.\n"
    more = ("Everything in the half dtype, no master weights, no loss "
            "scaling — the speed-of-light baseline for perf comparisons.\n")

    def __call__(self, properties, half=jnp.bfloat16):
        properties.enabled = True
        properties.opt_level = "O3"
        properties.cast_model_type = half
        properties.patch_jax_functions = False
        properties.keep_batchnorm_fp32 = False
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O4:
    """fp8 (E4M3/E5M2) compute with delayed scaling (ISSUE 13)."""

    brief = "O4: fp8 matmuls (E4M3 fwd / E5M2 grad) with delayed scaling.\n"
    more = ("O2's storage discipline (bf16 model, fp32 norms + master "
            "weights, dynamic loss scale) plus fp8 matmul epilogues: "
            "registered sites quantize operands to E4M3 and backward "
            "cotangents to E5M2 under per-tensor delayed scales from "
            "AmaxHistory rings (apex_tpu.amp.scaler.Fp8DelayedScaler). "
            "The precision sanitizer rejects unsafe fp8 graphs "
            "statically (fp8-unscaled / fp8-stale-amax).\n")

    def __call__(self, properties, half=jnp.bfloat16):
        properties.enabled = True
        properties.opt_level = "O4"
        properties.cast_model_type = half
        properties.patch_jax_functions = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        properties.loss_scale = "dynamic"
        properties.fp8 = True
        return properties


opt_levels = {"O0": O0(), "O1": O1(), "O2": O2(), "O3": O3(),
              "O4": O4()}


@dataclasses.dataclass(frozen=True)
class Policy:
    """Dtype policy derived from an opt level (jmp-style three-dtype policy)."""

    param_dtype: Any
    compute_dtype: Any
    output_dtype: Any
    keep_batchnorm_fp32: bool = True

    def cast_to_compute(self, tree):
        """Cast activations/params entering a compute region (O1 boundary cast)."""
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    def cast_to_param(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.param_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    def cast_to_output(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.output_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    def cast_model(self, params):
        """Cast a model param tree to param_dtype, keeping norm/bn params fp32
        when ``keep_batchnorm_fp32`` (ref apex/amp/_initialize.py BN handling).

        Norm parameters are recognized by their flax module path (e.g.
        ``BatchNorm_0``, ``FusedLayerNorm_0``) — the tree-path analog of the
        reference's isinstance checks on module types.
        """
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        treedef = jax.tree_util.tree_structure(params)

        def cast_one(path, leaf):
            if not jnp.issubdtype(leaf.dtype, jnp.floating):
                return leaf
            if self.keep_batchnorm_fp32:
                keys = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path).lower()
                if any(h in keys for h in _NORM_KEY_HINTS):
                    return leaf.astype(jnp.float32)
            return leaf.astype(self.param_dtype)

        leaves = [cast_one(path, leaf) for path, leaf in flat]
        return jax.tree_util.tree_unflatten(treedef, leaves)


def initialize(
    models=None,
    optimizers=None,
    enabled: bool = True,
    opt_level: str = "O1",
    cast_model_type=None,
    patch_jax_functions=None,
    keep_batchnorm_fp32=None,
    master_weights=None,
    loss_scale=None,
    min_loss_scale=None,
    max_loss_scale=2.0 ** 24,
    half_dtype=jnp.bfloat16,
    verbosity: int = 1,
    **kwargs,
):
    """Ref apex/amp/frontend.py:initialize (O0–O3 convenience wrapper).

    Functional JAX form: ``models`` is a params pytree (or None). Returns
    ``(cast_params, optimizers, handle)`` when params are given, else just
    the :class:`AmpHandle`. The handle carries the dtype :class:`Policy` and
    the functional :class:`LossScaler`; see ``apex_tpu/amp/handle.py``.
    """
    from apex_tpu.amp.handle import AmpHandle

    _amp_state.verbosity = verbosity
    props = _opt_level_props(opt_level, half_dtype)
    if not enabled:
        props.enabled = False
    # user overrides (ref frontend.py override block)
    if cast_model_type is not None:
        if props.opt_level == "O1" and cast_model_type not in (None, jnp.float32):
            warn_or_err("O1 keeps model weights fp32; use O2/O3 to cast weights.")
        props.cast_model_type = cast_model_type
    if patch_jax_functions is not None:
        props.patch_jax_functions = patch_jax_functions
    if keep_batchnorm_fp32 is not None:
        if isinstance(keep_batchnorm_fp32, str):
            keep_batchnorm_fp32 = keep_batchnorm_fp32 == "True"
        props.keep_batchnorm_fp32 = keep_batchnorm_fp32
    if master_weights is not None:
        props.master_weights = master_weights
    if loss_scale is not None:
        props.loss_scale = loss_scale

    maybe_print(f"Selected optimization level {opt_level}", True)

    handle = AmpHandle(props, min_loss_scale=min_loss_scale,
                       max_loss_scale=max_loss_scale, half_dtype=half_dtype)
    _amp_state.handle = handle
    _amp_state.opt_properties = props

    if models is None:
        return handle

    # disabled amp is a complete no-op (ref frontend.py: if not enabled, return
    # models/optimizers unchanged)
    cast_params = (
        handle.policy.cast_model(models)
        if (props.enabled and props.cast_model_type) else models)
    if optimizers is None:
        return cast_params, handle
    if props.enabled:  # disabled amp leaves the optimizer untouched too
        handle.attach(optimizers)
    return cast_params, optimizers, handle


def state_dict(destination=None):
    """Module-level amp checkpoint (ref apex/amp/frontend.py:state_dict)."""
    if _amp_state.handle is None:
        return {}
    return _amp_state.handle.state_dict()


def load_state_dict(state_dict_):
    """Ref apex/amp/frontend.py:load_state_dict."""
    if _amp_state.handle is None:
        raise RuntimeError("amp.initialize must be called before amp.load_state_dict")
    _amp_state.handle.load_state_dict(state_dict_)
