"""Op-category precision tables — TPU re-design of ``apex.amp.lists``.

Ref: apex/amp/lists/{functional_overrides,torch_overrides,tensor_overrides}.py.

The reference monkeypatches torch functions at O1 so MXU-friendly ops run
fp16 and range-sensitive ops run fp32. Under XLA nothing can (or should) be
patched — casting is decided where the op is *called*. These tables encode
the same classification for JAX ops; ``Policy.run_fp32`` /
``Policy.cast_to_compute`` (frontend.py) and the fused kernels consume them:
every apex_tpu fused kernel (layer_norm, softmax, cross-entropy) already
computes fp32 internally regardless of storage dtype, which is exactly the
behavior the FP32_FUNCS list enforces on GPU.
"""

# MXU-friendly: run in compute (bf16/fp16) precision — ref functional_overrides.py FP16_FUNCS
COMPUTE_PRECISION_OPS = frozenset({
    "dot", "dot_general", "conv", "conv_general_dilated", "einsum", "matmul",
    "dense", "linear", "attention_qk", "attention_av",
})

# Range-sensitive: force fp32 math — ref functional_overrides.py FP32_FUNCS
FP32_OPS = frozenset({
    "softmax", "log_softmax", "layer_norm", "rms_norm", "batch_norm",
    "group_norm", "cross_entropy", "nll_loss", "mse_loss", "cosine_similarity",
    "exp", "log", "pow", "sum", "mean", "var", "std", "norm", "cumsum",
    "erf", "erfinv", "softplus", "sigmoid_focal_loss",
})

# Type-promotion ops: widest input dtype wins — ref tensor_overrides.py CASTS
PROMOTE_OPS = frozenset({
    "add", "sub", "mul", "div", "where", "concatenate", "stack", "maximum",
    "minimum",
})


def classify(op_name: str) -> str:
    """Return 'compute', 'fp32', or 'promote' for an op name."""
    if op_name in COMPUTE_PRECISION_OPS:
        return "compute"
    if op_name in FP32_OPS:
        return "fp32"
    return "promote"


# --------------------------------------------------------------- fp8 (O4)
# The O4 policy table ("FP8 Formats for Deep Learning", Micikevicius et
# al. 2022): contractions run on the MXU in fp8 — E4M3 for the forward
# operands (activations + weights: more mantissa, 448 max), E5M2 for the
# backward cotangents (more range, 57344 max) — every tensor scaled by
# its delayed per-tensor factor before the cast
# (apex_tpu.amp.scaler.Fp8DelayedScaler over AmaxHistory rings).
# Everything else keeps the O2 discipline: bf16 storage/elementwise,
# fp32 for range-sensitive math and optimizer state.

#: ops whose *forward* operands quantize to E4M3 under O4. These are the
#: only op shapes the fp8 tier converts — all are matmul-family MXU work
#: routed through ops.precision.matmul_fp8 / einsum_fp8.
FP8_E4M3_FWD_OPS = frozenset({
    "dot", "dot_general", "matmul", "einsum", "dense", "linear",
})

#: ops whose *backward* cotangents quantize to E5M2 under O4 (the vjp
#: side of the table above — matmul_fp8's custom_vjp implements it).
FP8_E5M2_GRAD_OPS = FP8_E4M3_FWD_OPS

#: MXU-friendly but fp8-unsafe: stays in the bf16 compute dtype under O4
#: (attention logits/probs keep bf16 until an fp8 flash path exists;
#: convs are out of the llama workload's scope).
FP8_BF16_FALLBACK_OPS = frozenset({
    "attention_qk", "attention_av", "conv", "conv_general_dilated",
})

#: range-sensitive or state math: fp32 under O4, exactly the O1/O2
#: FP32_OPS discipline plus the scaling machinery itself (amax
#: reductions and scale arithmetic must never quantize).
FP8_FP32_OPS = FP32_OPS | frozenset({"amax", "scale", "optimizer_update"})


def classify_fp8(op_name: str) -> str:
    """O4 classification for an op name: ``'fp8'`` (E4M3 fwd / E5M2
    grad via the delayed-scaling epilogues), ``'fp32'``, ``'bf16'``
    (explicitly listed MXU-but-fp8-unsafe work), or ``'promote'`` for
    ops in none of the tables — widest-input promotion, the same
    default :func:`classify` gives O1."""
    if op_name in FP8_E4M3_FWD_OPS:
        return "fp8"
    if op_name in FP8_FP32_OPS:
        return "fp32"
    if op_name in FP8_BF16_FALLBACK_OPS:
        return "bf16"
    return "promote"
