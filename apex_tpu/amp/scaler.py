"""Loss scaling — TPU re-design of ``apex.amp.scaler.LossScaler``.

Ref: apex/amp/scaler.py (+ apex/fp16_utils/loss_scaler.py).

The CUDA scaler syncs an overflow flag to the host every step
(``overflow = scale_check.item()``) and skips ``optimizer.step()`` in Python.
Here the whole protocol is in-graph: the overflow check is a fused
``isfinite`` reduction, the skip is a ``lax.cond``/``where``, and the
dynamic-scale automaton (halve on overflow, double every ``scale_window``
clean steps) updates as traced arithmetic — zero host syncs per step.

bf16 training on TPU usually needs no loss scaling (bf16 has fp32's
exponent range); the scaler exists for fp16 parity and for gradient-range
safety nets. ``LossScaler(enabled=False)`` compiles to nothing.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


def _promote_varying(x, axes):
    """Mark ``x`` varying over the mesh axes in ``axes`` it isn't already
    (no-op outside shard_map / for already-varying values), with the
    pcast→pvary fallback for older jax."""
    try:
        have = getattr(jax.typeof(x), "vma", frozenset()) or frozenset()
    except Exception:
        have = frozenset()
    missing = tuple(sorted(set(axes) - set(have)))
    if not missing:
        return x
    try:
        return jax.lax.pcast(x, missing, to="varying")
    except (AttributeError, TypeError):
        return jax.lax.pvary(x, missing)


class LossScaleState(NamedTuple):
    """Functional scaler state (carried through the jitted train step).

    ``steps``/``last_overflow_step``/``skip_streak`` are the ISSUE 9
    readout fields: the health detectors need *when* the last overflow
    hit and *how many in a row*, not just the cumulative count — a
    scaler stuck skipping every step looks identical to a healthy one
    through ``overflows`` alone until the loss curve dies.
    """

    loss_scale: jax.Array      # f32 scalar
    unskipped: jax.Array       # i32: clean steps since last rescale (ref scaler.py:_unskipped)
    overflows: jax.Array       # i32: total overflow count (diagnostics)
    steps: jax.Array           # i32: total update() calls
    last_overflow_step: jax.Array  # i32: step index of newest overflow (-1 = never)
    skip_streak: jax.Array     # i32: consecutive overflow-skipped steps


class LossScaler:
    """Static + dynamic loss scaling with in-graph overflow skip.

    ``dynamic=True`` mirrors apex's default dynamic scaler
    (init 2**16, x2 growth every 2000 unskipped steps, /2 on overflow).
    """

    def __init__(self, loss_scale="dynamic", init_scale=2.0 ** 16,
                 scale_factor=2.0, scale_window=2000,
                 min_loss_scale=None, max_loss_scale=2.0 ** 24, enabled=True,
                 backoff_factor=None):
        self.dynamic = loss_scale == "dynamic"
        self._static_scale = 1.0 if self.dynamic else float(loss_scale)
        self.init_scale = init_scale if self.dynamic else self._static_scale
        self.scale_factor = scale_factor
        # apex default: backoff is symmetric (1/growth); torch-GradScaler
        # style asymmetric backoff is supported via an explicit factor
        self.backoff_factor = (1.0 / scale_factor if backoff_factor is None
                               else backoff_factor)
        self.scale_window = scale_window
        self.min_loss_scale = min_loss_scale
        self.max_loss_scale = max_loss_scale
        self.enabled = enabled

    def init(self) -> LossScaleState:
        return LossScaleState(
            loss_scale=jnp.asarray(self.init_scale if self.enabled else 1.0, jnp.float32),
            unskipped=jnp.zeros([], jnp.int32),
            overflows=jnp.zeros([], jnp.int32),
            steps=jnp.zeros([], jnp.int32),
            last_overflow_step=jnp.full([], -1, jnp.int32),
            skip_streak=jnp.zeros([], jnp.int32),
        )

    # ---- in-graph protocol -------------------------------------------------

    def scale_loss(self, loss, state: LossScaleState):
        """Ref apex/amp/handle.py:scale_loss — multiply before backward."""
        if not self.enabled:
            return loss
        return loss * state.loss_scale.astype(loss.dtype)

    def unscale(self, grads, state: LossScaleState):
        """Unscale grads and detect inf/nan in one fused pass.

        Returns ``(unscaled_grads, overflow)``; overflow is a traced bool
        (ref apex/amp/scaler.py:unscale + axpby_check_overflow).
        """
        if not self.enabled:
            return grads, jnp.zeros([], jnp.bool_)
        inv = 1.0 / state.loss_scale
        unscaled = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
        leaves = jax.tree_util.tree_leaves(unscaled)
        finite = jnp.array(True)
        for l in leaves:
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(l)))
        return unscaled, jnp.logical_not(finite)

    def update(self, state: LossScaleState, overflow) -> LossScaleState:
        """Dynamic-scale automaton (ref apex/amp/scaler.py:update_scale).

        The diagnostics fields (overflow count/step/streak) advance for
        ANY enabled scaler — a static scale still skips steps on
        overflow via ``scaled_update``'s cond, and those skips must be
        observable; only the scale value itself is dynamic-gated.
        """
        if not self.enabled:
            return state
        overflow = jnp.asarray(overflow)
        ovf_i = overflow.astype(jnp.int32)
        # this update closes step index `state.steps` (0-based)
        diag = dict(
            overflows=state.overflows + ovf_i,
            steps=state.steps + 1,
            last_overflow_step=jnp.where(
                overflow, state.steps,
                state.last_overflow_step).astype(jnp.int32),
            skip_streak=jnp.where(overflow, state.skip_streak + 1,
                                  0).astype(jnp.int32),
        )
        if not self.dynamic:
            return state._replace(**diag)
        halved = state.loss_scale * self.backoff_factor
        if self.min_loss_scale is not None:  # ref default: no floor
            halved = jnp.maximum(halved, self.min_loss_scale)
        new_scale = jnp.where(
            overflow,
            halved,
            jnp.where(
                state.unskipped + 1 >= self.scale_window,
                jnp.minimum(state.loss_scale * self.scale_factor, self.max_loss_scale),
                state.loss_scale,
            ),
        )
        new_unskipped = jnp.where(
            overflow | (state.unskipped + 1 >= self.scale_window),
            0, state.unskipped + 1).astype(jnp.int32)
        return state._replace(
            loss_scale=new_scale,
            unskipped=new_unskipped,
            **diag,
        )

    def loss_scale(self, state: LossScaleState):
        return state.loss_scale

    # ---- host-side diagnostics (ISSUE 2 satellite) ------------------------

    def overflow_count(self, state: LossScaleState) -> int:
        """Cumulative overflow/skip count as a host int.

        The in-graph automaton tracks ``state.overflows`` as a traced
        i32 (zero host syncs per step); this is the sanctioned read-out
        for logging cadence — one device fetch per CALL, so poll it at
        report intervals, not per step. Until now the count was only
        provable via multichip dryrun logs; this makes it first-class.
        """
        return int(jax.device_get(state.overflows))

    def report(self, state: LossScaleState, registry=None,
               prefix: str = "amp", grads=None, top_k: int = 3) -> dict:
        """Publish scaler health to a metrics registry (default: the
        process registry): gauges ``<prefix>/loss_scale``,
        ``<prefix>/overflow_count``, ``<prefix>/unskipped_steps``,
        plus (ISSUE 9) ``<prefix>/last_overflow_step`` and
        ``<prefix>/skip_streak`` — the fields the numerics
        ``HealthMonitor``'s overflow-streak detector consumes.
        Returns the values as a dict. One host sync per call.

        ``grads``: pass the (scaled) grads pytree when the last update
        overflowed and the readout should say WHICH tensors blew up —
        one fused stats pass names the top-``top_k`` tensors by amax
        (+ any outright non-finite paths) in an ``amp_overflow`` event
        and a ``top_offenders`` key. Skipped on clean steps, so the
        stats pass costs nothing in the steady state.
        """
        from apex_tpu.observability import get_registry

        host = jax.device_get(state)
        values = {
            "loss_scale": float(host.loss_scale),
            "overflow_count": int(host.overflows),
            "unskipped_steps": int(host.unskipped),
            "last_overflow_step": int(host.last_overflow_step),
            "skip_streak": int(host.skip_streak),
        }
        reg = registry if registry is not None else get_registry()
        for name, v in values.items():
            reg.gauge(f"{prefix}/{name}").set(v)
        if grads is not None and values["skip_streak"] > 0:
            from apex_tpu.observability import numerics

            per_tensor = numerics.host_tensor_stats(grads)
            summary = numerics.summarize_stats(per_tensor, top_k=top_k)
            values["top_offenders"] = summary["worst_amax"]
            reg.event("amp_overflow", prefix=prefix,
                      step=values["last_overflow_step"],
                      skip_streak=values["skip_streak"],
                      loss_scale=values["loss_scale"],
                      top_offenders=summary["worst_amax"],
                      nonfinite_paths=summary["nonfinite_paths"])
        return values

    # ---- checkpointing (ref apex/amp/frontend.py:state_dict) --------------

    def state_dict(self, state: LossScaleState) -> dict:
        host = jax.device_get(state)
        return {
            "loss_scale": host.loss_scale.item(),
            "unskipped": host.unskipped.item(),
            "overflows": host.overflows.item(),
            "steps": host.steps.item(),
            "last_overflow_step": host.last_overflow_step.item(),
            "skip_streak": host.skip_streak.item(),
        }

    def load_state_dict(self, d: dict) -> LossScaleState:
        # .get defaults: dicts written before the ISSUE 9 fields load
        # with the "never overflowed yet" readout
        return LossScaleState(
            loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
            unskipped=jnp.asarray(d["unskipped"], jnp.int32),
            overflows=jnp.asarray(d.get("overflows", 0), jnp.int32),
            steps=jnp.asarray(d.get("steps", 0), jnp.int32),
            last_overflow_step=jnp.asarray(
                d.get("last_overflow_step", -1), jnp.int32),
            skip_streak=jnp.asarray(d.get("skip_streak", 0), jnp.int32),
        )


def scaled_update(tx, scaler: LossScaler, grads, opt_state, params,
                  scaler_state, overflow_reduce_axes=()):
    """One amp step: unscale → overflow check → conditional optimizer update.

    The TPU-native equivalent of apex's ``scale_loss`` context epilogue +
    patched ``optimizer.step`` skip (ref apex/amp/_process_optimizer.py).
    On overflow the optimizer state and params are left untouched via
    ``lax.cond`` — the whole step stays on device.

    Inside ``shard_map``, pass every mesh axis name in
    ``overflow_reduce_axes``: the overflow flag is psum-voted across them
    so ALL ranks take the same cond branch (the in-graph analog of the
    reference's NCCL-allreduced overflow buffer,
    ref apex/amp/scaler.py:unscale_with_stashed + _amp_state master flag).

    Returns ``(updates, new_opt_state, new_scaler_state, overflow)``.
    """
    unscaled, overflow = scaler.unscale(grads, scaler_state)
    if overflow_reduce_axes:
        ovf = _promote_varying(overflow.astype(jnp.float32),
                               overflow_reduce_axes)
        overflow = jax.lax.psum(ovf, tuple(overflow_reduce_axes)) > 0

    def do_update(_):
        return tx.update(unscaled, opt_state, params)

    # both cond branches must produce identical avals; derive the skip
    # branch's zeros from the update branch's output shapes/dtypes (updates
    # may be in grad dtype while params are in model dtype). Under
    # shard_map the update branch's avals can be VARYING over mesh axes
    # (e.g. grads a custom_vjp kernel left per-device local) — match each
    # leaf's vma or lax.cond rejects the branches with a type error.
    out_shapes = jax.eval_shape(do_update, None)

    def _match_vma(x, sd):
        return _promote_varying(x, getattr(sd, "vma", frozenset())
                                or frozenset())

    def skip(_):
        zeros = jax.tree_util.tree_map(
            lambda sd: _match_vma(jnp.zeros(sd.shape, sd.dtype), sd),
            out_shapes[0])
        kept = jax.tree_util.tree_map(_match_vma, opt_state, out_shapes[1])
        return zeros, kept

    updates, new_opt_state = jax.lax.cond(overflow, skip, do_update, None)
    new_scaler_state = scaler.update(scaler_state, overflow)
    return updates, new_opt_state, new_scaler_state, overflow
