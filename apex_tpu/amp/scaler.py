"""Loss scaling — TPU re-design of ``apex.amp.scaler.LossScaler``.

Ref: apex/amp/scaler.py (+ apex/fp16_utils/loss_scaler.py).

The CUDA scaler syncs an overflow flag to the host every step
(``overflow = scale_check.item()``) and skips ``optimizer.step()`` in Python.
Here the whole protocol is in-graph: the overflow check is a fused
``isfinite`` reduction, the skip is a ``lax.cond``/``where``, and the
dynamic-scale automaton (halve on overflow, double every ``scale_window``
clean steps) updates as traced arithmetic — zero host syncs per step.

bf16 training on TPU usually needs no loss scaling (bf16 has fp32's
exponent range); the scaler exists for fp16 parity and for gradient-range
safety nets. ``LossScaler(enabled=False)`` compiles to nothing.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


def _promote_varying(x, axes):
    """Mark ``x`` varying over the mesh axes in ``axes`` it isn't already
    (no-op outside shard_map / for already-varying values), with the
    pcast→pvary fallback for older jax."""
    try:
        have = getattr(jax.typeof(x), "vma", frozenset()) or frozenset()
    except Exception:
        have = frozenset()
    missing = tuple(sorted(set(axes) - set(have)))
    if not missing:
        return x
    try:
        return jax.lax.pcast(x, missing, to="varying")
    except (AttributeError, TypeError):
        return jax.lax.pvary(x, missing)


class LossScaleState(NamedTuple):
    """Functional scaler state (carried through the jitted train step).

    ``steps``/``last_overflow_step``/``skip_streak`` are the ISSUE 9
    readout fields: the health detectors need *when* the last overflow
    hit and *how many in a row*, not just the cumulative count — a
    scaler stuck skipping every step looks identical to a healthy one
    through ``overflows`` alone until the loss curve dies.
    """

    loss_scale: jax.Array      # f32 scalar
    unskipped: jax.Array       # i32: clean steps since last rescale (ref scaler.py:_unskipped)
    overflows: jax.Array       # i32: total overflow count (diagnostics)
    steps: jax.Array           # i32: total update() calls
    last_overflow_step: jax.Array  # i32: step index of newest overflow (-1 = never)
    skip_streak: jax.Array     # i32: consecutive overflow-skipped steps


class LossScaler:
    """Static + dynamic loss scaling with in-graph overflow skip.

    ``dynamic=True`` mirrors apex's default dynamic scaler
    (init 2**16, x2 growth every 2000 unskipped steps, /2 on overflow).
    """

    def __init__(self, loss_scale="dynamic", init_scale=2.0 ** 16,
                 scale_factor=2.0, scale_window=2000,
                 min_loss_scale=None, max_loss_scale=2.0 ** 24, enabled=True,
                 backoff_factor=None):
        self.dynamic = loss_scale == "dynamic"
        self._static_scale = 1.0 if self.dynamic else float(loss_scale)
        self.init_scale = init_scale if self.dynamic else self._static_scale
        self.scale_factor = scale_factor
        # apex default: backoff is symmetric (1/growth); torch-GradScaler
        # style asymmetric backoff is supported via an explicit factor
        self.backoff_factor = (1.0 / scale_factor if backoff_factor is None
                               else backoff_factor)
        self.scale_window = scale_window
        self.min_loss_scale = min_loss_scale
        self.max_loss_scale = max_loss_scale
        self.enabled = enabled

    def init(self) -> LossScaleState:
        return LossScaleState(
            loss_scale=jnp.asarray(self.init_scale if self.enabled else 1.0, jnp.float32),
            unskipped=jnp.zeros([], jnp.int32),
            overflows=jnp.zeros([], jnp.int32),
            steps=jnp.zeros([], jnp.int32),
            last_overflow_step=jnp.full([], -1, jnp.int32),
            skip_streak=jnp.zeros([], jnp.int32),
        )

    # ---- in-graph protocol -------------------------------------------------

    def scale_loss(self, loss, state: LossScaleState):
        """Ref apex/amp/handle.py:scale_loss — multiply before backward."""
        if not self.enabled:
            return loss
        return loss * state.loss_scale.astype(loss.dtype)

    def unscale(self, grads, state: LossScaleState):
        """Unscale grads and detect inf/nan in one fused pass.

        Returns ``(unscaled_grads, overflow)``; overflow is a traced bool
        (ref apex/amp/scaler.py:unscale + axpby_check_overflow).
        """
        if not self.enabled:
            return grads, jnp.zeros([], jnp.bool_)
        inv = 1.0 / state.loss_scale
        unscaled = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
        leaves = jax.tree_util.tree_leaves(unscaled)
        finite = jnp.array(True)
        for l in leaves:
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(l)))
        return unscaled, jnp.logical_not(finite)

    def update(self, state: LossScaleState, overflow) -> LossScaleState:
        """Dynamic-scale automaton (ref apex/amp/scaler.py:update_scale).

        The diagnostics fields (overflow count/step/streak) advance for
        ANY enabled scaler — a static scale still skips steps on
        overflow via ``scaled_update``'s cond, and those skips must be
        observable; only the scale value itself is dynamic-gated.
        """
        if not self.enabled:
            return state
        overflow = jnp.asarray(overflow)
        ovf_i = overflow.astype(jnp.int32)
        # this update closes step index `state.steps` (0-based)
        diag = dict(
            overflows=state.overflows + ovf_i,
            steps=state.steps + 1,
            last_overflow_step=jnp.where(
                overflow, state.steps,
                state.last_overflow_step).astype(jnp.int32),
            skip_streak=jnp.where(overflow, state.skip_streak + 1,
                                  0).astype(jnp.int32),
        )
        if not self.dynamic:
            return state._replace(**diag)
        halved = state.loss_scale * self.backoff_factor
        if self.min_loss_scale is not None:  # ref default: no floor
            halved = jnp.maximum(halved, self.min_loss_scale)
        new_scale = jnp.where(
            overflow,
            halved,
            jnp.where(
                state.unskipped + 1 >= self.scale_window,
                jnp.minimum(state.loss_scale * self.scale_factor, self.max_loss_scale),
                state.loss_scale,
            ),
        )
        new_unskipped = jnp.where(
            overflow | (state.unskipped + 1 >= self.scale_window),
            0, state.unskipped + 1).astype(jnp.int32)
        return state._replace(
            loss_scale=new_scale,
            unskipped=new_unskipped,
            **diag,
        )

    def loss_scale(self, state: LossScaleState):
        return state.loss_scale

    # ---- host-side diagnostics (ISSUE 2 satellite) ------------------------

    def overflow_count(self, state: LossScaleState) -> int:
        """Cumulative overflow/skip count as a host int.

        The in-graph automaton tracks ``state.overflows`` as a traced
        i32 (zero host syncs per step); this is the sanctioned read-out
        for logging cadence — one device fetch per CALL, so poll it at
        report intervals, not per step. Until now the count was only
        provable via multichip dryrun logs; this makes it first-class.
        """
        return int(jax.device_get(state.overflows))

    def report(self, state: LossScaleState, registry=None,
               prefix: str = "amp", grads=None, top_k: int = 3) -> dict:
        """Publish scaler health to a metrics registry (default: the
        process registry): gauges ``<prefix>/loss_scale``,
        ``<prefix>/overflow_count``, ``<prefix>/unskipped_steps``,
        plus (ISSUE 9) ``<prefix>/last_overflow_step`` and
        ``<prefix>/skip_streak`` — the fields the numerics
        ``HealthMonitor``'s overflow-streak detector consumes.
        Returns the values as a dict. One host sync per call.

        ``grads``: pass the (scaled) grads pytree when the last update
        overflowed and the readout should say WHICH tensors blew up —
        one fused stats pass names the top-``top_k`` tensors by amax
        (+ any outright non-finite paths) in an ``amp_overflow`` event
        and a ``top_offenders`` key. Skipped on clean steps, so the
        stats pass costs nothing in the steady state.
        """
        from apex_tpu.observability import get_registry

        host = jax.device_get(state)
        values = {
            "loss_scale": float(host.loss_scale),
            "overflow_count": int(host.overflows),
            "unskipped_steps": int(host.unskipped),
            "last_overflow_step": int(host.last_overflow_step),
            "skip_streak": int(host.skip_streak),
        }
        reg = registry if registry is not None else get_registry()
        for name, v in values.items():
            reg.gauge(f"{prefix}/{name}").set(v)
        if grads is not None and values["skip_streak"] > 0:
            from apex_tpu.observability import numerics

            per_tensor = numerics.host_tensor_stats(grads)
            summary = numerics.summarize_stats(per_tensor, top_k=top_k)
            values["top_offenders"] = summary["worst_amax"]
            reg.event("amp_overflow", prefix=prefix,
                      step=values["last_overflow_step"],
                      skip_streak=values["skip_streak"],
                      loss_scale=values["loss_scale"],
                      top_offenders=summary["worst_amax"],
                      nonfinite_paths=summary["nonfinite_paths"])
        return values

    # ---- checkpointing (ref apex/amp/frontend.py:state_dict) --------------

    def state_dict(self, state: LossScaleState) -> dict:
        host = jax.device_get(state)
        return {
            "loss_scale": host.loss_scale.item(),
            "unskipped": host.unskipped.item(),
            "overflows": host.overflows.item(),
            "steps": host.steps.item(),
            "last_overflow_step": host.last_overflow_step.item(),
            "skip_streak": host.skip_streak.item(),
        }

    def load_state_dict(self, d: dict) -> LossScaleState:
        # Compat contract (ISSUE 13 satellite, explicit tests in
        # tests/run_amp/test_fp8.py): every field except loss_scale
        # defaults, so legacy (pre-ISSUE-9 / pre-fp8) dicts load with
        # the "never overflowed yet" readout — and unknown EXTRA keys
        # (e.g. the O4 handle's "fp8" block read by an older build) are
        # simply ignored, never fatal.
        return LossScaleState(
            loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
            unskipped=jnp.asarray(d.get("unskipped", 0), jnp.int32),
            overflows=jnp.asarray(d.get("overflows", 0), jnp.int32),
            steps=jnp.asarray(d.get("steps", 0), jnp.int32),
            last_overflow_step=jnp.asarray(
                d.get("last_overflow_step", -1), jnp.int32),
            skip_streak=jnp.asarray(d.get("skip_streak", 0), jnp.int32),
        )


# --------------------------------------------------------------- fp8 (O4)
# Delayed-scaling automaton on top of the ISSUE 9 AmaxHistory rings
# (observability/numerics/history.py): each registered matmul site owns
# three ring rows — its two forward operands (E4M3) and its grad
# cotangent (E5M2). Scales are computed from the ring max (previous
# steps' amaxes — one step of staleness buys an on-device scale), the
# per-step update is a single column write per ring, and the whole
# state is a plain pytree that rides checkpoint.py's atomic manifest
# bit-identically (proved under the PR 5 chaos harness in
# tests/run_resilience/test_fp8_roundtrip.py).
#
# The *mechanism* is trace-time: a step enters `scaler.step(state)` and
# every `ops.precision.matmul_amp` call site inside the context turns
# into a scaled fp8 matmul, recording its amax observations into the
# context (plain Python at trace time, so the whole protocol jits).
# Sites are identified by (name, trace-order ordinal) — deterministic
# for a fixed step function; sites the scaler was not built with fall
# back to the fp32-accum path (which is what keeps decoder matmuls
# inside lax.scan/vmap safe: a collected tracer may never escape a
# transform, so only top-level sites are ever registered).


class Fp8ScalingState(NamedTuple):
    """Functional delayed-scaling state — carry it through the jitted
    train step and checkpoint it with the rest of the train state."""

    fwd: Any     # AmaxHistoryState over <site>/a, <site>/b rows (E4M3)
    grad: Any    # AmaxHistoryState over <site>/g rows (E5M2)
    steps: Any   # i32: update() calls applied


_FP8_STACK: list = []


def current_fp8():
    """The innermost active fp8 context (``Fp8DelayedScaler.step`` /
    ``record_fp8_sites``), or None when the fp8 tier is off — the hook
    ``ops.precision.matmul_amp`` consults at every routed call site."""
    return _FP8_STACK[-1] if _FP8_STACK else None


class _Fp8ContextBase:
    def __enter__(self):
        _FP8_STACK.append(self)
        return self

    def __exit__(self, *exc):
        if _FP8_STACK and _FP8_STACK[-1] is self:
            _FP8_STACK.pop()
        return False

    def _site(self, name: str) -> str:
        k = self._counts.get(name, 0)
        self._counts[name] = k + 1
        return f"{name}#{k}"


def _fp32acc_fallback(a, b, out_dtype):
    """Non-fp8 path for context matmuls: the accumulator stays fp32 all
    the way to ``out_dtype`` — a ``keep_acc`` caller asking for the
    fp32 result must NOT see the product round-trip through the
    storage dtype first (that would push the epilogue's backward
    reductions into bf16, exactly what matmul_fp32acc's keep_acc
    exists to avoid)."""
    from apex_tpu.ops.precision import matmul_fp32acc

    y = matmul_fp32acc(a, b, keep_acc=True)
    return y.astype(jnp.result_type(a, b) if out_dtype is None
                    else out_dtype)


class Fp8SiteRecorder(_Fp8ContextBase):
    """Discovery context: records every fp8-eligible call site's name in
    trace order (``with Fp8SiteRecorder() as rec: jax.eval_shape(fn,
    ...)``) while computing through the fp32-accum path. Feed
    ``rec.sites`` to :class:`Fp8DelayedScaler`."""

    def __init__(self):
        self.sites = []
        self._counts = {}

    def matmul(self, a, b, name="matmul", out_dtype=None):
        self._site(name)
        self.sites.append(name)
        return _fp32acc_fallback(a, b, out_dtype)


class _Fp8Apply(_Fp8ContextBase):
    """The live O4 context one traced step enters: resolves each site's
    delayed scales from the carried state, rewrites the matmul through
    ``ops.precision.matmul_fp8``, and collects this step's amax
    observations for :meth:`Fp8DelayedScaler.update`.

    Gradients MUST be computed through :meth:`value_and_grad` (not bare
    ``jax.value_and_grad``): the forward amaxes ride out of the grad
    transform as an aux output and the E5M2 cotangent amaxes come back
    as the gradients of per-site probe scalars — both plain functional
    outputs, so nothing collected inside the transform ever leaks a
    tracer."""

    def __init__(self, scaler: "Fp8DelayedScaler", state: Fp8ScalingState):
        self.scaler = scaler
        self.state = state
        self._counts = {}
        self._fwd_scales, self._grad_scales = scaler.scales(state)
        self._fwd_amax = {}     # row index -> traced scalar (stash)
        self._probes = None     # f32[ng] inside value_and_grad's aug
        self._harvest = None    # (fwd f32[nf], grad f32[ng]) once done
        self.skipped_sites = []  # names that fell back (unregistered)

    def matmul(self, a, b, name="matmul", out_dtype=None):
        from apex_tpu.ops import precision as _prec

        site = self._site(name)
        paths = self.scaler.fwd_history.paths
        if f"{site}/a" not in paths:
            # not registered with this scaler: fp32-accum fallback. This
            # is load-bearing, not best-effort — sites under scan/vmap
            # (llama decoder layers) must not leak collected tracers out
            # of their transform, so only registered top-level sites
            # convert.
            self.skipped_sites.append(site)
            return _fp32acc_fallback(a, b, out_dtype)
        ia = self.scaler.fwd_history.index(f"{site}/a")
        ib = self.scaler.fwd_history.index(f"{site}/b")
        ig = self.scaler.grad_history.index(f"{site}/g")
        # the amax observations come out of the SAME fused
        # cast-and-scale pass that quantizes — one HBM read per
        # operand, not a second standalone reduction
        y, amax_a, amax_b = _prec.matmul_fp8_stats(
            a, b, self._fwd_scales[ia], self._fwd_scales[ib],
            grad_scale=self._grad_scales[ig], out_dtype=out_dtype,
            grad_probe=(None if self._probes is None
                        else self._probes[ig]))
        self._fwd_amax[ia] = amax_a
        self._fwd_amax[ib] = amax_b
        return y

    def _stack_fwd(self):
        zero = jnp.zeros([], jnp.float32)
        return jnp.stack([
            self._fwd_amax.get(i, zero)
            for i in range(len(self.scaler.fwd_history.paths))])

    def value_and_grad(self, fn, argnums=0, has_aux=False):
        """fp8-aware ``jax.value_and_grad``: same signature/return
        shape, plus the amax bookkeeping described on the class. Call
        it INSIDE the context, on the loss whose matmuls route through
        this context's sites."""
        import jax as _jax

        scalar_argnums = isinstance(argnums, int)
        nums = (argnums,) if scalar_argnums else tuple(argnums)
        ng = len(self.scaler.grad_history.paths)

        def call(*args, **kwargs):
            def aug(probes, *a, **k):
                # fresh ordinals per differentiated trace: an eval
                # forward before this call (or a previous
                # value_and_grad in a grad-accumulation loop) must not
                # shift a registered site to `name#1` — that would
                # silently fall back to fp32acc and write a zero ring
                # column
                self._probes = probes
                self._counts = {}
                self._fwd_amax = {}
                try:
                    out = fn(*a, **k)
                finally:
                    self._probes = None
                loss, aux = out if has_aux else (out, None)
                fwd = self._stack_fwd()
                self._fwd_amax = {}  # drop inner-trace stash
                return loss, (aux, fwd)

            probes0 = jnp.zeros((ng,), jnp.float32)
            (loss, (aux, fwd)), grads = _jax.value_and_grad(
                aug, argnums=(0,) + tuple(n + 1 for n in nums),
                has_aux=True)(probes0, *args, **kwargs)
            # merge with any previous harvest (microbatch accumulation
            # calls value_and_grad repeatedly): the step's observation
            # is the max over every traversal, never the last one
            if self._harvest is None:
                self._harvest = (fwd, grads[0])
            else:
                self._harvest = (jnp.maximum(self._harvest[0], fwd),
                                 jnp.maximum(self._harvest[1],
                                             grads[0]))
            # restart site ordinals for whatever follows (another grad
            # call, an eval forward) — transpose-time recompute traces
            # have already run inside the value_and_grad call above
            self._counts = {}
            user = grads[1:]
            user = user[0] if scalar_argnums else user
            return ((loss, aux) if has_aux else loss), user

        return call

    def fwd_amax(self):
        """This step's stacked E4M3 amax observations (``f32[nf]``);
        unobserved rows write 0 (a 0 never votes in the ring max)."""
        if self._harvest is not None:
            return self._harvest[0]
        return self._stack_fwd()

    def grad_amax(self):
        """Stacked E5M2 cotangent amaxes (``f32[ng]``) — the probe
        gradients :meth:`value_and_grad` harvested; all 0 when no
        backward ran (forward-only steps observe nothing)."""
        if self._harvest is not None:
            return self._harvest[1]
        return jnp.zeros((len(self.scaler.grad_history.paths),),
                         jnp.float32)


class Fp8DelayedScaler:
    """Per-tensor delayed scaling for the O4 fp8 tier.

    ``sites``: ordered matmul-site names (duplicates allowed — they
    become ``name#0``, ``name#1``, ... in trace order), each owning two
    E4M3 forward rows and one E5M2 grad row in the amax rings. The
    object is static configuration; all mutable state is the
    :class:`Fp8ScalingState` pytree, so ``scales``/``update`` are
    jit-safe and the state checkpoints like any other leaf.

    Protocol (inside the traced step)::

        with fp8.step(fp8_state) as ctx:
            loss, grads = jax.value_and_grad(loss_fn)(params, ...)
        new_fp8_state = fp8.update(fp8_state, ctx,
                                   reduce_axes=("dp",))  # in shard_map
    """

    def __init__(self, sites, history: int = 16, margin: float = 0.0):
        from apex_tpu.observability.numerics.history import AmaxHistory

        counts: dict = {}
        canon = []
        for s in sites:
            k = counts.get(s, 0)
            counts[s] = k + 1
            canon.append(f"{s}#{k}")
        if not canon:
            raise ValueError("Fp8DelayedScaler needs at least one site")
        self.sites = tuple(canon)
        self.history = int(history)
        self.margin = float(margin)
        self.fwd_history = AmaxHistory(
            [f"{c}/{op}" for c in canon for op in ("a", "b")],
            length=history)
        self.grad_history = AmaxHistory(
            [f"{c}/g" for c in canon], length=history)

    @classmethod
    def for_step(cls, fn, *example_args, history: int = 16,
                 margin: float = 0.0) -> "Fp8DelayedScaler":
        """Build a scaler sized for ``fn``'s fp8 sites by abstractly
        tracing it under a discovery context (``jax.eval_shape`` — no
        FLOPs, no device buffers). ``fn`` should be the step whose
        matmuls route through ``ops.precision.matmul_amp`` — including
        its backward (pass the ``value_and_grad`` form) so recompute
        sites register too. Sites under ``lax.scan``/``vmap``/``remat``
        are recorded like any other but will be skipped at apply time;
        prefer explicit ``Fp8DelayedScaler([names...])`` when the step
        mixes transformed and top-level sites."""
        import jax

        with Fp8SiteRecorder() as rec:
            jax.eval_shape(fn, *example_args)
        return cls(rec.sites, history=history, margin=margin)

    # ---- jit-safe state protocol -------------------------------------

    def init(self) -> Fp8ScalingState:
        return Fp8ScalingState(
            fwd=self.fwd_history.init(),
            grad=self.grad_history.init(),
            steps=jnp.zeros([], jnp.int32),
        )

    def scales(self, state: Fp8ScalingState):
        """(fwd_scales f32[2*n_sites], grad_scales f32[n_sites]) —
        delayed per-tensor factors from the ring max: multiply a tensor
        by its scale before the fp8 cast so the history's max lands at
        the format edge / 2^margin. Fresh rows (no signal yet) scale
        by 1."""
        from apex_tpu.observability.numerics.history import (
            F8_E4M3_MAX,
            F8_E5M2_MAX,
        )

        return (self.fwd_history.scales(state.fwd, fp8_max=F8_E4M3_MAX,
                                        margin=self.margin),
                self.grad_history.scales(state.grad, fp8_max=F8_E5M2_MAX,
                                         margin=self.margin))

    def step(self, state: Fp8ScalingState) -> _Fp8Apply:
        """The per-step context manager (see class docstring)."""
        return _Fp8Apply(self, state)

    def update(self, state: Fp8ScalingState, ctx: _Fp8Apply,
               reduce_axes=()) -> Fp8ScalingState:
        """Write this step's collected amaxes into the rings (one
        column write per ring). Inside ``shard_map`` pass every mesh
        axis in ``reduce_axes``: observations are pmax-voted so ALL
        ranks write identical columns and the delayed scales stay
        replicated (the fp8 analog of ``scaled_update``'s psum'd
        overflow flag)."""
        fwd = ctx.fwd_amax()
        grad = ctx.grad_amax()
        if reduce_axes:
            axes = tuple(reduce_axes)
            fwd = jax.lax.pmax(_promote_varying(fwd, axes), axes)
            grad = jax.lax.pmax(_promote_varying(grad, axes), axes)
        return Fp8ScalingState(
            fwd=self.fwd_history.update(state.fwd, fwd),
            grad=self.grad_history.update(state.grad, grad),
            steps=state.steps + 1,
        )

    # ---- host-side serialization -------------------------------------

    def state_dict(self, state: Fp8ScalingState) -> dict:
        return {
            "sites": list(self.sites),
            "history": self.history,
            "margin": self.margin,
            "fwd": self.fwd_history.state_dict(state.fwd),
            "grad": self.grad_history.state_dict(state.grad),
            "steps": int(jax.device_get(state.steps)),
        }

    def load_state_dict(self, d: dict) -> Fp8ScalingState:
        if tuple(d.get("sites", ())) != self.sites:
            raise ValueError(
                "fp8 scaling state was recorded for a different site "
                f"set ({list(d.get('sites', ()))} vs {list(self.sites)});"
                " refusing to misalign the amax rings")
        return Fp8ScalingState(
            fwd=self.fwd_history.load_state_dict(d["fwd"]),
            grad=self.grad_history.load_state_dict(d["grad"]),
            # .get default: dicts written before the steps counter load
            # as "no updates seen yet"
            steps=jnp.asarray(d.get("steps", 0), jnp.int32),
        )


def scaled_update(tx, scaler: LossScaler, grads, opt_state, params,
                  scaler_state, overflow_reduce_axes=()):
    """One amp step: unscale → overflow check → conditional optimizer update.

    The TPU-native equivalent of apex's ``scale_loss`` context epilogue +
    patched ``optimizer.step`` skip (ref apex/amp/_process_optimizer.py).
    On overflow the optimizer state and params are left untouched via
    ``lax.cond`` — the whole step stays on device.

    Inside ``shard_map``, pass every mesh axis name in
    ``overflow_reduce_axes``: the overflow flag is psum-voted across them
    so ALL ranks take the same cond branch (the in-graph analog of the
    reference's NCCL-allreduced overflow buffer,
    ref apex/amp/scaler.py:unscale_with_stashed + _amp_state master flag).

    Returns ``(updates, new_opt_state, new_scaler_state, overflow)``.
    """
    unscaled, overflow = scaler.unscale(grads, scaler_state)
    if overflow_reduce_axes:
        ovf = _promote_varying(overflow.astype(jnp.float32),
                               overflow_reduce_axes)
        overflow = jax.lax.psum(ovf, tuple(overflow_reduce_axes)) > 0

    def do_update(_):
        return tx.update(unscaled, opt_state, params)

    # both cond branches must produce identical avals; derive the skip
    # branch's zeros from the update branch's output shapes/dtypes (updates
    # may be in grad dtype while params are in model dtype). Under
    # shard_map the update branch's avals can be VARYING over mesh axes
    # (e.g. grads a custom_vjp kernel left per-device local) — match each
    # leaf's vma or lax.cond rejects the branches with a type error.
    out_shapes = jax.eval_shape(do_update, None)

    def _match_vma(x, sd):
        return _promote_varying(x, getattr(sd, "vma", frozenset())
                                or frozenset())

    def skip(_):
        zeros = jax.tree_util.tree_map(
            lambda sd: _match_vma(jnp.zeros(sd.shape, sd.dtype), sd),
            out_shapes[0])
        kept = jax.tree_util.tree_map(_match_vma, opt_state, out_shapes[1])
        return zeros, kept

    updates, new_opt_state = jax.lax.cond(overflow, skip, do_update, None)
    new_scaler_state = scaler.update(scaler_state, overflow)
    return updates, new_opt_state, new_scaler_state, overflow
