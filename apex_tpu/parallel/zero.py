"""ZeRO-1 sharded optimizer tier for the overlapped DDP comms engine.

Ref: apex/contrib/optimizers/distributed_fused_adam.py (the reference's
ZeRO shard of optimizer state over the process group) and the ZeRO
paper's stage-1 partitioning; the contrib port
(:mod:`apex_tpu.contrib.optimizers.distributed_fused_adam`) keeps the
reference's master-weights shape. This module is the *engine* tier:

- gradients are packed into the :class:`~apex_tpu.parallel.overlap.
  OverlapPlan` buckets (reverse-order greedy, grad-ready order) and
  **reduce-scattered** per bucket (``lax.psum_scatter``) with the same
  ``lax.optimization_barrier`` issue-order chain as the overlapped
  allreduce — each rank receives only its ``1/n`` shard of the summed
  gradient, ``(n-1)/n`` of the bytes an allreduce moves;
- fused Adam updates only the local optimizer-state shard (``mu``/
  ``nu`` fp32 shards — per-device optimizer HBM shrinks by ``1/dp``;
  donate the state at the jit boundary and the update is in-place);
- the updated **parameter** shard is all-gathered in the parameter's
  own storage dtype, so with bf16 params + fp32 grads the whole sync
  costs ``1.5(n-1)/n`` of the fp32 bytes — 0.75x the allreduce path
  (:func:`~apex_tpu.parallel.overlap.grad_sync_comms_bytes` is the
  shared price).

Bit-parity contract (asserted in tests/run_parallel/test_zero1.py on
the 8-device simulated mesh): for fp32 gradients the ZeRO-1 step is
bit-identical to ``sync_gradients`` + replicated ``fused_adam(flat=
True)`` — params AND optimizer state (each rank's shard equals the
matching slice of the replicated flat buffers). For bf16 grads the
reduction runs in fp32 (the cast happens before the scatter), which is
*better* than the replicated path's bf16 psum — documented difference,
not parity.

State is checkpoint-friendly: outside ``shard_map`` the shard buffers
are ordinary global arrays sharded ``P(axis)`` along dim 0 (a tiled
``psum_scatter``/``all_gather`` keeps original element order), so they
ride :mod:`apex_tpu.checkpoint`'s atomic manifest unchanged and survive
preempt/crash-restart via the resilience runtime bit-identically.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

from apex_tpu.observability import span
from apex_tpu.observability.fleet import probe as fleet_probe
from apex_tpu.optimizers import _math
from apex_tpu.parallel.overlap import (
    OverlapPlan,
    _chain,
    _pack,
    _token_of,
    _unpack_into,
    plan_overlap,
)

ScalarOrSchedule = Union[float, Callable[[jax.Array], jax.Array]]


class Zero1AdamState(NamedTuple):
    """Sharded FusedAdam state: one fp32 ``mu``/``nu`` buffer per plan
    bucket. Inside ``shard_map`` each buffer is the local
    ``padded/n`` shard; outside it is the global ``(padded,)`` array
    (shard ``P(axis)``)."""

    count: jax.Array
    mu: tuple
    nu: tuple


def _lr_at(lr: ScalarOrSchedule, count):
    return lr(count) if callable(lr) else lr


class Zero1FusedAdam:
    """Bucketed ZeRO-1 FusedAdam over a data-parallel mesh axis.

    Functional usage (``step`` must run inside ``shard_map`` with
    ``axis_name`` bound; ``init`` runs outside and returns GLOBAL
    state arrays to be passed in with dim-0 sharded specs —
    :meth:`state_specs`)::

        opt = Zero1FusedAdam(lr=1e-3, axis_name="dp", num_shards=8)
        state = opt.init(params)                    # global buffers
        specs = opt.state_specs(params)             # P("dp") per shard
        # inside the shard_mapped train step:
        new_params, new_state = opt.step(grads, state, params)

    Arguments mirror :func:`apex_tpu.optimizers.fused_adam`;
    ``gradient_average``/``gradient_predivide_factor`` fold the DDP
    gradient averaging into the scatter (do NOT also call
    ``sync_gradients`` — that would double-reduce)."""

    def __init__(self, lr: ScalarOrSchedule = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-8, adam_w_mode: bool = True,
                 weight_decay: float = 0.0, axis_name: str = "dp",
                 num_shards: Optional[int] = None,
                 bucket_cap_mb: float = 10.0,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0):
        if num_shards is None:
            num_shards = jax.device_count()
        self.lr = lr
        self.bias_correction = bias_correction
        self.b1, self.b2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.axis_name = axis_name
        self.num_shards = int(num_shards)
        self.bucket_cap_mb = bucket_cap_mb
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor

    # ------------------------------------------------------------ plan

    def plan_for(self, params) -> OverlapPlan:
        """The bucket schedule (padded to the shard quantum)."""
        return plan_overlap(params, self.bucket_cap_mb,
                            num_shards=self.num_shards)

    # ------------------------------------------------------------ init

    def init(self, params) -> Zero1AdamState:
        """Global zero state: one ``(bucket.padded,)`` fp32 buffer per
        bucket for each moment. Shard them ``P(axis)`` on dim 0 when
        entering ``shard_map`` (:meth:`state_specs`)."""
        plan = self.plan_for(params)
        mu = tuple(jnp.zeros((b.padded,), jnp.float32)
                   for b in plan.buckets)
        return Zero1AdamState(count=jnp.zeros([], jnp.int32), mu=mu,
                              nu=tuple(jnp.zeros_like(m) for m in mu))

    def state_specs(self, params) -> Zero1AdamState:
        """Per-leaf PartitionSpec pytree for :class:`Zero1AdamState`
        (pass as the state's ``in_specs``/``out_specs``): one
        ``P(axis)`` per bucket buffer — moment shards along the axis —
        and a replicated step counter."""
        from jax.sharding import PartitionSpec as P

        plan = self.plan_for(params)
        return Zero1AdamState(
            count=P(),
            mu=tuple(P(self.axis_name) for _ in plan.buckets),
            nu=tuple(P(self.axis_name) for _ in plan.buckets))

    # ------------------------------------------------------------ step

    def step(self, grads, state: Zero1AdamState, params):
        """One ZeRO-1 update; call INSIDE ``shard_map``. Returns
        ``(new_params, new_state)`` — params fully updated on every
        rank (all-gathered), state advanced only in the local shard."""
        n = jax.lax.axis_size(self.axis_name)
        if n != self.num_shards:
            raise ValueError(
                f"Zero1FusedAdam was built for num_shards="
                f"{self.num_shards} but axis {self.axis_name!r} has "
                f"size {n} — state shards would not line up")
        rank = jax.lax.axis_index(self.axis_name)
        plan = self.plan_for(params)
        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        g_leaves = jax.tree_util.tree_leaves(grads)
        if len(g_leaves) != len(p_leaves):
            raise ValueError(
                f"grads have {len(g_leaves)} leaves, params "
                f"{len(p_leaves)} — trees diverged")

        count = state.count + 1
        step_f = count.astype(jnp.float32)
        lr_t = _lr_at(self.lr, state.count)  # optax convention
        kw = dict(lr=lr_t, b1=self.b1, b2=self.b2, eps=self.eps,
                  weight_decay=self.weight_decay,
                  adam_w_mode=self.adam_w_mode, step=step_f,
                  bias_correction=self.bias_correction)
        pre = self.gradient_predivide_factor

        out = [None] * len(p_leaves)
        mu_out, nu_out = [], []
        token = None
        for k, bucket in enumerate(plan.buckets):
            shard_len = bucket.padded // n
            site = f"ddp/zero1/bucket{k}/{bucket.dtype}"
            with span(site):
                # grads travel fp32 (the fused_adam flat packing),
                # params in their own storage dtype
                gflat = _pack(g_leaves, bucket, cast=jnp.float32)
                if pre != 1.0:
                    gflat = gflat / pre
                gflat, token = _chain(gflat, token)
                # fleet barrier-wait probe (ISSUE 12): identity when
                # off; armed, per-rank enter/exit brackets the
                # scatter+gather pair (the ZeRO-1 sync region)
                gflat = fleet_probe.collective_enter(
                    gflat, site, self.axis_name)
                g_shard = jax.lax.psum_scatter(
                    gflat, self.axis_name, scatter_dimension=0,
                    tiled=True)
                if self.gradient_average:
                    g_shard = g_shard * jnp.asarray(pre / n,
                                                    g_shard.dtype)
                pflat = _pack(p_leaves, bucket)
                p_shard = jax.lax.dynamic_slice_in_dim(
                    pflat, rank * shard_len, shard_len)
                d, m, v = _math.adam_step(
                    g_shard, p_shard, state.mu[k], state.nu[k], **kw)
                new_p_shard = p_shard + d.astype(pflat.dtype)
                new_pflat = jax.lax.all_gather(
                    new_p_shard, self.axis_name, tiled=True)
                new_pflat = fleet_probe.collective_exit(
                    new_pflat, site, self.axis_name)
            token = _token_of(new_pflat)
            mu_out.append(m)
            nu_out.append(v)
            _unpack_into(out, new_pflat, bucket)
        new_params = jax.tree_util.tree_unflatten(treedef, out)
        return new_params, Zero1AdamState(
            count=count, mu=tuple(mu_out), nu=tuple(nu_out))

    # ------------------------------------------------------- utilities

    def state_layout(self, params) -> dict:
        """The shard layout the checkpoint actually persists — what the
        state engine's ``reshard-illegal`` check consumes: the dp axis,
        the shard count the buffers were padded for, and per bucket the
        ``{dtype, total, padded}`` triple that decides whether a new
        shard count is a pure reshard (``padded % n == 0`` AND
        re-planning at ``n`` reproduces the same padding)."""
        plan = self.plan_for(params)
        return {
            "axis": self.axis_name,
            "num_shards": self.num_shards,
            "buckets": [{"dtype": b.dtype, "total": int(b.total),
                         "padded": int(b.padded)}
                        for b in plan.buckets],
        }

    def elastic_candidates(self, params, max_shards: Optional[int] = None
                           ) -> tuple:
        """Shard counts a saved state can be re-laid-out onto without
        repacking: every ``n`` (1..max_shards, default 2x the current
        count) for which EVERY bucket keeps its flat layout —
        ``padded % n == 0`` and ``_pad_up(total, n) == padded``, i.e.
        re-planning at ``n`` pads each bucket to the same length the
        saved buffers already have. Always includes the current
        ``num_shards``. The claim is machine-checked: the state
        engine's ``reshard-illegal`` proof runs over exactly this set
        in the registered ZeRO-1 target."""
        from apex_tpu.parallel.overlap import _pad_up

        plan = self.plan_for(params)
        limit = max_shards if max_shards is not None \
            else 2 * self.num_shards
        out = []
        for n in range(1, max(limit, self.num_shards) + 1):
            ok = all(b.padded % n == 0
                     and _pad_up(b.total, n) == b.padded
                     for b in plan.buckets)
            if ok or n == self.num_shards:
                out.append(n)
        return tuple(out)

    def comms_bytes(self, params) -> int:
        """Per-device grad-sync bytes of one step (the shared price —
        see :func:`~apex_tpu.parallel.overlap.grad_sync_comms_bytes`)."""
        from apex_tpu.parallel.overlap import grad_sync_comms_bytes

        return grad_sync_comms_bytes(params, self.num_shards,
                                     mode="zero1")

    def unpack_state(self, params, state: Zero1AdamState):
        """GLOBAL state buffers -> ``(mu_tree, nu_tree)`` shaped like
        ``params`` (inspection / parity testing / migration off the
        sharded layout). A tiled scatter keeps element order, so the
        global buffer is just the padded flat packing."""
        plan = self.plan_for(params)
        _, treedef = jax.tree_util.tree_flatten(params)
        trees = []
        for bufs in (state.mu, state.nu):
            if len(bufs) != len(plan.buckets):
                raise ValueError(
                    f"state has {len(bufs)} bucket buffers, plan "
                    f"{len(plan.buckets)} — state/plan diverged")
            leaves: list = [None] * plan.n_leaves
            for buf, bucket in zip(bufs, plan.buckets):
                _unpack_into(leaves, buf, bucket)
            trees.append(jax.tree_util.tree_unflatten(treedef, leaves))
        return tuple(trees)


def zero1_fused_adam(**kwargs) -> Zero1FusedAdam:
    """Factory mirroring :func:`apex_tpu.optimizers.fused_adam`'s
    call shape."""
    return Zero1FusedAdam(**kwargs)
