"""DistributedDataParallel — TPU re-design of ``apex.parallel.distributed``.

Ref: apex/parallel/distributed.py (+ csrc/flatten_unflatten.cpp).

The reference intercepts ``.grad`` hooks, fills flat buckets, and overlaps
NCCL allreduces with the backward pass. Under XLA the same overlap falls out
of compilation: gradient psums issued inside the jitted step are scheduled
by XLA concurrently with independent backward compute, riding the ICI mesh.
What remains of DDP is therefore:

- :func:`sync_gradients` — per-leaf ``lax.pmean``/``psum`` over the data
  axis (the default; preserves shardings, XLA fuses/overlaps);
- :func:`sync_gradients_flat` — explicit flat-bucket variant mirroring the
  reference's ``message_size`` bucketing: leaves are packed into per-dtype
  buffers (optionally planned by the C++ bucketizer in csrc/) and reduced
  with a handful of large collectives;
- :class:`DistributedDataParallel` — an apex-shaped wrapper over a flax
  module / apply_fn carrying the options (``gradient_average``,
  ``gradient_predivide_factor``, ``delay_allreduce``, ``message_size``).

Use inside ``shard_map``/``pmap`` with the mesh axis named ``data`` (or pass
``axis_name``).

IMPORTANT (jax ≥0.8 shard_map semantics): inside ``shard_map``, ``jax.grad``
w.r.t. *replicated* (unvaried, ``P()``) params already inserts the cross-
replica ``psum`` — the transpose of the implicit broadcast. In that pattern
grads arrive globally **summed**; use :func:`average_reduced` (divide by
world size), NOT :func:`sync_gradients`, or you double-reduce. Explicit
:func:`sync_gradients` is for genuinely per-replica grads: pmap-style
per-device param copies, or params made varying with ``jax.lax.pvary``.

CAVEAT to the auto-psum: a ``jax.custom_vjp`` in the model (every Pallas
fused kernel — layer_norm, rms_norm, flash attention) hides the broadcast
from transposition, so the grads of params feeding ONLY through custom_vjp
ops arrive per-device **local** (varying) while everything else arrives
summed (invariant) — a mixed tree that :func:`average_reduced` silently
mis-scales. :func:`sync_autodiff_gradients` inspects each leaf's varying
set and repairs both kinds; it is the safe default for replicated-param
DDP over real models.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.observability import span
from apex_tpu.observability.fleet import probe as fleet_probe
from apex_tpu.ops.flat import flatten_tree, unflatten_tree


def sync_gradients(grads, axis_name: str = "data", gradient_average: bool = True,
                   gradient_predivide_factor: float = 1.0):
    """Allreduce a gradient pytree across the data-parallel axis.

    Ref apex/parallel/distributed.py:allreduce_params / allreduce hooks.
    ``gradient_predivide_factor`` splits the division between before and
    after the reduction to avoid overflow in fp16 sums (ref distributed.py
    predivide logic).
    """
    # fleet barrier-wait probe sites (ISSUE 12): one per leaf — the
    # per-leaf psums are independent and can overlap, so a shared site
    # key would clobber its own enter/exit timestamps. tree_map visits
    # leaves in deterministic flatten order, so the numbering is
    # stable across traces.
    leaf_counter = itertools.count()

    def reduce_leaf(g):
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        site = f"ddp/allreduce/leaf{next(leaf_counter)}"
        g = fleet_probe.collective_enter(g, site, axis_name)
        g = jax.lax.psum(g, axis_name)
        g = fleet_probe.collective_exit(g, site, axis_name)
        if gradient_average:
            # axis_size is a compile-time constant; psum(ones) here
            # would emit a real collective for it (apex_tpu.analysis
            # dead-collective)
            n = jax.lax.axis_size(axis_name)
            g = g * jnp.asarray(gradient_predivide_factor / n, g.dtype)
        return g

    with span("ddp/allreduce"):
        return jax.tree_util.tree_map(reduce_leaf, grads)


def sync_gradients_flat(grads, axis_name: str = "data", gradient_average: bool = True,
                        gradient_predivide_factor: float = 1.0):
    """Flat-bucket allreduce: pack per-dtype, reduce once per dtype, unpack.

    The explicit analog of the reference's flat NCCL buckets
    (ref apex/parallel/distributed.py:flat_dist_call).
    ``gradient_predivide_factor`` splits the averaging around the
    reduction exactly as :func:`sync_gradients` does (pre-divide before
    the psum, multiply by ``factor/n`` after), so the flat path keeps
    the same fp16-overflow headroom.
    """
    pre = gradient_predivide_factor
    with span("ddp/allreduce_flat"):
        bufs, meta = flatten_tree(grads)
        reduced = {}
        for k, buf in bufs.items():
            with span(f"ddp/bucket/{k}"):
                if pre != 1.0:
                    buf = buf / pre
                buf = fleet_probe.collective_enter(
                    buf, f"ddp/bucket/{k}", axis_name)
                r = jax.lax.psum(buf, axis_name)
                r = fleet_probe.collective_exit(
                    r, f"ddp/bucket/{k}", axis_name)
                if gradient_average:
                    # static axis size, not psum(ones): the probe would
                    # be a dead collective riding every bucket
                    n = jax.lax.axis_size(axis_name)
                    r = r * jnp.asarray(pre / n, r.dtype)
            reduced[k] = r
        return unflatten_tree(reduced, meta)


def sync_gradients_bucketed(grads, axis_name: str = "data",
                            gradient_average: bool = True,
                            bucket_cap_mb: float = 10.0,
                            gradient_predivide_factor: float = 1.0):
    """Size-capped flat-bucket allreduce (ref apex DDP ``message_size``
    bucketing, apex/parallel/distributed.py).

    The bucket plan comes from the C++ host runtime
    (csrc/host_runtime.cpp apex_plan_buckets — reverse-order greedy, the
    grad-ready order of backprop); packing and the psum per bucket run
    inside the jitted step. Multiple buckets give XLA independent
    collectives to overlap with compute, mirroring the reference's
    overlapped NCCL buckets.
    """
    from apex_tpu.runtime import plan_buckets

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    # plan on host (static under trace): group same-dtype leaves by cap
    order = sorted(range(len(leaves)),
                   key=lambda i: jnp.dtype(leaves[i].dtype).name)
    cap = int(bucket_cap_mb * 1024 * 1024)
    plans = {}  # dtype -> (leaf indices, bucket ids)
    for dt in sorted({jnp.dtype(l.dtype).name for l in leaves}):
        idxs = [i for i in order if jnp.dtype(leaves[i].dtype).name == dt]
        sizes = [leaves[i].size * leaves[i].dtype.itemsize for i in idxs]
        plans[dt] = (idxs, plan_buckets(sizes, cap))

    out = [None] * len(leaves)
    n = jax.lax.axis_size(axis_name)
    pre = gradient_predivide_factor
    for dt, (idxs, bucket_ids) in plans.items():
        n_buckets = max(bucket_ids) + 1 if bucket_ids else 0
        for b in range(n_buckets):
            members = [i for i, bid in zip(idxs, bucket_ids) if bid == b]
            with span(f"ddp/bucket{b}/{dt}"):
                flat = jnp.concatenate([leaves[i].ravel() for i in members])
                if pre != 1.0:
                    flat = flat / pre
                red = jax.lax.psum(flat, axis_name)
                if gradient_average:
                    red = red * jnp.asarray(pre / n, red.dtype)
            off = 0
            for i in members:
                sz = leaves[i].size
                out[i] = red[off:off + sz].reshape(leaves[i].shape)
                off += sz
    return jax.tree_util.tree_unflatten(treedef, out)


def average_reduced(grads, axis_name: str = "data"):
    """Turn auto-psummed grads (replicated-params pattern, see module note)
    into data-parallel *averaged* grads: divide by the axis size."""
    def avg(g):
        n = jax.lax.axis_size(axis_name)
        return (g / jnp.asarray(n, g.dtype)).astype(g.dtype)
    return jax.tree_util.tree_map(avg, grads)


def sync_autodiff_gradients(grads, axis_name: str = "data"):
    """Per-leaf vma-aware gradient averaging for the replicated-params
    pattern (see the module-note CAVEAT): autodiff auto-psums the grads of
    replicated params — EXCEPT those flowing only through ``custom_vjp``
    ops (the fused kernels), which arrive per-device local. Inspecting
    ``jax.typeof(leaf).vma``: a leaf still varying over ``axis_name`` gets
    an explicit ``pmean``; an invariant (already-summed) leaf is divided
    by the axis size. Either way the result is the invariant global-batch
    -mean gradient, safe for ``lax.cond``-based overflow skips."""
    def one(g):
        vma = getattr(jax.typeof(g), "vma", frozenset())
        if axis_name in vma:
            return jax.lax.pmean(g, axis_name)
        n = jax.lax.axis_size(axis_name)
        return (g / jnp.asarray(n, g.dtype)).astype(g.dtype)
    return jax.tree_util.tree_map(one, grads)


class Reducer:
    """Manually-triggered parameter allreducer (ref apex/parallel/__init__.py
    Reducer: "allreduce_params() averages parameters across processes")."""

    def __init__(self, params_or_module=None, axis_name: str = "data"):
        self.axis_name = axis_name
        self.params = params_or_module

    def reduce(self, tree=None):
        tree = tree if tree is not None else self.params
        n_fn = lambda x: jnp.asarray(
            jax.lax.axis_size(self.axis_name), x.dtype)
        return jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, self.axis_name) / n_fn(x), tree)


class DistributedDataParallel:
    """apex-shaped DDP wrapper for flax modules / apply functions.

    Ref apex/parallel/distributed.py:DistributedDataParallel.__init__
    (message_size, delay_allreduce, gradient_average,
    gradient_predivide_factor...).

    Functional usage (inside the jitted, shard_mapped train step)::

        ddp = DistributedDataParallel(model.apply, axis_name="data")
        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = ddp.sync(grads)           # bucketed allreduce over 'data'

    or wrap the grad fn once: ``grad_fn = ddp.wrap_grad_fn(jax.grad(loss_fn))``.
    With ``delay_allreduce=True`` :meth:`sync` is a no-op until
    :meth:`allreduce` is called explicitly (gradient accumulation).
    """

    def __init__(self, module_or_apply: Any = None, message_size: int = 10000000,
                 delay_allreduce: bool = False, shared_param: Optional[bool] = None,
                 allreduce_trigger_params=None, retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False, num_allreduce_streams: int = 1,
                 allreduce_communicators=None, gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0, gradient_average_split_factor=None,
                 prof: bool = False, axis_name: str = "data", flat_buckets: bool = True,
                 overlap_buckets: bool = False, bucket_cap_mb: float = 10.0):
        if shared_param is not None:
            raise ValueError(
                "shared_param is deprecated (matches the reference's error; "
                "ref distributed.py:__init__)")
        del allreduce_trigger_params, retain_allreduce_buffers  # GPU stream details
        del num_allreduce_streams, allreduce_communicators, prof
        del gradient_average_split_factor, message_size  # XLA schedules collectives
        self.module = module_or_apply
        self.axis_name = axis_name
        self.delay_allreduce = delay_allreduce
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.flat_buckets = flat_buckets
        self.overlap_buckets = overlap_buckets
        self.bucket_cap_mb = bucket_cap_mb

    def __call__(self, *args, **kwargs):
        if self.module is None:
            raise ValueError("DistributedDataParallel was built without a module")
        fn = getattr(self.module, "apply", self.module)
        return fn(*args, **kwargs)

    def _sync_fn(self, grads):
        if self.overlap_buckets:
            from apex_tpu.parallel.overlap import sync_gradients_overlapped

            return sync_gradients_overlapped(
                grads, self.axis_name, self.gradient_average,
                self.gradient_predivide_factor,
                bucket_cap_mb=self.bucket_cap_mb)
        if self.flat_buckets:
            return sync_gradients_flat(
                grads, self.axis_name, self.gradient_average,
                self.gradient_predivide_factor)
        return sync_gradients(grads, self.axis_name, self.gradient_average,
                              self.gradient_predivide_factor)

    def _reduce(self, grads):
        if self.allreduce_always_fp32:
            orig = grads
            grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
            return jax.tree_util.tree_map(
                lambda r, g: r.astype(g.dtype), self._sync_fn(grads), orig)
        return self._sync_fn(grads)

    def sync(self, grads):
        """Reduce grads across the data axis (no-op when delay_allreduce)."""
        if self.delay_allreduce:
            return grads
        return self._reduce(grads)

    def allreduce(self, grads):
        """Explicit reduction for the delay_allreduce accumulation pattern."""
        return self._reduce(grads)

    def average_reduced(self, grads):
        """Average grads that were already psummed by autodiff (the
        replicated-params pattern — see module docstring). vma-aware:
        leaves a custom_vjp kernel left unsummed get a real pmean."""
        if not self.gradient_average:
            return grads
        return sync_autodiff_gradients(grads, self.axis_name)

    def wrap_grad_fn(self, grad_fn: Callable) -> Callable:
        """Return a grad fn whose outputs are already synced (per-replica
        grads pattern)."""
        def wrapped(*args, **kwargs):
            out = grad_fn(*args, **kwargs)
            if isinstance(out, tuple):  # value_and_grad
                return (*out[:-1], self.sync(out[-1]))
            return self.sync(out)
        return wrapped
