"""User-facing auto-sharding API (ISSUE 8): turn a planner
:class:`~apex_tpu.analysis.planner.Plan` into things a training script
can execute — a mesh, PartitionSpec trees, and
``with_sharding_constraint`` application.

    from apex_tpu.parallel import auto_shard

    plan = auto_shard.plan_for("llama", devices=8)
    mesh = auto_shard.mesh_for(plan)          # Mesh over (pp, dp, tp)
    specs = auto_shard.spec_group(plan, "layers")   # {name: PartitionSpec}
    data  = auto_shard.data_spec(plan)

``examples/llama_train.py --auto-shard`` is the end-to-end customer:
it replaces its hand-picked ``--pp/--dp/--tp`` and spec tables with the
plan's. Plans round-trip through JSON (:func:`save_plan` /
:func:`load_plan`) so a search run on a dev box can ship its verdict to
the fleet; the file is byte-stable for identical inputs, so a committed
plan doubles as a regression anchor (``tools/metrics_report.py
--compare`` gates plan flips between runs).
"""

from __future__ import annotations

import json

from apex_tpu.analysis import planner
from apex_tpu.analysis.planner import (  # noqa: F401  (re-exported API)
    Plan,
    PlanError,
    entries_to_spec,
    spec_entries,
)

__all__ = [
    "Plan", "PlanError", "plan_for", "mesh_for", "spec_group",
    "data_spec", "constrain", "save_plan", "load_plan",
    "spec_entries", "entries_to_spec",
]


def plan_for(model="llama", devices=None, **kw) -> Plan:
    """Search + verify a plan for ``model`` (see
    :func:`apex_tpu.analysis.planner.plan`)."""
    return planner.plan(model=model, devices=devices, **kw)


def mesh_for(plan: Plan, devices=None):
    """A ``jax.sharding.Mesh`` shaped like the plan's (pp, dp, tp).

    ``devices``: explicit device list (default: the first
    ``plan.devices`` visible devices)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    mesh = plan.mesh
    n = mesh["pp"] * mesh["dp"] * mesh["tp"]
    devs = list(devices) if devices is not None else jax.devices()[:n]
    if len(devs) < n:
        raise ValueError(
            f"plan wants {n} devices (pp={mesh['pp']} dp={mesh['dp']} "
            f"tp={mesh['tp']}), only {len(devs)} available")
    return Mesh(np.asarray(devs[:n]).reshape(
        mesh["pp"], mesh["dp"], mesh["tp"]), ("pp", "dp", "tp"))


def spec_group(plan: Plan, group: str) -> dict:
    """One named spec table of the plan ("layers", "io", "params", ...)
    as {name: PartitionSpec}."""
    table = plan.specs.get(group)
    if table is None:
        raise KeyError(
            f"plan for {plan.model!r} has no spec group {group!r}; "
            f"has {sorted(plan.specs)}")
    return {name: entries_to_spec(entries)
            for name, entries in table.items()}


def data_spec(plan: Plan):
    """The plan's input-batch PartitionSpec."""
    return entries_to_spec(plan.specs.get("data", []))


def constrain(x, plan: Plan, group: str, name=None):
    """Apply the plan's sharding for ``group`` (or ``group[name]``) to
    ``x`` via ``with_sharding_constraint`` — the GSPMD way to pin a
    planned placement inside a jitted step."""
    import jax

    if name is None:
        spec = data_spec(plan) if group == "data" \
            else entries_to_spec(plan.specs[group])
    else:
        spec = spec_group(plan, group)[name]
    return jax.lax.with_sharding_constraint(x, spec)


def save_plan(plan: Plan, path: str) -> str:
    with open(path, "w") as f:
        f.write(plan.to_json())
    return path


def load_plan(path: str) -> Plan:
    """Re-hydrate a saved plan. Loud on schema drift — a stale plan
    applied to a newer repo is exactly the silent failure the plan file
    exists to prevent."""
    with open(path) as f:
        try:
            data = json.load(f)
        except ValueError as e:
            raise ValueError(f"plan file {path} is not JSON: {e}")
    if not isinstance(data, dict) or data.get("kind") != planner.PLAN_KIND:
        raise ValueError(
            f"{path} is not an {planner.PLAN_KIND} file")
    version = data.get("schema_version")
    if version != planner.PLAN_SCHEMA_VERSION:
        raise ValueError(
            f"{path} has plan schema_version {version}; this reader "
            f"knows {planner.PLAN_SCHEMA_VERSION}")
    candidates = [planner.Candidate(
        pp=c["mesh"]["pp"], dp=c["mesh"]["dp"], tp=c["mesh"]["tp"],
        layout=c["layout"], comms_bytes=c["comms_bytes"],
        peak_hbm_bytes=c["peak_hbm_bytes"],
        # pre-ISSUE-19 plans carry no calibrated column: no prior means
        # calibrated == modeled, exactly what the planner would emit
        calibrated_hbm_bytes=c.get("calibrated_hbm_bytes",
                                   c["peak_hbm_bytes"]),
        modeled_step_ms=c["modeled_step_ms"], status=c["status"],
        detail=c.get("detail", "")) for c in data.get("candidates", ())]
    return Plan(
        model=data["model"], devices=data["devices"],
        device_kind=data["device_kind"],
        hbm_budget_bytes=data["hbm_budget_bytes"], mesh=data["mesh"],
        layout=data["layout"], specs=data["specs"],
        predicted=data["predicted"], candidates=candidates,
        model_kw=data.get("model_kw", {}),
        hbm_prior=data.get("hbm_prior", "none"))
