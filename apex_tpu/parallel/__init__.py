"""Distributed data parallelism (TPU re-design of ``apex.parallel``).

Ref: apex/parallel/__init__.py.
"""

from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    sync_gradients,
    sync_gradients_flat,
    sync_gradients_bucketed,
    average_reduced,
    sync_autodiff_gradients,
)
from apex_tpu.parallel.overlap import (
    OverlapPlan,
    grad_sync_comms_bytes,
    overlapped_value_and_grad,
    plan_overlap,
    sync_gradients_overlapped,
)
from apex_tpu.parallel.zero import Zero1AdamState, Zero1FusedAdam, zero1_fused_adam
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm, convert_syncbn_model
from apex_tpu.parallel.larc import LARC, larc
from apex_tpu.parallel import auto_shard, multiproc


def create_syncbn_process_group(group_size, axis_name="data",
                                world_size=None):
    """ref apex/parallel/__init__.py:58 — stats subgroups for SyncBN.

    The reference builds NCCL subgroups of ``group_size`` consecutive
    ranks and returns the current GPU's group. On a mesh there is no
    group object to build: the return value is the
    ``(axis_name, group_size)`` pair to pass straight through
    ``SyncBatchNorm(process_group=...)``, with the reference's
    conventions kept — ``group_size=0`` means whole-axis sync and
    returns ``None``; the size must divide the axis.

    ``world_size`` defaults to ``jax.device_count()``, which equals the
    sync axis only on a single-axis mesh; on a multi-axis mesh pass the
    ``axis_name`` axis's size explicitly, or the 0/whole-axis decisions
    here are made against the wrong total (the divisibility check inside
    SyncBatchNorm still catches a non-dividing size at trace time).
    """
    import jax

    if world_size is None:
        world_size = jax.device_count()
    if group_size == 0 or group_size == world_size:
        return None
    if group_size < 0 or world_size % group_size:
        raise ValueError(
            f"group_size={group_size} must be positive and divide the "
            f"axis size {world_size}")
    return (axis_name, int(group_size))


__all__ = [
    "DistributedDataParallel", "Reducer",
    "sync_gradients", "sync_gradients_flat", "sync_gradients_bucketed",
    "average_reduced", "sync_autodiff_gradients",
    "OverlapPlan", "plan_overlap", "sync_gradients_overlapped",
    "overlapped_value_and_grad", "grad_sync_comms_bytes",
    "Zero1AdamState", "Zero1FusedAdam", "zero1_fused_adam",
    "SyncBatchNorm", "convert_syncbn_model", "create_syncbn_process_group",
    "LARC", "larc", "auto_shard", "multiproc",
]
