"""Distributed data parallelism (TPU re-design of ``apex.parallel``).

Ref: apex/parallel/__init__.py.
"""

from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    sync_gradients,
    sync_gradients_flat,
    average_reduced,
    sync_autodiff_gradients,
)
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm, convert_syncbn_model
from apex_tpu.parallel.larc import LARC, larc
from apex_tpu.parallel import multiproc

__all__ = [
    "DistributedDataParallel", "Reducer",
    "sync_gradients", "sync_gradients_flat", "average_reduced",
    "sync_autodiff_gradients",
    "SyncBatchNorm", "convert_syncbn_model",
    "LARC", "larc", "multiproc",
]
