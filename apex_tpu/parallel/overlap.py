"""Overlapped DDP comms engine — backward-interleaved bucket allreduce.

Ref: apex/parallel/distributed.py (the grad-ready bucketing + overlapped
NCCL allreduces PyTorch DDP performs with .grad hooks) and
csrc/host_runtime.cpp ``apex_plan_buckets`` (the reverse-order greedy
bucket planner — grad-ready order ≈ reverse parameter order).

:func:`sync_gradients_bucketed` reduces after the whole backward has
produced every gradient *in program order*; nothing in the emitted HLO
tells XLA which collective should go first, so a late bucket can be
scheduled ahead of the first-ready one and the comms tail lands after
the backward instead of under it. This module makes the overlap schedule
explicit:

- :func:`plan_overlap` — a static host-side :class:`OverlapPlan` from
  ``runtime.plan_buckets`` (the C++ reverse-order greedy when the .so is
  present): per-dtype flat buckets capped at ``bucket_cap_mb``, emitted
  in grad-ready order (bucket 0 holds the LAST parameters — the first
  gradients backprop completes).
- :func:`sync_gradients_overlapped` — per-bucket flat psums where each
  bucket's packed buffer is tied to the *previous* bucket's reduced
  result with ``lax.optimization_barrier``. The chain pins the issue
  order (first-ready first, the single-NCCL-stream semantic) while each
  psum's data deps stay just its member leaves, so XLA overlaps every
  collective with the backward compute still in flight.
- :func:`overlapped_value_and_grad` — the layer-wise ``custom_vjp``-hook
  variant: each bucket's reduction is emitted INTO the backward jaxpr as
  the transpose of a per-bucket identity hook on the parameters, i.e.
  the collective appears exactly where the bucket's cotangent completes.
  Returns grads already reduced.

Both paths are bit-identical to the single-psum :func:`sync_gradients`
(same predivide -> psum -> ``* predivide/axis_size`` arithmetic; packing
is elementwise-neutral), asserted on the 8-device simulated mesh in
``tests/run_parallel/test_overlap.py``.

:func:`grad_sync_comms_bytes` is the shared comms price for the
schedule (allreduce ``2(n-1)/n`` vs ZeRO-1 reduce-scatter + all-gather
``1.5(n-1)/n`` when params are stored in half precision) — the analysis
planner and the ``ddp/comms_bytes`` gauge both read it, so the static
estimate and the runtime metric can never disagree on the model.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.observability import span
from apex_tpu.observability.fleet import probe as fleet_probe


@dataclasses.dataclass(frozen=True)
class OverlapBucket:
    """One flat bucket: contiguous run of same-dtype leaves."""

    dtype: str        # dtype name of the packed buffer
    indices: tuple    # leaf indices (tree_flatten order), ascending
    shapes: tuple     # per-leaf shapes
    sizes: tuple      # per-leaf element counts
    total: int        # sum(sizes)
    padded: int       # total rounded up to a multiple of num_shards

    @property
    def offsets(self):
        off, out = 0, []
        for s in self.sizes:
            out.append(off)
            off += s
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class OverlapPlan:
    """Static bucket schedule for one gradient pytree. ``buckets`` are
    in grad-ready (issue) order; ``num_shards`` is the ZeRO padding
    quantum (1 for plain allreduce plans)."""

    buckets: tuple
    n_leaves: int
    bucket_cap_mb: float
    num_shards: int = 1

    def total_bytes(self) -> int:
        return sum(b.total * jnp.dtype(b.dtype).itemsize
                   for b in self.buckets)


def _pad_up(total: int, k: int) -> int:
    return total + ((-total) % max(1, k))


def plan_overlap(tree, bucket_cap_mb: float = 10.0,
                 num_shards: int = 1) -> OverlapPlan:
    """Plan grad-ready-ordered flat buckets for ``tree``.

    Buckets come from :func:`apex_tpu.runtime.plan_buckets` — the
    reference's reverse-order greedy, so bucket 0 collects the LAST
    leaves (whose grads the backward finishes first) and the issue
    order follows gradient readiness. Leaves are grouped per dtype
    (flat buffers need a uniform dtype); within a dtype the bucket
    members are a contiguous ascending index run. ``num_shards`` > 1
    pads every bucket to a multiple of it (the ZeRO-1 scatter/gather
    quantum)."""
    from apex_tpu.runtime import plan_buckets

    leaves, _ = jax.tree_util.tree_flatten(tree)
    cap = int(bucket_cap_mb * 1024 * 1024)
    by_dtype: dict[str, list[int]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype).name, []).append(i)
    buckets = []
    for dt in sorted(by_dtype):
        idxs = by_dtype[dt]
        sizes_b = [leaves[i].size * leaves[i].dtype.itemsize
                   for i in idxs]
        ids = plan_buckets(sizes_b, cap)
        n_buckets = max(ids) + 1 if ids else 0
        # bucket id 0 = the tail of the parameter list = first-ready
        for b in range(n_buckets):
            members = [i for i, bid in zip(idxs, ids) if bid == b]
            sizes = tuple(leaves[i].size for i in members)
            total = int(sum(sizes))
            buckets.append(OverlapBucket(
                dtype=dt, indices=tuple(members),
                shapes=tuple(tuple(leaves[i].shape) for i in members),
                sizes=sizes, total=total,
                padded=_pad_up(total, num_shards)))
    return OverlapPlan(buckets=tuple(buckets), n_leaves=len(leaves),
                       bucket_cap_mb=bucket_cap_mb,
                       num_shards=max(1, int(num_shards)))


def _check_plan(plan: OverlapPlan, leaves) -> None:
    if plan.n_leaves != len(leaves):
        raise ValueError(
            f"OverlapPlan was built for {plan.n_leaves} leaves, tree "
            f"has {len(leaves)} — plan and gradient tree diverged")
    for b in plan.buckets:
        for i, shape in zip(b.indices, b.shapes):
            if tuple(leaves[i].shape) != shape:
                raise ValueError(
                    f"OverlapPlan leaf {i} expects shape {shape}, got "
                    f"{tuple(leaves[i].shape)} — plan and tree diverged")


def _chain(flat, token):
    """Tie this bucket's packed buffer to the previous bucket's reduced
    result: the barrier makes XLA issue the collectives in grad-ready
    order (the reference's single comm stream) without adding any real
    compute or comms."""
    if token is None:
        return flat, None
    flat, token = jax.lax.optimization_barrier((flat, token))
    return flat, token


def _token_of(red):
    # a 1-element static slice: enough of a data dep to order the next
    # barrier, too small to keep the full buffer alive
    return jax.lax.slice_in_dim(red, 0, 1)


def _pack(leaves, bucket: OverlapBucket, cast=None):
    parts = [leaves[i].ravel() for i in bucket.indices]
    if cast is not None:
        parts = [p.astype(cast) for p in parts]
    flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
    if bucket.padded != bucket.total:
        flat = jnp.pad(flat, (0, bucket.padded - bucket.total))
    return flat


def _unpack_into(out, red, bucket: OverlapBucket):
    for i, off, sz, shape in zip(bucket.indices, bucket.offsets,
                                 bucket.sizes, bucket.shapes):
        out[i] = red[off:off + sz].reshape(shape)


def sync_gradients_overlapped(grads, axis_name: str = "data",
                              gradient_average: bool = True,
                              gradient_predivide_factor: float = 1.0,
                              bucket_cap_mb: float = 10.0,
                              plan: Optional[OverlapPlan] = None):
    """Grad-ready-ordered, barrier-chained bucket allreduce.

    Bit-identical to :func:`~apex_tpu.parallel.sync_gradients` (same
    predivide -> psum -> ``* predivide/n`` chain; flat packing is
    elementwise-neutral), but each bucket's psum depends only on its
    member leaves plus the previous bucket's token, so issued inside a
    jitted step the collectives run under the remaining backward
    compute in bucket-plan order."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    if plan is None:
        plan = plan_overlap(grads, bucket_cap_mb)
    _check_plan(plan, leaves)
    pre = gradient_predivide_factor
    n = jax.lax.axis_size(axis_name)
    out = [None] * len(leaves)
    token = None
    for k, bucket in enumerate(plan.buckets):
        site = f"ddp/overlap/bucket{k}/{bucket.dtype}"
        with span(site):
            flat = _pack(leaves, bucket)
            if pre != 1.0:
                flat = flat / pre
            flat, token = _chain(flat, token)
            # fleet barrier-wait probe (ISSUE 12): identity when off;
            # armed, it stamps per-rank enter/exit around the psum so
            # the straggler detector sees each rank's wait
            flat = fleet_probe.collective_enter(flat, site, axis_name)
            red = jax.lax.psum(flat, axis_name)
            red = fleet_probe.collective_exit(red, site, axis_name)
            if gradient_average:
                # static axis size (never psum(ones) — dead-collective)
                red = red * jnp.asarray(pre / n, red.dtype)
        token = _token_of(red)
        _unpack_into(out, red, bucket)
    return jax.tree_util.tree_unflatten(treedef, out)


def overlapped_value_and_grad(
        loss_fn: Callable, axis_name: str = "data",
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        bucket_cap_mb: float = 10.0,
        plan: Optional[OverlapPlan] = None,
        has_aux: bool = False) -> Callable:
    """``value_and_grad`` whose backward carries the bucket schedule.

    Each bucket's parameters pass through a ``custom_vjp`` identity
    hook whose transpose packs the bucket's cotangents and reduces them
    over ``axis_name`` — the collective is emitted into the backward at
    the point the bucket's grads complete (the reference's .grad-hook
    placement), instead of as a separate sync pass after it. Grads come
    back already reduced; bit-identical to ``jax.grad`` +
    :func:`~apex_tpu.parallel.sync_gradients`.

    ``loss_fn``'s first argument must be the parameter pytree."""
    pre = gradient_predivide_factor

    def _make_hook(bucket: OverlapBucket, tag: int):
        @jax.custom_vjp
        def hook(*leaves):
            return leaves

        def fwd(*leaves):
            return leaves, None

        def bwd(_, cts):
            with span(f"ddp/overlap/bwd_bucket{tag}/{bucket.dtype}"):
                # pack the accumulated bucket cotangents and reduce them
                # right here in the backward
                local = _pack(list(cts), _rebase(bucket))
                if pre != 1.0:
                    local = local / pre
                red = jax.lax.psum(local, axis_name)
                if gradient_average:
                    n = jax.lax.axis_size(axis_name)
                    red = red * jnp.asarray(pre / n, red.dtype)
            outs: list = [None] * len(bucket.indices)
            _unpack_into(outs, red, _rebase(bucket))
            return tuple(outs)

        hook.defvjp(fwd, bwd)
        return hook

    def _rebase(bucket: OverlapBucket) -> OverlapBucket:
        # inside the hook the bucket's leaves are positions 0..k-1
        return dataclasses.replace(
            bucket, indices=tuple(range(len(bucket.indices))))

    def wrapped(params, *args, **kwargs):
        plan_ = plan if plan is not None else plan_overlap(
            params, bucket_cap_mb)
        _check_plan(plan_, jax.tree_util.tree_leaves(params))

        def hooked_loss(params, *a, **kw):
            # the hooks must sit INSIDE the differentiated function so
            # their transposes (the per-bucket reductions) are emitted
            # into the backward
            leaves, treedef = jax.tree_util.tree_flatten(params)
            hooked = list(leaves)
            for tag, bucket in enumerate(plan_.buckets):
                hook = _make_hook(bucket, tag)
                outs = hook(*[leaves[i] for i in bucket.indices])
                for i, o in zip(bucket.indices, outs):
                    hooked[i] = o
            return loss_fn(jax.tree_util.tree_unflatten(treedef, hooked),
                           *a, **kw)

        return jax.value_and_grad(hooked_loss, has_aux=has_aux)(
            params, *args, **kwargs)

    return wrapped


# --------------------------------------------------------- comms model

GRAD_SYNC_MODES = ("allreduce", "zero1")


def grad_sync_bytes_from_sizes(grad_bytes: int, param_bytes: int,
                               axis_size: int,
                               mode: str = "allreduce") -> int:
    """Size-based core of :func:`grad_sync_comms_bytes` — the form the
    auto-sharding planner prices candidates with (it has byte totals,
    not live trees)."""
    n = max(1, int(axis_size))
    if n <= 1:
        return 0
    if mode == "allreduce":
        return int(2 * grad_bytes * (n - 1) / n)
    if mode == "zero1":
        return int((grad_bytes + param_bytes) * (n - 1) / n)
    raise ValueError(
        f"unknown grad-sync mode {mode!r}; valid: "
        f"{', '.join(GRAD_SYNC_MODES)}")


def grad_sync_comms_bytes(tree, axis_size: int, mode: str = "allreduce",
                          grad_dtype=jnp.float32) -> int:
    """Per-device bytes the data-parallel gradient sync moves for one
    step over ``tree`` (the parameter pytree), under the ring model the
    sharding-flow estimator uses (`collective_bytes`):

    - ``allreduce``: psum of every gradient — ``2(n-1)/n`` of the grad
      bytes (grads travel in ``grad_dtype``, fp32 by default);
    - ``zero1``: reduce-scatter of the grads (``(n-1)/n`` of the grad
      bytes) + all-gather of the updated params in their own storage
      dtype (``(n-1)/n`` of the PARAM bytes) — 0.75x the allreduce when
      params are stored at half the gradient width (bf16 params, fp32
      grads), the ZeRO-1 pitch.

    Shared between the planner's comms model, the analysis targets and
    the ``ddp/comms_bytes`` gauge so they cannot drift apart."""
    leaves = jax.tree_util.tree_leaves(tree)
    gsize = jnp.dtype(grad_dtype).itemsize
    grad_bytes = sum(leaf.size * gsize for leaf in leaves)
    param_bytes = sum(leaf.size * jnp.dtype(leaf.dtype).itemsize
                      for leaf in leaves)
    return grad_sync_bytes_from_sizes(grad_bytes, param_bytes,
                                      axis_size, mode)
