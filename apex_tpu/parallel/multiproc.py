"""Multi-host launcher — TPU re-design of ``apex.parallel.multiproc``.

Ref: apex/parallel/multiproc.py (spawns one process per GPU with
WORLD_SIZE/RANK env vars fed to ``torch.distributed``). The TPU runtime
already runs one process per HOST, so the launcher has two roles:

- **on a pod**: each host process calls :func:`initialize_distributed`
  (``jax.distributed.initialize`` reads the TPU metadata) and runs the
  script — ``python -m apex_tpu.parallel.multiproc script.py``.
- **local development / CI**: ``--nprocs N`` spawns N worker processes
  on this machine wired to a localhost coordinator — the multi-HOST
  (DCN) path, exercised for real: collectives cross the process
  boundary over the Gloo transport exactly as they would cross hosts.
  ``--cpu --devices-per-proc D`` gives each worker D virtual CPU
  devices, so ``N x D`` global devices form the mesh.

Example (the analog of ``torch.distributed.launch --nproc_per_node``)::

    python -m apex_tpu.parallel.multiproc --nprocs 2 --cpu \
        --devices-per-proc 4 train.py --steps 10
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Initialize the multi-host runtime (NCCL init_process_group analog).

    Reads ``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES`` / ``PROCESS_ID``
    from the environment when args are None (the launcher sets them);
    with neither, defers to the TPU-pod metadata autodetection. Honors
    ``APEX_TPU_FORCE_CPU=1`` by pinning the cpu platform through
    jax.config BEFORE touching the backend (an env-var JAX_PLATFORMS
    is not enough under a sitecustomize that registers other plugins).
    """
    import jax

    if os.environ.get("APEX_TPU_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")

    # idempotent: the launcher's worker shim initializes before exec'ing
    # the script, and the script may initialize again by itself
    if jax.distributed.is_initialized():
        return jax.process_index(), jax.process_count()

    if coordinator_address is None:
        coordinator_address = os.environ.get("COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])

    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)
    # back-fill the fleet-identity env (ISSUE 12) so every telemetry
    # writer — which reads the env, never jax, to stay backend-free —
    # rank-suffixes its artifacts from here on. setdefault: an identity
    # the launcher already exported (with run_id) wins. ONLY for a real
    # fleet: a set index marks the process a fleet member, and a solo
    # run must keep writing un-suffixed legacy artifact names.
    if jax.process_count() > 1:
        os.environ.setdefault("APEX_TPU_PROCESS_INDEX",
                              str(jax.process_index()))
        os.environ.setdefault("APEX_TPU_PROCESS_COUNT",
                              str(jax.process_count()))
    return jax.process_index(), jax.process_count()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def simulated_mesh_env(n: int = 8, env=None) -> dict:
    """Environment for a subprocess that must see ``n`` simulated CPU
    devices (``--xla_force_host_platform_device_count``) — the 8-way
    proving ground every comms path runs on when real multi-chip
    hardware is absent (ISSUE 11). Existing force-count flags are
    rewritten, the platform is pinned to cpu, and
    ``APEX_TPU_SIMULATED_MESH`` marks the child so benches can record
    ``simulated: true`` in their JSON lines."""
    import re

    base = dict(os.environ if env is None else env)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   base.get("XLA_FLAGS", ""))
    base["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}").strip()
    base["JAX_PLATFORMS"] = "cpu"
    base["APEX_TPU_FORCE_CPU"] = "1"
    base["APEX_TPU_SIMULATED_MESH"] = str(n)
    return base


def run_simulated(argv, n: int = 8, timeout: float = 600.0,
                  env=None) -> "subprocess.CompletedProcess":
    """Run ``argv`` (absolute program + args) in a subprocess against an
    ``n``-device simulated CPU mesh; returns the CompletedProcess with
    captured text output. The jax.distributed-aware sibling is
    :func:`launch` (real multi-process over a localhost coordinator);
    this one is the in-process-mesh harness tests and benches re-exec
    through when fewer than 2 real devices are present."""
    return subprocess.run(
        list(argv), capture_output=True, text=True, timeout=timeout,
        env=simulated_mesh_env(n, env=env))


def launch(script_args, nprocs: int, devices_per_proc: int = 1,
           cpu: bool = False, env=None) -> int:
    """Spawn ``nprocs`` workers of ``python -m apex_tpu.parallel.multiproc
    <script_args>`` against a localhost coordinator; returns the first
    nonzero worker exit code (0 when all succeed). Workers inherit the
    caller's env plus the coordinator variables (and the CPU forcing
    knobs when ``cpu``)."""
    addr = f"127.0.0.1:{_free_port()}"
    base = dict(os.environ if env is None else env)
    base.update(COORDINATOR_ADDRESS=addr, NUM_PROCESSES=str(nprocs))
    # shared run id for the fleet's telemetry shards (ISSUE 12):
    # merge_fleet / the flight-record collector group by it. The
    # port-qualified launcher pid is unique per launch on this host.
    base.setdefault("APEX_TPU_RUN_ID",
                    f"fleet-{os.getpid()}-{addr.rsplit(':', 1)[-1]}")
    if cpu:
        base["APEX_TPU_FORCE_CPU"] = "1"
        flags = base.get("XLA_FLAGS", "")
        import re

        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       flags)
        base["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={devices_per_proc}"
        ).strip()
    procs = []
    for pid in range(nprocs):
        # fleet identity per worker (ISSUE 12): index/count exported up
        # front so telemetry written BEFORE jax.distributed comes up is
        # already rank-suffixed and stamped
        env_p = dict(base, PROCESS_ID=str(pid),
                     APEX_TPU_PROCESS_INDEX=str(pid),
                     APEX_TPU_PROCESS_COUNT=str(nprocs))
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "apex_tpu.parallel.multiproc",
             *script_args], env=env_p))
    # wait on EVERY worker before returning (a short-circuit here would
    # orphan still-running workers after the first failure)
    rcs = [p.wait() for p in procs]
    return next((rc for rc in rcs if rc), 0)


def main():
    """CLI: ``python -m apex_tpu.parallel.multiproc [--nprocs N]
    [--cpu] [--devices-per-proc D] script.py [args...]``.

    Without ``--nprocs`` this IS the worker: initialize the distributed
    runtime (coordinator env or pod metadata) and exec the script
    in-process. With ``--nprocs`` it spawns that many workers locally.
    """
    argv = sys.argv[1:]
    nprocs, devices_per_proc, cpu = None, 1, False
    while argv and argv[0].startswith("--"):
        flag = argv.pop(0)
        if flag == "--nprocs":
            nprocs = int(argv.pop(0))
        elif flag == "--devices-per-proc":
            devices_per_proc = int(argv.pop(0))
        elif flag == "--cpu":
            cpu = True
        else:
            print(f"unknown flag {flag}")
            return 2
    if not argv:
        print("usage: python -m apex_tpu.parallel.multiproc "
              "[--nprocs N] [--cpu] [--devices-per-proc D] "
              "<script> [args...]")
        return 1

    if nprocs is not None:
        return launch(argv, nprocs, devices_per_proc, cpu)

    initialize_distributed()
    script = argv[0]
    sys.argv = argv
    with open(script) as f:
        code = compile(f.read(), script, "exec")
    exec(code, {"__name__": "__main__", "__file__": script})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
