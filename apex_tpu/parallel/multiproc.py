"""Multi-host launcher — TPU re-design of ``apex.parallel.multiproc``.

Ref: apex/parallel/multiproc.py (spawns one process per GPU with
WORLD_SIZE/RANK env vars). On TPU pods each host runs one process that owns
its local chips; bootstrap goes through ``jax.distributed.initialize`` which
reads the TPU metadata (or explicit coordinator args) instead of
torch.distributed env vars.
"""

from __future__ import annotations

import os
import sys


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Initialize the multi-host runtime (NCCL init_process_group analog)."""
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)
    return jax.process_index(), jax.process_count()


def main():
    """CLI parity shim: ``python -m apex_tpu.parallel.multiproc script.py ...``

    On GPU the reference forks one worker per device. On TPU the runtime
    already runs one process per host, so this simply initializes the
    distributed runtime and execs the target script in-process.
    """
    argv = sys.argv[1:]
    if not argv:
        print("usage: python -m apex_tpu.parallel.multiproc <script> [args...]")
        return 1
    initialize_distributed(
        coordinator_address=os.environ.get("COORDINATOR_ADDRESS"),
        num_processes=(int(os.environ["NUM_PROCESSES"])
                       if "NUM_PROCESSES" in os.environ else None),
        process_id=(int(os.environ["PROCESS_ID"])
                    if "PROCESS_ID" in os.environ else None),
    )
    script = argv[0]
    sys.argv = argv
    with open(script) as f:
        code = compile(f.read(), script, "exec")
    exec(code, {"__name__": "__main__", "__file__": script})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
