"""Stateful optimizer shim over functional (optax-style) transforms.

The reference optimizers subclass ``torch.optim.Optimizer`` (mutable state,
``.step()``). TPU-native training is functional — the transform's ``update``
runs inside the user's jitted train step. ``FusedOptimizer`` wraps a
transform with an apex-flavoured stateful API for drop-in familiarity and for
the eager-ish scripting path; serious training should use the transform
directly (``tx.init`` / ``tx.update``).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import optax


class FusedOptimizer:
    """Apex-style stateful wrapper: holds params + opt state, ``step(grads)``.

    Unlike torch there are no ``.grad`` attributes: gradients are passed to
    ``step`` explicitly (a pytree matching params). ``zero_grad`` exists for
    API parity and is a no-op (ref e.g. apex/optimizers/fused_adam.py:85
    ``zero_grad``).
    """

    def __init__(self, params, tx: optax.GradientTransformation, defaults: dict,
                 tx_factory: Optional[Callable] = None):
        self.defaults = dict(defaults)
        self.tx = tx
        # rebuild hook: tx_factory(**overrides) -> GradientTransformation with
        # the same hyperparams except the overrides (used by e.g. LARC to zero
        # the inner weight decay, ref apex/parallel/LARC.py step()).
        self._tx_factory = tx_factory
        self.params = params
        self.state = tx.init(params)
        self._jit_step = jax.jit(self._functional_step)

    def _functional_step(self, grads, state, params):
        updates, new_state = self.tx.update(grads, state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_state

    def step(self, grads=None, closure: Optional[Callable] = None):
        """Apply one fused update. Returns the new params (also stored on self)."""
        loss = closure() if closure is not None else None
        if grads is None:
            raise ValueError(
                "apex_tpu optimizers are functional: pass grads to step() "
                "(there is no .grad attribute to read on TPU)."
            )
        self.params, self.state = self._jit_step(grads, self.state, self.params)
        return loss if loss is not None else self.params

    def zero_grad(self, set_to_none: bool = True):  # noqa: ARG002 - parity no-op
        return None

    def state_dict(self) -> dict:
        return {"state": self.state, "defaults": self.defaults}

    def load_state_dict(self, state_dict: dict) -> None:
        new_state = state_dict["state"]
        have = jax.tree_util.tree_structure(self.state)
        got = jax.tree_util.tree_structure(new_state)
        if have != got:
            raise ValueError(
                f"loaded optimizer state structure {got} does not match "
                f"current optimizer structure {have}")
        self.state = new_state
        self.defaults.update(state_dict.get("defaults", {}))
