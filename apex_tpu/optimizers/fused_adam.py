"""FusedAdam — TPU re-design of ``apex.optimizers.FusedAdam``.

Ref: apex/optimizers/fused_adam.py + csrc/multi_tensor_adam.cu.

The CUDA version fuses (a) the Adam elementwise chain and (b) the
per-parameter kernel launches via multi-tensor apply. On TPU both fusions
fall out of compilation: ``fused_adam`` returns an optax-compatible
transform whose whole update is one jitted executable; ``flat=True``
additionally packs every parameter into one buffer per dtype so the update
is a single fused elementwise kernel no matter how many parameters exist
(the exact end state multi-tensor apply approximates on GPU).

Drop-in replacement for ``optax.adamw`` / ``optax.adam`` (adam_w_mode=False).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers import _math
from apex_tpu.optimizers._base import FusedOptimizer
from apex_tpu.ops.flat import flatten_tree, unflatten_tree

ScalarOrSchedule = Union[float, Callable[[jax.Array], jax.Array]]


class FusedAdamState(NamedTuple):
    count: jax.Array  # int32 step counter (apex keeps this per group; ours is global)
    mu: Any
    nu: Any


def _lr_at(lr: ScalarOrSchedule, count):
    return lr(count) if callable(lr) else lr


def fused_adam(
    lr: ScalarOrSchedule = 1e-3,
    bias_correction: bool = True,
    betas=(0.9, 0.999),
    eps: float = 1e-8,
    adam_w_mode: bool = True,
    weight_decay: float = 0.0,
    flat: bool = False,
    use_kernel: Union[bool, None] = None,
) -> optax.GradientTransformation:
    """Functional FusedAdam. Arguments mirror apex/optimizers/fused_adam.py:64.

    ``use_kernel`` (flat mode only): run the flat update through the
    Pallas kernel (ops/fused_adam_kernel.py — the multi_tensor_adam.cu
    analog) instead of the XLA-fused jnp chain. ``None`` defers to the
    pallas gate (kernel on TPU); the bench races both paths.
    """
    b1, b2 = betas

    def init(params):
        if flat:
            bufs, meta = flatten_tree(params)
            zeros = {k: jnp.zeros((v.size,), jnp.float32) for k, v in bufs.items()}
            mu = dict(zeros)
            nu = {k: jnp.zeros_like(v) for k, v in zeros.items()}
        else:
            mu = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
            nu = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedAdamState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params (for weight decay / bias)")
        count = state.count + 1
        step = count.astype(jnp.float32)
        lr_t = _lr_at(lr, state.count)  # optax convention: schedule sees pre-increment count
        kw = dict(
            lr=lr_t, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
            adam_w_mode=adam_w_mode, step=step, bias_correction=bias_correction,
        )
        from apex_tpu.observability import get_registry, span

        if flat:
            from apex_tpu.ops import pallas_config

            # default OFF even on TPU (unlike the other fused kernels):
            # the flat update is a pure bandwidth-bound elementwise chain
            # that XLA already fuses to minimal HBM traffic, so the
            # Pallas kernel can at best tie — and lost the r3 CPU race
            # (docs/kernel_cost_study.md). The verdict lives in
            # pallas_config._KERNEL_AUTO['flat_adam'];
            # force('on')/use_kernel=True opts in; bench_kernels races
            # both and flips the table if on-chip numbers ever disagree.
            kernel_on = (use_kernel if use_kernel is not None
                         else pallas_config.use_pallas("flat_adam"))
            # the _KERNEL_AUTO outcome, observable: the counter ticks
            # once per TRACE of this update (not per step — eval_shape
            # and cond-branch traces count too), and the scope names the
            # ops so an on-silicon trace attributes kernel time to
            # flat/pallas vs flat/xla — the per-kernel race table's
            # missing evidence
            path = "pallas" if kernel_on else "xla"
            get_registry().counter("optimizer/fused_adam/dispatch",
                                   path=f"flat_{path}").inc()
            with span(f"fused_adam/flat/{path}"):
                # Group by *param* dtype; grads may arrive in a different
                # dtype (e.g. fp32 grads over bf16 params) and are packed
                # fp32 anyway.
                pbufs, meta = flatten_tree(params)
                _, _, specs = meta
                g_leaves = jax.tree_util.tree_leaves(grads)
                deltas, mu, nu = {}, {}, {}
                for k, (idxs, spec) in specs.items():
                    gbuf = jnp.concatenate(
                        [g_leaves[i].ravel().astype(jnp.float32)
                         for i in idxs])
                    if kernel_on:
                        from apex_tpu.ops.fused_adam_kernel import (
                            adam_flat_pallas,
                        )

                        # slab geometry is tuner-supplied: the wrapper
                        # resolves it outside its inner jit, so a fresh
                        # tune changes the static key and retraces
                        d, m, v = adam_flat_pallas(
                            gbuf, pbufs[k], state.mu[k], state.nu[k],
                            jnp.asarray(lr_t, jnp.float32), step,
                            b1=b1, b2=b2, eps=eps,
                            weight_decay=weight_decay,
                            adam_w_mode=adam_w_mode,
                            bias_correction=bias_correction,
                            interpret=pallas_config.interpret())
                    else:
                        d, m, v = _math.adam_step(
                            gbuf, pbufs[k], state.mu[k], state.nu[k], **kw)
                    deltas[k] = d.astype(spec.dtype)
                    mu[k], nu[k] = m, v
                updates = unflatten_tree(deltas, meta)
        else:
            get_registry().counter("optimizer/fused_adam/dispatch",
                                   path="tree").inc()
            with span("fused_adam/tree"):
                g_leaves, treedef = jax.tree_util.tree_flatten(grads)
                p_leaves = jax.tree_util.tree_leaves(params)
                m_leaves = jax.tree_util.tree_leaves(state.mu)
                v_leaves = jax.tree_util.tree_leaves(state.nu)
                results = [
                    _math.adam_step(g, p, m, v, **kw)
                    for g, p, m, v in zip(g_leaves, p_leaves, m_leaves,
                                          v_leaves)
                ]
                updates = treedef.unflatten(
                    [r[0].astype(p.dtype)
                     for r, p in zip(results, p_leaves)])
                mu = treedef.unflatten([r[1] for r in results])
                nu = treedef.unflatten([r[2] for r in results])
        return updates, FusedAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


class FusedAdam(FusedOptimizer):
    """Stateful apex-style API (ref apex/optimizers/fused_adam.py:64).

    ``opt = FusedAdam(params, lr=1e-3); new_params = opt.step(grads)``
    """

    def __init__(self, params, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, adam_w_mode=True, weight_decay=0.0, amsgrad=False,
                 set_grad_none=True, flat=False):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        del set_grad_none  # grads are functional; retained for API parity
        kw = dict(lr=lr, bias_correction=bias_correction, betas=betas, eps=eps,
                  adam_w_mode=adam_w_mode, weight_decay=weight_decay, flat=flat)
        super().__init__(params, fused_adam(**kw), dict(
            lr=lr, bias_correction=bias_correction, betas=betas, eps=eps,
            weight_decay=weight_decay),
            tx_factory=lambda **ov: fused_adam(**{**kw, **ov}))
