"""Fused MLP (TPU re-design of ``apex.mlp``; ref apex/mlp/mlp.py:26 MLP,
csrc/mlp.cpp / mlp_cuda).

The CUDA extension fuses the whole dense-bias-activation chain into one
kernel launch sequence with a single workspace. Under XLA one jitted call
already compiles the chain into fused HLO (gemm + bias + act per layer, no
intermediate round-trips beyond the gemm outputs), so the value here is the
API and the activation semantics (none | relu | sigmoid, ref mlp.py:40-47),
plus a ``custom_vjp`` that recomputes activations in the backward pass the
way mlp_cuda's backward reuses its saved outputs.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.ops.precision import matmul_amp

_ACTIVATIONS = ("none", "relu", "sigmoid")


def _act(y, activation: str):
    if activation == "relu":
        return jax.nn.relu(y)
    if activation == "sigmoid":
        return jax.nn.sigmoid(y)
    return y


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _mlp_function_vjp(bias: bool, activation: str, x, *weights_and_biases):
    return _forward(bias, activation, x, weights_and_biases)


def _forward(bias, activation, x, wb):
    step = 2 if bias else 1
    n = len(wb) // step
    y = x
    for i in range(n):
        w = wb[i * step]
        # accumulator pinned >= fp32 with bias+activation kept in the
        # accumulator dtype, storage dtype restored per layer (enforced
        # by the mlp_train_step precision target — apex_tpu.analysis
        # lowprec-accum; downcasting before the bias add would push the
        # bias-grad reduction into bf16). Under the O4 fp8 context the
        # registered "mlp" sites take the E4M3 delayed-scaling epilogue
        # instead (the fp8_mlp_train_step target pins that path).
        out_dtype = jnp.promote_types(y.dtype, w.dtype)
        y = matmul_amp(y, w, name="mlp", keep_acc=True)
        if bias:
            y = y + wb[i * step + 1]
        if i < n - 1:
            y = _act(y, activation)
        y = y.astype(out_dtype)
    return y


def _mlp_fwd(bias, activation, x, *wb):
    # save only inputs/params; hidden activations are recomputed in bwd
    # (remat — trades FLOPs for HBM exactly like jax.checkpoint)
    return _forward(bias, activation, x, wb), (x, wb)


def _mlp_bwd(bias, activation, res, g):
    x, wb = res

    def f(x, *wb):
        return _forward(bias, activation, x, wb)

    _, vjp = jax.vjp(f, x, *wb)
    return vjp(g)


_mlp_function_vjp.defvjp(_mlp_fwd, _mlp_bwd)


def mlp_function(bias: bool, activation: str, x, *weights_and_biases):
    """Functional fused MLP (ref mlp.py:24 ``mlp_function``).

    ``weights_and_biases``: ``w0, b0, w1, b1, ...`` when ``bias`` else
    ``w0, w1, ...``; weights are ``(in, out)``. Activation applies to every
    layer except the last (ref mlp.py MlpFunction/C++ semantics: hidden
    layers activated, output layer linear).

    Under the O4 fp8 context the recompute ``custom_vjp`` steps aside
    and AD flows straight through ``matmul_fp8``'s own vjp: a custom
    backward's sub-trace cannot see the context's amax probes, and the
    fp8 residency (quantized operands saved for the backward) IS the
    activation-memory win remat was buying here.
    """
    from apex_tpu.amp.scaler import current_fp8

    if current_fp8() is not None:
        return _forward(bias, activation, x, weights_and_biases)
    return _mlp_function_vjp(bias, activation, x, *weights_and_biases)

# O1 boundary cast: the matmul chain is MXU work → compute dtype
# (consumes amp/lists.py via amp_call's classification; ref apex registers
# mlp through amp.half_function the same way)
from apex_tpu.amp.amp import half_function as _half_function  # noqa: E402

mlp_function = _half_function(mlp_function)


class MLP:
    """apex-shaped MLP container (ref mlp.py:26).

    ``mlp_sizes`` e.g. ``[1024, 1024, 1024]`` builds two layers. Parameters
    live in ``.params`` (a pytree usable with the functional optimizers);
    ``__call__(x[, params])`` runs the fused chain.
    """

    def __init__(self, mlp_sizes: Sequence[int], bias: bool = True,
                 activation: str = "relu", seed: int = 0,
                 dtype=jnp.float32):
        if activation not in _ACTIVATIONS:
            raise TypeError(
                f"activation must be one of {_ACTIVATIONS}, got {activation}")
        self.mlp_sizes = list(mlp_sizes)
        self.num_layers = len(mlp_sizes) - 1
        self.bias = bias
        self.activation = activation
        self.params = self._init(jax.random.PRNGKey(seed), dtype)

    def _init(self, key, dtype):
        # ref mlp.py reset_parameters: kaiming-uniform-ish over fan_in
        params = []
        for i in range(self.num_layers):
            fan_in, fan_out = self.mlp_sizes[i], self.mlp_sizes[i + 1]
            key, kw, kb = jax.random.split(key, 3)
            bound = 1.0 / fan_in ** 0.5
            layer = {"w": jax.random.uniform(
                kw, (fan_in, fan_out), dtype, -bound, bound)}
            if self.bias:
                layer["b"] = jax.random.uniform(
                    kb, (fan_out,), dtype, -bound, bound)
            params.append(layer)
        return params

    def _flat(self, params):
        flat = []
        for layer in params:
            flat.append(layer["w"])
            if self.bias:
                flat.append(layer["b"])
        return flat

    def __call__(self, x, params: Optional[list] = None):
        p = params if params is not None else self.params
        return mlp_function(self.bias, self.activation, x, *self._flat(p))
