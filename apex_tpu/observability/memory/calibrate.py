"""Measured-vs-modeled HBM calibration (ISSUE 15 tentpole piece 3).

The sharding-flow estimator (PR 4) prices every registered target's
per-device peak HBM, and the auto-sharding planner (PR 8) *prunes
candidate layouts* on that number — yet it had never been checked
against what XLA actually allocates. This module closes the loop:

- re-run a registered sharding-flow target with the
  :func:`~apex_tpu.analysis.sharding_checks.capture_traces` hook
  armed, so the exact ``(fn, example_args)`` the estimator modeled is
  in hand;
- AOT-compile the same program
  (:meth:`CompiledMemoryCapture.capture`) and read XLA's
  ``memory_analysis()`` total (argument + output + temp − alias);
- publish ``memory/hbm_calibration_ratio{target=}`` = measured /
  modeled, plus the raw modeled/measured byte gauges.

The ratio is not expected to be 1.0 — the liveness model and XLA's
buffer assignment count different things (donation timing, fusion
temps, layout padding) — but it IS expected to be *stable*: a drifting
ratio means the cost model and the compiler disagree in a new way, and
every planner pruning decision inherits that error.
``tools/metrics_report.py --compare`` gates exactly that drift, which
turns silent planner mis-pruning into a failing diff. On a real TPU
relay window the same run gives the cost model its first on-silicon
ground truth (``tools/relay_hunter.py`` persists it).

Per-target compile failures degrade to a ``memory_calibration_skipped``
event (jax 0.4.37 cannot execute every analyzable program) — callers
assert on how many ratios LANDED, not on zero skips.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["DEFAULT_CALIBRATION_TARGETS", "calibrate_targets"]

# Sharding-flow targets that both trace AND compile on the CPU backend
# under jax 0.4.37 — the calibration set bench.py runs per-invocation.
# Deliberately spans the families the estimator's error modes differ
# over: a collective-only step, a shard_map'd kernel, donated optimizer
# state, and the dp-sharded ZeRO path.
DEFAULT_CALIBRATION_TARGETS = (
    "ddp_bucket_allreduce_step",
    "tp_fused_softmax_sharded",
    "fused_adam_master_sharded_step",
    "moe_dispatch",
    "zero1_fused_adam_step",
)


def calibrate_targets(names=None, registry=None,
                      capture=None) -> dict:
    """Run measured-vs-modeled HBM calibration over ``names`` (default
    :data:`DEFAULT_CALIBRATION_TARGETS`; must be registered
    sharding-flow targets). Returns ``{target: row}`` where a
    successful row carries ``modeled_bytes`` / ``measured_bytes`` /
    ``ratio`` / the per-executable ``breakdown``, and a skipped one
    carries ``error``.

    ``capture``: an optional
    :class:`~apex_tpu.observability.memory.compiled
    .CompiledMemoryCapture` to record the compiled stats into (default:
    the installed process capture, or a detached throwaway).
    """
    from apex_tpu.analysis import sharding_checks, targets as targets_mod
    from apex_tpu.observability.memory import compiled as compiled_mod
    from apex_tpu.observability.registry import get_registry

    reg = registry if registry is not None else get_registry()
    cap = capture
    if cap is None:
        cap = compiled_mod.current_capture()
    if cap is None:
        cap = compiled_mod.CompiledMemoryCapture(registry=reg)

    names = tuple(names) if names is not None \
        else DEFAULT_CALIBRATION_TARGETS
    # validated against the SHARDING target set specifically: only a
    # target that calls analyze_sharding can be trace-captured, so a
    # precision/spmd target name is as unknown here as a typo
    unknown = [n for n in names
               if n not in targets_mod.SHARDING_TARGETS]
    if unknown:
        raise ValueError(
            f"unknown sharding-flow target(s) {sorted(unknown)}; "
            f"registered: {sorted(targets_mod.SHARDING_TARGETS)}")

    results: dict = {}
    for name in names:
        row = _calibrate_one(name, targets_mod, sharding_checks, cap,
                             reg)
        results[name] = row
        if "ratio" in row:
            reg.gauge("memory/hbm_calibration_ratio", target=name).set(
                row["ratio"])
            reg.gauge("memory/hbm_modeled_bytes", target=name).set(
                row["modeled_bytes"])
            reg.gauge("memory/hbm_measured_bytes", target=name).set(
                row["measured_bytes"])
            reg.event("memory_calibration", target=name,
                      modeled_bytes=row["modeled_bytes"],
                      measured_bytes=row["measured_bytes"],
                      ratio=row["ratio"])
        else:
            reg.counter("memory/calibration_skipped").inc()
            reg.event("memory_calibration_skipped", target=name,
                      error=row["error"])
    return results


def _calibrate_one(name, targets_mod, sharding_checks, cap, reg) -> dict:
    """One target's calibration row; failures land as {"error": ...}
    (a target that cannot compile on this backend is a skip, not a
    crash of the whole calibration pass)."""
    captured: dict = {}
    try:
        with sharding_checks.capture_traces(captured):
            targets_mod.TARGETS[name]()
    except Exception as e:  # noqa: BLE001 — the target itself failed
        return {"error": f"target failed: {e!r:.200}"}
    trace = captured.get(name)
    if trace is None:
        return {"error": "target ran no analyze_sharding trace under "
                         "this name (jaxpr-level entry?)"}
    modeled = _modeled_peak(name, targets_mod)
    if modeled is None:
        return {"error": "no peak_hbm_bytes estimate in SHARDING_STATS"}
    try:
        _compiled, fields = cap.capture(
            trace["fn"], *trace["example_args"],
            name=f"calibrate/{name}",
            donate_argnums=trace["donate_argnums"] or ())
    except Exception as e:  # noqa: BLE001 — 0.4.37 cannot compile
        # every analyzable program (shard_map AD/replication gaps)
        return {"error": f"compile failed: {e!r:.200}"}
    if fields is None:
        return {"error": "backend reported no memory_analysis"}
    measured = fields["total_bytes"]
    if modeled <= 0:
        return {"error": f"modeled peak is {modeled} bytes — nothing "
                         f"to calibrate against"}
    return {
        "modeled_bytes": int(modeled),
        "measured_bytes": int(measured),
        "ratio": round(measured / modeled, 4),
        "breakdown": fields,
    }


def _modeled_peak(name, targets_mod) -> Optional[int]:
    stats = targets_mod.SHARDING_STATS.get(name) or {}
    peak = stats.get("peak_hbm_bytes")
    return int(peak) if isinstance(peak, (int, float)) else None
