"""Live HBM telemetry (ISSUE 15 tentpole piece 1).

Every telemetry tier so far sees time, numerics and the fleet — none
sees memory, even though the planner prunes layouts on a *modeled*
peak-HBM number and an OOM kills a run with nothing but an opaque
RESOURCE_EXHAUSTED string. :class:`MemoryMonitor` is the live side of
the story:

- **decimated live-bytes snapshots** — one walk over
  ``jax.live_arrays()`` (per-device local-byte attribution: a sharded
  array charges each holding device its shard) plus
  ``device.memory_stats()`` where the backend reports it (TPU/GPU:
  ``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``; the CPU
  backend reports nothing and the allocator fields stay None). Like
  the numerics :class:`~apex_tpu.observability.numerics.StatsCollector`
  the walk runs only every ``every`` steps — off-cadence steps cost
  nothing — and bench.py derives the cadence that keeps the amortized
  cost under 2% of step time;
- **per-step high-watermark** — the largest live-byte total any
  snapshot saw (plus the allocator's own ``peak_bytes_in_use`` where
  available), the number the modeled ``hbm-budget`` check is
  calibrated against;
- **top-k largest buffers** — shape/dtype/bytes of the arrays that
  dominate the live set, the first thing an OOM post-mortem needs;
- the ``memory/*`` gauge family + ``memory_snapshot`` events in the
  registry, and :meth:`MemoryMonitor.dump` — an identity-stamped,
  ``rank_path``-suffixed JSON artifact (two fleet ranks handed the
  same path can never clobber each other).

This module (with the rest of the memory package and
``ops/pallas_config.py``) is the sanctioned home of raw memory
introspection: direct ``jax.live_arrays()`` / ``.memory_stats()`` /
``device_memory_profile()`` calls anywhere else in the library are
linted (``raw-memory-introspection``) — ad-hoc host pulls of the live
set in a step loop serialize the pipeline exactly like the per-tensor
isnan anti-pattern the numerics tier retired.

jax imports are lazy and every read is guarded: a telemetry pull must
never take down (or force backend init in) the run it observes.
"""

from __future__ import annotations

import json
import sys
from typing import Optional

__all__ = [
    "MEMORY_SCHEMA_VERSION", "live_buffer_records", "device_live_bytes",
    "device_memory_stats", "memory_snapshot", "MemoryMonitor",
    "active_monitor", "set_active_monitor", "flight_section",
]

MEMORY_SCHEMA_VERSION = 1

#: the allocator fields a PJRT backend may report (TPU reports all
#: three; CPU reports none) — pulled verbatim into snapshots.
MEMORY_STATS_FIELDS = ("bytes_in_use", "peak_bytes_in_use",
                       "bytes_limit", "largest_alloc_size")


def live_buffer_records(top_k: Optional[int] = None) -> list:
    """One record per live (addressable, non-deleted) jax array,
    largest first: ``{shape, dtype, nbytes, devices, per_device}``.
    ``nbytes`` is the array's PHYSICAL footprint on this process —
    summed over addressable shards, so a replicated array counts one
    copy per holding device — and ``per_device`` attributes it.
    ``top_k`` truncates after sorting. The walk is host-only — no
    device sync, no dispatch."""
    import jax

    records = []
    skipped = 0
    for arr in jax.live_arrays():
        try:
            per_device = _per_device_bytes(arr)
            shape = tuple(int(d) for d in arr.shape)
            dtype = str(arr.dtype)
        except Exception:  # noqa: BLE001 — a deleted/donated buffer
            # can race the walk; telemetry counts + skips it rather
            # than raise
            skipped += 1
            continue
        records.append({"shape": list(shape), "dtype": dtype,
                        "nbytes": sum(per_device.values()),
                        "devices": sorted(per_device),
                        "per_device": per_device})
    if skipped:
        from apex_tpu.observability.registry import get_registry
        get_registry().counter("memory/buffers_skipped").inc(skipped)
    records.sort(key=lambda r: (-r["nbytes"], r["dtype"],
                                tuple(r["shape"])))
    return records[:top_k] if top_k is not None else records


def _per_device_bytes(arr) -> dict:
    """{device_str: physical bytes} for one array, from its
    addressable shards — a REPLICATED array charges every holding
    device the full buffer (each physically holds a copy; the logical
    ``nbytes`` alone would undercount by the replication factor
    exactly the params/optimizer state that dominate the live set).
    Falls back to an even split of the logical size when the shard
    surface is unavailable."""
    try:
        out: dict = {}
        for shard in arr.addressable_shards:
            dev = str(shard.device)
            out[dev] = out.get(dev, 0) + int(shard.data.nbytes)
        if out:
            return out
    except Exception:  # noqa: BLE001 — optional surface; fall through
        pass
    devs = sorted(str(d) for d in arr.devices()) or ["<unknown>"]
    share = int(arr.nbytes) // len(devs)
    return {d: share for d in devs}


def device_live_bytes(records: Optional[list] = None) -> dict:
    """Per-device PHYSICAL live bytes: ``{device_str: bytes}``. Pass
    the ``live_buffer_records()`` list already in hand to avoid a
    second walk (``memory_snapshot`` does — the snapshot cost the <2%
    decimation budget is derived from must be ONE walk)."""
    if records is None:
        records = live_buffer_records()
    per_device: dict = {}
    for rec in records:
        for dev, nbytes in rec["per_device"].items():
            per_device[dev] = per_device.get(dev, 0) + nbytes
    return {d: int(b) for d, b in sorted(per_device.items())}


def device_memory_stats(device=None) -> dict:
    """The PJRT allocator's own view of ``device`` (default: the first
    device), restricted to :data:`MEMORY_STATS_FIELDS`. Empty on
    backends that report nothing (CPU) — absence, never fabricated
    zeros."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    try:
        stats = dev.memory_stats()
    except Exception:  # noqa: BLE001 — optional PJRT surface
        stats = None
    if not stats:
        return {}
    return {k: int(stats[k]) for k in MEMORY_STATS_FIELDS
            if isinstance(stats.get(k), (int, float))}


def memory_snapshot(top_k: int = 5) -> dict:
    """One full live-memory snapshot (the :class:`MemoryMonitor` unit
    of work): physical live-byte totals, per-device attribution, the
    top-k largest buffers, and the allocator stats where reported.
    ONE live-array walk end to end — the snapshot cost is what the
    <2% decimation budget is derived from."""
    buffers = live_buffer_records()
    total = sum(r["nbytes"] for r in buffers)
    return {
        "live_bytes": int(total),
        "live_buffers": len(buffers),
        "per_device": device_live_bytes(buffers),
        "top": [{k: r[k] for k in ("shape", "dtype", "nbytes")}
                for r in buffers[:top_k]],
        "memory_stats": device_memory_stats() or None,
    }


class MemoryMonitor:
    """Decimated live-HBM driver: ``observe(step)`` takes a snapshot
    every ``every`` steps, tracks the high-watermark, and publishes the
    ``memory/*`` family; off-cadence steps cost nothing.

    Publishes per snapshot (all labeled ``source=<name>``):

    - gauges ``memory/live_bytes``, ``memory/live_buffers``,
      ``memory/watermark_bytes`` (+ ``memory/bytes_in_use`` /
      ``memory/peak_bytes_in_use`` / ``memory/bytes_limit`` when the
      backend reports them);
    - timer ``memory/snapshot_pass`` — the walk's own cost, so the
      <2% overhead budget is measured, not assumed;
    - counter ``memory/snapshots``; event ``memory_snapshot`` with the
      top-k buffers.

    ``last`` keeps the most recent summary — the ``memory`` block
    ``StepReporter.step(..., memory=monitor.last)`` attaches. The
    constructed monitor becomes the process's *active* monitor
    (:func:`active_monitor`), which is how flight-recorder and OOM
    dumps find the watermark without a handle.
    """

    def __init__(self, name: str = "memory", every: int = 16,
                 registry=None, top_k: int = 5):
        self.name = name
        self.every = max(int(every), 1)
        self.top_k = int(top_k)
        self._registry = registry
        self.last: Optional[dict] = None
        self.watermark_bytes: int = 0
        self.watermark_step: Optional[int] = None
        self.snapshots: int = 0
        set_active_monitor(self)

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from apex_tpu.observability.registry import get_registry
        return get_registry()

    def observe(self, step: int) -> Optional[dict]:
        """Take a snapshot when ``step`` is on cadence; returns the
        summary dict (also kept as ``last``), or None off-cadence."""
        if step % self.every:
            return None
        reg = self._reg()
        timer = reg.timer("memory/snapshot_pass", source=self.name)
        timer.start()
        try:
            snap = memory_snapshot(top_k=self.top_k)
        except BaseException:
            timer.cancel()
            raise
        elapsed = timer.stop()
        snap["step"] = int(step)
        snap["snapshot_ms"] = round(elapsed * 1e3, 3)
        if snap["live_bytes"] > self.watermark_bytes:
            self.watermark_bytes = snap["live_bytes"]
            self.watermark_step = int(step)
        snap["watermark_bytes"] = self.watermark_bytes
        snap["watermark_step"] = self.watermark_step
        self.snapshots += 1
        reg.counter("memory/snapshots", source=self.name).inc()
        reg.gauge("memory/live_bytes", source=self.name).set(
            snap["live_bytes"])
        reg.gauge("memory/live_buffers", source=self.name).set(
            snap["live_buffers"])
        reg.gauge("memory/watermark_bytes", source=self.name).set(
            self.watermark_bytes)
        for key, value in (snap.get("memory_stats") or {}).items():
            reg.gauge(f"memory/{key}", source=self.name).set(value)
        reg.event("memory_snapshot", source=self.name, step=int(step),
                  live_bytes=snap["live_bytes"],
                  live_buffers=snap["live_buffers"],
                  watermark_bytes=self.watermark_bytes,
                  top=snap["top"])
        self.last = snap
        return snap

    def summary(self) -> dict:
        """The compact block flight-recorder / OOM dumps embed:
        watermark + the latest snapshot (None when no snapshot ran)."""
        return {
            "watermark_bytes": self.watermark_bytes,
            "watermark_step": self.watermark_step,
            "snapshots": self.snapshots,
            "last": self.last,
        }

    def dump(self, path: str) -> str:
        """Write the monitor's state (a fresh snapshot + watermark +
        per-executable compiled stats when captured) as one
        identity-stamped JSON artifact at the ``rank_path``-suffixed
        variant of ``path``; returns the resolved path."""
        from apex_tpu.observability.fleet.identity import (
            identity_fields,
            rank_path,
        )
        from apex_tpu.observability.memory import compiled as compiled_mod

        cap = compiled_mod.current_capture()
        payload = {
            "kind": "apex_tpu.memory_record",
            "schema_version": MEMORY_SCHEMA_VERSION,
            **identity_fields(),
            **self.summary(),
            "snapshot": memory_snapshot(top_k=self.top_k),
            "compiled": cap.snapshot() if cap is not None else None,
        }
        resolved = rank_path(path)
        with open(resolved, "w") as f:
            json.dump(payload, f, indent=1, default=repr)
        self._reg().event("memory_dump", source=self.name,
                          path=resolved)
        return resolved


# ---------------------------------------------------- active monitor

_ACTIVE: "MemoryMonitor | None" = None


def active_monitor() -> "MemoryMonitor | None":
    """The most recently constructed :class:`MemoryMonitor` (None when
    no tier is running one) — the handle-free lookup the flight
    recorder and OOM forensics use."""
    return _ACTIVE


def set_active_monitor(monitor: "MemoryMonitor | None"):
    """Swap the process's active monitor; returns the previous one."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, monitor
    return prev


def _backend_ready() -> bool:
    """True when a jax backend is ALREADY initialized — the guard that
    keeps a telemetry write from being the thing that forces backend
    init (``jax.live_arrays()`` goes through ``get_backend()``)."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge as xb
        return bool(getattr(xb, "_backends", None))
    except Exception:  # noqa: BLE001 — private surface moved; a
        # process that imported jax almost certainly initialized it
        return True


def flight_section() -> "dict | None":
    """The ``memory`` block a flight-recorder / stall dump embeds:
    current live bytes + the active monitor's watermark and top
    buffers. Never raises and never forces backend init — returns None
    when no backend is up or any read fails (a post-mortem must not
    take down the run it observes)."""
    if not _backend_ready():
        return None
    try:
        monitor = active_monitor()
        section = {"live_bytes": None, "live_buffers": None,
                   "watermark_bytes": None, "top": None}
        snap = memory_snapshot(
            top_k=monitor.top_k if monitor is not None else 5)
        section["live_bytes"] = snap["live_bytes"]
        section["live_buffers"] = snap["live_buffers"]
        section["top"] = snap["top"]
        if snap.get("memory_stats"):
            section["memory_stats"] = snap["memory_stats"]
        if monitor is not None:
            section["watermark_bytes"] = monitor.watermark_bytes
            section["watermark_step"] = monitor.watermark_step
        return section
    except Exception:  # noqa: BLE001 — diagnostics only
        return None
