"""OOM forensics (ISSUE 15 tentpole piece 4).

An OOM today kills a run with nothing but an opaque
``RESOURCE_EXHAUSTED`` string. This module turns that string into a
structured post-mortem:

- :func:`is_oom_error` — classify an exception as resource
  exhaustion (the same markers ``bench.py``'s fallback ladder keys on);
- :func:`parse_resource_exhausted` — pull the numbers out of the
  message: requested bytes (``... allocate N bytes``, ``Attempting to
  allocate 1.17G``, the TPU compiler's ``Used X of Y hbm``), the
  allocator breakdown table (reserved/program/arguments/HLO temp) and
  the ``Largest program allocations`` entries, all best-effort — a
  message shape the parser has never seen degrades to
  ``matched=False``, never a raise;
- :func:`dump_memrec` — write the ``memrec_*.json`` artifact: the
  parse, the active :class:`~.hbm.MemoryMonitor`'s watermark + last
  snapshot, a fresh live-buffer snapshot, the per-executable compiled
  stats table, every thread's stack (the flight recorder's shared
  ingredient) and the trailing registry events. Rank + pid + serial in
  the filename keep concurrent dumps collision-free, exactly like
  ``flightrec_*``;
- :func:`oom_forensics` — the one-call driver
  :class:`~apex_tpu.resilience.ResilientTrainLoop` runs when a step
  dies OOM-shaped: dump + return the compact verdict (requested bytes,
  largest live buffer, watermark) that rides every ``rollback`` event
  and ``TrainAborted.report["memory"]``.

The ``oom`` fault kind in :mod:`apex_tpu.resilience.faults` raises a
message shaped like the real thing, so this whole path is
chaos-testable on CPU.
"""

from __future__ import annotations

import itertools
import json
import os
import re
import time
from typing import Optional

__all__ = [
    "OOM_MARKERS", "is_oom_error", "parse_resource_exhausted",
    "dump_memrec", "oom_forensics",
]

#: substrings that mark an exception as resource exhaustion (matched
#: against repr(), mirroring bench.py's fallback-ladder classifier).
OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
               "Ran out of memory", "OOM")

# "... allocate 1073741824 bytes" (BFC / host allocators)
_ALLOC_BYTES_RE = re.compile(
    r"allocat(?:e|ing)\s+([\d,]+)\s*bytes", re.IGNORECASE)
# "Attempting to allocate 1.17G" / "Used 19.46G of 15.48G hbm"
_SIZE = r"([\d.]+)\s*([KMGTP]i?)?B?"
_ALLOC_SIZE_RE = re.compile(
    r"(?:attempting to allocate|trying to allocate)\s+" + _SIZE,
    re.IGNORECASE)
_USED_OF_RE = re.compile(
    r"Used\s+" + _SIZE + r"\s+of\s+" + _SIZE, re.IGNORECASE)
_FREE_RE = re.compile(r"([\d.]+)\s*([KMGTP]i?)?B?\s+free",
                      re.IGNORECASE)
# the TPU compiler's usage table: "    program          18.93G"
_BREAKDOWN_RE = re.compile(
    r"^\s{2,}(reserved|program|arguments|global|scoped|HLO temp|"
    r"stack)\s+" + _SIZE + r"\s*(?:\(|$)", re.MULTILINE)
# "  1. Size: 2.50G" entries under "Largest program allocations"
_LARGEST_RE = re.compile(r"^\s*\d+\.\s+Size:\s+" + _SIZE,
                         re.MULTILINE)
_OPERATOR_RE = re.compile(r'Operator:\s*op_name="([^"]*)"')

_SUFFIX = {None: 1, "": 1,
           "K": 1 << 10, "Ki": 1 << 10, "M": 1 << 20, "Mi": 1 << 20,
           "G": 1 << 30, "Gi": 1 << 30, "T": 1 << 40, "Ti": 1 << 40,
           "P": 1 << 50, "Pi": 1 << 50}

# process-wide memrec serial (same collision contract as flightrec_*)
_DUMP_SEQ = itertools.count()


def _to_bytes(num: str, suffix: Optional[str]) -> Optional[int]:
    try:
        return int(float(num.replace(",", ""))
                   * _SUFFIX.get(suffix or "", 1))
    except (TypeError, ValueError):
        return None


def is_oom_error(exc) -> bool:
    """True when ``exc`` (an exception or message string) is resource
    exhaustion — a cheaper rung (smaller batch, rollback) may dodge it;
    anything else must fail fast."""
    text = exc if isinstance(exc, str) else repr(exc)
    return any(marker in text for marker in OOM_MARKERS)


def parse_resource_exhausted(text: str) -> dict:
    """Best-effort structured parse of a RESOURCE_EXHAUSTED message.

    Returns ``{matched, requested_bytes, limit_bytes, free_bytes,
    breakdown, largest_allocations}`` — unknown fields None/empty, and
    ``matched`` False when no byte figure parsed at all (the caller
    still gets the raw message elsewhere)."""
    text = text or ""
    requested = None
    limit = None
    m = _ALLOC_BYTES_RE.search(text)
    if m:
        requested = _to_bytes(m.group(1), None)
    if requested is None:
        m = _ALLOC_SIZE_RE.search(text)
        if m:
            requested = _to_bytes(m.group(1), m.group(2))
    m = _USED_OF_RE.search(text)
    if m:
        if requested is None:
            requested = _to_bytes(m.group(1), m.group(2))
        limit = _to_bytes(m.group(3), m.group(4))
    free = None
    m = _FREE_RE.search(text)
    if m:
        free = _to_bytes(m.group(1), m.group(2))

    breakdown = {}
    for m in _BREAKDOWN_RE.finditer(text):
        nbytes = _to_bytes(m.group(2), m.group(3))
        if nbytes is not None:
            breakdown[m.group(1)] = nbytes

    # each size entry's Operator line is searched only in ITS span
    # (up to the next numbered entry): an entry without one (padding /
    # unknown allocations) must not shift every later attribution
    largest = []
    size_matches = list(_LARGEST_RE.finditer(text))
    for i, m in enumerate(size_matches):
        nbytes = _to_bytes(m.group(1), m.group(2))
        if nbytes is None:
            continue
        entry = {"nbytes": nbytes}
        span_end = (size_matches[i + 1].start()
                    if i + 1 < len(size_matches) else len(text))
        op = _OPERATOR_RE.search(text, m.end(), span_end)
        if op:
            entry["op_name"] = op.group(1)
        largest.append(entry)

    return {
        "matched": requested is not None or bool(breakdown)
        or bool(largest),
        "requested_bytes": requested,
        "limit_bytes": limit,
        "free_bytes": free,
        "breakdown": breakdown,
        "largest_allocations": largest,
    }


def _default_dir() -> str:
    # the flight recorder owns the artifact-directory policy — a memrec
    # must land next to the flightrec so one story tells both dumps
    from apex_tpu.observability.profiling import flight_recorder
    return flight_recorder._default_dir()


def dump_memrec(error=None, *, monitor=None, registry=None,
                directory: Optional[str] = None,
                step: Optional[int] = None, kind: str = "oom",
                max_events: int = 100) -> Optional[str]:
    """Write the ``memrec_*.json`` OOM post-mortem; returns its path
    (None when even the write failed — forensics must never take down
    the run). ``monitor`` defaults to the active
    :class:`~.hbm.MemoryMonitor`."""
    from apex_tpu.observability.fleet.identity import (
        FleetIdentity,
        identity_fields,
        process_identity,
    )
    from apex_tpu.observability.memory import compiled as compiled_mod
    from apex_tpu.observability.memory import hbm
    from apex_tpu.observability.profiling.flight_recorder import (
        thread_stacks,
    )

    reg = registry
    if reg is None:
        from apex_tpu.observability.registry import get_registry
        reg = get_registry()
    if monitor is None:
        monitor = hbm.active_monitor()
    try:
        ident = process_identity()
    except ValueError:
        ident = FleetIdentity(0, 1, None)
    error_text = None if error is None else (
        error if isinstance(error, str) else repr(error))
    try:
        snapshot = hbm.memory_snapshot(
            top_k=monitor.top_k if monitor is not None else 5)
    except Exception as e:  # noqa: BLE001 — the backend may be the
        # thing that just died; the parse + watermark still dump
        snapshot = {"error": repr(e)[:200]}
    cap = compiled_mod.current_capture()
    payload = {
        "kind": "apex_tpu.memory_record",
        "schema_version": hbm.MEMORY_SCHEMA_VERSION,
        **identity_fields(ident),
        "trigger": kind,
        "pid": os.getpid(),
        "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "step": step,
        "error": None if error_text is None else error_text[:4000],
        "oom": None if error_text is None
        else parse_resource_exhausted(error_text),
        "monitor": monitor.summary() if monitor is not None else None,
        "snapshot": snapshot,
        "compiled": cap.snapshot() if cap is not None else None,
        "thread_stacks": thread_stacks(),
        "events": (reg.events()[-max_events:] if max_events > 0
                   else []),
    }
    fname = (f"memrec_{time.strftime('%Y%m%d-%H%M%S')}_"
             f"r{ident.process_index}_{os.getpid()}_"
             f"{next(_DUMP_SEQ)}_{kind}.json")
    path = os.path.join(directory or _default_dir(), fname)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=repr)
    except OSError as e:
        reg.counter("memory/memrec_dump_failures").inc()
        reg.event("memrec_dump_failed", error=repr(e)[:200])
        return None
    reg.counter("memory/memrec_dumps").inc()
    reg.event("memory_record", path=path, trigger=kind, step=step)
    return path


def oom_forensics(error, *, monitor=None, registry=None,
                  directory: Optional[str] = None,
                  step: Optional[int] = None) -> dict:
    """The one-call OOM post-mortem the resilience loop runs: dump a
    memrec artifact and return the compact verdict dict
    (``requested_bytes``, ``largest_buffer``, ``live_bytes``,
    ``watermark_bytes``, ``memrec`` path, the truncated error). Never
    raises — any failure degrades to fields of the verdict."""
    from apex_tpu.observability.memory import hbm

    if monitor is None:
        monitor = hbm.active_monitor()
    error_text = error if isinstance(error, str) else repr(error)
    parsed = parse_resource_exhausted(error_text)
    verdict = {
        "requested_bytes": parsed.get("requested_bytes"),
        "limit_bytes": parsed.get("limit_bytes"),
        "largest_buffer": None,
        "live_bytes": None,
        "watermark_bytes": (monitor.watermark_bytes
                            if monitor is not None else None),
        "error": error_text[:500],
        "memrec": None,
    }
    try:
        snap = hbm.memory_snapshot(top_k=1)
        verdict["live_bytes"] = snap["live_bytes"]
        if snap["top"]:
            verdict["largest_buffer"] = snap["top"][0]
    except Exception:  # noqa: BLE001 — the backend may be down; the
        # monitor's last snapshot is the fallback attribution
        if monitor is not None and monitor.last:
            verdict["live_bytes"] = monitor.last.get("live_bytes")
            top = monitor.last.get("top") or []
            verdict["largest_buffer"] = top[0] if top else None
    try:
        verdict["memrec"] = dump_memrec(
            error, monitor=monitor, registry=registry,
            directory=directory, step=step)
    except Exception:  # noqa: BLE001 — verdict without artifact is
        # still a verdict
        verdict["memrec"] = None
    return verdict
