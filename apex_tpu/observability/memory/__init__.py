"""apex_tpu.observability.memory — the memory observability tier
(ISSUE 15).

The stack could already see time (spans, flight recorder), numerics
(stats, NaN provenance) and the fleet (skew, desync) — this package
makes it memory-SIGHTED, and grounds the sharding cost model in
measurement:

- :mod:`~apex_tpu.observability.memory.hbm` —
  :class:`MemoryMonitor`: decimated live-bytes snapshots
  (``jax.live_arrays()`` per-device attribution +
  ``device.memory_stats()`` where reported), per-step high-watermarks,
  top-k largest buffers, the ``memory/*`` gauge family, and
  identity-stamped ``rank_path``-suffixed dumps;
- :mod:`~apex_tpu.observability.memory.compiled` —
  :class:`CompiledMemoryCapture`: hooks the PR 2 recompile listener so
  every jitted-fn compile records XLA's ``memory_analysis()``
  (argument/output/temp/generated-code bytes) — a per-executable
  static memory view;
- :mod:`~apex_tpu.observability.memory.calibrate` —
  :func:`calibrate_targets`: re-compile the registered sharding-flow
  targets and publish ``memory/hbm_calibration_ratio{target=}`` =
  XLA-measured / estimator-modeled peak, so cost-model drift becomes a
  gated regression (``tools/metrics_report.py --compare``) instead of
  silent planner mis-pruning;
- :mod:`~apex_tpu.observability.memory.oom` — OOM forensics:
  RESOURCE_EXHAUSTED parsing, the ``memrec_*.json`` post-mortem
  artifact, and the verdict
  :class:`~apex_tpu.resilience.ResilientTrainLoop` attaches to
  ``rollback`` events and ``TrainAborted.report["memory"]`` (the
  ``oom`` fault kind makes the path chaos-testable).

Consumers: ``StepReporter`` records carry a ``memory`` block, flight
records grow a ``memory`` section, ``pallas_config.device_hbm_bytes``
prefers the live ``bytes_limit``, bench.py emits the ``memory`` JSON
object (snapshot cadence derived to keep overhead <2% of step time),
``examples/llama_train.py`` runs the monitor, and
``tools/relay_hunter.py`` persists a real-TPU calibration snapshot.
Docs: ``docs/observability.md`` ("Memory telemetry").

This package (plus ``ops/pallas_config.py``) is the sanctioned home of
raw memory introspection — direct ``jax.live_arrays()`` /
``.memory_stats()`` / ``device_memory_profile()`` calls elsewhere are
linted (``raw-memory-introspection``).
"""

from apex_tpu.observability.memory.calibrate import (  # noqa: F401
    DEFAULT_CALIBRATION_TARGETS,
    calibrate_targets,
)
from apex_tpu.observability.memory.compiled import (  # noqa: F401
    CompiledMemoryCapture,
    current_capture,
    install_compiled_capture,
    memory_analysis_fields,
    uninstall_compiled_capture,
)
from apex_tpu.observability.memory.hbm import (  # noqa: F401
    MEMORY_SCHEMA_VERSION,
    MemoryMonitor,
    active_monitor,
    device_live_bytes,
    device_memory_stats,
    flight_section,
    live_buffer_records,
    memory_snapshot,
    set_active_monitor,
)
from apex_tpu.observability.memory.oom import (  # noqa: F401
    OOM_MARKERS,
    dump_memrec,
    is_oom_error,
    oom_forensics,
    parse_resource_exhausted,
)

__all__ = [
    "MEMORY_SCHEMA_VERSION", "MemoryMonitor", "memory_snapshot",
    "live_buffer_records", "device_live_bytes", "device_memory_stats",
    "active_monitor", "set_active_monitor", "flight_section",
    "CompiledMemoryCapture", "install_compiled_capture",
    "uninstall_compiled_capture", "current_capture",
    "memory_analysis_fields",
    "DEFAULT_CALIBRATION_TARGETS", "calibrate_targets",
    "OOM_MARKERS", "is_oom_error", "parse_resource_exhausted",
    "dump_memrec", "oom_forensics",
]
