"""Per-executable static memory view from XLA (ISSUE 15 tentpole
piece 2).

XLA already knows exactly what every compiled program will allocate —
``compiled.memory_analysis()`` reports argument / output / temp /
generated-code bytes per executable — but nothing in the stack ever
read it. :class:`CompiledMemoryCapture` hooks the PR 2 recompile
listener so every jitted-fn compile records that static view into the
registry:

- the listener's per-function ``jax_log_compiles`` record fires at
  compile *start* (name known, executable not yet built) and the
  ``jax.monitoring`` backend-compile duration event fires *after* the
  executable exists — the capture remembers the pending name on the
  first and sweeps ``client.live_executables()`` for new executables
  on the second, attributing their ``get_compiled_memory_stats()`` to
  the function that just compiled;
- :meth:`CompiledMemoryCapture.capture` is the explicit AOT path
  (``jit(fn).lower(*args).compile()`` + record) the calibration tier
  uses for programs it builds itself.

Per function the capture keeps the LATEST stats plus a compile count;
gauges land as ``memory/compiled_total_bytes{fn=}`` so the
biggest-executable view rides every metrics dump, and the full table
rides ``MemoryMonitor.dump`` / ``memrec_*.json`` OOM artifacts.

jax-lazy like the rest of the package; a failed sweep degrades to a
counter, never an exception in the logging filter it rides.
"""

from __future__ import annotations

import threading
from typing import Optional

__all__ = [
    "memory_analysis_fields", "CompiledMemoryCapture",
    "install_compiled_capture", "uninstall_compiled_capture",
    "current_capture",
]

#: the CompiledMemoryStats fields recorded per executable, in table
#: order ("alias" bytes are donation credit: argument bytes re-used as
#: outputs).
COMPILED_STAT_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)


def memory_analysis_fields(analysis) -> "dict | None":
    """A ``compiled.memory_analysis()`` / ``get_compiled_memory_stats``
    result as a plain dict (+ the derived ``total_bytes`` = argument +
    output + temp − alias, the executable's device footprint). None
    when the backend returned nothing."""
    if analysis is None:
        return None
    out = {}
    for attr, key in COMPILED_STAT_FIELDS:
        value = getattr(analysis, attr, None)
        if value is None:
            return None
        out[key] = int(value)
    out["total_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                          + out["temp_bytes"] - out["alias_bytes"])
    return out


class CompiledMemoryCapture:
    """Collects per-executable XLA memory stats; see module doc.

    Thread-safe: the recompile listener's observers fire from whatever
    thread compiled.
    """

    def __init__(self, registry=None):
        self._registry = registry
        self._lock = threading.Lock()
        self._by_fn: dict = {}
        # executables are keyed by wrapper id(): jaxlib exposes no
        # stable fingerprint/name and LoadedExecutable is not
        # weakref-able. The wrapper objects ARE stable across
        # live_executables() calls (probed at install; a build that
        # hands out fresh wrappers per call would misattribute, so the
        # sweep self-disables there). Residual limitation: an id
        # reused after an executable unloads can shadow one later
        # executable's row — a missed telemetry row, never a wrong one.
        self._seen_execs: set = set()
        self._pending_fn: Optional[str] = None
        self._listener = None
        self._sweep_disabled = False

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from apex_tpu.observability.registry import get_registry
        return get_registry()

    # ---------------------------------------------------------- hooks

    def install(self) -> "CompiledMemoryCapture":
        """Attach to the (installed-if-needed) recompile listener.
        Executables alive *before* install are primed as seen, so a
        pre-existing program is never misattributed to the next
        compile. Wrapper identity is probed: a jaxlib build whose
        ``live_executables()`` returns fresh wrapper objects per call
        would defeat both the priming and the new-executable diff, so
        the sweep self-disables (counted) rather than misattribute."""
        from apex_tpu.observability import recompile

        self._listener = recompile.install()
        first = self._live_executables()
        second = self._live_executables()
        if first and {id(ex) for ex in first}.isdisjoint(
                id(ex) for ex in second):
            self._sweep_disabled = True
            self._reg().counter(
                "memory/compiled_sweep_unstable_wrappers").inc()
        with self._lock:
            for ex in first + second:
                self._seen_execs.add(id(ex))
        self._listener.add_observer(self._observe)
        return self

    def uninstall(self) -> None:
        if self._listener is not None:
            self._listener.remove_observer(self._observe)
            self._listener = None

    def _observe(self, kind: str, name) -> None:
        if kind == "compile":
            with self._lock:
                self._pending_fn = name
        elif kind == "backend_compile":
            self.sweep()

    @staticmethod
    def _live_executables() -> list:
        import jax

        try:
            return list(jax.devices()[0].client.live_executables())
        except Exception:  # noqa: BLE001 — optional PJRT surface
            return []

    def sweep(self) -> int:
        """Record every live executable not yet seen, attributed to the
        last per-function compile record (``<unattributed>`` when the
        log feed degraded). Returns how many were recorded."""
        if self._sweep_disabled:
            return 0
        execs = self._live_executables()
        recorded = 0
        with self._lock:
            fn_name = self._pending_fn or "<unattributed>"
            fresh = [ex for ex in execs
                     if id(ex) not in self._seen_execs]
            for ex in fresh:
                self._seen_execs.add(id(ex))
            self._pending_fn = None
        for ex in fresh:
            try:
                fields = memory_analysis_fields(
                    ex.get_compiled_memory_stats())
            except Exception:  # noqa: BLE001 — backend without the
                # stats surface: count the miss, keep the run alive
                fields = None
            if fields is None:
                self._reg().counter(
                    "memory/compiled_stats_unavailable").inc()
                continue
            self.record(fn_name, fields)
            recorded += 1
        return recorded

    # --------------------------------------------------------- record

    def record(self, fn_name: str, fields: dict) -> dict:
        """Record one executable's stats under ``fn_name`` (latest
        wins; ``compiles`` counts how many landed)."""
        with self._lock:
            row = self._by_fn.setdefault(fn_name, {"compiles": 0})
            row["compiles"] += 1
            row.update({k: v for k, v in fields.items()})
            snapshot = dict(row)  # copied under the lock: a
            # concurrent record() of the same fn mutates `row`
        reg = self._reg()
        reg.counter("memory/compiled_captures", fn=fn_name).inc()
        reg.gauge("memory/compiled_total_bytes", fn=fn_name).set(
            fields["total_bytes"])
        return snapshot

    def capture(self, fn, *args, name: Optional[str] = None,
                donate_argnums=(), **kwargs):
        """AOT-compile ``fn(*args, **kwargs)`` and record its memory
        analysis under ``name``; returns ``(compiled, fields)``. The
        explicit path for programs the runtime never dispatches (the
        calibration tier's sharding-target traces)."""
        import jax

        name = name or getattr(fn, "__name__", "fn")
        compiled = jax.jit(fn, donate_argnums=donate_argnums).lower(
            *args, **kwargs).compile()
        fields = memory_analysis_fields(compiled.memory_analysis())
        if fields is not None:
            self.record(name, fields)
        return compiled, fields

    # ----------------------------------------------------------- read

    def snapshot(self) -> dict:
        """{fn name: {compiles, argument/output/temp/alias/
        generated_code/total bytes}} — the per-executable table."""
        with self._lock:
            return {name: dict(row)
                    for name, row in sorted(self._by_fn.items())}


# ------------------------------------------------------ process default

_CURRENT: "CompiledMemoryCapture | None" = None
_CURRENT_LOCK = threading.Lock()


def install_compiled_capture(registry=None) -> CompiledMemoryCapture:
    """Install (or return the already-installed) process capture —
    idempotent, like ``recompile.install``."""
    global _CURRENT
    with _CURRENT_LOCK:
        if _CURRENT is None:
            _CURRENT = CompiledMemoryCapture(registry=registry).install()
        elif registry is not None:
            _CURRENT._registry = registry
        return _CURRENT


def uninstall_compiled_capture() -> None:
    """Detach the process capture (its table stays readable)."""
    global _CURRENT
    with _CURRENT_LOCK:
        if _CURRENT is not None:
            _CURRENT.uninstall()
            _CURRENT = None


def current_capture() -> "CompiledMemoryCapture | None":
    return _CURRENT
