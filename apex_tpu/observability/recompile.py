"""Runtime recompile/retrace accounting (ISSUE 2 tentpole piece 3).

``apex_tpu.analysis`` lints recompile *hazards* statically (unhashable
static args, closure captures); this module counts what actually
happened at runtime and turns the count into a budget a bench run can
fail on. Two feeds, both installed by :func:`install`:

- ``jax.monitoring`` duration events (``/jax/core/compile/*``) give the
  process-total trace/lower/compile counts and seconds — version-stable,
  but carry no function names.
- with ``jax_log_compiles`` enabled, jax logs one
  ``"Compiling <name> with global shapes..."`` record per cache-miss
  compile; a logging filter on the emitting loggers parses the name for
  PER-FUNCTION compile counts (retraces = compiles - 1) and swallows
  the records so enabling the flag doesn't spray stderr. When jax's
  logger layout changes the per-function table degrades to empty while
  the monitoring totals keep working.

Counts also land in a :class:`~apex_tpu.observability.registry
.MetricRegistry`: counter ``jax/compiles{fn=...}``, histogram
``jax/backend_compile_secs``.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import re
import threading

from apex_tpu.observability.registry import get_registry

__all__ = [
    "RecompileListener", "RetraceBudgetExceeded", "install", "uninstall",
    "current", "retrace_guard",
]

# jax loggers that emit the per-compile records under jax_log_compiles
# (jax 0.4.x: pxla logs "Compiling <name> with global shapes and types
# ...", dispatch logs the "Finished tracing/compilation ..." lines).
_JAX_LOG_COMPILE_LOGGERS = ("jax._src.interpreters.pxla",
                            "jax._src.dispatch")
_COMPILING_RE = re.compile(r"^Compiling ([\w<>.\-]+) ")
_FINISHED_RE = re.compile(r"^Finished (tracing \+ transforming|"
                          r"jaxpr to MLIR module conversion|"
                          r"XLA compilation)")

# monitoring event names (jax 0.4.37 _src/dispatch.py)
_EV_TRACE = "/jax/core/compile/jaxpr_trace_duration"
_EV_LOWER = "/jax/core/compile/jaxpr_to_mlir_module_duration"
_EV_COMPILE = "/jax/core/compile/backend_compile_duration"


class RetraceBudgetExceeded(RuntimeError):
    """A guarded region retraced more than its budget allows."""


class RecompileListener:
    """Aggregates compile activity while installed; see module doc."""

    def __init__(self, registry=None):
        self.registry = registry
        self._lock = threading.Lock()
        self.compiles_by_fn = collections.Counter()
        self.totals = collections.Counter()      # event name -> count
        self.seconds = collections.defaultdict(float)
        # compile observers (ISSUE 15): callbacks cb(kind, name) fired
        # on "compile" (a per-function jax_log_compiles record — name
        # known, executable not yet built) and "backend_compile" (the
        # monitoring duration event AFTER the executable exists — the
        # moment the memory tier sweeps live_executables for its
        # per-executable memory_analysis view)
        self._observers: list = []
        self.observer_errors = 0

    # ---- feed: jax.monitoring duration events

    def _on_duration(self, name: str, secs: float) -> None:
        if not name.startswith("/jax/core/compile/"):
            return
        with self._lock:
            self.totals[name] += 1
            self.seconds[name] += secs
        if self.registry is not None and name == _EV_COMPILE:
            self.registry.histogram("jax/backend_compile_secs").observe(secs)
        if name == _EV_COMPILE:
            self._notify("backend_compile", None)

    # ---- feed: jax_log_compiles records

    def _on_compile_record(self, fn_name: str) -> None:
        with self._lock:
            self.compiles_by_fn[fn_name] += 1
        if self.registry is not None:
            self.registry.counter("jax/compiles", fn=fn_name).inc()
        self._notify("compile", fn_name)

    # ---- compile observers (ISSUE 15)

    def add_observer(self, cb) -> None:
        """Register ``cb(kind, name)`` to fire on compile activity
        (``kind`` in {"compile", "backend_compile"}); idempotent."""
        with self._lock:
            if cb not in self._observers:
                self._observers.append(cb)

    def remove_observer(self, cb) -> None:
        with self._lock:
            if cb in self._observers:
                self._observers.remove(cb)

    def _notify(self, kind: str, name) -> None:
        with self._lock:
            observers = list(self._observers)
        for cb in observers:
            try:
                cb(kind, name)
            except Exception:  # noqa: BLE001 — an observer must never
                # break the compile (or the logging filter) it rides
                with self._lock:  # += is a read-modify-write; compile
                    # records land from jax's logging + monitoring
                    # hooks on whatever thread compiled
                    self.observer_errors += 1

    # ---- read side

    def compiles(self, fn: "str | None" = None):
        """Per-function compile counts (dict), or one function's count."""
        with self._lock:
            if fn is not None:
                return self.compiles_by_fn.get(fn, 0)
            return dict(self.compiles_by_fn)

    def retraces(self, fn: "str | None" = None):
        """Compiles beyond the first per function — the recompiles a
        steady-state training loop should never see."""
        with self._lock:
            table = {name: n - 1 for name, n in self.compiles_by_fn.items()
                     if n > 1}
            if fn is not None:
                return table.get(fn, 0)
            return table

    def total_retraces(self) -> int:
        return sum(self.retraces().values())

    def backend_compiles(self) -> int:
        """Process-total backend compiles from jax.monitoring (includes
        jax-internal helper jits the per-function table may not name)."""
        with self._lock:
            return self.totals[_EV_COMPILE]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "compiles_by_fn": dict(self.compiles_by_fn),
                "retraces_by_fn": {n: c - 1 for n, c in
                                   self.compiles_by_fn.items() if c > 1},
                "backend_compiles": self.totals[_EV_COMPILE],
                "backend_compile_secs": round(
                    self.seconds[_EV_COMPILE], 3),
                "trace_events": self.totals[_EV_TRACE],
            }


class _CompileLogFilter(logging.Filter):
    """Captures per-function compile records; swallows the log spam we
    induced by enabling jax_log_compiles (records pass through untouched
    when the user had the flag on themselves)."""

    def __init__(self, state):
        super().__init__()
        self._state = state

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — never break logging
            return True
        m = _COMPILING_RE.match(msg)
        if m and self._state.listener is not None:
            self._state.listener._on_compile_record(m.group(1))
        if self._state.we_enabled_flag and (m or _FINISHED_RE.match(msg)):
            return False
        return True


class _State:
    def __init__(self):
        self.listener: "RecompileListener | None" = None
        self.monitoring_registered = False
        self.filters: list = []
        self.we_enabled_flag = False
        self.lock = threading.Lock()


_STATE = _State()


def _monitoring_callback(name, secs, **_kw):
    listener = _STATE.listener
    if listener is not None:
        listener._on_duration(name, secs)


def install(registry=None) -> RecompileListener:
    """Install (or return the already-installed) process listener.

    Idempotent: repeated calls return the same listener (updating its
    registry only if one is passed). ``jax.monitoring`` has no
    single-listener unregister, so the monitoring hook is registered
    once per process and routed through the module state — after
    :func:`uninstall` it goes inert rather than away.
    """
    import jax

    with _STATE.lock:
        if _STATE.listener is not None:
            if registry is not None:
                _STATE.listener.registry = registry
            return _STATE.listener
        listener = RecompileListener(
            registry if registry is not None else get_registry())
        if not _STATE.monitoring_registered:
            jax.monitoring.register_event_duration_secs_listener(
                _monitoring_callback)
            _STATE.monitoring_registered = True
        _STATE.we_enabled_flag = not jax.config.jax_log_compiles
        if _STATE.we_enabled_flag:
            jax.config.update("jax_log_compiles", True)
        for lname in _JAX_LOG_COMPILE_LOGGERS:
            filt = _CompileLogFilter(_STATE)
            logging.getLogger(lname).addFilter(filt)
            _STATE.filters.append((lname, filt))
        _STATE.listener = listener
        return listener


def uninstall() -> None:
    """Detach the log filters, restore jax_log_compiles, and deactivate
    the monitoring hook. Counts on the returned-by-install listener stop
    growing but remain readable."""
    import jax

    with _STATE.lock:
        if _STATE.listener is None:
            return
        for lname, filt in _STATE.filters:
            logging.getLogger(lname).removeFilter(filt)
        _STATE.filters.clear()
        if _STATE.we_enabled_flag:
            jax.config.update("jax_log_compiles", False)
        _STATE.we_enabled_flag = False
        _STATE.listener = None


def current() -> "RecompileListener | None":
    return _STATE.listener


@contextlib.contextmanager
def retrace_guard(budget: int = 0, registry=None, fns=None):
    """Fail a region that retraces more than ``budget`` times.

    The runtime teeth behind the analysis subsystem's static
    "recompile hazard" lint: wrap a bench/training loop and any
    steady-state retrace beyond the budget raises
    :class:`RetraceBudgetExceeded` naming the offending functions.
    First-compiles are free — only compiles of a function already
    compiled once inside OR before the region count.

        with retrace_guard(budget=0):
            for batch in data:
                train_step(params, batch)   # must not retrace

    ``fns``: optional iterable of jitted-function names to watch; other
    names are ignored. Use it when the region also BUILDS inputs —
    jax's internal helper jits (``broadcast_in_dim``, ...) recompile per
    fresh shape and would otherwise spend the budget on noise.
    """
    listener = install(registry=registry)
    watch = None if fns is None else set(fns)
    before = listener.compiles()
    yield listener
    after = listener.compiles()
    retraced = {}
    for fn_name, n in after.items():
        if watch is not None and fn_name not in watch:
            continue
        prior = before.get(fn_name, 0)
        # compiles in-region beyond the function's first-ever compile
        in_region = n - prior
        free = 1 if prior == 0 else 0
        if in_region - free > 0:
            retraced[fn_name] = in_region - free
    total = sum(retraced.values())
    if registry is not None or listener.registry is not None:
        reg = registry if registry is not None else listener.registry
        reg.counter("jax/guarded_retraces").inc(total)
    if total > budget:
        raise RetraceBudgetExceeded(
            f"{total} retrace(s) exceed budget {budget}: " + ", ".join(
                f"{name} x{n}" for name, n in sorted(retraced.items())))
