"""Named trace scopes — one helper for both timelines.

``jax.profiler.TraceAnnotation`` marks the HOST timeline (visible while
the Python frame is open: dispatch, schedule phases, timer brackets).
``jax.named_scope`` attaches the name to the HLO metadata of every op
built inside it, so the DEVICE timeline of the next on-silicon capture
carries the same names — that is what finally lets ``trace_report.py``
attribute per-kernel time to "fused_adam/flat/pallas" vs
"fused_adam/flat/xla" instead of anonymous fusions (the per-kernel race
table the ISSUE wants).

:func:`scope` enters both. Inside traced code the annotation half only
brackets trace time (harmless); the named_scope half is the one that
survives into the compiled program. Without an active profiler both are
no-ops costing two context-manager enters.

jax is imported lazily so ``apex_tpu.observability`` stays importable
in backend-free processes (the bench launcher, the report CLI).
"""

from __future__ import annotations

import contextlib

__all__ = ["scope", "annotate"]

_jax = None


def _get_jax():
    global _jax
    if _jax is None:
        import jax
        _jax = jax
    return _jax


@contextlib.contextmanager
def scope(name: str):
    """Open a named region on both the host and device timelines."""
    jax = _get_jax()
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


def annotate(name: str):
    """Decorator form: every call to the wrapped fn runs under
    :func:`scope(name)` (default: the function's qualname)."""
    def deco(fn):
        import functools

        label = name or getattr(fn, "__qualname__", repr(fn))

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with scope(label):
                return fn(*args, **kwargs)
        return wrapped
    return deco
