"""Per-step phase attribution (ISSUE 7 tentpole piece 3).

A step-time number says *that* a step was slow; this module says
*where it went*. Two signal sources, correlated per training step:

- **host spans** from the always-on ring tracer
  (:mod:`~apex_tpu.observability.profiling.spans`): every hot-path
  ``span()`` — pipeline phases, TP/SP collectives, DDP buckets,
  fused-adam dispatch — classified into ``data`` / ``comms`` /
  ``compute``, with the unattributed remainder reported as ``host``
  (Python, dispatch, everything nobody instrumented). Fractions are
  of the step span's wall time and sum to ~1.0 by construction.
- **device categories** from an xplane capture
  (:mod:`~apex_tpu.observability.profiling.xplane`), when one exists:
  the real silicon-side compute/comms split plus the compute↔comms
  overlap efficiency.

:class:`StepPhases` wraps one training step (``with phases.step():``)
and yields a fields dict made to splat straight into
``StepReporter.step(..., **phases.last_fields())`` — so the per-step
record every bench/example already emits finally decomposes MFU.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

from apex_tpu.observability.profiling.spans import (
    Span,
    SpanTracer,
    get_tracer,
    span,
)

__all__ = [
    "HOST_PHASES", "classify_span", "compute_breakdown", "StepPhases",
    "device_phase_fields",
]

#: phases a host span can land in; ``host`` is the residual.
HOST_PHASES = ("data", "compute", "comms", "host")

# Ordered (phase, prefixes, tokens) rules — FIRST match wins, so
# pp/send_recv (comms) must be tested before the pp/ compute prefix.
_RULES = (
    ("data", ("data",), ("batch", "dataload")),
    ("comms", ("tp/", "sp/", "ddp/", "comms"),
     ("send_recv", "allreduce", "all_gather", "reduce_scatter",
      "scatter", "ppermute", "psum", "broadcast")),
    ("compute", ("pp/", "fused_adam/", "timer/", "compute", "fwd",
                 "bwd", "optimizer"),
     ("forward", "backward", "stage_compute", "grad_accum", "loss",
      "matmul", "attention")),
)


def classify_span(name: str) -> Optional[str]:
    """Host phase for a span name, or None (→ ``host`` residual)."""
    low = (name or "").lower()
    for phase, prefixes, tokens in _RULES:
        if low.startswith(prefixes):
            return phase
        if any(tok in low for tok in tokens):
            return phase
    return None


def _merged(intervals: List[tuple]) -> List[tuple]:
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [tuple(x) for x in out]


def _total(intervals: List[tuple]) -> int:
    return sum(e - s for s, e in _merged(intervals))


def _intersection(a: List[tuple], b: List[tuple]) -> int:
    a, b = _merged(a), _merged(b)
    i = j = overlap = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if s < e:
            overlap += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return overlap


def compute_breakdown(spans: List[Span], step: Span) -> dict:
    """Attribute one step span's wall time across host phases.

    On the step's own thread, every instant is attributed to the
    DEEPEST classified span covering it (a segment sweep — nesting
    never double-counts, at any depth); the residual is ``host``.
    Fractions sum to ~1.0. Classified spans on OTHER threads (async
    data loaders, checkpoint writers) enter the overlap computation
    only.

    ``overlap_efficiency``: intersection of comms-classified and
    compute-classified intervals (all threads, clipped to the step
    window) over the smaller side's total — 1.0 means the cheaper of
    the two was entirely hidden under the other, None when either side
    recorded nothing.
    """
    window = (step.start_ns, step.end_ns)
    dur = max(step.end_ns - step.start_ns, 1)
    inside: List[tuple] = []     # (start, end, phase, tid, depth)
    for s in spans:
        if s.seq == step.seq:
            continue
        lo = max(s.start_ns, window[0])
        hi = min(s.end_ns, window[1])
        if hi <= lo:
            continue
        phase = classify_span(s.name)
        if phase is not None:
            inside.append((lo, hi, phase, s.tid, s.depth))

    # on the step's thread, attribute each segment of the window to
    # the DEEPEST classified span covering it — a sweep over the span
    # boundaries. Per-span "self minus descendants" double-subtracts
    # once spans nest 3+ deep (a grandchild is inside its parent AND
    # its grandparent), which misreported 20% of a fully-instrumented
    # pp/forward_backward > pp/forward > pp/stage_compute step as host
    phase_ns = {ph: 0 for ph in HOST_PHASES}
    own = [iv for iv in inside if iv[3] == step.tid]
    points = sorted({p for lo, hi, _p, _t, _d in own for p in (lo, hi)})
    for p0, p1 in zip(points, points[1:]):
        if p1 <= p0:
            continue
        covering = [iv for iv in own if iv[0] <= p0 and iv[1] >= p1]
        if covering:
            deepest = max(covering, key=lambda iv: iv[4])
            phase_ns[deepest[2]] += p1 - p0

    attributed = sum(phase_ns[ph] for ph in ("data", "compute", "comms"))
    phase_ns["host"] = max(dur - attributed, 0)
    fractions = {ph: round(phase_ns[ph] / dur, 4) for ph in HOST_PHASES}

    comms_iv = [(lo, hi) for lo, hi, ph, _t, _d in inside
                if ph == "comms"]
    compute_iv = [(lo, hi) for lo, hi, ph, _t, _d in inside
                  if ph == "compute"]
    overlap = None
    smaller = min(_total(comms_iv), _total(compute_iv))
    if smaller > 0:
        overlap = round(_intersection(comms_iv, compute_iv) / smaller, 4)

    out = {"phases": fractions}
    if overlap is not None:
        out["overlap_efficiency"] = overlap
    return out


def device_phase_fields(attribution) -> dict:
    """Device-side fields from an
    :class:`~apex_tpu.observability.profiling.xplane.DeviceAttribution`
    — merged next to the host breakdown in a step record."""
    out = {"device_phases": attribution.fractions()}
    eff = attribution.overlap_efficiency()
    if eff is not None:
        out["device_overlap_efficiency"] = eff
    return out


class StepPhases:
    """Per-step phase tracker: ``with phases.step(): <train step>``
    brackets the step in a ``step`` span and computes the breakdown of
    everything the ring recorded inside it.

    ``last_fields()`` returns the splat-ready dict
    (``{"phases": {...}, "overlap_efficiency": ...}``) for
    ``StepReporter.step(step_time_s, **phases.last_fields())``.
    """

    def __init__(self, tracer: Optional[SpanTracer] = None,
                 name: str = "step"):
        self._tracer = tracer
        self.name = name
        self._last: Dict = {}

    @property
    def tracer(self) -> SpanTracer:
        return self._tracer if self._tracer is not None else get_tracer()

    @contextlib.contextmanager
    def step(self):
        tracer = self.tracer
        mark = tracer.mark()
        with span(self.name):
            yield
        done = tracer.completed(mark)
        step_span = next(
            (s for s in reversed(done) if s.name == self.name), None)
        if step_span is None:  # ring overflowed within one step
            self._last = {}
            return
        self._last = compute_breakdown(done, step_span)

    def last_fields(self) -> dict:
        """The most recent step's breakdown fields ({} before any
        step, or when the ring overflowed mid-step)."""
        return dict(self._last)
