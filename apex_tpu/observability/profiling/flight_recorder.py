"""Stall flight recorder (ISSUE 7 tentpole piece 4).

The PR 6 fused-adam inversion was only caught because a human watched
one live capture; a hung multi-host step today leaves *nothing*. The
flight recorder makes every run leave a post-mortem:

- a **watchdog thread** (the resilience ``PreemptionWatcher`` sensor
  pattern: install/uninstall, saved signal handlers, thread-safe flag,
  registry counters) polls the in-flight step. A step is *stalled*
  when it exceeds ``stall_factor ×`` the trailing-median step time
  (once ``min_history`` steps are recorded) or a hard ``deadline_s``
  wall limit — whichever is tighter;
- a **SIGQUIT handler** (the classic ``kill -QUIT`` / Go-runtime
  gesture) triggers the same dump on demand from an operator;
- the **dump artifact** is one timestamped JSON file: the span ring
  buffer (completed + per-thread *open* spans — where everyone is
  stuck), every thread's Python stack, the last N registry events, the
  resilience/observability counter snapshot, and the step-time history
  that defined "stalled".

Wire-up is one call: pass ``flight_recorder=recorder`` to
``ResilientTrainLoop`` (examples/llama_train.py does exactly this —
the loop drives the ``step_started``/``step_finished`` pair itself),
or wrap a bare step function with ``recorder.wrap_step(step_fn)``;
never both, or every step is bracketed and median-fed twice.
``recorder.sensor()`` plugs into a ``PreemptionWatcher`` so a
fleet can choose to treat a stalled step as a preemption (emergency
checkpoint + exit 75) after the dump lands.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import statistics
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Optional

from apex_tpu.observability.profiling.spans import SpanTracer, get_tracer

__all__ = ["FlightRecorder", "DEFAULT_STALL_FACTOR", "thread_stacks"]

DEFAULT_STALL_FACTOR = 3.0


def thread_stacks() -> dict:
    """Every thread's Python stack, keyed by thread id — the shared
    post-mortem ingredient of flight records and the memory tier's
    ``memrec_*.json`` OOM artifacts (ISSUE 15)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for tid, frame in sys._current_frames().items():
        stacks[str(tid)] = {
            "thread": names.get(tid, f"thread-{tid}"),
            "stack": [line.rstrip("\n") for line in
                      traceback.format_stack(frame)],
        }
    return stacks

# process-wide dump serial: two recorders (or two dumps of one) in the
# same second share a timestamp AND a pid — the serial is what keeps
# their artifact names distinct (ISSUE 12 satellite)
_DUMP_SEQ = itertools.count()


def _default_dir() -> str:
    return os.environ.get("APEX_TPU_FLIGHT_DIR", os.getcwd())


def _memory_section():
    """The memory tier's flight block, degraded to None on any
    failure (the import is lazy so a trimmed install without the
    memory package still dumps)."""
    try:
        from apex_tpu.observability.memory import hbm
        return hbm.flight_section()
    except Exception:  # noqa: BLE001 — diagnostics only
        return None


class FlightRecorder:
    """Watchdog + SIGQUIT handler + dump writer behind one object.

    Parameters
    ----------
    directory: where dump artifacts land (``APEX_TPU_FLIGHT_DIR`` env
        default, else cwd).
    stall_factor: a step slower than ``stall_factor × trailing
        median`` is stalled (needs ``min_history`` completed steps).
    min_history / history: how many completed step times arm / feed
        the trailing median.
    deadline_s: hard wall limit per step regardless of history (None
        disables; this is what catches a hang on step 0).
    poll_s: watchdog poll cadence.
    max_events: how many trailing registry events the dump carries.
    signals: signals that force a dump (default SIGQUIT); install only
        works on the main thread — elsewhere the watchdog still runs
        (the PreemptionWatcher degradation contract).
    """

    def __init__(self, *, directory: Optional[str] = None,
                 tracer: Optional[SpanTracer] = None, registry=None,
                 stall_factor: float = DEFAULT_STALL_FACTOR,
                 min_history: int = 5, history: int = 64,
                 deadline_s: Optional[float] = None, poll_s: float = 0.5,
                 max_events: int = 100, signals=None):
        if stall_factor <= 1.0:
            raise ValueError(
                f"stall_factor must be > 1 (got {stall_factor}): at "
                f"<= 1 every median step is a 'stall'")
        self.directory = directory or _default_dir()
        self._tracer = tracer
        self._registry = registry
        self.stall_factor = float(stall_factor)
        self.min_history = int(min_history)
        self.deadline_s = deadline_s
        self.poll_s = float(poll_s)
        self.max_events = int(max_events)
        if signals is None:
            # resolved here, not in the def default: SIGQUIT does not
            # exist on Windows and a default argument evaluates at
            # import time
            sigquit = getattr(signal, "SIGQUIT", None)
            signals = (sigquit,) if sigquit is not None else ()
        self.signals = tuple(signals)
        self._history: deque = deque(maxlen=int(history))
        self._lock = threading.Lock()
        self._step: Optional[int] = None       # in-flight step index
        self._step_started: Optional[float] = None
        self._dumped_step: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._installed: dict = {}
        self._stall_reason: Optional[str] = None
        # set by the signal handler, serviced by the watchdog thread:
        # dump() takes the recorder's and the registry's locks, and a
        # handler runs ON TOP of whatever main-thread frame holds them
        # — dumping inline would deadlock the process it post-mortems
        self._signal_pending = threading.Event()
        self._signal_name = ""
        self.dumps: list = []                  # paths written this run

    # ------------------------------------------------------- plumbing

    @property
    def tracer(self) -> SpanTracer:
        return self._tracer if self._tracer is not None else get_tracer()

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from apex_tpu.observability import get_registry
        return get_registry()

    # ------------------------------------------------------ step feed

    def step_started(self, step: int) -> None:
        with self._lock:
            self._step = int(step)
            self._step_started = time.monotonic()
            # a fresh attempt re-arms detection even for a replayed
            # index: _dumped_step dedups watchdog polls within one
            # attempt, it must not stop a rolled-back-and-replayed
            # step from ever dumping again
            self._dumped_step = None

    def step_finished(self, duration_s: Optional[float] = None,
                      record: bool = True) -> None:
        """Close the in-flight step. ``record=False`` clears the marker
        without feeding the trailing-median history — for attempts that
        RAISED: their near-zero duration is not a step time, and under
        a retry storm it would collapse the median until every healthy
        step read as a stall."""
        with self._lock:
            if duration_s is None and self._step_started is not None:
                duration_s = time.monotonic() - self._step_started
            if record and duration_s is not None:
                self._history.append(float(duration_s))
            self._step = None
            self._step_started = None

    def wrap_step(self, step_fn: Callable) -> Callable:
        """``step_fn(state, step) -> (state, metrics)`` instrumented
        with the started/finished pair — hand the result to
        ``ResilientTrainLoop``."""
        def recorded(state, step):
            self.step_started(step)
            try:
                out = step_fn(state, step)
            except BaseException:
                self.step_finished(record=False)
                raise
            self.step_finished()
            return out
        return recorded

    def threshold_s(self) -> Optional[float]:
        """Current stall threshold: min(stall_factor × trailing
        median, deadline_s) — None while both legs are unarmed."""
        with self._lock:
            hist = list(self._history)
        legs = []
        if len(hist) >= self.min_history:
            legs.append(self.stall_factor * statistics.median(hist))
        if self.deadline_s is not None:
            legs.append(float(self.deadline_s))
        return min(legs) if legs else None

    @property
    def stalled(self) -> bool:
        return self._stall_reason is not None

    def sensor(self) -> Callable[[], str]:
        """A ``PreemptionWatcher``-shaped sensor: truthy (the stall
        reason) once a stall dump fired — lets a deployment escalate a
        hung step into the emergency-checkpoint + exit-75 path."""
        def sense():
            return self._stall_reason or ""
        return sense

    # ------------------------------------------------------- watchdog

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self._signal_pending.is_set():
                self._signal_pending.clear()
                self.dump(reason=f"signal {self._signal_name}",
                          kind="signal")
            with self._lock:
                started = self._step_started
                step = self._step
            if started is None or step == self._dumped_step:
                continue
            limit = self.threshold_s()
            if limit is None:
                continue
            elapsed = time.monotonic() - started
            if elapsed > limit:
                reason = (f"step {step} stalled: {elapsed:.3f}s "
                          f"> threshold {limit:.3f}s")
                # same lock step_started() holds to clear _dumped_step:
                # an unlocked write here races the step thread re-arming
                # a replayed step. dump() stays OUTSIDE the lock — it
                # opens files and takes this lock again for its state
                # snapshot.
                with self._lock:
                    self._dumped_step = step
                    self._stall_reason = reason
                self.dump(reason=reason, kind="stall")

    def install(self) -> "FlightRecorder":
        """Start the watchdog thread and register the dump signals
        (main thread only — elsewhere the watchdog still arms)."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watch, name="apex-flight-recorder",
                daemon=True)
            self._thread.start()
        for sig in self.signals:
            if sig in self._installed:  # re-install would save our own
                continue                # handler as the "previous" one
            try:
                self._installed[sig] = signal.signal(sig, self._on_signal)
            except ValueError:  # not the main thread — watchdog only
                break
        return self

    def uninstall(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        while self._installed:
            sig, prev = self._installed.popitem()
            try:
                signal.signal(sig, prev)
            except ValueError:
                break

    def __enter__(self) -> "FlightRecorder":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def _on_signal(self, signum, frame) -> None:
        # async-signal-safe: only flag the request — the watchdog
        # thread does the actual dump (which takes locks the
        # interrupted frame may hold)
        self._signal_name = signal.Signals(signum).name
        self._signal_pending.set()

    # ----------------------------------------------------------- dump

    def _thread_stacks(self) -> dict:
        return thread_stacks()

    def dump(self, reason: str = "manual",
             kind: str = "manual") -> Optional[str]:
        """Write the post-mortem artifact; returns its path (None when
        even the write failed — the recorder must never take down the
        run it observes)."""
        from apex_tpu.observability.fleet import probe as fleet_probe
        from apex_tpu.observability.fleet.identity import (
            FleetIdentity,
            identity_fields,
            process_identity,
        )

        reg = self._reg()
        tracer = self.tracer
        with self._lock:
            step = self._step
            started = self._step_started
            hist = list(self._history)
        try:
            ident = process_identity()
        except ValueError:
            # a malformed identity env must not take down the dump —
            # the recorder's contract is that a post-mortem never
            # kills (or here: never silences) the run it observes
            ident = FleetIdentity(0, 1, None)
        payload = {
            "kind": "apex_tpu.flight_record",
            "schema_version": 1,
            **identity_fields(ident),
            "last_collective": fleet_probe.last_collective(),
            "last_collectives": {
                str(r): s
                for r, s in fleet_probe.last_collectives().items()},
            "reason": reason,
            "trigger": kind,
            "pid": os.getpid(),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "step": step,
            "step_elapsed_s": (None if started is None
                               else round(time.monotonic() - started, 3)),
            "step_history_s": [round(h, 4) for h in hist],
            "threshold_s": self.threshold_s(),
            "open_spans": {
                str(tid): [{"name": n, "age_s": round(age, 3)}
                           for n, age in frames]
                for tid, frames in tracer.open_spans().items()},
            "spans": [s.to_dict() for s in tracer.completed()],
            "thread_names": {str(k): v
                             for k, v in tracer.thread_names().items()},
            "thread_stacks": self._thread_stacks(),
            # ISSUE 15: a stall dump and an OOM memrec tell one
            # coherent story — current live bytes, watermark and the
            # top buffers ride every flight record (None when no
            # backend is up or the read fails; the section must never
            # take down the dump)
            "memory": _memory_section(),
            "events": (reg.events()[-self.max_events:]
                       if self.max_events > 0 else []),
            "counters": {
                m.name + (str(sorted(m.labels.items()))
                          if m.labels else ""): m.value
                for m in reg.metrics() if m.kind == "counter"},
        }
        # rank + pid + per-process serial keep concurrent dumps (two
        # ranks sharing a fleet dir, or two watchdogs firing in the
        # same second of one process) from ever clobbering each other
        fname = (f"flightrec_{time.strftime('%Y%m%d-%H%M%S')}_"
                 f"r{ident.process_index}_{os.getpid()}_"
                 f"{next(_DUMP_SEQ)}_{kind}.json")
        path = os.path.join(self.directory, fname)
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(path, "w") as f:
                json.dump(payload, f, indent=1, default=repr)
        except OSError as e:
            reg.counter("observability/flight_dump_failures").inc()
            reg.event("flight_dump_failed", reason=reason,
                      error=repr(e)[:200])
            return None
        reg.counter("observability/flight_dumps").inc()
        reg.event("flight_record", path=path, reason=reason, step=step)
        self.dumps.append(path)
        return path
