"""apex_tpu.observability.profiling — span tracing, per-step phase
attribution and the stall flight recorder (ISSUE 7).

The profiling tier the reference ships as ``apex.pyprof``, rebuilt on
PR 2's registry/scope plumbing:

- :mod:`~apex_tpu.observability.profiling.spans` — always-on
  ring-buffer span tracer; ``span()`` supersedes the bare ``scope()``
  on every hot path and exports Chrome/Perfetto trace-event JSON;
- :mod:`~apex_tpu.observability.profiling.xplane` — device-side
  per-phase attribution from a ``jax.profiler`` capture (the library
  form of ``tools/trace_report.py``);
- :mod:`~apex_tpu.observability.profiling.step_phases` — host↔device
  correlation per training step: the StepReporter phase breakdown
  (host/data/compute/comms + overlap efficiency);
- :mod:`~apex_tpu.observability.profiling.flight_recorder` — stall
  watchdog + SIGQUIT post-mortem dumps.

CLI: ``python -m apex_tpu.observability trace <run>`` exports either a
span dump or an xplane capture as Perfetto-loadable JSON.

``apex_tpu/pyprof`` remains as the legacy reference-named shim; its
parse/report internals are consumed here and new code should import
from this package.
"""

from apex_tpu.observability.profiling.flight_recorder import (  # noqa: F401
    FlightRecorder,
)
from apex_tpu.observability.profiling.spans import (  # noqa: F401
    Span,
    SpanTracer,
    get_tracer,
    decode_span_payload,
    load_spans,
    set_tracer,
    span,
    spans_from_dicts,
    to_trace_events,
    write_chrome_trace,
)
from apex_tpu.observability.profiling.step_phases import (  # noqa: F401
    StepPhases,
    classify_span,
    compute_breakdown,
    device_phase_fields,
)
from apex_tpu.observability.profiling.xplane import (  # noqa: F401
    PHASES,
    DeviceAttribution,
    attribute_capture,
    attribute_report,
    capture_trace_events,
    phase_of,
)

__all__ = [
    "Span", "SpanTracer", "span", "get_tracer", "set_tracer",
    "to_trace_events", "write_chrome_trace", "load_spans",
    "decode_span_payload", "spans_from_dicts",
    "StepPhases", "classify_span", "compute_breakdown",
    "device_phase_fields",
    "PHASES", "DeviceAttribution", "attribute_capture",
    "attribute_report", "capture_trace_events", "phase_of",
    "FlightRecorder",
]
