"""Device-side trace attribution as a library (ISSUE 7 tentpole
piece 2).

``tools/trace_report.py`` grew the xplane-parsing and per-op
attribution logic ad hoc; this module is its library home so the step-
phase correlator, the bench and the CLI all consume ONE implementation
(the tool is now a thin wrapper). Built on the existing parser/report
stack (:mod:`apex_tpu.pyprof.parse` / :mod:`apex_tpu.pyprof.prof` —
kept as the legacy-named shim), it adds the **coarse phase rollup**
the per-step breakdown needs:

========   =====================================================
phase      fine categories (pyprof.parse.CATEGORIES)
========   =====================================================
comms      collective, host-transfer
attention  attention-kernel
gather-    gather-scatter
scatter
data-      data-movement (async copies reported separately — they
movement   overlap compute by construction)
compute    matmul, convolution, custom-kernel, rng, reduction,
           fusion-elementwise, control remainder
========   =====================================================

``bytes_accessed`` is ``None`` (not 0.0) when the capture carried no
per-op bytes stat — a host-only CPU capture measures time, not HBM
traffic, and a zero there misled TRACE_REPORT_r05.json.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

__all__ = [
    "PHASES", "phase_of", "DeviceAttribution", "attribute_report",
    "attribute_capture", "capture_trace_events",
]

# coarse phase -> fine pyprof categories. "compute" is the catch-all:
# anything that is neither communication nor memory traffic is the
# device doing arithmetic (or scheduler remainder too small to split).
PHASES = ("compute", "comms", "data-movement", "attention",
          "gather-scatter")

_PHASE_OF_CATEGORY = {
    "collective": "comms",
    "host-transfer": "comms",
    "attention-kernel": "attention",
    "gather-scatter": "gather-scatter",
    "data-movement": "data-movement",
}


def phase_of(category: str) -> str:
    """Coarse phase for a fine pyprof category name."""
    return _PHASE_OF_CATEGORY.get(category, "compute")


@dataclasses.dataclass
class DeviceAttribution:
    """Per-phase device attribution for one capture.

    ``self_us`` sums exclusive op time per phase; ``share`` divides by
    the summed **measured** self time only (phases always sum to ~1.0);
    ``bytes_accessed``/``flops`` are ``None`` when the capture carried
    no such stats (host-only planes), never a fabricated 0.0.
    """

    phases: Dict[str, dict]
    total_self_us: float
    steps_us: List[float]
    async_copy_us: float = 0.0

    @property
    def step_wall_us(self) -> float:
        """Device wall time from the profiler's own 'Steps' markers
        (0.0 when the capture has none — e.g. CPU CI captures)."""
        return sum(self.steps_us)

    def fractions(self) -> Dict[str, float]:
        """{phase: share of measured self time}; sums to ~1.0 whenever
        any op time was measured."""
        return {ph: rec["share"] for ph, rec in self.phases.items()}

    def overlap_efficiency(self) -> Optional[float]:
        """compute↔comms overlap proxy from device totals: how much of
        the busy time the step wall absorbed. 1.0 = perfectly hidden
        (busy sums exceed wall by the whole smaller side), 0.0 = fully
        serialized. None without step markers (no wall reference)."""
        wall = self.step_wall_us
        if not wall:
            return None
        compute = sum(rec["self_us"] for ph, rec in self.phases.items()
                      if ph != "comms")
        comms = self.phases.get("comms", {}).get("self_us", 0.0)
        smaller = min(compute, comms)
        if smaller <= 0:
            return None  # nothing to overlap
        hidden = max(0.0, (compute + comms + self.async_copy_us) - wall)
        return round(min(1.0, hidden / smaller), 4)

    def to_dict(self) -> dict:
        out = {"phases": self.phases,
               "total_self_us": self.total_self_us,
               "async_copy_us": self.async_copy_us}
        if self.steps_us:
            out["steps"] = {"n": len(self.steps_us),
                            "mean_ms": sum(self.steps_us)
                            / len(self.steps_us) / 1e3}
        eff = self.overlap_efficiency()
        if eff is not None:
            out["overlap_efficiency"] = eff
        return out


def attribute_report(report) -> DeviceAttribution:
    """Roll a :class:`apex_tpu.pyprof.prof.Report` up into the coarse
    phase attribution."""
    phases: Dict[str, dict] = {
        ph: {"self_us": 0.0, "occurrences": 0, "flops": None,
             "bytes_accessed": None, "share": 0.0}
        for ph in PHASES}
    for name, cat in report.by_category().items():
        rec = phases[phase_of(name)]
        rec["self_us"] += cat["self_us"]
        rec["occurrences"] += int(cat["occurrences"])
        for field in ("flops", "bytes_accessed"):
            v = cat.get(field)
            if v is not None:
                rec[field] = (rec[field] or 0.0) + v
    total = sum(rec["self_us"] for rec in phases.values())
    for rec in phases.values():
        rec["self_us"] = round(rec["self_us"], 3)
        rec["share"] = round(rec["self_us"] / total, 4) if total else 0.0
    async_us = sum(o.total_us for o in getattr(report, "async_ops", []))
    return DeviceAttribution(phases=phases, total_self_us=round(total, 3),
                             steps_us=list(report.steps_us),
                             async_copy_us=round(async_us, 3))


def attribute_capture(path: str) -> DeviceAttribution:
    """Parse a ``jax.profiler`` dump (logdir / run dir / .xplane.pb)
    straight to the coarse phase attribution."""
    from apex_tpu.pyprof.prof import Report

    return attribute_report(Report.from_capture(path))


def capture_trace_events(path: str, pid: int = 0) -> List[dict]:
    """An xplane capture's device ops as Chrome trace-event dicts
    (``X`` complete events, one track per phase) — the device half of
    ``python -m apex_tpu.observability trace``. Event times are
    synthetic sequential offsets per phase track (the xplane record
    keeps durations, not a shared epoch), so the result shows *where
    the time went*, not the real interleaving — open the raw capture in
    xprof/TensorBoard for that."""
    from apex_tpu.pyprof.parse import find_xplane_paths, parse_xspace

    records = parse_xspace(find_xplane_paths(path))
    device = [r for r in records if r.plane.startswith("/device:")
              and r.line == "XLA Ops"]
    if not device:  # CPU captures: host threadpool HLO events
        device = records
    else:
        # async DMA copies live on their own xplane line; the
        # attribution path sums them into async_copy_us, so the export
        # must not silently drop them — they get their own track
        device = device + [
            r for r in records if r.plane.startswith("/device:")
            and r.line == "Async XLA Ops"]
    tracks: Dict[str, float] = {}
    track_names = PHASES + ("async-copy",)
    tid_of = {ph: i + 1 for i, ph in enumerate(track_names)}
    events: List[dict] = [
        {"name": "thread_name", "ph": "M", "pid": pid,
         "tid": tid_of[ph], "args": {"name": f"device/{ph}"}}
        for ph in track_names]
    for rec in device:
        ph = ("async-copy" if rec.line == "Async XLA Ops"
              else phase_of(rec.category))
        cursor = tracks.get(ph, 0.0)
        dur_us = rec.self_ps / 1e6
        events.append({"name": rec.name, "cat": rec.category,
                       "ph": "X", "ts": round(cursor, 3),
                       "dur": round(dur_us, 3),
                       "pid": pid, "tid": tid_of[ph]})
        tracks[ph] = cursor + dur_us
    return events
