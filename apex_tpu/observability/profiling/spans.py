"""Hierarchical host-side span tracer (ISSUE 7 tentpole piece 1).

One :func:`span` context manager does three things at once:

- records a (name, thread, start, end, depth) entry into a fixed-size
  **ring buffer** on the process tracer — always on, thread-safe, and
  allocation-free on the hot path (slots are preallocated lists mutated
  in place), so production steps can stay instrumented;
- keeps a per-thread stack of **open** spans, which is what the flight
  recorder snapshots when a step hangs (a completed-spans-only log says
  nothing about *where* a stuck step is stuck);
- enters the existing :func:`apex_tpu.observability.scope` pair
  (``TraceAnnotation`` for the live ``jax.profiler`` host timeline,
  ``named_scope`` for HLO metadata), so the one call site feeds the
  ring buffer, the xplane capture AND the compiled program's op names.

The ring exports as Chrome/Perfetto **trace-event JSON** (``B``/``E``
duration events plus ``M`` thread-name metadata) — load the file at
``ui.perfetto.dev`` or ``chrome://tracing``. ``python -m
apex_tpu.observability trace`` wraps the export for saved dumps and
xplane captures.

Clock: ``time.monotonic_ns`` (this module lives under observability/,
one of the sanctioned raw-clock owners). Span times are HOST times —
device work launched inside a span completes asynchronously; device
attribution comes from :mod:`~apex_tpu.observability.profiling.xplane`.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import List, Optional

__all__ = [
    "Span", "SpanTracer", "span", "get_tracer", "set_tracer",
    "to_trace_events", "write_chrome_trace", "load_spans",
    "spans_from_dicts",
]

# ring slot layout (a plain list, mutated in place — no per-span object
# allocation once the ring has wrapped)
_NAME, _TID, _START_NS, _END_NS, _DEPTH, _SEQ = range(6)

_DEFAULT_CAPACITY = 4096


class Span:
    """Read-only view of one completed span (built lazily by readers —
    the hot path never constructs these)."""

    __slots__ = ("name", "tid", "start_ns", "end_ns", "depth", "seq")

    def __init__(self, name, tid, start_ns, end_ns, depth, seq):
        self.name = name
        self.tid = tid
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.depth = depth
        self.seq = seq

    @property
    def duration_ns(self) -> int:
        return self.end_ns - self.start_ns

    def to_dict(self) -> dict:
        return {"name": self.name, "tid": self.tid,
                "start_ns": self.start_ns, "end_ns": self.end_ns,
                "depth": self.depth, "seq": self.seq}


class SpanTracer:
    """Fixed-capacity ring of completed spans + per-thread open stacks.

    ``capacity`` bounds memory forever: a week-long run keeps the last
    ``capacity`` spans, which is exactly what a post-mortem needs. The
    ring slots are preallocated lists; recording a span mutates one
    slot under a short lock — no allocation, no unbounded growth.

    Open-span stacks are kept in a shared ``{tid: stack}`` dict rather
    than ``threading.local`` so the flight recorder's watchdog THREAD
    can snapshot every other thread's in-flight spans mid-hang; each
    stack is only ever mutated by its owner thread.
    """

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._ring: List[list] = [
            [None, 0, 0, 0, 0, -1] for _ in range(capacity)]
        self._lock = threading.Lock()
        self._next = 0          # monotonically increasing write seq
        self._stacks: dict = {}  # tid -> [[name, start_ns], ...] (open)
        # every thread that ever recorded, for stable tid numbering
        self._tids: dict = {}

    # ------------------------------------------------------- hot path

    def begin(self, name: str) -> None:
        """Open a span on the calling thread. Prefer ``with span(...)``;
        the paired :meth:`end` MUST run (the ``unclosed-span`` lint
        polices call sites)."""
        tid = threading.get_ident()
        stack = self._stacks.get(tid)
        if stack is None:
            with self._lock:
                stack = self._stacks.setdefault(tid, [])
                self._tids.setdefault(
                    tid, threading.current_thread().name)
        stack.append([name, time.monotonic_ns()])

    def end(self) -> None:
        """Close the innermost open span on the calling thread and
        commit it to the ring."""
        tid = threading.get_ident()
        stack = self._stacks.get(tid)
        if not stack:
            return  # unbalanced end: drop rather than corrupt the ring
        name, start_ns = stack.pop()
        end_ns = time.monotonic_ns()
        depth = len(stack)
        with self._lock:
            seq = self._next
            self._next = seq + 1
            slot = self._ring[seq % self.capacity]
            slot[_NAME] = name
            slot[_TID] = tid
            slot[_START_NS] = start_ns
            slot[_END_NS] = end_ns
            slot[_DEPTH] = depth
            slot[_SEQ] = seq

    # -------------------------------------------------------- readers

    def mark(self) -> int:
        """Current write position — pass to :meth:`completed` to read
        only spans recorded after this point."""
        with self._lock:
            return self._next

    def completed(self, since: int = 0) -> List[Span]:
        """Completed spans with ``seq >= since`` still in the ring, in
        commit order. Spans older than the ring's capacity are gone —
        that is the ring's contract, not an error."""
        with self._lock:
            slots = [list(s) for s in self._ring if s[_SEQ] >= since]
        slots.sort(key=lambda s: s[_SEQ])
        return [Span(s[_NAME], s[_TID], s[_START_NS], s[_END_NS],
                     s[_DEPTH], s[_SEQ]) for s in slots]

    def dropped(self, since: int = 0) -> int:
        """How many spans recorded after ``since`` have already been
        overwritten (readers must know when the window overflowed)."""
        with self._lock:
            oldest = max(0, self._next - self.capacity)
        return max(0, oldest - since)

    def open_spans(self) -> dict:
        """{tid: [(name, age_s), ...]} of currently-open spans across
        ALL threads — innermost last. This is the flight recorder's
        'where is everyone stuck' snapshot; it is safe to call from any
        thread mid-hang (stacks are copied, owners keep mutating)."""
        now = time.monotonic_ns()
        with self._lock:
            stacks = {tid: list(stack)
                      for tid, stack in self._stacks.items()}
        out = {}
        for tid, stack in stacks.items():
            frames = [(frame[0], (now - frame[1]) / 1e9)
                      for frame in stack]
            if frames:
                out[tid] = frames
        return out

    def thread_names(self) -> dict:
        with self._lock:
            return dict(self._tids)

    def clear(self) -> None:
        with self._lock:
            for slot in self._ring:
                slot[_NAME] = None
                slot[_SEQ] = -1
            self._next = 0
            self._tids.clear()
            self._stacks.clear()

    # --------------------------------------------------------- export

    def to_trace_events(self, since: int = 0) -> List[dict]:
        """Chrome trace-event list (see :func:`to_trace_events`)."""
        return to_trace_events(self.completed(since),
                               thread_names=self.thread_names())

    def write_chrome_trace(self, path: str, since: int = 0) -> int:
        """Write the ring as a Perfetto-loadable trace; returns the
        number of spans exported."""
        spans = self.completed(since)
        write_chrome_trace(path, spans, thread_names=self.thread_names())
        return len(spans)

    @staticmethod
    def save_path(path: str) -> str:
        """Where :meth:`save` actually lands for ``path`` — the
        ``.rank{i}``-suffixed variant for fleet members, ``path``
        verbatim for solo processes (the
        :meth:`MetricRegistry.dump_path` analog)."""
        from apex_tpu.observability.fleet.identity import rank_path
        return rank_path(path)

    def save(self, path: str, since: int = 0) -> int:
        """Persist the raw ring as a span-dump JSON (re-exportable with
        ``python -m apex_tpu.observability trace``); returns the span
        count. Fleet members (ISSUE 12) write the ``.rank{i}``-suffixed
        variant of ``path`` (:meth:`save_path` resolves it) with the
        ``{process_index, process_count, run_id}`` stamp, so concurrent
        rank dumps never clobber and the fleet CLI can join them
        rank→pid."""
        from apex_tpu.observability.fleet.identity import (
            identity_fields,
            is_fleet_member,
            process_identity,
            rank_path,
        )

        spans = self.completed(since)
        payload = {
            "kind": "apex_tpu.spans",
            "schema_version": 1,
            "pid": os.getpid(),
            "thread_names": {str(k): v
                             for k, v in self.thread_names().items()},
            "dropped": self.dropped(since),
            "spans": [s.to_dict() for s in spans],
        }
        ident = process_identity()
        if is_fleet_member(ident):
            payload.update(identity_fields(ident))
        with open(rank_path(path, ident), "w") as f:
            json.dump(payload, f, indent=1)
        return len(spans)


def spans_from_dicts(dicts) -> List[Span]:
    """Decode :meth:`Span.to_dict` records (a span dump's or a flight
    record's ``spans`` list) back into :class:`Span` objects — the ONE
    deserializer for the serialized span schema."""
    return [Span(d["name"], d["tid"], d["start_ns"], d["end_ns"],
                 d.get("depth", 0), d.get("seq", i))
            for i, d in enumerate(dicts)
            if d.get("name") is not None]


def decode_span_payload(payload, where: str = "<payload>",
                        kinds=("apex_tpu.spans",)):
    """(spans, thread_names) from an already-parsed dump payload — the
    ONE schema gate + decoder behind :func:`load_spans` and the CLI's
    trace export (flight records embed the identical span layout under
    their own ``kind``, passed via ``kinds``)."""
    if not isinstance(payload, dict) or payload.get("kind") not in kinds:
        raise ValueError(f"{where}: not an apex_tpu span dump")
    version = payload.get("schema_version")
    if version != 1:
        raise ValueError(f"{where}: span-dump schema_version {version} "
                         f"is unknown to this reader (knows [1])")
    spans = spans_from_dicts(payload.get("spans", []))
    names = {int(k): v for k, v in
             (payload.get("thread_names") or {}).items()}
    return spans, names


def load_spans(path: str):
    """Read a :meth:`SpanTracer.save` dump back as
    (spans, thread_names); raises ValueError on any other JSON."""
    with open(path) as f:
        payload = json.load(f)
    return decode_span_payload(payload, where=path)


# ------------------------------------------------- trace-event export

def to_trace_events(spans, thread_names: Optional[dict] = None,
                    pid: Optional[int] = None) -> List[dict]:
    """Spans → Chrome trace-event dicts (``B``/``E`` pairs + thread-name
    metadata), ready for ``json.dump({"traceEvents": [...]})``.

    Ordering contract (validated by tests/run_observability):
    ``ts`` is non-decreasing across the whole list, and per (pid, tid)
    every ``B`` has a matching later ``E`` with correct nesting — even
    when a coarse monotonic clock collapses several begins/ends onto
    one timestamp (zero-duration spans included). tids are renumbered
    to small stable ints (sorted by first appearance) so repeated
    exports of the same dump are byte-identical.

    Per thread, the true begin/end sequence is RECONSTRUCTED from the
    ring's commit order: spans commit in post-order (``end()`` pops),
    and a span's descendants commit contiguously just before it at
    greater depths — so nesting never depends on timestamp tie-breaks,
    which cannot disambiguate events a coarse clock stamped alike."""
    pid = os.getpid() if pid is None else pid
    thread_names = thread_names or {}
    spans = sorted(spans, key=lambda s: s.seq)
    # stable small tids: order of first appearance in commit order
    tid_map: dict = {}
    per_tid: dict = {}
    for s in spans:
        if s.tid not in tid_map:
            tid_map[s.tid] = len(tid_map) + 1
        per_tid.setdefault(s.tid, []).append(s)

    def rebuild(tid_spans, tid):
        """Post-order + depth → the chronological event list."""
        pending = []  # chronological [(depth, [event, ...]), ...]
        for s in tid_spans:
            # this span's subtree roots: the trailing pending entries
            # at greater depth (they committed just before it)
            kids = []
            while pending and pending[-1][0] > s.depth:
                kids.append(pending.pop())
            kids.reverse()
            ev = [{"name": s.name, "ph": "B", "ts": s.start_ns / 1e3,
                   "pid": pid, "tid": tid}]
            for _d, sub in kids:
                ev.extend(sub)
            ev.append({"name": s.name, "ph": "E", "ts": s.end_ns / 1e3,
                       "pid": pid, "tid": tid})
            pending.append((s.depth, ev))
        # leftovers are chronological top-level siblings (orphans whose
        # parent never committed — ring wrap — stay top-level)
        return [e for _d, sub in pending for e in sub]

    events = []
    for real_tid, tid in sorted(tid_map.items(), key=lambda kv: kv[1]):
        events.extend(rebuild(per_tid[real_tid], tid))
    # global ts ordering across threads; sorted() is stable, so each
    # thread's reconstructed order (non-decreasing ts by construction)
    # survives ties
    events.sort(key=lambda ev: ev["ts"])
    out = []
    for real_tid, tid in sorted(tid_map.items(), key=lambda kv: kv[1]):
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid,
                    "args": {"name": thread_names.get(
                        real_tid, f"thread-{tid}")}})
    out.extend(events)
    return out


def write_chrome_trace(path: str, spans,
                       thread_names: Optional[dict] = None,
                       pid: Optional[int] = None) -> None:
    """Write spans as a Perfetto/chrome://tracing-loadable JSON file."""
    payload = {
        "traceEvents": to_trace_events(spans, thread_names, pid=pid),
        "displayTimeUnit": "ms",
    }
    with open(path, "w") as f:
        json.dump(payload, f)


# ---------------------------------------------------- process default

_TRACER = SpanTracer()
_TRACER_LOCK = threading.Lock()


def get_tracer() -> SpanTracer:
    """The always-on process tracer every :func:`span` records into."""
    return _TRACER


def set_tracer(tracer: SpanTracer) -> SpanTracer:
    """Swap the process tracer (tests, multi-run tools); returns the
    previous one."""
    global _TRACER
    with _TRACER_LOCK:
        prev, _TRACER = _TRACER, tracer
    return prev


@contextlib.contextmanager
def span(name: str):
    """Open a named region on every timeline at once: the span ring
    buffer (host post-mortem), the live profiler host timeline
    (``TraceAnnotation``) and the compiled program's HLO metadata
    (``named_scope``). The drop-in successor of
    :func:`apex_tpu.observability.scope` — same signature, same device
    semantics, plus the always-on host record."""
    from apex_tpu.observability.scope import scope as _scope

    tracer = get_tracer()
    tracer.begin(name)
    try:
        with _scope(name):
            yield
    finally:
        tracer.end()
