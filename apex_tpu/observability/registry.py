"""Thread-safe metric registry — the one sink every subsystem reports
through (ISSUE 2 tentpole piece 1).

Round 5's lesson is that perf claims die without a shared evidence
format: the MFU=330 instrument bug, the unmeasured Pallas-vs-XLA table,
and the ad-hoc JSON blobs in bench.py all trace back to each layer
inventing its own measurement plumbing. This module is the common spine:

- :class:`Counter` / :class:`Gauge` / :class:`Histogram` — the classic
  metric kinds, keyed by (name, labels).
- :class:`Timer` — a histogram of seconds whose ``stop(block_on=...)``
  goes through ``apex_tpu.runtime.timing`` (host-fetch sync, fetch-cost
  subtraction), never a bare ``block_until_ready``; while running it
  holds an ``observability.scope`` so the phase shows up named in a
  profiler trace.
- :class:`MetricRegistry` — the thread-safe container, with structured
  :meth:`~MetricRegistry.event` records, JSONL export
  (:meth:`~MetricRegistry.dump`) and the merge/summary reader
  (:func:`read_jsonl` / :func:`summarize`).

This module is jax-free at import time and never forces backend init;
device values enter only through ``Timer.stop(block_on=...)`` (lazy
import). Note the parent ``apex_tpu`` package's ``__init__`` does
import jax — a process that must stay wholly jax-free (the bench
launcher) writes the :func:`append_event` record shape inline instead
of importing anything from here.
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
from typing import Optional

__all__ = [
    "Counter", "Gauge", "Histogram", "Timer", "MetricRegistry",
    "get_registry", "set_registry", "read_jsonl", "summarize",
    "append_event",
]

# Bounded per-histogram sample reservoir for percentile estimates; the
# exact count/total/min/max are tracked separately and never truncated.
_MAX_SAMPLES = 512


class _Metric:
    """Shared identity/serialization for all metric kinds."""

    kind = "metric"

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()

    def _base_record(self) -> dict:
        rec = {"type": self.kind, "name": self.name}
        if self.labels:
            rec["labels"] = self.labels
        return rec


class Counter(_Metric):
    """Monotonic count (dispatches, retraces, overflows...)."""

    kind = "counter"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease "
                             f"(inc({n}))")
        with self._lock:
            self.value += n

    def to_record(self) -> dict:
        return {**self._base_record(), "value": self.value}


class Gauge(_Metric):
    """Last-written value (loss scale, device count, a config choice)."""

    kind = "gauge"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.value = None

    def set(self, value) -> None:
        with self._lock:
            self.value = value

    def to_record(self) -> dict:
        return {**self._base_record(), "value": self.value}


class Histogram(_Metric):
    """Streaming distribution: exact count/total/min/max plus a bounded
    reservoir for p50/p90/p99 estimates."""

    kind = "histogram"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._samples = collections.deque(maxlen=_MAX_SAMPLES)

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            self._samples.append(value)

    def _percentile(self, sorted_samples, q: float) -> float:
        idx = min(len(sorted_samples) - 1,
                  int(q * (len(sorted_samples) - 1) + 0.5))
        return sorted_samples[idx]

    def to_record(self) -> dict:
        with self._lock:
            rec = {**self._base_record(), "count": self.count,
                   "total": self.total, "min": self.min, "max": self.max,
                   "mean": (self.total / self.count) if self.count else None}
            if self._samples:
                s = sorted(self._samples)
                rec.update(p50=self._percentile(s, 0.50),
                           p90=self._percentile(s, 0.90),
                           p99=self._percentile(s, 0.99))
        return rec


class Timer(Histogram):
    """A histogram of seconds with start/stop + corrected device sync.

    ``stop(block_on=out)`` syncs via ``apex_tpu.runtime.timing.sync``
    (host fetch — ``block_until_ready`` is a no-op over the axon tunnel,
    the r5 MFU=330 bug) and subtracts the measured per-process fetch
    constant so the sync's own RTT never counts as phase time. A running
    timer holds a profiler/HLO scope named ``timer/<name>`` so phases
    also land named in traces.

    ``total`` accumulates elapsed seconds across start/stop pairs until
    :meth:`reset_total` — the accumulation contract the reference-shaped
    ``pipeline_parallel.Timers`` adapter needs — while every stop also
    feeds the histogram for JSONL export.
    """

    kind = "timer"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.total_elapsed = 0.0
        self._start: Optional[float] = None
        self._scope_cm = None

    @property
    def running(self) -> bool:
        return self._start is not None

    def start(self) -> None:
        if self._start is not None:
            raise RuntimeError(f"timer {self.name!r} is already running")
        from apex_tpu.observability.scope import scope
        # manual enter is the Timer's own CM protocol: stop()/cancel()
        # guarantee the paired __exit__ on every path
        self._scope_cm = scope(f"timer/{self.name}")  # apex-lint: disable=unclosed-span
        self._scope_cm.__enter__()
        self._start = time.perf_counter()

    def stop(self, block_on=None) -> float:
        """End the interval; returns the (corrected) elapsed seconds.

        ``block_on``: pytree of device values the timed region produced —
        synced so the interval covers device execution, with the fetch
        constant subtracted. Omit for host-only regions.
        """
        if self._start is None:
            raise RuntimeError(f"timer {self.name!r} is not running")
        start = self._start
        overhead = 0.0
        try:
            if block_on is not None:
                from apex_tpu.runtime import timing
                timing.sync(block_on)
                now = time.perf_counter()
                overhead = timing.cached_fetch_cost(block_on)
            else:
                now = time.perf_counter()
        finally:
            # the sync can surface a deferred XLA error — the timer must
            # not stay wedged "running" with its trace scopes open, or
            # the next start() masks the real failure
            self._start = None
            if self._scope_cm is not None:
                self._scope_cm.__exit__(None, None, None)
                self._scope_cm = None
        elapsed = max(now - start - overhead, 0.0)
        with self._lock:
            self.total_elapsed += elapsed
        self.observe(elapsed)
        return elapsed

    def cancel(self) -> None:
        """Abandon a running interval without recording it (closes the
        trace scope so profiler nesting stays balanced)."""
        self._start = None
        if self._scope_cm is not None:
            self._scope_cm.__exit__(None, None, None)
            self._scope_cm = None

    def reset_total(self) -> float:
        with self._lock:
            total, self.total_elapsed = self.total_elapsed, 0.0
        return total

    @contextlib.contextmanager
    def time(self, block_on_fn=None):
        """``with reg.timer("fwd").time(lambda: out):`` — times the body;
        ``block_on_fn`` (zero-arg) supplies the device output to sync on
        at exit (a callable because the output usually doesn't exist
        until the body ran)."""
        self.start()
        try:
            yield self
            out = block_on_fn() if block_on_fn is not None else None
        except BaseException:
            self.cancel()
            raise
        self.stop(out)

    def to_record(self) -> dict:
        rec = super().to_record()
        rec["total_elapsed"] = self.total_elapsed
        rec["unit"] = "s"
        return rec


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram,
          "timer": Timer}


class MetricRegistry:
    """Thread-safe container of metrics + structured events.

    Metric identity is (kind, name, labels): two calls with the same
    coordinates return the SAME object, so call sites never need to
    cache handles. Events are append-only ordered records
    (``seq`` stamps arrival order — wall timestamps are deliberately
    not recorded; runs through the axon tunnel have no trustworthy
    shared clock and record order is what the readers need).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}
        self._events: list = []

    # ------------------------------------------------------------ metrics

    def _get(self, kind: str, name: str, labels: dict):
        if not name:
            raise ValueError("metric name must be non-empty")
        key = (kind, name, tuple(sorted(labels.items())))
        with self._lock:
            metric = self._metrics.get(key)
            if metric is None:
                metric = _KINDS[kind](name, labels)
                self._metrics[key] = metric
            return metric

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get("histogram", name, labels)

    def timer(self, name: str, **labels) -> Timer:
        return self._get("timer", name, labels)

    def event(self, name: str, **fields) -> dict:
        """Append a structured event record; returns it."""
        if not name:
            raise ValueError("event name must be non-empty")
        with self._lock:
            rec = {"type": "event", "name": name, "seq": len(self._events)}
            if fields:
                rec["fields"] = _jsonable(fields)
            self._events.append(rec)
        return rec

    # ------------------------------------------------------------- export

    def metrics(self) -> list:
        with self._lock:
            return list(self._metrics.values())

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def to_records(self) -> list:
        """Every metric and event as one JSON-able dict each, metrics
        sorted by (type, name), events in arrival order."""
        recs = [m.to_record() for m in self.metrics()]
        recs.sort(key=lambda r: (r["type"], r["name"],
                                 sorted((r.get("labels") or {}).items())))
        return [_jsonable(r) for r in recs] + self.events()

    def dump(self, path: str, mode: str = "w") -> list:
        """Write one JSONL record per metric/event; returns the records.

        Fleet-aware (ISSUE 12): a fleet member (``APEX_TPU_PROCESS_*``
        identity set, or process_count > 1) writes to the ``.rank{i}``-
        suffixed variant of ``path`` — two ranks handed the same shared
        path can never interleave — and every record carries the
        ``{process_index, process_count, run_id}`` stamp
        ``merge_fleet`` groups by. Solo processes write ``path``
        verbatim with unstamped records, byte-identical to pre-fleet
        dumps. :meth:`dump_path` is the resolved destination.
        """
        stamp = _fleet_stamp()
        records = self.to_records()
        if stamp:
            records = [dict(rec, **stamp) for rec in records]
        with open(self.dump_path(path), mode) as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        return records

    @staticmethod
    def dump_path(path: str) -> str:
        """Where :meth:`dump` actually lands for ``path`` (the
        per-rank suffixed variant for fleet members)."""
        from apex_tpu.observability.fleet.identity import rank_path
        return rank_path(path)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._events.clear()


def _jsonable(value):
    """Best-effort conversion to JSON-encodable values: numpy / jax
    scalars become Python numbers, arrays become lists, everything else
    unknown becomes repr() — a metrics dump must never raise."""
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    item = getattr(value, "item", None)
    if callable(item) and getattr(value, "ndim", None) in (0, None):
        try:
            return item()
        except Exception:  # noqa: BLE001 — fall through to repr
            pass
    tolist = getattr(value, "tolist", None)
    if callable(tolist):
        try:
            return tolist()
        except Exception:  # noqa: BLE001
            pass
    return repr(value)


# --------------------------------------------------------- global default

_GLOBAL = MetricRegistry()
_GLOBAL_LOCK = threading.Lock()


def get_registry() -> MetricRegistry:
    """The process-wide default registry every instrumented subsystem
    reports to unless handed an explicit one."""
    return _GLOBAL


def set_registry(registry: MetricRegistry) -> MetricRegistry:
    """Swap the process default (tests, multi-run tools); returns the
    previous registry."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        prev, _GLOBAL = _GLOBAL, registry
    return prev


# ------------------------------------------------------------ file helpers

def append_event(path: str, name: str, **fields) -> dict:
    """Append one structured event record to a metrics JSONL file without
    a registry — for processes (like the bench launcher) that own no
    metrics but must contribute an event (e.g. ``tpu_init_error``).
    Fleet members append to the ``.rank{i}``-suffixed path with the
    identity stamp, like :meth:`MetricRegistry.dump`."""
    rec = {"type": "event", "name": name, "seq": -1, **_fleet_stamp()}
    if fields:
        rec["fields"] = _jsonable(fields)
    with open(MetricRegistry.dump_path(path), "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def _fleet_stamp() -> dict:
    """{process_index, process_count, run_id} for fleet members, {}
    for solo processes (legacy dumps stay byte-identical). Env-driven
    and jax-free — a metrics write must never force backend init."""
    from apex_tpu.observability.fleet.identity import (
        identity_fields,
        is_fleet_member,
        process_identity,
    )

    ident = process_identity()
    return identity_fields(ident) if is_fleet_member(ident) else {}


def read_jsonl(path: str) -> list:
    """Parse a metrics JSONL file; malformed lines are returned as
    ``{"type": "parse-error", ...}`` records rather than raised — a
    truncated dump from a killed worker must still mostly read."""
    records = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                records.append({"type": "parse-error", "line": i + 1,
                                "error": str(e)})
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                records.append({"type": "parse-error", "line": i + 1,
                                "error": "record is not an object"})
    return records


def summarize(records) -> dict:
    """Merge records (possibly from several dumps of the same run) into
    one summary dict:

    - counters with the same (name, labels) sum;
    - gauges keep the LAST value;
    - histograms/timers merge count/total/min/max exactly (percentiles
      are per-dump estimates and are kept only when a single record
      contributed — merging quantiles would fabricate precision);
    - events are listed in order; parse errors are counted.
    """
    counters: dict = {}
    gauges: dict = {}
    hists: dict = {}
    events = []
    parse_errors = 0

    def key(rec):
        return (rec.get("name", ""),
                tuple(sorted((rec.get("labels") or {}).items())))

    for rec in records:
        rtype = rec.get("type")
        if rtype == "counter":
            counters[key(rec)] = counters.get(key(rec), 0) + \
                (rec.get("value") or 0)
        elif rtype == "gauge":
            gauges[key(rec)] = rec.get("value")
        elif rtype in ("histogram", "timer"):
            k = (rtype,) + key(rec)
            cur = hists.get(k)
            if cur is None:
                hists[k] = {f: rec.get(f) for f in
                            ("count", "total", "min", "max",
                             "p50", "p90", "p99", "unit")}
                hists[k]["type"] = rtype
            else:
                cur["count"] = (cur.get("count") or 0) + \
                    (rec.get("count") or 0)
                cur["total"] = (cur.get("total") or 0.0) + \
                    (rec.get("total") or 0.0)
                for f, pick in (("min", min), ("max", max)):
                    vals = [v for v in (cur.get(f), rec.get(f))
                            if v is not None]
                    cur[f] = pick(vals) if vals else None
                for f in ("p50", "p90", "p99"):
                    cur[f] = None  # cannot merge quantile estimates
        elif rtype == "event":
            events.append(rec)
        elif rtype == "parse-error":
            parse_errors += 1

    def unkey(k):
        name, labels = k
        return name + ("" if not labels else
                       "{" + ",".join(f"{a}={b}" for a, b in labels) + "}")

    for h in hists.values():
        h["mean"] = (h["total"] / h["count"]) if h.get("count") else None
    return {
        "counters": {unkey(k): v for k, v in sorted(counters.items())},
        "gauges": {unkey(k): v for k, v in sorted(gauges.items())},
        "histograms": {t + ":" + unkey((n, l)): v
                       for (t, n, l), v in sorted(hists.items())},
        "events": events,
        "parse_errors": parse_errors,
    }
