"""Goodput accounting over a :class:`~.ledger.RunLedger` (ISSUE 17
tentpole, part b).

Classifies every attributable wall-clock second of a run into one
cause (:data:`CAUSES`) and reduces the result to the numbers ROADMAP's
elastic-training story needs: the goodput ratio (fraction of wall time
spent in first-completion training steps), lost-seconds-by-cause, the
badput top-3, and a per-rank skew-adjusted fleet goodput (the slowest
rank gates the fleet, so fleet goodput is the min over ranks).

Attribution policy, per rank in timeline order:

- ``step`` intervals: the first completion of a step index is
  ``productive_step``; any later completion of the same index is
  ``rollback_replay`` (work redone after a rollback/restart is badput
  by definition). When a rank has both loop ``step_done`` events and
  StepReporter ``step`` records, the loop durations win and the
  reporter records only contribute their ``phases`` fractions.
- outlier split: a step slower than ``stall_factor`` x the trailing
  median (the flight recorder's own stall definition) sheds its excess
  over the median — to ``compile`` if it is the first step of an
  attempt (warmup covers (re)tracing + dispatch), else to ``stall``.
  Flight-recorder stall markers in the ledger corroborate but are not
  required — the split is duration-driven, so ledgers from runs
  without a watchdog still account stalls.
- ``data_wait``: a step record carrying StepPhases fractions moves its
  ``phases["data"]`` share of the step to ``data_wait``.
- ``startup`` windows: restore/GC seconds stamped by the loop are
  subtracted (they are accounted under ``ckpt_restore`` and the
  attempt cause directly), the remainder is ``init`` for a cold
  attempt and ``restart`` for a resumed one.
- ``ckpt_save`` / ``ckpt_restore`` / ``preempt_drain`` intervals map
  1:1 from their ``duration_s`` stamps.
- wall minus everything attributed is ``unknown`` — callers that know
  the run's real wall (bench, the chaos tests) pass ``wall_s`` so idle
  gaps between attempts surface instead of vanishing.

Cause fractions always sum to 1.0 over the accounted wall by
construction.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .ledger import RunLedger

__all__ = [
    "ACCOUNTING_KIND", "ACCOUNTING_SCHEMA_VERSION", "CAUSES",
    "FAULT_CAUSES", "STALL_FACTOR", "MIN_STEP_HISTORY", "MIN_STALL_S",
    "account", "classify", "publish", "render", "to_trace_events",
]

ACCOUNTING_KIND = "apex_tpu.goodput_accounting"
ACCOUNTING_SCHEMA_VERSION = 1

#: every wall-clock second lands in exactly one of these.
CAUSES = (
    "productive_step", "init", "compile", "data_wait", "ckpt_save",
    "ckpt_restore", "stall", "preempt_drain", "restart",
    "rollback_replay", "unknown",
)

#: the causes only a fault (injected or real) can produce — an
#: uninterrupted run must report zero seconds in all of them.
FAULT_CAUSES = ("stall", "preempt_drain", "restart", "rollback_replay")

#: outlier threshold, deliberately identical to FlightRecorder's
#: stall_factor so the two tiers agree on what a stall is.
STALL_FACTOR = 3.0
MIN_STEP_HISTORY = 5
#: absolute floor on the excess an outlier step sheds: when steps run
#: in the sub-millisecond range (tiny CPU models), OS scheduler jitter
#: alone clears 3x the median — excess below this is noise, not a
#: stall, and charging it would break the FAULT_CAUSES == 0 invariant
#: for uninterrupted runs.
MIN_STALL_S = 0.05


def _r(x: float) -> float:
    return round(float(x), 6)


def classify(ledger: RunLedger, wall_s: Optional[float] = None,
             stall_factor: float = STALL_FACTOR,
             min_history: int = MIN_STEP_HISTORY
             ) -> Tuple[dict, List[dict]]:
    """(accounting, segments): the accounting summary plus the
    per-interval cause segments the Perfetto export renders."""
    per_rank = {}
    segments: List[dict] = []
    completed = replayed = 0
    for rank in ledger.ranks or [0]:
        causes, segs, stats = _classify_rank(
            ledger.rank_intervals(rank), stall_factor, min_history)
        attributed = sum(causes.values())
        wall = max(wall_s or 0.0, ledger.wall_hints.get(rank, 0.0),
                   attributed)
        unknown = max(0.0, wall - attributed)
        causes["unknown"] = unknown
        if unknown > 0:
            segs.append({"rank": rank, "cause": "unknown",
                         "seconds": unknown, "event": "unattributed"})
        productive = causes["productive_step"]
        ratio = productive / wall if wall > 0 else 0.0
        per_rank[str(rank)] = {
            "wall_s": _r(wall), "productive_s": _r(productive),
            "goodput_ratio": _r(ratio),
            "causes": {c: _r(causes[c]) for c in CAUSES},
        }
        segments.extend(segs)
        completed += stats["completed"]
        replayed += stats["replayed"]

    ranks = sorted(per_rank)
    walls = [per_rank[r]["wall_s"] for r in ranks]
    ratios = [per_rank[r]["goodput_ratio"] for r in ranks]
    total = {c: sum(per_rank[r]["causes"][c] for r in ranks)
             for c in CAUSES}
    wall_total = sum(walls)
    lost = {c: _r(total[c]) for c in CAUSES if c != "productive_step"}
    badput = sorted(((c, s) for c, s in lost.items() if s > 0),
                    key=lambda cs: (-cs[1], cs[0]))[:3]
    accounting = {
        "kind": ACCOUNTING_KIND,
        "schema_version": ACCOUNTING_SCHEMA_VERSION,
        "run_id": ledger.run_id,
        "ranks": [int(r) for r in ranks],
        "wall_s": _r(max(walls) if walls else 0.0),
        "productive_s": _r(total["productive_step"]),
        "goodput_ratio": _r(sum(ratios) / len(ratios) if ratios else 0.0),
        "fleet_goodput": _r(min(ratios) if ratios else 0.0),
        "lost_s": lost,
        "fractions": {c: _r(total[c] / wall_total) if wall_total > 0
                      else 0.0 for c in CAUSES},
        "badput_top": [{"cause": c, "seconds": _r(s)} for c, s in badput],
        "steps": {"completed": completed, "replayed": replayed},
        "per_rank": per_rank,
    }
    return accounting, segments


def account(ledger: RunLedger, wall_s: Optional[float] = None,
            stall_factor: float = STALL_FACTOR,
            min_history: int = MIN_STEP_HISTORY) -> dict:
    """The accounting summary alone (most callers)."""
    return classify(ledger, wall_s, stall_factor, min_history)[0]


def _classify_rank(intervals, stall_factor, min_history):
    causes = {c: 0.0 for c in CAUSES if c != "unknown"}
    segs: List[dict] = []

    def seg(iv, cause, seconds):
        causes[cause] += seconds
        entry = {"rank": iv["rank"], "ord": iv["ord"], "cause": cause,
                 "seconds": seconds}
        for key in ("step", "event"):
            if iv.get(key) is not None:
                entry[key] = iv[key]
        segs.append(entry)

    # a rank with loop step_done events uses those as the step source;
    # reporter records then only carry phases (avoids double counting).
    has_loop = any(iv["kind"] == "step" and iv.get("event") == "step_done"
                   for iv in intervals)
    phase_by_step = {}
    if has_loop:
        for iv in intervals:
            if (iv["kind"] == "step" and iv.get("source") == "reporter"
                    and isinstance(iv.get("phases"), dict)
                    and iv.get("step") is not None):
                phase_by_step[iv["step"]] = iv["phases"]

    # lookahead: a GC window belongs to the attempt it precedes.
    next_resumed = [None] * len(intervals)
    upcoming = None
    for i in range(len(intervals) - 1, -1, -1):
        next_resumed[i] = upcoming
        if intervals[i]["kind"] == "startup":
            upcoming = bool(intervals[i].get("resumed"))

    seen = set()
    pending_restore = pending_gc = 0.0
    attempt_first = False
    steps = []  # (interval, duration, replay, attempt_first)
    for i, iv in enumerate(intervals):
        kind = iv["kind"]
        dur = iv.get("duration_s") or 0.0
        if kind == "step":
            if has_loop and iv.get("source") == "reporter":
                continue
            idx = iv.get("step")
            replay = idx is not None and idx in seen
            if idx is not None:
                seen.add(idx)
            steps.append((iv, dur, replay, attempt_first))
            attempt_first = False
        elif kind == "startup":
            remainder = max(0.0, dur - pending_restore - pending_gc)
            pending_restore = pending_gc = 0.0
            seg(iv, "restart" if iv.get("resumed") else "init", remainder)
            attempt_first = True
        elif kind == "ckpt_restore":
            seg(iv, "ckpt_restore", dur)
            if not iv.get("rollback"):
                pending_restore += dur
        elif kind == "ckpt_gc":
            seg(iv, "restart" if next_resumed[i] else "init", dur)
            pending_gc += dur
        elif kind == "ckpt_save":
            seg(iv, "ckpt_save", dur)
        elif kind == "preempt_drain":
            seg(iv, "preempt_drain", dur)
        # stall/marker intervals carry no seconds of their own

    baseline = [d for _, d, _, first in steps if not first] or \
               [d for _, d, _, _ in steps]
    median = sorted(baseline)[len(baseline) // 2] if baseline else 0.0
    split = len(baseline) >= min_history and median > 0
    for iv, dur, replay, first in steps:
        excess = (dur - median if split and dur > stall_factor * median
                  else 0.0)
        if excess < MIN_STALL_S:
            excess = 0.0
        if excess > 0:
            seg(iv, "compile" if first else "stall", excess)
        remaining = dur - excess
        phases = iv.get("phases") or phase_by_step.get(iv.get("step"))
        frac = (phases or {}).get("data")
        if isinstance(frac, (int, float)) and 0 < frac <= 1:
            data_s = min(remaining, frac * dur)
            if data_s > 0:
                seg(iv, "data_wait", data_s)
                remaining -= data_s
        seg(iv, "rollback_replay" if replay else "productive_step",
            remaining)
    stats = {"completed": sum(1 for _, _, r, _ in steps if not r),
             "replayed": sum(1 for _, _, r, _ in steps if r)}
    return causes, segs, stats


# ------------------------------------------------------- publication

def publish(accounting: dict, registry) -> None:
    """Export the accounting as the ``goodput/*`` gauge family on a
    registry (bench calls this before its final dump so the family
    rides the metrics JSONL into ``tools/metrics_report.py``)."""
    registry.gauge("goodput/ratio").set(accounting["goodput_ratio"])
    registry.gauge("goodput/fleet_ratio").set(accounting["fleet_goodput"])
    registry.gauge("goodput/wall_s").set(accounting["wall_s"])
    registry.gauge("goodput/productive_s").set(accounting["productive_s"])
    for cause, seconds in sorted(accounting["lost_s"].items()):
        registry.gauge("goodput/lost_s", cause=cause).set(seconds)
    for place, entry in enumerate(accounting["badput_top"], start=1):
        registry.gauge("goodput/badput_rank",
                       cause=entry["cause"]).set(place)
    for rank, pr in sorted(accounting["per_rank"].items()):
        registry.gauge("goodput/rank_ratio",
                       rank=rank).set(pr["goodput_ratio"])
    registry.gauge("goodput/steps_replayed").set(
        accounting["steps"]["replayed"])


def render(accounting: dict) -> str:
    """The human accounting table the CLI prints."""
    lines = []
    run = accounting.get("run_id") or "-"
    lines.append(f"goodput — run {run}, "
                 f"ranks {accounting['ranks'] or [0]}")
    lines.append(f"  wall      {accounting['wall_s']:>12.3f} s")
    lines.append(f"  productive{accounting['productive_s']:>12.3f} s")
    lines.append(f"  goodput   {accounting['goodput_ratio']:>12.4f}"
                 f"   (fleet min {accounting['fleet_goodput']:.4f})")
    steps = accounting["steps"]
    lines.append(f"  steps     {steps['completed']:>8} completed"
                 f"  {steps['replayed']} replayed")
    lines.append("  cause breakdown:")
    fractions = accounting["fractions"]
    for cause in CAUSES:
        if cause == "productive_step":
            continue
        seconds = accounting["lost_s"].get(cause, 0.0)
        if seconds <= 0 and fractions.get(cause, 0.0) <= 0:
            continue
        lines.append(f"    {cause:<16}{seconds:>12.3f} s"
                     f"  {100 * fractions[cause]:>6.2f}%")
    if accounting["badput_top"]:
        top = ", ".join(f"{e['cause']} ({e['seconds']:.3f}s)"
                        for e in accounting["badput_top"])
        lines.append(f"  badput top: {top}")
    else:
        lines.append("  badput top: none — fully attributed to "
                     "productive work")
    return "\n".join(lines)


# ------------------------------------------------------ trace export

def to_trace_events(segments: List[dict]) -> List[dict]:
    """Cause segments -> Chrome trace events: one process per rank,
    one track (tid) per cause, intervals laid end-to-end per rank in
    timeline order (events carry no wall timestamps, so the layout is
    ordinal — durations are real, absolute positions are not)."""
    tids = {cause: i for i, cause in enumerate(CAUSES)}
    events: List[dict] = []
    ranks = sorted({seg["rank"] for seg in segments})
    for rank in ranks:
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "tid": 0, "args": {"name": f"rank {rank}"}})
        for cause, tid in tids.items():
            events.append({"ph": "M", "name": "thread_name", "pid": rank,
                           "tid": tid, "args": {"name": cause}})
        cursor = 0.0
        for seg in sorted((s for s in segments if s["rank"] == rank),
                          key=lambda s: s.get("ord", 1 << 30)):
            dur_us = max(0.0, seg["seconds"]) * 1e6
            args = {"cause": seg["cause"]}
            if seg.get("step") is not None:
                args["step"] = seg["step"]
            events.append({"ph": "X", "name": seg.get("event")
                           or seg["cause"], "pid": rank,
                           "tid": tids[seg["cause"]],
                           "ts": round(cursor, 3),
                           "dur": round(dur_us, 3), "cat": "goodput",
                           "args": args})
            cursor += dur_us
    events.sort(key=lambda e: (e.get("ts", -1.0), e["pid"], e["tid"]))
    return events
