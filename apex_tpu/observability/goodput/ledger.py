"""The unified run ledger (ISSUE 17 tentpole, part a).

A training run scatters its story across artifact families: metrics /
event JSONL (``BENCH_METRICS*.jsonl``, fleet ``.rank*`` shards), span
dumps, ``flightrec_*`` / ``memrec_*`` / ``fleetrec_*`` post-mortems and
the checkpoint directory's commit markers. None of them answers *where
did the wall-clock go* on its own: events deliberately carry no wall
timestamps (``seq`` arrival order only — there is no trustworthy shared
clock across hosts), so durations live in the ``duration_s`` /
``startup_s`` stamps the resilience loop writes, in Timer records and
in step reports.

:class:`RunLedger` ingests every family and normalizes it into ONE
ordered, rank-aware timeline of typed intervals::

    {"kind": "step", "rank": 0, "ord": 17, "step": 4,
     "duration_s": 0.0021, "source": "loop", ...}

Interval kinds (``INTERVAL_KINDS``) are the raw vocabulary;
:mod:`.accounting` folds them into wall-clock *causes*. The ledger
itself never interprets — it only orders and types, so the same ledger
can be re-accounted under a different policy.

Serialization is schema-versioned (``apex_tpu.run_ledger`` v1), loud on
drift (unknown kind/version raises, matching the span-dump reader) and
byte-stable: ``load(path).to_json() == open(path).read()`` for any
ledger this module wrote — the re-export test pins it.
"""

from __future__ import annotations

import glob
import json
import os
from typing import List, Optional

from ..fleet.merge import fleet_shards
from ..registry import read_jsonl

__all__ = [
    "LEDGER_KIND", "LEDGER_SCHEMA_VERSION", "INTERVAL_KINDS",
    "RunLedger", "ledger_from_records",
]

LEDGER_KIND = "apex_tpu.run_ledger"
LEDGER_SCHEMA_VERSION = 1

#: the typed-interval vocabulary. ``marker`` intervals have zero
#: duration — they anchor context (rollbacks, aborts, post-mortem
#: artifacts) on the timeline without claiming wall time.
INTERVAL_KINDS = (
    "step",           # one completed training step (step/step_done)
    "startup",        # attempt bring-up window (attempt_start)
    "ckpt_save",      # checkpoint_saved / checkpoint_failed
    "ckpt_restore",   # resumed / restore_failed
    "ckpt_gc",        # gc_partial_checkpoints
    "preempt_drain",  # preempt_exit (emergency save + drain)
    "stall",          # flight-recorder stall dump marker
    "marker",         # zero-duration context anchor
)

# event name -> ingestion rule. Names and required fields are pinned by
# events.GOODPUT_CRITICAL; the catalog test keeps emitters honest.
_EVENT_KINDS = {
    "step_done": "step",
    "attempt_start": "startup",
    "checkpoint_saved": "ckpt_save",
    "checkpoint_failed": "ckpt_save",
    "resumed": "ckpt_restore",
    "restore_failed": "ckpt_restore",
    "gc_partial_checkpoints": "ckpt_gc",
    "preempt_exit": "preempt_drain",
}
_MARKER_EVENTS = (
    "rollback", "train_aborted", "preemption", "chaos_probe",
    "flight_record", "emergency_save_failed", "emergency_flush_failed",
    "resilience_give_up", "bench_start",
)

# post-mortem record files the directory scan picks up, by filename
# prefix -> the payload kind the file must carry (schema gate).
_RECORD_FAMILIES = {
    "flightrec_": "apex_tpu.flight_record",
    "memrec_": "apex_tpu.memory_record",
    "fleetrec_": "apex_tpu.fleet_flight_record",
}


def _num(value, default=None):
    return float(value) if isinstance(value, (int, float)) else default


class RunLedger:
    """One ordered, rank-aware timeline for a whole run.

    Build empty, then ``ingest_*`` artifact families in any order;
    intervals keep a global ``ord`` so the merged timeline is
    deterministic regardless of ingestion interleaving (per-source
    records stay in their own arrival order).
    """

    def __init__(self, run_id: Optional[str] = None):
        self.run_id = run_id
        self.intervals: List[dict] = []
        self.sources: List[dict] = []
        self.checkpoint_steps: List[int] = []
        self.wall_hints: dict = {}   # rank -> seconds (span coverage)
        self._ord = 0

    # ------------------------------------------------------ ingestion

    def ingest_metrics(self, base: str) -> int:
        """Ingest a metrics JSONL family — ``base`` names any shard,
        the shared path, or a directory; ``.rank*`` siblings join via
        the fleet globber. Returns the number of intervals added."""
        shards = fleet_shards(base)
        if not shards and os.path.isfile(base):
            shards = [(None, base)]
        if not shards:
            raise FileNotFoundError(f"no metrics shards behind {base!r}")
        added = 0
        for rank, path in shards:
            added += self.ingest_records(read_jsonl(path), rank=rank,
                                         where=path)
        return added

    def ingest_records(self, records, rank=None, where="<records>") -> int:
        """Ingest already-parsed metrics records (one shard / registry
        dump). ``rank`` falls back to the fleet identity stamp the
        records carry, then 0."""
        stamped = next((r.get("process_index") for r in records
                        if isinstance(r, dict)
                        and r.get("process_index") is not None), None)
        if rank is None:
            rank = stamped if stamped is not None else 0
        if self.run_id is None:
            self.run_id = next((r.get("run_id") for r in records
                                if isinstance(r, dict) and r.get("run_id")),
                               None)
        added = errors = 0
        for rec in records:
            if not isinstance(rec, dict):
                continue
            rtype = rec.get("type")
            if rtype == "parse-error":
                errors += 1
                continue
            if rtype != "event":
                continue
            added += self._ingest_event(rec, rank)
        self.sources.append({"family": "metrics", "where": where,
                             "rank": rank, "records": len(records),
                             "parse_errors": errors})
        return added

    def _ingest_event(self, rec: dict, rank: int) -> int:
        name = rec.get("name")
        fields = rec.get("fields") or {}
        seq = rec.get("seq")
        kind = _EVENT_KINDS.get(name)
        if kind == "step":
            self._add(kind, rank, seq, event=name,
                      step=fields.get("step"),
                      duration_s=_num(fields.get("duration_s")),
                      phases=fields.get("phases"))
            return 1
        if kind == "startup":
            self._add(kind, rank, seq, event=name,
                      step=fields.get("start_step"),
                      duration_s=_num(fields.get("startup_s")),
                      resumed=bool(fields.get("resumed")))
            return 1
        if kind is not None:
            extra = {}
            if name in ("checkpoint_failed", "restore_failed"):
                extra["failed"] = True
            if name == "resumed" and fields.get("rollback"):
                extra["rollback"] = True
            self._add(kind, rank, seq, event=name,
                      step=fields.get("step"),
                      duration_s=_num(fields.get("duration_s")), **extra)
            return 1
        if name == "step":  # StepReporter record: step_time_ms, phases
            ms = _num(fields.get("step_time_ms"))
            self._add("step", rank, seq, event=name,
                      step=fields.get("step"),
                      duration_s=None if ms is None else ms / 1e3,
                      source="reporter", phases=fields.get("phases"))
            return 1
        if name in _MARKER_EVENTS:
            self._add("marker", rank, seq, event=name,
                      step=fields.get("step"), duration_s=0.0,
                      detail={k: v for k, v in fields.items()
                              if isinstance(v, (str, int, float, bool))})
            return 1
        return 0

    def ingest_span_dump(self, path: str) -> int:
        """Ingest a span dump (or flight record's embedded spans) for
        its wall-clock coverage hint — spans carry the only monotonic
        timestamps in the artifact set, so per-rank coverage bounds the
        accounting's ``unknown`` bucket when no wall is given."""
        from ..profiling.spans import decode_span_payload
        with open(path) as f:
            payload = json.load(f)
        spans, _ = decode_span_payload(
            payload, where=path,
            kinds=("apex_tpu.spans", "apex_tpu.flight_record"))
        rank = payload.get("process_index") or 0
        if spans:
            lo = min(s.start_ns for s in spans)
            hi = max(s.end_ns for s in spans)
            hint = max(0.0, (hi - lo) / 1e9)
            self.wall_hints[rank] = max(self.wall_hints.get(rank, 0.0),
                                        hint)
        self.sources.append({"family": "spans", "where": path,
                             "rank": rank, "records": len(spans),
                             "parse_errors": 0})
        return len(spans)

    def ingest_record_file(self, path: str) -> int:
        """Ingest one flightrec/memrec/fleetrec post-mortem JSON as a
        timeline marker (flight stall dumps become ``stall`` markers —
        corroboration for the accounting's outlier split). Loud on an
        unknown payload kind or schema version."""
        family = next((f for f in _RECORD_FAMILIES
                       if os.path.basename(path).startswith(f)), None)
        with open(path) as f:
            payload = json.load(f)
        kind = payload.get("kind") if isinstance(payload, dict) else None
        if family is not None and kind != _RECORD_FAMILIES[family]:
            raise ValueError(f"{path}: payload kind {kind!r} does not "
                             f"match family {_RECORD_FAMILIES[family]!r}")
        if kind not in _RECORD_FAMILIES.values():
            raise ValueError(f"{path}: unknown record kind {kind!r}")
        version = payload.get("schema_version")
        if version != 1:
            raise ValueError(f"{path}: record schema_version {version!r} "
                             "is unknown to this reader (knows [1])")
        rank = payload.get("process_index") or 0
        trigger = payload.get("trigger")
        ikind = ("stall" if kind == "apex_tpu.flight_record"
                 and trigger == "stall" else "marker")
        detail = {"record_kind": kind}
        for key in ("trigger", "step_elapsed_s", "threshold_s",
                    "verdict", "reason"):
            if isinstance(payload.get(key), (str, int, float, bool)):
                detail[key] = payload[key]
        self._add(ikind, rank, None, event=os.path.basename(path),
                  step=payload.get("step"), duration_s=0.0, detail=detail)
        if kind == "apex_tpu.flight_record" and payload.get("spans"):
            try:
                self.ingest_span_dump(path)
            except ValueError:
                pass
        self.sources.append({"family": "records", "where": path,
                             "rank": rank, "records": 1,
                             "parse_errors": 0})
        return 1

    def ingest_record_dir(self, directory: str) -> int:
        """Scan a directory for flightrec/memrec/fleetrec post-mortems
        and metrics-adjacent span dumps."""
        added = 0
        for prefix in _RECORD_FAMILIES:
            for path in sorted(glob.glob(
                    os.path.join(directory, prefix + "*.json"))):
                added += self.ingest_record_file(path)
        return added

    def ingest_checkpoints(self, directory: str) -> int:
        """Record the committed (valid) checkpoint steps — the
        manifest side of the restore story."""
        from ...checkpoint import valid_steps
        steps = valid_steps(directory)
        self.checkpoint_steps = sorted(set(self.checkpoint_steps)
                                       | set(steps))
        self.sources.append({"family": "checkpoints", "where": directory,
                             "rank": None, "records": len(steps),
                             "parse_errors": 0})
        return len(steps)

    def _add(self, kind, rank, seq, **extra):
        if kind not in INTERVAL_KINDS:
            raise ValueError(f"unknown interval kind {kind!r}")
        iv = {"kind": kind, "rank": int(rank or 0), "ord": self._ord,
              "seq": seq}
        iv.update({k: v for k, v in extra.items() if v is not None})
        self.intervals.append(iv)
        self._ord += 1

    # --------------------------------------------------------- access

    @property
    def ranks(self) -> List[int]:
        return sorted({iv["rank"] for iv in self.intervals})

    def rank_intervals(self, rank: int) -> List[dict]:
        return [iv for iv in self.intervals if iv["rank"] == rank]

    # -------------------------------------------------- serialization

    def to_payload(self) -> dict:
        return {
            "kind": LEDGER_KIND,
            "schema_version": LEDGER_SCHEMA_VERSION,
            "run_id": self.run_id,
            "ranks": self.ranks,
            "checkpoint_steps": self.checkpoint_steps,
            "wall_hints": {str(r): v for r, v in
                           sorted(self.wall_hints.items())},
            "sources": self.sources,
            "intervals": self.intervals,
        }

    def to_json(self) -> str:
        """Deterministic, byte-stable serialization: key-sorted,
        fixed separators, trailing newline."""
        return json.dumps(self.to_payload(), sort_keys=True,
                          separators=(",", ":")) + "\n"

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.to_json())
        return path

    @classmethod
    def from_payload(cls, payload, where: str = "<payload>") -> "RunLedger":
        if not isinstance(payload, dict) or payload.get("kind") != LEDGER_KIND:
            raise ValueError(f"{where}: not an {LEDGER_KIND} payload")
        version = payload.get("schema_version")
        if version != LEDGER_SCHEMA_VERSION:
            raise ValueError(
                f"{where}: run-ledger schema_version {version!r} is "
                f"unknown to this reader (knows [{LEDGER_SCHEMA_VERSION}])")
        ledger = cls(run_id=payload.get("run_id"))
        ledger.checkpoint_steps = list(payload.get("checkpoint_steps") or [])
        ledger.wall_hints = {int(k): float(v) for k, v in
                             (payload.get("wall_hints") or {}).items()}
        ledger.sources = list(payload.get("sources") or [])
        ledger.intervals = list(payload.get("intervals") or [])
        ledger._ord = 1 + max((iv.get("ord", -1) for iv in ledger.intervals),
                              default=-1)
        return ledger

    @classmethod
    def load(cls, path: str) -> "RunLedger":
        with open(path) as f:
            payload = json.load(f)
        return cls.from_payload(payload, where=path)


def ledger_from_records(records, rank=None, run_id=None) -> RunLedger:
    """One-shot: in-memory registry records -> ledger (the bench path:
    no dump round-trip needed to account the run just finished)."""
    ledger = RunLedger(run_id=run_id)
    ledger.ingest_records(records, rank=rank)
    return ledger
