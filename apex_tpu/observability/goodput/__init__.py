"""Goodput accounting + unified run ledger (ISSUE 17).

:mod:`.ledger` normalizes every artifact family a run produces into
one ordered, rank-aware timeline; :mod:`.accounting` classifies the
wall-clock into causes and reduces it to the goodput ratio and
lost-seconds-by-cause. ``python -m apex_tpu.observability goodput``
is the CLI face; ``bench.py`` publishes the ``goodput/*`` gauge family
on every run and ``tools/metrics_report.py --compare`` gates ratio
drops.
"""

from .ledger import (
    INTERVAL_KINDS,
    LEDGER_KIND,
    LEDGER_SCHEMA_VERSION,
    RunLedger,
    ledger_from_records,
)
from .accounting import (
    ACCOUNTING_KIND,
    ACCOUNTING_SCHEMA_VERSION,
    CAUSES,
    FAULT_CAUSES,
    MIN_STEP_HISTORY,
    STALL_FACTOR,
    account,
    classify,
    publish,
    render,
    to_trace_events,
)

__all__ = [
    "INTERVAL_KINDS", "LEDGER_KIND", "LEDGER_SCHEMA_VERSION",
    "RunLedger", "ledger_from_records",
    "ACCOUNTING_KIND", "ACCOUNTING_SCHEMA_VERSION", "CAUSES",
    "FAULT_CAUSES", "MIN_STEP_HISTORY", "STALL_FACTOR",
    "account", "classify", "publish", "render", "to_trace_events",
]
