"""StepReporter — one training step, one structured record
(ISSUE 2 tentpole piece 2).

The per-step evidence format every model-level bench and example emits:
step time, tokens/s, achieved-FLOPs and MFU estimate (the PaLM-appendix
accounting ``tools/trace_report.py`` / bench.py use), loss, loss-scale
value and cumulative overflow count pulled from ``amp/scaler.py`` state,
grad norm, plus free-form extras. Records land in the registry's event
stream (so one ``dump()`` carries metrics AND the step log) and in
registry metrics (``<name>/step_time_ms`` histogram, ``<name>/steps``
counter, ``<name>/loss`` gauge).

MFU sanity is enforced at the source: a computed MFU > 1 is physically
impossible and means the timing failed to sync the device (the r5
MFU=330 bug) — the record carries ``mfu_suspect`` so an impossible
number can never again pass silently as a result.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.observability.registry import MetricRegistry, get_registry

__all__ = [
    "PEAK_FLOPS_BY_KIND", "peak_flops", "transformer_step_flops",
    "StepReporter", "STEP_RECORD_FIELDS",
]

# bf16 peak FLOP/s per chip by device generation (public figures).
# Single source of truth — bench.py and the examples look these up here.
PEAK_FLOPS_BY_KIND = (
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops(device_kind: str) -> Optional[float]:
    """Peak bf16 FLOP/s for a ``jax.devices()[0].device_kind`` string
    (substring match), or None for unknown/CPU devices."""
    kind = (device_kind or "").lower()
    for key, peak in PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak
    return None


def transformer_step_flops(n_params: int, n_layers: int, hidden: int,
                           seq: int, batch: int) -> int:
    """fwd+bwd FLOPs of one decoder train step: ``B·S·(6N + 12·L·h·S)``
    (PaLM appendix accounting — 6N for the parameter matmuls fwd+bwd,
    the second term for attention score/value matmuls)."""
    return batch * seq * (6 * n_params + 12 * n_layers * hidden * seq)


# Fields every step record carries (None when the caller didn't supply
# the ingredient). tests/run_observability and the analysis
# step-record-schema target validate against this, so the schema cannot
# drift silently from its consumers. ``numerics`` is the ISSUE 9 block:
# the latest decimated stats-pass summary
# (``numerics.StatsCollector.last`` — finite flag, non-finite paths,
# top-k amax tensors, stats-pass cost). ``memory`` is the ISSUE 15
# block: the latest decimated live-HBM snapshot
# (``memory.MemoryMonitor.last`` — live bytes, watermark, top-k
# buffers, snapshot cost). ``process_index`` / ``process_count`` are
# the ISSUE 12 fleet stamp (0 / 1 for a solo process), so a merged
# fleet view can attribute every step record to its rank; ``run_id``
# rides as an extra field only when set.
STEP_RECORD_FIELDS = (
    "reporter", "step", "step_time_ms", "loss", "loss_scale",
    "overflow_count", "grad_norm", "tokens_per_sec", "tflops_per_sec",
    "mfu", "numerics", "memory", "process_index", "process_count",
)


def _host_float(value):
    """Pull a scalar (Python/numpy/jax) to a host float, or None."""
    if value is None:
        return None
    item = getattr(value, "item", None)
    if callable(item):
        try:
            return float(value.item())
        except Exception:  # noqa: BLE001 — non-scalar handed in
            return None
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


class StepReporter:
    """Turns one timed training step into a structured record.

    ``tokens_per_step`` and ``flops_per_step`` parameterize the
    throughput/MFU derivation (use :func:`transformer_step_flops`);
    ``peak`` overrides the device lookup (pass it off-TPU when reporting
    numbers measured elsewhere). All device-dependent lookups are lazy
    and guarded, so a reporter can be constructed before — or without —
    backend init.
    """

    def __init__(self, name: str, registry: Optional[MetricRegistry] = None,
                 tokens_per_step: Optional[float] = None,
                 flops_per_step: Optional[float] = None,
                 device_kind: Optional[str] = None,
                 peak: Optional[float] = None):
        self.name = name
        self.registry = registry if registry is not None else get_registry()
        self.tokens_per_step = tokens_per_step
        self.flops_per_step = flops_per_step
        if device_kind is None:
            try:
                import jax
                device_kind = jax.devices()[0].device_kind
            except Exception:  # noqa: BLE001 — backend-free process
                device_kind = None
        self.device_kind = device_kind
        self.peak = peak if peak is not None else (
            peak_flops(device_kind) if device_kind else None)
        self.records: list = []

    def step(self, step_time_s: float, *, loss=None, scaler_state=None,
             grad_norm=None, numerics=None, memory=None,
             **extra) -> dict:
        """Record one step; returns the record's ``fields`` dict.

        ``scaler_state``: an ``amp.scaler.LossScaleState`` (or anything
        with ``loss_scale``/``overflows`` attrs) — the loss-scale value
        and cumulative overflow count are host-read from it.
        ``numerics``: the latest stats-pass summary dict
        (``numerics.StatsCollector.last``) — attach it every step; the
        collector only refreshes it on its decimated cadence, so the
        record says which stats window it was inside.
        ``memory``: the latest live-HBM snapshot dict
        (``memory.MemoryMonitor.last``) — same decimated-cadence
        contract as ``numerics``.
        """
        from apex_tpu.observability.fleet.identity import (
            process_identity,
        )

        step_time_s = float(step_time_s)
        if step_time_s <= 0:
            raise ValueError(f"step_time_s must be positive, "
                             f"got {step_time_s}")
        ident = process_identity()
        fields = {
            "reporter": self.name,
            "step": len(self.records),
            "step_time_ms": round(step_time_s * 1e3, 3),
            "loss": _host_float(loss),
            "loss_scale": None,
            "overflow_count": None,
            "grad_norm": _host_float(grad_norm),
            "tokens_per_sec": None,
            "tflops_per_sec": None,
            "mfu": None,
            "numerics": dict(numerics) if numerics else None,
            "memory": dict(memory) if memory else None,
            "process_index": ident.process_index,
            "process_count": ident.process_count,
        }
        if ident.run_id:
            fields["run_id"] = ident.run_id
        if scaler_state is not None:
            fields["loss_scale"] = _host_float(
                getattr(scaler_state, "loss_scale", None))
            ovf = _host_float(getattr(scaler_state, "overflows", None))
            fields["overflow_count"] = None if ovf is None else int(ovf)
        if self.tokens_per_step:
            fields["tokens_per_sec"] = round(
                self.tokens_per_step / step_time_s, 1)
        if self.flops_per_step:
            achieved = self.flops_per_step / step_time_s
            fields["tflops_per_sec"] = round(achieved / 1e12, 2)
            if self.peak:
                mfu = achieved / self.peak
                fields["mfu"] = round(mfu, 4)
                if mfu > 1.0:
                    fields["mfu_suspect"] = (
                        "MFU>1 is impossible: timing failed to sync the "
                        "device")
        if self.device_kind:
            fields["device_kind"] = self.device_kind
        fields.update(extra)

        reg = self.registry
        reg.histogram(f"{self.name}/step_time_ms").observe(
            fields["step_time_ms"])
        reg.counter(f"{self.name}/steps").inc()
        if fields["loss"] is not None:
            reg.gauge(f"{self.name}/loss").set(fields["loss"])
        if fields["loss_scale"] is not None:
            reg.gauge(f"{self.name}/loss_scale").set(fields["loss_scale"])
        if fields["overflow_count"] is not None:
            reg.gauge(f"{self.name}/overflow_count").set(
                fields["overflow_count"])
        reg.event("step", **fields)

        self.records.append(fields)
        return fields

    def summary(self) -> dict:
        """Mean/min step time + last throughput fields over recorded
        steps — the shape bench.py folds into its extras dict."""
        if not self.records:
            return {}
        times = [r["step_time_ms"] for r in self.records]
        out = {"steps": len(self.records),
               "step_time_ms_mean": round(sum(times) / len(times), 3),
               "step_time_ms_min": round(min(times), 3)}
        last = self.records[-1]
        for f in ("tokens_per_sec", "tflops_per_sec", "mfu",
                  "device_kind"):
            if last.get(f) is not None:
                out[f] = last[f]
        return out
