"""The event-name catalog (ISSUE 17 satellite).

Every ``reg.event(name, ...)`` site in ``apex_tpu/`` (and bench.py /
the examples) must emit a name registered here — the run ledger
(:mod:`apex_tpu.observability.goodput`) parses the event stream by
name, and an unregistered rename would silently drop its intervals
from the goodput accounting. ``tests/run_observability/
test_event_catalog.py`` AST-scans the tree against this table, so a
new event site fails tier-1 until it is catalogued.

:data:`EVENT_CATALOG` maps each event name to the tuple of fields the
emitter guarantees on every record (a *minimum* — emitters may add
more). Only the goodput-critical events pin fields beyond the name;
for the rest an empty tuple just reserves the name.

:data:`GOODPUT_CRITICAL` is the subset the ledger's interval
reconstruction depends on: their required fields are load-bearing and
may only grow, never shrink or rename (the same backward-compatible
contract as ``step_report.STEP_RECORD_FIELDS``).
"""

from __future__ import annotations

__all__ = ["EVENT_CATALOG", "GOODPUT_CRITICAL", "DYNAMIC_EVENT_SITES"]

#: event name -> minimum guaranteed fields (empty = name-only
#: reservation). Sorted by subsystem for reviewability.
EVENT_CATALOG = {
    # observability core / step reporting
    "step": ("reporter", "step", "step_time_ms"),
    "tpu_init_error": (),
    # recompile accounting (bench.py retrace budget)
    "retrace_budget_exceeded": ("retraces", "budget"),
    # profiling / flight recorder
    "flight_record": ("path", "reason", "step"),
    "flight_dump_failed": ("reason", "error"),
    # numerics tier
    "numerics_stats": ("source",),
    "numerics_nonfinite": ("source", "step"),
    "numerics_grad_spike": ("source", "step"),
    "numerics_loss_spike": ("source", "step"),
    "numerics_loss_plateau": ("source", "step"),
    "numerics_overflow_streak": ("source", "step"),
    "numerics_provenance": ("step",),
    # amp
    "amp_overflow": (),
    # fleet tier
    "fleet/desync": (),
    "fleet/straggler": (),
    "fleet_desync_check_failed": ("step", "error"),
    # memory tier
    "memory_snapshot": ("source", "step"),
    "memory_dump": ("source",),
    "memory_calibration": ("target",),
    "memory_calibration_skipped": ("target",),
    "memory_record": ("path", "trigger", "step"),
    "memrec_dump_failed": ("error",),
    "memory_verdict": ("step",),
    # tuning
    "tuning_result": ("kernel", "bucket"),
    "kernel_dispatch": ("component", "choice"),
    # auto-shard planner
    "plan": ("model", "devices"),
    "plan_calibration": ("model",),
    # bench harness
    "bench_start": ("platform",),
    "fp8_race": (),
    # resilience: the goodput-critical set + the checkpoint ladder.
    # duration_s stamps (ISSUE 17) are seconds of host wall time spent
    # in the phase the event closes — the ledger's interval source.
    "preemption": ("reason",),
    "preempt_exit": ("step", "reason", "checkpoint", "duration_s"),
    "checkpoint_failed": ("step", "error", "duration_s"),
    "checkpoint_saved": ("step", "duration_s"),
    "emergency_flush_failed": ("step", "error"),
    "emergency_save_failed": ("step", "error", "duration_s"),
    "gc_partial_checkpoints": ("removed", "duration_s"),
    "restore_failed": ("step", "error", "duration_s"),
    "resumed": ("step", "duration_s"),
    "attempt_start": ("start_step", "num_steps", "resumed",
                      "startup_s"),
    "step_done": ("step", "duration_s"),
    "rollback": ("step", "attempt", "error"),
    "train_aborted": ("step", "rollbacks", "reason"),
    "resilience_give_up": ("scope", "attempts"),
    "chaos_probe": ("completed", "restarts", "steps", "plan"),
    # serving (ISSUE 20): the engine's drain record — queue + in-flight
    # counts at the moment the preemption contract fired
    "serving_drain": ("reason", "iteration", "inflight", "queued",
                      "dump_dir"),
}

#: the events whose required fields the run ledger's interval
#: reconstruction parses (ledger.py keys on exactly these names —
#: renaming one here without updating the ledger is a schema break,
#: which is the point of pinning them).
GOODPUT_CRITICAL = (
    "step", "step_done", "attempt_start", "resumed", "rollback",
    "preempt_exit", "train_aborted", "checkpoint_saved",
    "checkpoint_failed", "gc_partial_checkpoints", "restore_failed",
    "flight_record",
)

#: call sites whose event NAME is computed at runtime (the catalog
#: test cannot resolve a literal there). Each entry maps
#: "module.path:qualified_context" -> the names that site can emit —
#: all of which must still be catalogued above.
DYNAMIC_EVENT_SITES = {
    "apex_tpu/observability/numerics/health.py": (
        "numerics_nonfinite", "numerics_grad_spike",
        "numerics_loss_spike", "numerics_loss_plateau",
        "numerics_overflow_streak",
    ),
}
