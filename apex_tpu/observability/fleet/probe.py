"""Jit-safe per-step barrier-wait probe around the grad-sync call sites.

A straggling rank is invisible from inside its own process: every rank
just sees "the allreduce got slow". What *is* measurable per rank is
the pre-collective wait — the gap between this rank's gradients being
ready (it reaches the collective) and the collective completing (every
rank arrived). Fast ranks wait long; the straggler barely waits at
all. Comparing those waits across ranks names the slow rank
(:mod:`~apex_tpu.observability.fleet.straggler`).

The probe is a pair of hooks the grad-sync call sites
(``parallel/overlap.py``, ``parallel/zero.py``,
``parallel/distributed.py``) wrap around their collectives::

    flat = probe.collective_enter(flat, "ddp/overlap/bucket0", axis_name)
    red = jax.lax.psum(flat, axis_name)
    red = probe.collective_exit(red, "ddp/overlap/bucket0", axis_name)

Disabled (the default) both are identity functions resolved at trace
time — zero ops in the compiled program, so production steps pay
nothing. Enabled (:func:`enable` / ``APEX_TPU_FLEET_PROBE=1``), they
lower to host callbacks that are safe under ``jit`` + ``shard_map``:

- ``collective_enter`` issues an ``io_callback`` carrying
  ``lax.axis_index(axis_name)`` whose result token is tied to the
  collective's operand with ``lax.optimization_barrier`` — the
  callback fires when THIS rank's gradients are ready, before the
  collective can issue;
- ``collective_exit`` issues a ``jax.debug.callback`` fed a slice of
  the reduced result — it fires once the collective completed.

Per (site, rank) the host records ``wait = t_exit - t_enter`` into the
``fleet/grad_sync_wait_s{site=,rank=}`` timer, remembers the last
collective each rank entered (the fleet flight-record collector reads
it to say where a stuck rank is stuck), and feeds the wait into the
process-local :class:`~apex_tpu.observability.fleet.straggler.
StragglerDetector` so a persistent skew emits ``fleet/straggler``
events live. On a simulated mesh all ranks share one process and the
probe yields genuine per-rank waits; on a real fleet each process
records its own ranks and :func:`~apex_tpu.observability.fleet.merge.
merge_fleet` joins them.

Do NOT wrap collectives inside a ``custom_vjp`` backward (the
``overlapped_value_and_grad`` hooks): callbacks are not differentiable
and the bwd already runs under the forward's instrumented sites.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

__all__ = [
    "enable", "disable", "enabled", "collective_enter",
    "collective_exit", "last_collective", "last_collectives",
    "wait_times", "reset", "set_detector",
]

_LOCK = threading.Lock()
_ENABLED: Optional[bool] = None      # None = consult the env once
_ENTERS: dict = {}                   # (site, rank) -> perf_counter at enter
_LAST: dict = {}                     # rank -> site of last collective entered
_WAITS: dict = {}                    # (site, rank) -> last wait seconds
_DETECTOR = None                     # optional straggler.StragglerDetector
_STEPS: dict = {}                    # site -> completed detector rounds
_FRESH: dict = {}                    # site -> ranks with a wait since the
#                                      last detector round fed


def enabled() -> bool:
    """Is the probe armed? Explicit :func:`enable`/:func:`disable` wins;
    otherwise ``APEX_TPU_FLEET_PROBE=1`` arms it."""
    if _ENABLED is not None:
        return _ENABLED
    return os.environ.get("APEX_TPU_FLEET_PROBE", "") == "1"


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def reset() -> None:
    """Drop recorded waits/markers and return to env-driven arming
    (tests; a long-lived process between runs)."""
    global _ENABLED, _DETECTOR
    with _LOCK:
        _ENABLED = None
        _DETECTOR = None
        _ENTERS.clear()
        _LAST.clear()
        _WAITS.clear()
        _STEPS.clear()
        _FRESH.clear()


def set_detector(detector) -> None:
    """Feed every completed (site, per-rank wait) round into a
    :class:`~apex_tpu.observability.fleet.straggler.StragglerDetector`
    (mode ``"wait"``) so skew verdicts fire live in-process."""
    global _DETECTOR
    _DETECTOR = detector


def last_collective(rank: Optional[int] = None) -> Optional[str]:
    """Site of the last collective this process's rank(s) entered —
    the flight recorder dumps this so the fleet collector can say
    which collective a stuck rank died inside. Without ``rank``:
    the most recent across all local ranks."""
    with _LOCK:
        if rank is not None:
            return _LAST.get(int(rank))
        # _LAST is insertion-ordered; the most recent write is last
        return next(reversed(_LAST.values()), None) if _LAST else None


def last_collectives() -> dict:
    """{rank: site} of each local rank's last entered collective."""
    with _LOCK:
        return dict(_LAST)


def wait_times() -> dict:
    """{(site, rank): last wait seconds} — test/inspection hook."""
    with _LOCK:
        return dict(_WAITS)


def _reg():
    from apex_tpu.observability import get_registry
    return get_registry()


def _on_enter(site: str, rank) -> None:
    rank = int(rank)
    with _LOCK:
        _ENTERS[(site, rank)] = time.perf_counter()
        # pop first so insertion order tracks recency (last_collective
        # without a rank returns the most recent write)
        _LAST.pop(rank, None)
        _LAST[rank] = site


def _on_exit(site: str, rank) -> None:
    rank = int(rank)
    now = time.perf_counter()
    detector_round = None
    with _LOCK:
        start = _ENTERS.pop((site, rank), None)
        if start is None:
            return  # exit without enter: a retraced/partial program
        wait = now - start
        _WAITS[(site, rank)] = wait
        if _DETECTOR is not None:
            # a "round" completes when every rank seen so far for this
            # site has a FRESH wait since the last round — host
            # callbacks carry no cross-device ordering guarantee, so
            # completion is tracked per rank, never inferred from
            # which rank's callback happened to land last
            fresh = _FRESH.setdefault(site, set())
            fresh.add(rank)
            ranks = {r for s, r in _WAITS if s == site}
            if fresh >= ranks:
                step = _STEPS.get(site, 0)
                _STEPS[site] = step + 1
                # a {rank: wait} mapping, NOT a positional list: the
                # locally-hosted ranks need not be 0..n-1
                detector_round = (step, {
                    r: _WAITS[(site, r)] for r in sorted(ranks)})
                fresh.clear()
    reg = _reg()
    reg.timer("fleet/grad_sync_wait_s", site=site,
              rank=str(rank)).observe(wait)
    if detector_round is not None:
        step, waits = detector_round
        _DETECTOR.observe(step, waits, site=site)


def collective_enter(x, site: str, axis_name):
    """Mark "this rank's operand is ready, entering ``site``" — returns
    ``x`` (tied to the host callback so the collective cannot be
    scheduled before the mark). Identity when the probe is off."""
    if not enabled():
        return x
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import io_callback

    def mark(r):
        _on_enter(site, r)
        return np.int32(0)

    rank = jax.lax.axis_index(axis_name)
    token = io_callback(mark, jax.ShapeDtypeStruct((), jnp.int32),
                        rank, ordered=False)
    x, _ = jax.lax.optimization_barrier((x, token))
    return x


def collective_exit(x, site: str, axis_name):
    """Mark "``site`` completed on this rank" — fed a slice of the
    reduced result so the callback cannot fire before the collective
    finished. Returns ``x`` unchanged; identity when the probe is
    off."""
    if not enabled():
        return x
    import jax

    rank = jax.lax.axis_index(axis_name)
    probe_slice = x.ravel()[0] if getattr(x, "ndim", 0) else x
    jax.debug.callback(lambda r, _v: _on_exit(site, r), rank, probe_slice)
    return x
