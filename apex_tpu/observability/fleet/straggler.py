"""Trailing-median cross-rank skew detector → ``fleet/straggler``.

One detector, two orientations of the same verdict:

- ``mode="wait"`` (the live grad-sync probe feed): each observation is
  the per-rank **pre-collective wait**. The straggler is the rank with
  the *smallest* trailing-median wait while the rest of the fleet
  waits long — everyone queues at the collective until the slow rank
  arrives, so the slow rank itself is the one that never waits.
- ``mode="step_time"`` (the merge-time feed over per-rank step-time
  shards): each observation is the per-rank **step duration**; the
  straggler is simply the rank with the *largest* trailing median.

Detection is trailing-median based so one noisy step never fires: per
rank a bounded deque of the last ``history`` observations; once every
rank has ``min_history`` samples, the fleet median (median of per-rank
medians) anchors the skew test. A rank is a straggler when the skew —
``spread / fleet_median`` with spread = |outlier median − fleet
median| — exceeds ``threshold``. Verdicts are edge-triggered per rank
(an event on the transition into straggling, a counter bump per
detection, re-armed when the rank recovers), emitted as
``fleet/straggler`` events naming the slow rank plus the
``fleet/stragglers{rank=}`` counter family.
"""

from __future__ import annotations

import collections
import statistics
from typing import Optional

__all__ = ["StragglerDetector", "DEFAULT_SKEW_THRESHOLDS"]

# Relative-skew trigger per mode. Wait skew is bounded by 1.0 (a wait
# cannot go below zero, so the outlier can sit at most one full fleet
# median below it) — 0.5 means "the straggler waits less than half of
# what the fleet does". Step-time skew is unbounded above; 1.0 means
# "one rank's steps take twice the fleet median".
DEFAULT_SKEW_THRESHOLDS = {"wait": 0.5, "step_time": 1.0}

_MODES = ("wait", "step_time")


class StragglerDetector:
    """Feed per-rank series, get ``fleet/straggler`` verdicts.

    Parameters
    ----------
    mode: ``"wait"`` (straggler = min wait) or ``"step_time"``
        (straggler = max duration).
    threshold: relative skew (spread over fleet median) that fires.
    min_history / history: samples per rank to arm / window size.
    registry: metric sink (default: the process registry).
    """

    def __init__(self, mode: str = "wait",
                 threshold: Optional[float] = None,
                 min_history: int = 5, history: int = 64,
                 registry=None):
        if mode not in _MODES:
            raise ValueError(f"unknown straggler mode {mode!r}; "
                             f"valid: {list(_MODES)}")
        if threshold is None:
            threshold = DEFAULT_SKEW_THRESHOLDS[mode]
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        self.mode = mode
        self.threshold = float(threshold)
        self.min_history = int(min_history)
        self.history = int(history)
        self._series: dict = {}   # rank -> deque of observations
        self._flagged: dict = {}  # rank -> True while straggling
        self._registry = registry
        self.verdicts: list = []  # every verdict dict emitted

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from apex_tpu.observability import get_registry
        return get_registry()

    # ---------------------------------------------------------- feed

    def observe(self, step: int, per_rank,
                site: str = "step") -> Optional[dict]:
        """Record one round of per-rank observations — either a
        ``{rank: value}`` mapping (the probe's form: the locally
        hosted ranks need not be ``0..n-1``) or a sequence indexed by
        rank. Returns the verdict dict when a NEW straggler was named
        this round, else None."""
        items = (per_rank.items() if isinstance(per_rank, dict)
                 else enumerate(per_rank))
        for rank, value in items:
            self._series.setdefault(
                int(rank),
                collections.deque(maxlen=self.history)).append(
                float(value))
        return self._detect(step, site)

    def medians(self) -> dict:
        """{rank: trailing median} over the armed ranks."""
        return {rank: statistics.median(series)
                for rank, series in sorted(self._series.items())
                if len(series) >= self.min_history}

    # --------------------------------------------------------- verdict

    def _detect(self, step: int, site: str) -> Optional[dict]:
        meds = self.medians()
        if len(meds) < 2 or len(meds) < len(self._series):
            return None  # not every rank armed yet
        fleet_median = statistics.median(meds.values())
        pick = min if self.mode == "wait" else max
        rank = pick(meds, key=lambda r: meds[r])
        spread = abs(meds[rank] - fleet_median)
        skew = spread / max(fleet_median, 1e-12)
        reg = self._reg()
        reg.gauge("fleet/skew", site=site).set(round(skew, 4))
        if skew <= self.threshold:
            # recovery re-arms the edge trigger for every rank
            self._flagged.clear()
            return None
        reg.counter("fleet/stragglers", rank=str(rank)).inc()
        verdict = {
            "step": int(step), "rank": int(rank), "site": site,
            "mode": self.mode, "skew": round(skew, 4),
            "rank_median_s": meds[rank], "fleet_median_s": fleet_median,
            "rank_medians": {str(r): round(m, 6)
                             for r, m in meds.items()},
        }
        newly = not self._flagged.get(rank)
        self._flagged = {rank: True}
        if newly:
            reg.event("fleet/straggler", **verdict)
            self.verdicts.append(verdict)
            return verdict
        return None
