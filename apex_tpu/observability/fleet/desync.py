"""Cross-rank desync detection — cheap on-device fingerprints.

Data-parallel replicas must stay bit-identical: params (and the grads
feeding them after the allreduce) are the same tensors on every rank.
When they silently diverge — a non-deterministic reduction, a
corrupted host transfer, one rank reading different data — the run
keeps "training" while each rank optimizes a different model, and
nothing surfaces until the loss curve is garbage. The fleet tier makes
divergence a step-attributed event:

- :func:`fingerprint` — jit-safe, on-device: one f32 checksum pair
  ``(sum, abs-sum)`` per leaf of the tree, stacked into a tiny
  ``(2·L,)`` vector (L = leaf count; two channels so a sign-symmetric
  perturbation cannot cancel out of the sum alone).
- :func:`fingerprint_delta` — the cheapest cross-rank flag, the
  ISSUE 12 ``psum``-vs-``pmax`` compare: for replica-identical values
  ``pmax(fp) == psum(fp)/n`` exactly; the returned scalar
  ``max |pmax − pmean|`` is 0.0 on a healthy step and nonzero the
  first step any rank diverges. One scalar, no gather.
- :func:`fingerprint_gather` — the attributing form:
  ``all_gather`` of the per-leaf fingerprints → ``(n, 2·L)``; the
  host-side :class:`DesyncDetector` names the offending rank (row
  furthest from the per-column median) and the first divergent
  tensor path (column → leaf).

Wire-up: compute ``fingerprint_gather`` inside the shard_mapped step
and return it in the step's metrics under ``"fleet_fingerprint"`` —
:class:`~apex_tpu.resilience.loop.ResilientTrainLoop` hands it to its
``desync_detector`` after every healthy step; a verdict trips the
PR 5 rollback ladder with the fleet verdict attached to the
``rollback`` events and the :class:`~apex_tpu.resilience.loop.
TrainAborted` report (``report["fleet"]``).
"""

from __future__ import annotations

from typing import Optional, Sequence

__all__ = [
    "leaf_paths", "fingerprint", "fingerprint_delta",
    "fingerprint_gather", "DesyncDetector",
]


def leaf_paths(tree) -> list:
    """Stable per-leaf path strings for ``tree`` (the names a desync
    verdict reports), in ``tree_flatten`` leaf order."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path) for path, _ in flat]


def fingerprint(tree):
    """Per-leaf ``(sum, abs-sum)`` checksums as one f32 ``(2·L,)``
    vector — jit-safe, fully on-device, O(elements) reads and O(L)
    output."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        raise ValueError("cannot fingerprint an empty tree")
    parts = []
    for leaf in leaves:
        x = jnp.asarray(leaf).astype(jnp.float32)
        parts.append(jnp.stack([jnp.sum(x), jnp.sum(jnp.abs(x))]))
    return jnp.concatenate(parts)


def fingerprint_delta(tree, axis_name: str):
    """Scalar cross-rank divergence flag (call inside ``shard_map``):
    ``max |pmax(fp) − pmean(fp)|`` over the fingerprint vector —
    exactly 0.0 while every rank holds identical values."""
    import jax
    import jax.numpy as jnp

    fp = fingerprint(tree)
    mean = jax.lax.pmean(fp, axis_name)
    high = jax.lax.pmax(fp, axis_name)
    return jnp.max(jnp.abs(high - mean))


def fingerprint_gather(tree, axis_name: str):
    """``(n, 2·L)`` matrix of every rank's fingerprint (call inside
    ``shard_map``) — the attributing form the
    :class:`DesyncDetector` consumes."""
    import jax

    return jax.lax.all_gather(fingerprint(tree), axis_name)


class DesyncDetector:
    """Host-side verdict over gathered fingerprints.

    ``paths``: the tree's leaf path strings (:func:`leaf_paths`) so a
    divergent column maps back to a tensor name. ``atol`` bounds the
    permitted cross-rank spread — 0.0 (default) demands bit-identical
    replicas, the DDP contract.
    """

    def __init__(self, paths: Sequence[str], atol: float = 0.0,
                 registry=None):
        self.paths = list(paths)
        self.atol = float(atol)
        self._registry = registry
        self.verdicts: list = []
        #: first step a verdict fired at (None while healthy)
        self.first_divergent_step: Optional[int] = None

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from apex_tpu.observability import get_registry
        return get_registry()

    def check(self, step: int, gathered) -> Optional[dict]:
        """Compare one step's ``(n, 2·L)`` fingerprint matrix; returns
        the verdict dict (also emitted as a ``fleet/desync`` event +
        ``fleet/desyncs`` counter) or None when the replicas agree."""
        import numpy as np

        mat = np.asarray(gathered, dtype=np.float64)
        if mat.ndim != 2 or mat.shape[1] != 2 * len(self.paths):
            raise ValueError(
                f"fingerprint matrix has shape {mat.shape}; expected "
                f"(ranks, {2 * len(self.paths)}) for {len(self.paths)} "
                f"leaves — detector and step tree diverged")
        med = np.median(mat, axis=0)
        dev = np.abs(mat - med)
        max_dev = float(dev.max())
        if max_dev <= self.atol:
            return None
        rank_dev = dev.max(axis=1)
        rank = int(rank_dev.argmax())
        col = int(dev[rank].argmax())
        leaf = col // 2
        verdict = {
            "step": int(step),
            "rank": rank,
            "tensor_path": self.paths[leaf],
            "channel": "sum" if col % 2 == 0 else "abs_sum",
            "max_delta": max_dev,
            "ranks": int(mat.shape[0]),
            "divergent_ranks": sorted(
                int(r) for r in np.nonzero(rank_dev > self.atol)[0]),
        }
        if self.first_divergent_step is None:
            self.first_divergent_step = int(step)
        verdict["first_divergent_step"] = self.first_divergent_step
        reg = self._reg()
        reg.counter("fleet/desyncs").inc()
        reg.event("fleet/desync", **verdict)
        self.verdicts.append(verdict)
        return verdict

    @classmethod
    def for_tree(cls, tree, atol: float = 0.0, registry=None):
        """Build a detector matching ``tree``'s leaf layout."""
        return cls(leaf_paths(tree), atol=atol, registry=registry)
