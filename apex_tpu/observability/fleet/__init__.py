"""apex_tpu.observability.fleet — cross-rank telemetry (ISSUE 12).

PR 10 made the stack multi-device; this tier makes its failure modes
attributable across ranks. Four pieces:

- **identity** (:mod:`~apex_tpu.observability.fleet.identity`) —
  env-driven ``(process_index, process_count, run_id)`` plus
  :func:`rank_path`, the automatic ``.rank{i}`` suffix every shared
  artifact write goes through. The registry, span tracer, flight
  recorder and StepReporter all stamp their records with it.
- **straggler detection** (:mod:`~.probe` + :mod:`~.straggler`) — a
  jit-safe per-step pre-collective wait probe around the grad-sync
  call sites (io_callback enter marker barrier-tied to the collective,
  exit callback fed the reduced result) feeding a trailing-median
  cross-rank skew detector that emits ``fleet/straggler`` events
  naming the slow rank.
- **desync detection** (:mod:`~.desync`) — cheap on-device per-step
  fingerprints (per-leaf (sum, |sum|) checksums; ``pmax`` vs ``pmean``
  equality is the one-scalar flag, ``all_gather`` the attributing
  form) with a host detector naming the offending rank, step and
  tensor path; ``ResilientTrainLoop`` trips the rollback ladder on a
  verdict.
- **fleet readers** (:mod:`~.merge` + :mod:`~.collector`) —
  ``merge_fleet`` joins per-rank metrics shards into one report
  (per-rank and cross-rank p50/p99, skew, straggler pass, rank→pid
  Perfetto export); ``merge_flight_records`` joins ``flightrec_*``
  shards into the fleet post-mortem naming the stuck rank and the
  last collective each rank entered.

CLI: ``python -m apex_tpu.observability fleet <shards...>`` /
``... fleet --flight DIR``.
"""

from apex_tpu.observability.fleet import probe  # noqa: F401
from apex_tpu.observability.fleet.collector import (  # noqa: F401
    find_flight_records,
    merge_flight_records,
    write_fleet_record,
)
from apex_tpu.observability.fleet.desync import (  # noqa: F401
    DesyncDetector,
    fingerprint,
    fingerprint_delta,
    fingerprint_gather,
    leaf_paths,
)
from apex_tpu.observability.fleet.identity import (  # noqa: F401
    FleetIdentity,
    identity_fields,
    is_fleet_member,
    process_identity,
    rank_of_path,
    rank_path,
    stamp_environ,
)
from apex_tpu.observability.fleet.merge import (  # noqa: F401
    fleet_metric_records,
    fleet_shards,
    fleet_trace_events,
    merge_fleet,
)
from apex_tpu.observability.fleet.straggler import (  # noqa: F401
    StragglerDetector,
)

__all__ = [
    "FleetIdentity", "process_identity", "identity_fields",
    "is_fleet_member", "rank_path", "rank_of_path", "stamp_environ",
    "probe", "StragglerDetector",
    "DesyncDetector", "fingerprint", "fingerprint_delta",
    "fingerprint_gather", "leaf_paths",
    "fleet_shards", "merge_fleet", "fleet_metric_records",
    "fleet_trace_events",
    "find_flight_records", "merge_flight_records", "write_fleet_record",
]
