"""Fleet identity — which rank is this process, and where may it write?

Every telemetry tier before ISSUE 12 was process-blind: per-rank JSONL
dumps raced on one ``APEX_TPU_METRICS`` path and flight-recorder
artifacts were timestamp-named, so two ranks (or a re-exec'd bench
child) clobbered each other's evidence. This module is the single
source of both answers:

- :func:`process_identity` — ``(process_index, process_count, run_id)``
  for this process. **Environment-driven**: ``APEX_TPU_PROCESS_INDEX``
  / ``APEX_TPU_PROCESS_COUNT`` / ``APEX_TPU_RUN_ID`` are authoritative
  (the :mod:`apex_tpu.parallel.multiproc` launcher exports them per
  worker, and ``initialize_distributed`` back-fills them from
  ``jax.process_index()`` after the runtime comes up). Reading the env
  instead of jax keeps :mod:`~apex_tpu.observability.registry` jax-free
  at dump time and never forces backend init from a telemetry write.
- :func:`rank_path` — the collision-free per-rank artifact path: a
  fleet member writing to a shared path gets an automatic ``.rank{i}``
  suffix before the extension (``metrics.jsonl`` →
  ``metrics.rank3.jsonl``); a solo process writes the path unchanged,
  so single-process dumps stay byte- and name-stable.
- :func:`identity_fields` — the ``{process_index, process_count,
  run_id}`` stamp every registry JSONL record, span dump, step record
  and flight-record artifact carries (the fleet reader
  :func:`~apex_tpu.observability.fleet.merge.merge_fleet` groups
  shards by it).

jax-free at import time and at every call.
"""

from __future__ import annotations

import os
import re
from typing import NamedTuple, Optional

__all__ = [
    "FleetIdentity", "process_identity", "identity_fields",
    "is_fleet_member", "rank_path", "rank_of_path", "stamp_environ",
    "ENV_INDEX", "ENV_COUNT", "ENV_RUN_ID",
]

ENV_INDEX = "APEX_TPU_PROCESS_INDEX"
ENV_COUNT = "APEX_TPU_PROCESS_COUNT"
ENV_RUN_ID = "APEX_TPU_RUN_ID"

_RANK_RE = re.compile(r"\.rank(\d+)(?=\.|$)")


class FleetIdentity(NamedTuple):
    process_index: int
    process_count: int
    run_id: Optional[str]


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not an integer — the fleet identity "
            f"env vars are set by apex_tpu.parallel.multiproc; a "
            f"malformed override would silently mis-route every "
            f"per-rank artifact")


def process_identity() -> FleetIdentity:
    """This process's fleet coordinates, env-first.

    With neither env var set this is a solo process:
    ``(0, 1, run_id-or-None)``. Setting ``APEX_TPU_PROCESS_INDEX``
    alone marks the process a fleet member of unknown size (count
    defaults to ``index + 1`` so the pair stays consistent).
    """
    index = _env_int(ENV_INDEX)
    count = _env_int(ENV_COUNT)
    if index is None:
        index = 0
        if count is None:
            count = 1
    elif count is None:
        count = index + 1
    if index < 0 or count < 1 or index >= count:
        raise ValueError(
            f"inconsistent fleet identity: {ENV_INDEX}={index} "
            f"{ENV_COUNT}={count} (need 0 <= index < count)")
    return FleetIdentity(index, count, os.environ.get(ENV_RUN_ID) or None)


def is_fleet_member(ident: Optional[FleetIdentity] = None) -> bool:
    """True when this process is one rank of a fleet — i.e. shared
    artifact paths must be rank-suffixed. A solo process (no identity
    env, count 1) is not a member, keeping legacy single-process
    artifact names unchanged."""
    if os.environ.get(ENV_INDEX) not in (None, ""):
        return True
    ident = ident if ident is not None else process_identity()
    return ident.process_count > 1


def identity_fields(ident: Optional[FleetIdentity] = None) -> dict:
    """The per-record stamp: ``{process_index, process_count, run_id}``
    (``run_id`` omitted when unset — readers treat absence as the
    anonymous local run)."""
    ident = ident if ident is not None else process_identity()
    fields = {"process_index": ident.process_index,
              "process_count": ident.process_count}
    if ident.run_id:
        fields["run_id"] = ident.run_id
    return fields


def rank_path(path: str, ident: Optional[FleetIdentity] = None) -> str:
    """Collision-free per-rank variant of a (possibly shared) path.

    Fleet members get ``.rank{i}`` inserted before the final extension
    (``out/metrics.jsonl`` → ``out/metrics.rank3.jsonl``;
    extensionless paths get the suffix appended). Solo processes and
    paths that already carry a ``.rank{n}`` component pass through
    unchanged, so the function is idempotent and safe to apply at
    every write site."""
    ident = ident if ident is not None else process_identity()
    if not is_fleet_member(ident):
        return path
    head, tail = os.path.split(path)
    if _RANK_RE.search(tail):
        return path
    root, ext = os.path.splitext(tail)
    return os.path.join(head, f"{root}.rank{ident.process_index}{ext}")


def rank_of_path(path: str) -> Optional[int]:
    """The rank a ``.rank{i}``-suffixed shard path belongs to, or None
    for a legacy un-suffixed file."""
    m = _RANK_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def stamp_environ(env: dict, index: int, count: int,
                  run_id: Optional[str] = None) -> dict:
    """Write the fleet identity into an environment dict (the launcher
    helper): returns ``env`` with the three identity vars set."""
    env[ENV_INDEX] = str(int(index))
    env[ENV_COUNT] = str(int(count))
    if run_id:
        env[ENV_RUN_ID] = str(run_id)
    return env
