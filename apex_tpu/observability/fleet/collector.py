"""Fleet flight-record collector — which rank is stuck, and where?

On a stall (each rank's own :class:`~apex_tpu.observability.profiling.
flight_recorder.FlightRecorder` watchdog) or an operator ``SIGQUIT``
every rank dumps its own ``flightrec_*.json`` shard — rank-stamped and
collision-free since ISSUE 12. This module is the join:

- :func:`find_flight_records` — discover the shard set in a directory
  (optionally filtered to one ``run_id``);
- :func:`merge_flight_records` — one fleet verdict: per-rank progress
  (step, elapsed, trigger), each rank's **last collective entered**
  (the grad-sync probe's marker when armed, else the innermost open /
  most recent completed collective-named span), and the **stuck
  rank(s)** — ranks whose dump fired on the stall trigger, else the
  rank furthest behind in step progress, else the longest-hung;
- :func:`write_fleet_record` — persist the merged verdict as a
  ``fleetrec_*.json`` artifact next to the shards.

CLI: ``python -m apex_tpu.observability fleet --flight DIR``.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import List, Optional

__all__ = [
    "find_flight_records", "merge_flight_records", "write_fleet_record",
    "COLLECTIVE_SPAN_MARKERS",
]

# span-name prefixes/fragments that mean "inside a collective": the DDP
# bucket schedules, the ZeRO-1 scatter/gather, the raw sync paths, and
# the fleet probe's own barrier-wait region.
COLLECTIVE_SPAN_MARKERS = (
    "ddp/", "zero1", "allreduce", "all_gather", "psum", "reduce_scatter",
    "fleet/barrier", "grad_sync",
)


def _is_collective(name: Optional[str]) -> bool:
    return bool(name) and any(m in name for m in COLLECTIVE_SPAN_MARKERS)


def find_flight_records(directory: str,
                        run_id: Optional[str] = None) -> List[str]:
    """Every ``flightrec_*.json`` under ``directory`` (newest last),
    filtered to ``run_id`` when given (legacy unstamped shards pass a
    None filter only)."""
    paths = sorted(glob.glob(os.path.join(directory, "flightrec_*.json")),
                   key=lambda p: (os.path.getmtime(p), p))
    if run_id is None:
        return paths
    out = []
    for path in paths:
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        if payload.get("run_id") == run_id:
            out.append(path)
    return out


def _last_collective_of(payload: dict) -> Optional[str]:
    """The collective this rank last entered, best evidence first:
    the probe's explicit marker, then the innermost OPEN span with a
    collective name (where a hung rank is actually parked), then the
    most recent completed collective span in the ring."""
    marker = payload.get("last_collective")
    if marker:
        return marker
    open_spans = payload.get("open_spans") or {}
    for frames in open_spans.values():
        for frame in reversed(frames):  # innermost last
            name = frame.get("name") if isinstance(frame, dict) else None
            if _is_collective(name):
                return name
    best = None
    best_seq = -1
    for span in payload.get("spans") or []:
        name = span.get("name")
        if _is_collective(name) and span.get("seq", -1) > best_seq:
            best, best_seq = name, span.get("seq", -1)
    return best


def merge_flight_records(paths_or_dir,
                         run_id: Optional[str] = None) -> dict:
    """Join per-rank flight-record shards into one fleet verdict.

    Accepts a directory (expanded via :func:`find_flight_records`) or
    an explicit path list. When one rank dumped several times the
    NEWEST shard represents it. Raises FileNotFoundError on an empty
    set — "no post-mortem found" must never read as "fleet healthy".
    """
    if isinstance(paths_or_dir, (list, tuple)):
        paths = list(paths_or_dir)
    else:
        paths = find_flight_records(paths_or_dir, run_id=run_id)
    if not paths:
        raise FileNotFoundError(
            f"no flightrec_*.json shards under {paths_or_dir!r}")

    ranks: dict = {}
    unreadable: list = []
    for path in paths:  # newest-last ordering makes "last write wins"
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            unreadable.append({"path": path, "error": repr(e)[:200]})
            continue
        rank = payload.get("process_index")
        if rank is None:
            rank = f"pid{payload.get('pid', '?')}"
        prev = ranks.get(rank)
        # a stall dump is the evidence this merge exists for — never
        # let a later routine exit/signal dump shadow it
        if prev is not None and prev["trigger"] == "stall" and \
                payload.get("trigger") != "stall":
            continue
        ranks[rank] = {
            "path": os.path.basename(path),
            "trigger": payload.get("trigger"),
            "reason": payload.get("reason"),
            "step": payload.get("step"),
            "step_elapsed_s": payload.get("step_elapsed_s"),
            "last_collective": _last_collective_of(payload),
            "open_span_count": sum(
                len(v) for v in (payload.get("open_spans") or {}).values()),
            "run_id": payload.get("run_id"),
            "process_count": payload.get("process_count"),
        }

    stuck = sorted(r for r, info in ranks.items()
                   if info["trigger"] == "stall")
    picked_by = "stall trigger"
    if not stuck and len(ranks) > 1:
        # no explicit stall dump: the rank furthest BEHIND in step
        # progress is the suspect (everyone else moved on past it)
        steps = {r: info["step"] for r, info in ranks.items()
                 if isinstance(info["step"], int)}
        if steps and max(steps.values()) > min(steps.values()):
            lag = min(steps.values())
            stuck = sorted(r for r, s in steps.items() if s == lag)
            picked_by = "step lag"
    if not stuck:
        hung = {r: info["step_elapsed_s"] for r, info in ranks.items()
                if isinstance(info["step_elapsed_s"], (int, float))}
        if hung:
            worst = max(hung.values())
            stuck = sorted(r for r, v in hung.items() if v == worst)
            picked_by = "longest in-flight step"

    verdict = None
    if stuck:
        first = ranks[stuck[0]]
        where = first.get("last_collective")
        verdict = (f"rank {stuck[0]} stuck at step {first.get('step')}"
                   + (f" in {where}" if where else "")
                   + f" ({picked_by})")
    return {
        "kind": "apex_tpu.fleet_flight_record",
        "schema_version": 1,
        "ranks": {str(k): v for k, v in sorted(
            ranks.items(), key=lambda kv: str(kv[0]))},
        "rank_count": len(ranks),
        "stuck_ranks": [str(r) for r in stuck],
        "picked_by": picked_by if stuck else None,
        "verdict": verdict,
        "unreadable": unreadable,
    }


def write_fleet_record(merged: dict, directory: str) -> str:
    """Persist the merged verdict as ``fleetrec_*.json``; returns the
    path."""
    os.makedirs(directory, exist_ok=True)
    fname = (f"fleetrec_{time.strftime('%Y%m%d-%H%M%S')}_"
             f"{os.getpid()}.json")
    path = os.path.join(directory, fname)
    with open(path, "w") as f:
        json.dump(merged, f, indent=1, default=repr)
    return path
