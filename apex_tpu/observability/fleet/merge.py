"""Join per-rank telemetry shards into one fleet view.

Per-rank writers (registry dumps, span dumps, flight records) land at
``.rank{i}``-suffixed paths (:func:`~apex_tpu.observability.fleet.
identity.rank_path`). This module is the reader side:

- :func:`fleet_shards` — discover the shard set behind a base path
  (``metrics.jsonl`` → every ``metrics.rank*.jsonl`` plus, tolerated,
  a legacy un-suffixed ``metrics.jsonl`` itself, reported as rank
  None);
- :func:`merge_fleet` — the fleet report: per-rank summaries and
  step-time p50/p99, cross-rank skew per step-time metric, a
  merge-time straggler pass (trailing-median over each rank's sampled
  step times), and the fleet events (``fleet/straggler``,
  ``fleet/desync``) collected from every shard;
- :func:`fleet_metric_records` — the report re-encoded as registry-
  shaped JSONL records (``fleet/step_time_skew{metric=}`` gauges,
  per-rank p50/p99 gauges, ``fleet/stragglers{rank=}`` counters,
  ``fleet/ranks``) so ``tools/metrics_report.py`` renders the fleet
  table and ``--compare`` can gate a rank-skew regression;
- :func:`fleet_trace_events` — Perfetto export of several ranks' span
  dumps/flight records with **rank → pid**, so the merged trace shows
  one process lane per rank at ``ui.perfetto.dev``.

CLI: ``python -m apex_tpu.observability fleet <base-or-shards...>``.
"""

from __future__ import annotations

import glob
import os
import statistics
from typing import List, Optional, Sequence, Tuple

from apex_tpu.observability.fleet.identity import rank_of_path
from apex_tpu.observability.fleet.straggler import StragglerDetector
from apex_tpu.observability.registry import read_jsonl, summarize

__all__ = [
    "fleet_shards", "merge_fleet", "fleet_metric_records",
    "fleet_trace_events",
]

FLEET_EVENT_NAMES = ("fleet/straggler", "fleet/desync")


def fleet_shards(base: str) -> List[Tuple[Optional[int], str]]:
    """(rank, path) pairs for the shard family behind ``base``.

    ``base`` may be a shared path (its ``.rank*`` siblings are
    globbed; a legacy un-suffixed file at ``base`` itself joins as
    rank None), an existing shard (resolved to its family), or a
    directory (every ``*.rank*.jsonl`` inside). Sorted by rank,
    legacy-unsuffixed last."""
    if os.path.isdir(base):
        paths = sorted(glob.glob(os.path.join(base, "*.rank*.jsonl")))
    else:
        head, tail = os.path.split(base)
        root, ext = os.path.splitext(tail)
        # strip an existing .rank{i} so any shard names its family
        if rank_of_path(base) is not None:
            root = root.rsplit(".rank", 1)[0]
        pattern = os.path.join(head, f"{root}.rank*{ext}")
        paths = sorted(glob.glob(pattern))
        legacy = os.path.join(head, root + ext)
        if os.path.isfile(legacy):
            paths.append(legacy)
    out = []
    for path in paths:
        out.append((rank_of_path(path), path))
    out.sort(key=lambda rp: (rp[0] is None, rp[0] if rp[0] is not None
                             else -1, rp[1]))
    return out


def _identity_of(records) -> dict:
    """The {process_index, process_count, run_id} stamp carried by a
    shard's records (first stamped record wins; legacy dumps carry
    none)."""
    for rec in records:
        if isinstance(rec, dict) and "process_index" in rec:
            return {k: rec.get(k) for k in
                    ("process_index", "process_count", "run_id")
                    if rec.get(k) is not None}
    return {}


def _step_time_stats(records) -> dict:
    """{metric name: {p50, p99, count, mean}} from */step_time_ms
    histogram/timer records."""
    out = {}
    for rec in records:
        name = rec.get("name", "")
        if not (isinstance(name, str) and name.endswith("/step_time_ms")
                and rec.get("type") in ("histogram", "timer")):
            continue
        out[name] = {k: rec.get(k)
                     for k in ("p50", "p99", "count", "mean")}
    return out


def merge_fleet(base_or_paths, straggler_threshold: Optional[float] = None,
                run_id: Optional[str] = None) -> dict:
    """The one fleet report over a shard family.

    ``base_or_paths``: a shared base path / directory / shard path
    (expanded via :func:`fleet_shards`) or an explicit iterable of
    shard paths. ``run_id`` filters stamped shards to one run (legacy
    unstamped shards always pass). Raises FileNotFoundError when no
    shard exists — an empty fleet report would read as "all healthy".
    """
    if isinstance(base_or_paths, (list, tuple)):
        shards = [(rank_of_path(p), p) for p in base_or_paths]
    else:
        shards = fleet_shards(base_or_paths)
    if not shards:
        raise FileNotFoundError(
            f"no fleet shards found for {base_or_paths!r} (looked for "
            f".rank*-suffixed siblings and the legacy un-suffixed file)")

    ranks: dict = {}
    fleet_events: list = []
    all_records: list = []
    for rank, path in shards:
        records = read_jsonl(path)
        ident = _identity_of(records)
        if run_id is not None and ident.get("run_id") not in (None,
                                                              run_id):
            continue
        if rank is None:
            rank = ident.get("process_index")
        key = "legacy" if rank is None else int(rank)
        ranks[key] = {
            "path": path,
            "identity": ident,
            "summary": summarize(records),
            "step_time": _step_time_stats(records),
        }
        all_records.extend(records)
        for rec in records:
            if rec.get("type") == "event" and \
                    rec.get("name") in FLEET_EVENT_NAMES:
                fleet_events.append({"rank": key, **rec})

    # ---- cross-rank skew + merge-time straggler pass
    numeric_ranks = sorted(k for k in ranks if isinstance(k, int))
    skew: dict = {}
    stragglers: list = []
    metrics = sorted({m for k in numeric_ranks
                      for m in ranks[k]["step_time"]})
    for metric in metrics:
        per_rank = {k: ranks[k]["step_time"][metric]
                    for k in numeric_ranks
                    if metric in ranks[k]["step_time"]
                    and isinstance(ranks[k]["step_time"][metric].get(
                        "p50"), (int, float))}
        if len(per_rank) < 2:
            continue
        p50s = {k: float(v["p50"]) for k, v in per_rank.items()}
        fleet_median = statistics.median(p50s.values())
        slow = max(p50s, key=lambda k: p50s[k])
        rel = ((p50s[slow] - fleet_median) / fleet_median
               if fleet_median > 0 else 0.0)
        skew[metric] = {
            "p50_by_rank": p50s,
            "p99_by_rank": {k: v.get("p99")
                            for k, v in per_rank.items()},
            "fleet_median_p50": fleet_median,
            "max_rank": slow,
            "skew": round(rel, 4),
        }
        detector = StragglerDetector(
            mode="step_time", threshold=straggler_threshold,
            min_history=1, registry=_NullRegistry())
        # rank-keyed mapping: a sparse shard family (some ranks never
        # dumped) must not fabricate phantom ranks
        verdict = detector.observe(0, p50s, site=metric)
        if verdict is not None:
            stragglers.append({"metric": metric, **verdict})

    return {
        "kind": "apex_tpu.fleet_report",
        "schema_version": 1,
        "ranks": ranks,
        "rank_count": len(numeric_ranks),
        "legacy_shards": int("legacy" in ranks),
        "step_time_skew": skew,
        "stragglers": stragglers,
        "fleet_events": fleet_events,
    }


class _NullRegistry:
    """Metric sink for merge-time detector passes: the merge is a
    READER — it must not publish into the live process registry."""

    def counter(self, *a, **k):
        return self

    def gauge(self, *a, **k):
        return self

    def inc(self, *a, **k):
        return None

    def set(self, *a, **k):
        return None

    def event(self, *a, **k):
        return {}


def fleet_metric_records(report: dict) -> list:
    """The fleet report as registry-shaped JSONL records — feed a
    merged dump to ``tools/metrics_report.py`` (fleet table rendering,
    ``--compare`` rank-skew gate)."""
    recs = [{"type": "gauge", "name": "fleet/ranks",
             "value": report["rank_count"]}]
    for metric, row in sorted(report["step_time_skew"].items()):
        recs.append({"type": "gauge", "name": "fleet/step_time_skew",
                     "labels": {"metric": metric},
                     "value": row["skew"]})
        for rank, p50 in sorted(row["p50_by_rank"].items()):
            recs.append({"type": "gauge",
                         "name": "fleet/step_time_p50_ms",
                         "labels": {"metric": metric,
                                    "rank": str(rank)},
                         "value": p50})
        for rank, p99 in sorted(row["p99_by_rank"].items()):
            if p99 is not None:
                recs.append({"type": "gauge",
                             "name": "fleet/step_time_p99_ms",
                             "labels": {"metric": metric,
                                        "rank": str(rank)},
                             "value": p99})
    by_rank: dict = {}
    for verdict in report["stragglers"]:
        by_rank[verdict["rank"]] = by_rank.get(verdict["rank"], 0) + 1
    for rank, n in sorted(by_rank.items()):
        recs.append({"type": "counter", "name": "fleet/stragglers",
                     "labels": {"rank": str(rank)}, "value": n})
    recs.append({"type": "counter", "name": "fleet/desync_events",
                 "value": sum(1 for ev in report["fleet_events"]
                              if ev.get("name") == "fleet/desync")})
    for i, ev in enumerate(report["fleet_events"]):
        recs.append({"type": "event", "name": ev.get("name"),
                     "seq": i, "fields": {
                         "rank": ev.get("rank"),
                         **(ev.get("fields") or {})}})
    return recs


def fleet_trace_events(rank_dumps: Sequence[Tuple[int, str]]) -> list:
    """Merged Perfetto trace events over several ranks' span dumps /
    flight records, one **pid per rank** so the fleet renders as one
    process lane per rank. ``rank_dumps``: (rank, path) pairs."""
    import json

    from apex_tpu.observability.profiling import (
        decode_span_payload,
        to_trace_events,
    )

    events: list = []
    kinds = ("apex_tpu.spans", "apex_tpu.flight_record")
    for rank, path in sorted(rank_dumps):
        with open(path) as f:
            payload = json.load(f)
        spans, names = decode_span_payload(payload, where=path,
                                           kinds=kinds)
        pid = int(rank)
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0,
                       "args": {"name": f"rank{pid}"}})
        events.extend(to_trace_events(spans, thread_names=names,
                                      pid=pid))
    return events
