"""Training-health detectors (ISSUE 9 tentpole piece 4): grad-norm
spikes, loss plateaus/spikes, scaler overflow streaks.

A numerics incident rarely starts at the NaN — it starts steps earlier
as a grad-norm spike or an overflow streak the scaler keeps eating.
:class:`HealthMonitor` watches the host-side per-step signals every
example/bench already has in hand (loss, grad norm, the scaler's
``report()`` dict) and emits the ``numerics/*`` counter/gauge family
plus structured events the moment a trajectory turns pathological —
BEFORE the resilience ladder has to roll anything back.

All detectors are trailing-median based (robust to the occasional
outlier step) and fire as edge triggers: one event when a condition is
entered, not one per step it persists.
"""

from __future__ import annotations

import collections
import math
import statistics
from typing import Optional

__all__ = ["HealthMonitor"]


def _finite(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


class HealthMonitor:
    """Feed one ``observe(step, ...)`` per training step; returns the
    list of detector events fired this step (also appended to the
    registry's event stream).

    Detectors:

    - **grad-norm spike** — ``grad_norm`` above ``grad_spike_factor``
      x the trailing-window median (counter
      ``numerics/grad_norm_spikes``, event ``numerics_grad_spike``;
      the ``numerics/grad_norm`` histogram feeds the ``--compare``
      p50 gate);
    - **loss spike** — same rule on ``loss``
      (``numerics/loss_spikes`` / ``numerics_loss_spike``);
    - **loss plateau** — the last ``plateau_window`` losses span less
      than ``plateau_rtol`` x their median magnitude
      (``numerics/loss_plateaus`` / ``numerics_loss_plateau``; off by
      default — short smoke runs plateau legitimately);
    - **non-finite signal** — a NaN/Inf loss or grad norm flips the
      ``numerics/finite{source=<name>:<signal>}`` gauge to 0 (the
      finite→non-finite ``--compare`` gate) and counts
      ``numerics/nonfinite_signals``;
    - **overflow streak** — the scaler's ``skip_streak`` (ISSUE 9 amp
      satellite: consecutive overflow-skipped steps) at or past
      ``overflow_streak_threshold`` fires
      ``numerics/overflow_streaks`` / ``numerics_overflow_streak``;
      ``last_overflow_step`` and the streak ride along as gauges.
    """

    def __init__(self, name: str = "train", registry=None,
                 window: int = 32, min_samples: int = 5,
                 grad_spike_factor: float = 10.0,
                 loss_spike_factor: float = 10.0,
                 plateau_window: int = 0,
                 plateau_rtol: float = 1e-4,
                 overflow_streak_threshold: int = 3):
        self.name = name
        self._registry = registry
        self.window = max(int(window), 2)
        self.min_samples = max(int(min_samples), 2)
        self.grad_spike_factor = float(grad_spike_factor)
        self.loss_spike_factor = float(loss_spike_factor)
        self.plateau_window = int(plateau_window)
        self.plateau_rtol = float(plateau_rtol)
        self.overflow_streak_threshold = int(overflow_streak_threshold)
        self._grads = collections.deque(maxlen=self.window)
        self._losses = collections.deque(maxlen=self.window)
        self._in_plateau = False
        self._streak_fired = False

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from apex_tpu.observability.registry import get_registry
        return get_registry()

    # ---- detectors ---------------------------------------------------

    def _spike(self, history, value: float, factor: float):
        """(median, spiked?) vs the trailing history (value not yet
        appended)."""
        if len(history) < self.min_samples:
            return None, False
        med = statistics.median(history)
        return med, med > 0 and value > factor * med

    def _check_signal(self, reg, events, step, signal: str, raw,
                      history, factor: float, counter: str,
                      event_name: str):
        if raw is None:
            return None
        value = _finite(raw)
        reg.gauge("numerics/finite",
                  source=f"{self.name}:{signal}").set(
            1.0 if value is not None else 0.0)
        if value is None:
            reg.counter("numerics/nonfinite_signals",
                        source=self.name, signal=signal).inc()
            events.append({"event": "numerics_nonfinite",
                           "signal": signal, "step": step})
            return None
        med, spiked = self._spike(history, value, factor)
        if spiked:
            reg.counter(counter, source=self.name).inc()
            events.append({"event": event_name, "step": step,
                           "value": value, "median": med,
                           "factor": factor})
        history.append(value)
        return value

    def observe(self, step: int, loss=None, grad_norm=None,
                scaler_report: Optional[dict] = None) -> list:
        """Record one step's signals; returns the detector events
        fired (each also lands as a registry event)."""
        reg = self._reg()
        events: list = []

        g = self._check_signal(
            reg, events, step, "grad_norm", grad_norm, self._grads,
            self.grad_spike_factor, "numerics/grad_norm_spikes",
            "numerics_grad_spike")
        if g is not None:
            reg.histogram("numerics/grad_norm",
                          source=self.name).observe(g)

        loss_f = self._check_signal(
            reg, events, step, "loss", loss, self._losses,
            self.loss_spike_factor, "numerics/loss_spikes",
            "numerics_loss_spike")
        if loss_f is not None and self.plateau_window > 1 and \
                len(self._losses) >= self.plateau_window:
            recent = list(self._losses)[-self.plateau_window:]
            span = max(recent) - min(recent)
            scale = max(abs(statistics.median(recent)), 1e-12)
            if span <= self.plateau_rtol * scale:
                if not self._in_plateau:
                    self._in_plateau = True
                    reg.counter("numerics/loss_plateaus",
                                source=self.name).inc()
                    events.append({"event": "numerics_loss_plateau",
                                   "step": step, "span": span,
                                   "window": self.plateau_window})
            else:
                self._in_plateau = False

        if scaler_report:
            streak = int(scaler_report.get("skip_streak", 0) or 0)
            last_ovf = scaler_report.get("last_overflow_step")
            reg.gauge("numerics/overflow_streak",
                      source=self.name).set(streak)
            if last_ovf is not None:
                reg.gauge("numerics/last_overflow_step",
                          source=self.name).set(int(last_ovf))
            if streak >= self.overflow_streak_threshold:
                if not self._streak_fired:
                    self._streak_fired = True
                    reg.counter("numerics/overflow_streaks",
                                source=self.name).inc()
                    events.append({
                        "event": "numerics_overflow_streak",
                        "step": step, "streak": streak,
                        "last_overflow_step": last_ovf,
                        "loss_scale": scaler_report.get("loss_scale"),
                    })
            else:
                self._streak_fired = False

        for ev in events:
            reg.event(ev["event"], source=self.name,
                      **{k: v for k, v in ev.items() if k != "event"})
        return events
