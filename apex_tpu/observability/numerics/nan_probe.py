"""NaN/Inf provenance (ISSUE 9 tentpole piece 3): which primitive
went non-finite first, and where in the source it lives.

When the resilience ladder trips on a non-finite step (or the amp
scaler overflows forever), a "state has NaNs" verdict is useless to an
oncall — the question is *which tensor* drifted and *which op* first
produced a non-finite value. This module answers it by replaying the
step's jaxpr under the unified interpreter's non-finite taint lattice
(:class:`apex_tpu.analysis.interp.NonFiniteLattice`): the walk
re-evaluates each primitive with the step's CONCRETE inputs, and the
first equation whose output is non-finite is classified

- ``origin``     — its inputs were finite: this primitive *created*
  the NaN/Inf (an exp overflow, a 0/0) — reported with its name and
  the user source location from the equation's ``source_info``;
- ``inherited``  — a non-finite value already entered through the
  jaxpr's inputs (an injected ``nan_grads`` corruption, a poisoned
  checkpoint): the primitive is the first to *touch* the taint, and
  the offending input tensor paths are named.

Replay runs eagerly on host at post-mortem time — it costs one step of
eager compute on the failure path and nothing on the hot path.
Everything degrades gracefully: a step function that is not traceable
(host pulls inside it) still yields a paths-only report from the
stats pass.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = ["Provenance", "probe_fn", "probe_tree", "step_provenance"]


@dataclasses.dataclass
class Provenance:
    """The post-mortem verdict a ``TrainAborted`` report carries."""

    ok: bool                          # True = nothing non-finite found
    kind: Optional[str] = None        # "origin" | "inherited"
    primitive: Optional[str] = None   # first offending primitive
    source: Optional[str] = None      # user source location
    input_paths: tuple = ()           # non-finite probe inputs
    output_paths: tuple = ()          # non-finite tensors (state/outs)
    message: str = ""

    def as_dict(self) -> dict:
        return {
            "ok": self.ok, "kind": self.kind,
            "primitive": self.primitive, "source": self.source,
            "input_paths": list(self.input_paths),
            "output_paths": list(self.output_paths),
            "message": self.message,
        }


def _source_of(eqn) -> Optional[str]:
    """Best-effort user source location of an equation ("file:line
    (function)") — jax-version-tolerant, never raises."""
    try:
        from jax._src import source_info_util
        return str(source_info_util.summarize(eqn.source_info))
    except Exception:  # noqa: BLE001 — provenance is best-effort
        return None


def probe_tree(tree) -> Provenance:
    """Paths-only provenance: name the non-finite tensors of ``tree``
    (one fused reduction + one fetch; no jaxpr replay)."""
    from apex_tpu.observability.numerics import stats

    paths = stats.nonfinite_paths(tree)
    if not paths:
        return Provenance(ok=True, message="all tensors finite")
    return Provenance(
        ok=False, output_paths=paths,
        message=f"{len(paths)} non-finite tensor(s)")


def probe_fn(fn, *args) -> Provenance:
    """Trace ``fn(*args)``, replay its jaxpr with the concrete ``args``
    under the non-finite taint lattice, and report the first offending
    equation (see module docstring). Raises whatever tracing raises —
    callers that probe arbitrary user functions should catch."""
    import jax

    from apex_tpu.analysis import interp
    from apex_tpu.observability.numerics import stats

    closed = jax.make_jaxpr(fn)(*args)
    flat, _treedef = jax.tree_util.tree_flatten(args)
    all_paths = stats.tree_paths(args) if len(args) > 1 else \
        stats.tree_paths(args[0]) if args else ()
    if len(all_paths) != len(flat):  # container mismatch: fall back to
        all_paths = tuple(f"arg[{i}]" for i in range(len(flat)))

    in_vals = [interp.NFVal.known(x) for x in flat]
    bad_inputs = tuple(all_paths[i] for i, v in enumerate(in_vals)
                       if v.finite is False)

    first: dict = {}

    def visit(eqn, ins, outs, ctx):
        if first:
            return
        if not any(o is not None and o.finite is False for o in outs):
            return
        inherited = any(v is not None and v.finite is False
                        for v in ins)
        first.update(
            kind="inherited" if inherited else "origin",
            primitive=eqn.primitive.name,
            source=_source_of(eqn))

    lattice = interp.NonFiniteLattice()
    (outs,) = interp.interpret_lattices(
        closed, [interp.LatticeRun(lattice, in_vals, visit)])

    if first:
        kind = first["kind"]
        prim = first["primitive"]
        src = first["source"]
        msg = (f"first non-finite value produced by primitive "
               f"'{prim}'" if kind == "origin" else
               f"non-finite input first consumed by primitive "
               f"'{prim}'")
        if src:
            msg += f" at {src}"
        return Provenance(ok=False, kind=kind, primitive=prim,
                          source=src, input_paths=bad_inputs,
                          message=msg)
    if bad_inputs:
        return Provenance(
            ok=False, kind="inherited", input_paths=bad_inputs,
            message="non-finite inputs never consumed by a replayable "
                    "primitive")
    if any(o is not None and o.finite is False for o in outs):
        return Provenance(
            ok=False, kind="origin",
            message="non-finite output from an unreplayable region "
                    "(opaque kernel)")
    return Provenance(ok=True, message="replay stayed finite")


def step_provenance(step_fn, prev_state, bad_state,
                    step: int) -> Provenance:
    """The resilience ladder's hook: provenance for a step whose
    output ``bad_state`` failed the finite check.

    1. The offending tensor paths come from one stats pass over
       ``bad_state`` (always works).
    2. When ``step_fn`` traces, replay it on ``prev_state`` — a NaN
       born inside the step is reported as ``origin`` with its
       primitive + source location.
    3. When that replay stays finite (the corruption entered OUTSIDE
       the traced step: an injected ``nan_grads`` fault, host-side
       mutation), replay on ``bad_state`` instead and name the first
       primitive that would consume the poison (``inherited``).

    Never raises: any probe failure degrades to the paths-only report.
    """
    try:
        base = probe_tree(bad_state)
    except Exception as e:  # noqa: BLE001 — even the stats pass can
        # die on an exotic state tree; provenance must never mask the
        # original training failure
        return Provenance(ok=False,
                          message=f"probe failed: {e!r:.200}")
    try:
        # replay on the pre-step state runs even when the STATE is
        # finite: a NaN loss with finite params (a metrics-only health
        # failure) still has an in-step origin worth naming
        prov = probe_fn(lambda s: step_fn(s, step), prev_state)
        if not prov.ok:
            prov.output_paths = base.output_paths
            return prov
        if base.ok:
            return base
        prov = probe_fn(lambda s: step_fn(s, step), bad_state)
        if not prov.ok:
            prov.output_paths = base.output_paths
            prov.message += (" (step replay on the pre-step state "
                             "was clean)")
            return prov
        base.message += ("; step replay stayed finite — the "
                         "non-finite values entered outside the "
                         "traced step")
    except Exception as e:  # noqa: BLE001 — untraceable step_fn
        base.message += f"; jaxpr replay unavailable ({e!r:.120})"
    return base
