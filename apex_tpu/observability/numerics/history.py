"""Per-tensor amax history rings (ISSUE 9 tentpole piece 2) — the fp8
delayed-scaling primitive ROADMAP item 5 is blocked on.

Transformer-Engine-style delayed scaling (PAPERS.md fp8-formats,
Micikevicius et al.) chooses each tensor's fp8 scale from the MAX of
its last H observed amaxes rather than the current step's — one step of
staleness buys a scale that is already on device when the cast runs.
:class:`AmaxHistory` keeps those rings for a whole pytree as ONE
``f32[n, H]`` matrix (n = inexact leaves, aligned with
``stats.leaf_paths`` order) plus a shared cursor, so the per-step
update is a single on-device column write fed straight from the
stacked ``TreeStats.amax`` vector — no per-tensor bookkeeping.

The ring state is a plain pytree of arrays
(:class:`AmaxHistoryState`), so it checkpoints by riding the train
state through ``apex_tpu.checkpoint``'s atomic manifest protocol
(commit marker + crc32) like any other leaf — auto-resume restores the
rings **bit-identical** (proved by
``tests/run_resilience/test_numerics_roundtrip.py`` under the PR 5
chaos harness), which is what keeps a delayed-scaling run's scale
choices replay-stable across preemption.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

__all__ = [
    "F8_E4M3_MAX", "F8_E5M2_MAX", "AmaxHistoryState", "AmaxHistory",
]

#: largest representable magnitudes of the fp8 formats the delayed
#: scales target (E4M3 for fwd activations/weights, E5M2 for grads).
F8_E4M3_MAX = 448.0
F8_E5M2_MAX = 57344.0


class AmaxHistoryState(NamedTuple):
    """Functional ring state — carry it in the train state pytree."""

    ring: object     # f32[n, H]  per-tensor amax ring
    cursor: object   # i32        next column to write
    filled: object   # i32        columns written so far (<= H)


class AmaxHistory:
    """Fixed-structure amax rings for the tensors named by ``paths``.

    The object itself is static configuration (paths, ring length);
    all mutable state lives in :class:`AmaxHistoryState` so
    ``update``/``amax``/``scales`` are jit-safe and the state
    checkpoints/donates like any other pytree.
    """

    def __init__(self, paths: Sequence[str], length: int = 16):
        if length < 1:
            raise ValueError(f"history length must be >= 1, "
                             f"got {length}")
        self.paths = tuple(str(p) for p in paths)
        self.length = int(length)

    @classmethod
    def for_tree(cls, tree, length: int = 16) -> "AmaxHistory":
        """History sized/ordered for ``tree``'s inexact leaves — the
        same order ``stats.tensor_stats`` stacks."""
        from apex_tpu.observability.numerics import stats
        return cls(stats.leaf_paths(tree), length=length)

    def index(self, path: str) -> int:
        return self.paths.index(path)

    # ---- jit-safe state protocol -------------------------------------

    def init(self) -> AmaxHistoryState:
        import jax.numpy as jnp
        return AmaxHistoryState(
            ring=jnp.zeros((len(self.paths), self.length), jnp.float32),
            cursor=jnp.zeros([], jnp.int32),
            filled=jnp.zeros([], jnp.int32),
        )

    def update(self, state: AmaxHistoryState,
               amax) -> AmaxHistoryState:
        """Write one step's stacked amax vector (``f32[n]`` —
        ``TreeStats.amax``) into the rings; one dynamic column write."""
        import jax
        import jax.numpy as jnp
        amax = jnp.asarray(amax, jnp.float32)
        ring = jax.lax.dynamic_update_slice(
            state.ring, amax[:, None], (0, state.cursor))
        return AmaxHistoryState(
            ring=ring,
            cursor=(state.cursor + 1) % self.length,
            filled=jnp.minimum(state.filled + 1, self.length),
        )

    def update_from(self, state: AmaxHistoryState,
                    tree_stats) -> AmaxHistoryState:
        """Feed a :class:`~.stats.TreeStats` straight in."""
        return self.update(state, tree_stats.amax)

    def amax(self, state: AmaxHistoryState):
        """Rolling per-tensor amax over the filled slots (``f32[n]``)
        — the delayed-scaling statistic. Unfilled slots never vote
        (amax is >= 0, so masking them to 0 is exact); an empty
        history reports 0."""
        import jax.numpy as jnp
        mask = jnp.arange(self.length) < state.filled
        return jnp.max(jnp.where(mask[None, :], state.ring, 0.0),
                       axis=1)

    def scales(self, state: AmaxHistoryState,
               fp8_max: float = F8_E4M3_MAX, margin: float = 0.0):
        """Per-tensor delayed scale ``fp8_max / (rolling_amax * 2^m)``
        (``f32[n]``): multiply a tensor by its scale before the fp8
        cast so the history's max lands at the format's edge. Tensors
        with no signal yet (rolling amax 0) scale by 1."""
        import jax.numpy as jnp
        rolling = self.amax(state) * (2.0 ** margin)
        return jnp.where(rolling > 0.0, fp8_max / jnp.maximum(
            rolling, jnp.finfo(jnp.float32).tiny), 1.0)

    # ---- host-side serialization (non-pytree paths) ------------------

    def state_dict(self, state: AmaxHistoryState) -> dict:
        """Plain-JSON form, for callers that persist outside the
        checkpoint tree. The pytree-through-checkpoint.py route is the
        canonical (bit-identical) one."""
        import jax
        host = jax.device_get(state)
        return {"paths": list(self.paths), "length": self.length,
                "ring": [[float(v) for v in row]
                         for row in host.ring],
                "cursor": int(host.cursor), "filled": int(host.filled)}

    def load_state_dict(self, d: dict) -> AmaxHistoryState:
        import jax.numpy as jnp
        if tuple(d.get("paths", ())) != self.paths:
            raise ValueError(
                "amax-history state was recorded for a different "
                "tensor set; refusing to misalign rings "
                f"({len(d.get('paths', ()))} recorded vs "
                f"{len(self.paths)} configured paths)")
        if int(d.get("length", self.length)) != self.length:
            raise ValueError(
                f"amax-history length mismatch: state has "
                f"{d.get('length')}, configured {self.length}")
        return AmaxHistoryState(
            ring=jnp.asarray(d["ring"], jnp.float32),
            cursor=jnp.asarray(d["cursor"], jnp.int32),
            filled=jnp.asarray(d["filled"], jnp.int32),
        )
