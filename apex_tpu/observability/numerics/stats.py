"""On-device tensor statistics for whole pytrees (ISSUE 9 tentpole
piece 1).

One jit of :func:`tensor_stats` computes amax / l2-norm /
underflow-fraction / zero-fraction / finite-flag for EVERY inexact leaf
of a tree as one fused program: per-leaf scalars stacked into five
small vectors, so the device does one pass over the data and the host
does ONE fetch for the whole tree. The anti-pattern this replaces — a
Python loop of ``bool(jnp.isnan(leaf).any())`` host pulls per tensor —
serializes the step pipeline on device round-trips and is now linted
(``host-isnan-in-step-loop``).

:class:`StatsCollector` is the decimated driver: stats are computed
AND pulled only every ``every`` steps, and the pull follows
``runtime/timing.py``'s corrected-sync rules — the host fetch of the
stacked result vectors IS the sync (``block_until_ready`` is a no-op
over the axon tunnel; a host fetch is the only wait that provably
waits), one fetch per pull, never per tensor.

The stacked ``amax`` vector is the substrate ROADMAP item 5's fp8
delayed scaling feeds on — :mod:`.history` rings it.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

__all__ = [
    "TENSOR_STAT_FIELDS", "TreeStats", "tree_paths", "leaf_paths",
    "tensor_stats", "host_tensor_stats", "nonfinite_paths",
    "summarize_stats", "StatsCollector",
]

#: per-tensor statistics every stats pass computes, in stack order.
TENSOR_STAT_FIELDS = ("amax", "l2", "underflow_frac", "zero_frac",
                      "finite")


class TreeStats(NamedTuple):
    """Stacked per-leaf statistics (one entry per inexact leaf, in
    ``leaf_paths`` order). All five live on device until one host
    fetch pulls the whole tuple."""

    amax: object            # f32[n]  max |x|
    l2: object              # f32[n]  sqrt(sum x^2)
    underflow_frac: object  # f32[n]  fraction with 0 < |x| < tiny
    zero_frac: object       # f32[n]  fraction exactly zero
    finite: object          # bool[n] all-finite flag


def _key_str(key) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


def _path_leaves(tree):
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [("/".join(_key_str(k) for k in path) or "<root>", leaf)
            for path, leaf in flat]


def _is_inexact(leaf) -> bool:
    import jax.numpy as jnp
    return hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.inexact)


def tree_paths(tree) -> tuple:
    """Slash-joined key path of EVERY leaf, in flatten order."""
    return tuple(p for p, _leaf in _path_leaves(tree))


def leaf_paths(tree) -> tuple:
    """Key paths of the inexact leaves only — the tensors a stats pass
    covers, aligned with the :class:`TreeStats` vectors."""
    return tuple(p for p, leaf in _path_leaves(tree)
                 if _is_inexact(leaf))


def tensor_stats(tree) -> TreeStats:
    """Per-tensor stats for every inexact leaf, on device, jit-safe.

    Call it inside a jitted step (free fusion with the step program) or
    through :class:`StatsCollector` (which jits it standalone). The
    underflow threshold is each leaf's own dtype's smallest normal, so
    a bf16 tensor reports bf16 underflow even though the reduction runs
    in f32.
    """
    import jax.numpy as jnp

    leaves = [leaf for _p, leaf in _path_leaves(tree)
              if _is_inexact(leaf)]
    if not leaves:
        z = jnp.zeros((0,), jnp.float32)
        return TreeStats(z, z, z, z, jnp.zeros((0,), jnp.bool_))
    amax, l2, under, zero, finite = [], [], [], [], []
    for leaf in leaves:
        tiny = float(jnp.finfo(leaf.dtype).tiny)
        x = leaf.astype(jnp.float32)
        ax = jnp.abs(x)
        amax.append(jnp.max(ax))
        l2.append(jnp.sqrt(jnp.sum(x * x)))
        under.append(jnp.mean(((ax > 0) & (ax < tiny)).astype(
            jnp.float32)))
        zero.append(jnp.mean((x == 0).astype(jnp.float32)))
        finite.append(jnp.all(jnp.isfinite(x)))
    return TreeStats(jnp.stack(amax), jnp.stack(l2), jnp.stack(under),
                     jnp.stack(zero), jnp.stack(finite))


def host_tensor_stats(tree, stats: Optional[TreeStats] = None) -> dict:
    """{path: {field: float/bool}} for every inexact leaf — ONE host
    fetch of the stacked vectors (the corrected-sync pull). Pass a
    precomputed ``stats`` to fetch results a jitted step already
    produced."""
    import jax

    paths = leaf_paths(tree)
    if stats is None:
        stats = _jitted_stats()(tree)
    host = jax.device_get(stats)
    out = {}
    for i, path in enumerate(paths):
        out[path] = {
            "amax": float(host.amax[i]),
            "l2": float(host.l2[i]),
            "underflow_frac": float(host.underflow_frac[i]),
            "zero_frac": float(host.zero_frac[i]),
            "finite": bool(host.finite[i]),
        }
    return out


def nonfinite_paths(tree, stats: Optional[TreeStats] = None) -> tuple:
    """Key paths of the leaves containing NaN/Inf (one device
    reduction + one fetch for the whole tree)."""
    per_tensor = host_tensor_stats(tree, stats)
    return tuple(p for p, s in per_tensor.items() if not s["finite"])


def summarize_stats(per_tensor: dict, top_k: int = 3) -> dict:
    """Fold a ``host_tensor_stats`` dict into the compact summary a
    step record / JSON line carries: all-finite flag, the non-finite
    paths, and the top-k tensors by amax."""
    import math

    def rank(s):  # non-finite tensors are the most broken: rank first
        return math.inf if not math.isfinite(s["amax"]) else s["amax"]

    worst = sorted(per_tensor.items(), key=lambda kv: -rank(kv[1]))
    return {
        "tensors": len(per_tensor),
        "finite": all(s["finite"] for s in per_tensor.values()),
        "nonfinite_paths": [p for p, s in per_tensor.items()
                            if not s["finite"]],
        # max over FINITE amaxes only — one NaN tensor must not turn
        # the whole summary (and every gauge built on it) into NaN;
        # the finite flag + nonfinite_paths already carry that fact
        "amax_max": max((s["amax"] for s in per_tensor.values()
                         if math.isfinite(s["amax"])), default=0.0),
        "worst_amax": [[p, round(s["amax"], 6)]
                       for p, s in worst[:top_k]],
        "underflow_frac_max": max(
            (s["underflow_frac"] for s in per_tensor.values()),
            default=0.0),
        "zero_frac_max": max((s["zero_frac"]
                              for s in per_tensor.values()),
                             default=0.0),
    }


_STATS_JIT = None


def _jitted_stats():
    global _STATS_JIT
    if _STATS_JIT is None:
        import jax
        _STATS_JIT = jax.jit(tensor_stats)
    return _STATS_JIT


class StatsCollector:
    """Decimated stats driver: ``observe(tree, step)`` runs the fused
    stats pass + the single host pull every ``every`` steps and
    publishes the ``numerics/*`` family to the registry; off-cadence
    steps cost nothing (not even a dispatch).

    Publishes per pull (all labeled ``source=<name>``):

    - gauge ``numerics/finite`` — 1.0/0.0 whole-tree finite flag (the
      ``--compare`` gate fails a run where this flips 1 → 0);
    - gauges ``numerics/amax_max``, ``numerics/underflow_frac_max``,
      ``numerics/zero_frac_max``;
    - timer ``numerics/stats_pass`` — the pass's own cost (compute +
      the one host fetch), so the <2% overhead budget is measured, not
      assumed;
    - counter ``numerics/stats_pulls``; event ``numerics_stats`` with
      the summary (non-finite paths, top-k amax tensors).

    ``last`` keeps the most recent summary — the ``numerics`` block
    ``StepReporter.step(..., numerics=collector.last)`` attaches.
    """

    def __init__(self, name: str = "numerics", every: int = 16,
                 registry=None, top_k: int = 3):
        self.name = name
        self.every = max(int(every), 1)
        self.top_k = top_k
        self._registry = registry
        self.last: Optional[dict] = None

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from apex_tpu.observability.registry import get_registry
        return get_registry()

    def observe(self, tree, step: int) -> Optional[dict]:
        """Run the pass when ``step`` is on cadence; returns the
        summary dict (also kept as ``last``), or None off-cadence."""
        if step % self.every:
            return None
        reg = self._reg()
        timer = reg.timer("numerics/stats_pass", source=self.name)
        timer.start()
        try:
            per_tensor = host_tensor_stats(tree)
        except BaseException:
            timer.cancel()
            raise
        elapsed = timer.stop()  # the device_get above was the sync
        summary = summarize_stats(per_tensor, top_k=self.top_k)
        summary["step"] = int(step)
        summary["stats_pass_ms"] = round(elapsed * 1e3, 3)
        reg.counter("numerics/stats_pulls", source=self.name).inc()
        reg.gauge("numerics/finite", source=self.name).set(
            1.0 if summary["finite"] else 0.0)
        reg.gauge("numerics/amax_max", source=self.name).set(
            summary["amax_max"])
        reg.gauge("numerics/underflow_frac_max", source=self.name).set(
            summary["underflow_frac_max"])
        reg.gauge("numerics/zero_frac_max", source=self.name).set(
            summary["zero_frac_max"])
        reg.event("numerics_stats", source=self.name, **{
            k: v for k, v in summary.items() if k != "tensors"})
        if not summary["finite"]:
            reg.counter("numerics/nonfinite_pulls",
                        source=self.name).inc()
        self.last = summary
        return summary
