"""apex_tpu.observability.numerics — the numerics observability tier
(ISSUE 9).

The stack could already time, trace and profile every step (ISSUEs
2+7); this package makes it numerically SIGHTED:

- :mod:`~apex_tpu.observability.numerics.stats` — jit-safe
  ``tensor_stats(tree)``: amax / l2 / underflow-fraction /
  zero-fraction / finite-flag for a whole pytree in one fused
  on-device reduction, pulled to host only on the
  :class:`StatsCollector`'s decimated cadence (one fetch per pull,
  corrected-sync rules — never a per-tensor ``block_until_ready``);
- :mod:`~apex_tpu.observability.numerics.history` —
  :class:`AmaxHistory` rings, the fp8 delayed-scaling primitive
  (ROADMAP item 5's substrate); ring state is a pytree that
  checkpoints bit-identical through ``checkpoint.py``'s atomic
  manifest;
- :mod:`~apex_tpu.observability.numerics.nan_probe` — NaN/Inf
  provenance: replay a failing step's jaxpr under the unified
  interpreter's non-finite taint lattice
  (``analysis.interp.NonFiniteLattice``) and name the first offending
  primitive + source location (or the poisoned input tensor paths);
- :mod:`~apex_tpu.observability.numerics.health` —
  :class:`HealthMonitor`: grad-norm-spike, loss-plateau/spike and
  scaler-overflow-streak detectors emitting the ``numerics/*``
  counter family.

Consumers: ``StepReporter`` records carry a ``numerics`` block,
``ResilientTrainLoop`` attaches probe provenance to rollback events
and ``TrainAborted`` reports, the amp scaler's ``report()`` feeds the
streak detector, bench.py emits a ``numerics`` object (stats-pass
overhead budgeted <2% of step time), and
``tools/metrics_report.py --compare`` gates finite→non-finite flips
and >10x grad-norm p50 jumps. Docs: ``docs/observability.md``.
"""

from apex_tpu.observability.numerics.health import (  # noqa: F401
    HealthMonitor,
)
from apex_tpu.observability.numerics.history import (  # noqa: F401
    F8_E4M3_MAX,
    F8_E5M2_MAX,
    AmaxHistory,
    AmaxHistoryState,
)
from apex_tpu.observability.numerics.nan_probe import (  # noqa: F401
    Provenance,
    probe_fn,
    probe_tree,
    step_provenance,
)
from apex_tpu.observability.numerics.stats import (  # noqa: F401
    TENSOR_STAT_FIELDS,
    StatsCollector,
    TreeStats,
    host_tensor_stats,
    leaf_paths,
    nonfinite_paths,
    summarize_stats,
    tensor_stats,
    tree_paths,
)

__all__ = [
    "TENSOR_STAT_FIELDS", "TreeStats", "tensor_stats",
    "host_tensor_stats", "leaf_paths", "tree_paths",
    "nonfinite_paths", "summarize_stats", "StatsCollector",
    "AmaxHistory", "AmaxHistoryState", "F8_E4M3_MAX", "F8_E5M2_MAX",
    "Provenance", "probe_fn", "probe_tree", "step_provenance",
    "HealthMonitor",
]
