import sys

from apex_tpu.observability.cli import main

if __name__ == "__main__":
    sys.exit(main())
