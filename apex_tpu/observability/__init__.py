"""apex_tpu.observability — unified runtime telemetry (ISSUE 2).

The single layer the whole stack reports through:

- :mod:`~apex_tpu.observability.registry` — thread-safe metrics
  (counter/gauge/histogram/corrected-sync timer), structured events,
  JSONL export and the merge/summary reader;
- :mod:`~apex_tpu.observability.scope` — named trace scopes on both the
  host (``TraceAnnotation``) and device (``named_scope`` → HLO metadata)
  timelines, wired into the pipeline/tensor-parallel/DDP/optimizer hot
  paths;
- :mod:`~apex_tpu.observability.recompile` — runtime compile/retrace
  accounting via ``jax.monitoring`` + ``jax_log_compiles``, with a
  budget guard that fails a run on steady-state retraces;
- :mod:`~apex_tpu.observability.step_report` — per-training-step
  records (step time, tokens/s, MFU, loss scale, overflow count);
- :mod:`~apex_tpu.observability.profiling` — span tracing (ring
  buffer + Perfetto export), per-step phase attribution, xplane
  device attribution, and the stall flight recorder (ISSUE 7);
- :mod:`~apex_tpu.observability.numerics` — on-device tensor stats
  (fused amax/l2/underflow/finite pass, decimated host pulls), amax
  history rings (the fp8 delayed-scaling substrate), NaN/Inf
  provenance via jaxpr replay, and training-health detectors
  (ISSUE 9);
- :mod:`~apex_tpu.observability.fleet` — cross-rank telemetry
  (ISSUE 12): rank identity + automatic ``.rank{i}`` artifact
  suffixing, the grad-sync barrier-wait probe + straggler detector,
  on-device desync fingerprints, and the fleet merge readers
  (metrics shards and flight records);
- :mod:`~apex_tpu.observability.memory` — the memory tier (ISSUE 15):
  live HBM telemetry (decimated live-bytes snapshots, watermarks,
  top-k buffers), per-executable compiled memory stats off the
  recompile listener, measured-vs-modeled HBM calibration of the
  sharding cost model, and OOM forensics (``memrec_*.json``);
- :mod:`~apex_tpu.observability.goodput` — the run ledger + goodput
  accounting tier (ISSUE 17): every artifact family normalized into
  one ordered, rank-aware timeline, wall-clock classified into causes
  (productive step / init / compile / data wait / checkpoint / stall /
  preempt drain / restart / rollback replay), and the ``goodput/*``
  gauge family (ratio, lost-seconds-by-cause, badput top-3, fleet
  min); event names are pinned by the
  :mod:`~apex_tpu.observability.events` catalog;
- ``python -m apex_tpu.observability report <metrics.jsonl>`` — the
  summary CLI (also ``tools/metrics_report.py``); ``... trace <run>``
  exports a span dump or xplane capture as Perfetto JSON;
  ``... fleet <shards>`` joins per-rank shards into one fleet view;
  ``... goodput <run>`` renders the run-ledger accounting table.

The modules themselves import jax lazily and never force backend init —
but importing them through the ``apex_tpu`` package still runs the
parent ``__init__`` (which imports jax). Truly backend-free processes
(the bench *launcher*) therefore write the JSONL event format inline
rather than importing this package; the record shape is pinned by
:func:`~apex_tpu.observability.registry.append_event`.
"""

from apex_tpu.observability.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    Timer,
    append_event,
    get_registry,
    read_jsonl,
    set_registry,
    summarize,
)
from apex_tpu.observability.recompile import (  # noqa: F401
    RecompileListener,
    RetraceBudgetExceeded,
    retrace_guard,
)
from apex_tpu.observability.recompile import (  # noqa: F401
    install as install_recompile_listener,
)
from apex_tpu.observability.recompile import (  # noqa: F401
    uninstall as uninstall_recompile_listener,
)
from apex_tpu.observability.profiling import (  # noqa: F401
    FlightRecorder,
    SpanTracer,
    StepPhases,
    get_tracer,
    set_tracer,
    span,
)
from apex_tpu.observability import numerics  # noqa: F401
from apex_tpu.observability.numerics import (  # noqa: F401
    AmaxHistory,
    HealthMonitor,
    StatsCollector,
)
from apex_tpu.observability import memory  # noqa: F401
from apex_tpu.observability.memory import (  # noqa: F401
    CompiledMemoryCapture,
    MemoryMonitor,
    calibrate_targets,
    install_compiled_capture,
)
from apex_tpu.observability import fleet  # noqa: F401
from apex_tpu.observability.fleet import (  # noqa: F401
    DesyncDetector,
    StragglerDetector,
    merge_fleet,
    merge_flight_records,
    process_identity,
    rank_path,
)
from apex_tpu.observability import goodput  # noqa: F401
from apex_tpu.observability.goodput import (  # noqa: F401
    RunLedger,
    ledger_from_records,
)
from apex_tpu.observability.goodput import (  # noqa: F401
    account as account_goodput,
)
from apex_tpu.observability.events import (  # noqa: F401
    EVENT_CATALOG,
    GOODPUT_CRITICAL,
)
from apex_tpu.observability.scope import annotate, scope  # noqa: F401
from apex_tpu.observability.step_report import (  # noqa: F401
    STEP_RECORD_FIELDS,
    StepReporter,
    peak_flops,
    transformer_step_flops,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "Timer", "MetricRegistry",
    "get_registry", "set_registry", "read_jsonl", "summarize",
    "append_event",
    "RecompileListener", "RetraceBudgetExceeded", "retrace_guard",
    "install_recompile_listener", "uninstall_recompile_listener",
    "scope", "annotate",
    "span", "SpanTracer", "get_tracer", "set_tracer",
    "StepPhases", "FlightRecorder",
    "StepReporter", "STEP_RECORD_FIELDS", "peak_flops",
    "transformer_step_flops",
    "numerics", "StatsCollector", "AmaxHistory", "HealthMonitor",
    "memory", "MemoryMonitor", "CompiledMemoryCapture",
    "install_compiled_capture", "calibrate_targets",
    "fleet", "DesyncDetector", "StragglerDetector", "merge_fleet",
    "merge_flight_records", "process_identity", "rank_path",
    "goodput", "RunLedger", "ledger_from_records", "account_goodput",
    "EVENT_CATALOG", "GOODPUT_CRITICAL",
]
