"""``python -m apex_tpu.observability {report,trace,fleet,memory} ...``

``report <metrics.jsonl> [...]`` summarizes one or more metrics JSONL
dumps (bench.py's ``BENCH_METRICS.jsonl``, a training run's step log):
counters sum, gauges keep their last value, histogram/timer stats
merge exactly, events print in order. ``--json`` emits the merged
summary as JSON for scripting; ``--events`` limits how many event
lines print (default 20, 0 = all).

``trace <run> [--out trace.json]`` exports a Perfetto-loadable
trace-event JSON (open at ``ui.perfetto.dev``) from any of:

- a span dump (``SpanTracer.save`` / flight-recorder artifact);
- an xplane capture (``jax.profiler`` logdir, run dir or .xplane.pb).

``fleet <base-or-shards...>`` (ISSUE 12) joins ``.rank{i}``-suffixed
per-rank metrics shards into one fleet view: per-rank step-time
p50/p99, cross-rank skew, the merge-time straggler pass, and every
``fleet/straggler`` / ``fleet/desync`` event. Options:

- ``--json`` — the full fleet report as JSON;
- ``--emit-metrics OUT.jsonl`` — write the fleet view as registry-
  shaped records (``fleet/*`` family) for ``tools/metrics_report.py``
  and its ``--compare`` rank-skew gate;
- ``--trace OUT.json`` — merged Perfetto export of the ranks' span
  dumps/flight records, one **pid per rank**;
- ``--flight DIR`` — instead of metrics shards, merge the
  ``flightrec_*`` shards in DIR into the fleet post-mortem naming the
  stuck rank (written as ``fleetrec_*.json`` unless ``--no-write``).

``memory [--out SNAP.json] [--targets a,b,...]`` (ISSUE 15) takes one
live memory snapshot on the current backend and runs the
measured-vs-modeled HBM calibration over the sharding-flow targets:
device kind + the live ``bytes_limit``, live-buffer totals and top
buffers, and the per-target ``ratio`` table. ``--out`` persists the
snapshot as JSON — on a real TPU relay window this is the cost
model's on-silicon ground truth (``tools/relay_hunter.py`` runs it
per clean window as ``TPU_MEMORY_r0X.json``).

``goodput <run>`` (ISSUE 17) builds the unified run ledger and prints
the goodput accounting table: ``run`` is a metrics JSONL (any
``.rank{i}`` shard names its whole family), a directory of run
artifacts (every ``*.jsonl`` plus ``flightrec_*``/``memrec_*``/
``fleetrec_*`` post-mortems), or a previously saved run-ledger JSON
(re-accounted without re-ingesting). Options:

- ``--wall S`` — the run's real wall-clock seconds; bounds the
  ``unknown`` bucket (events carry no wall timestamps, so idle gaps
  are invisible without it);
- ``--json`` — the accounting object as JSON;
- ``--out LEDGER.json`` — persist the (byte-stable) ledger;
- ``--trace OUT.json`` — Perfetto export, one track per cause;
- ``--records DIR`` / ``--ckpt DIR`` — fold in a post-mortem
  directory / the checkpoint manifest's committed steps.

Exit codes: 0 ok, 1 no records found (memory: no calibration ratio
landed; goodput: nothing ledger-relevant), 2 bad usage / unreadable
file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from apex_tpu.observability.registry import read_jsonl, summarize


def _fmt_num(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _render(summary: dict, events_limit: int) -> str:
    lines = []
    if summary["counters"]:
        lines.append("counters:")
        for name, v in summary["counters"].items():
            lines.append(f"  {name:48s} {_fmt_num(v)}")
    if summary["gauges"]:
        lines.append("gauges:")
        for name, v in summary["gauges"].items():
            lines.append(f"  {name:48s} {_fmt_num(v)}")
    if summary["histograms"]:
        lines.append("histograms:")
        for name, h in summary["histograms"].items():
            parts = [f"n={_fmt_num(h.get('count'))}",
                     f"mean={_fmt_num(h.get('mean'))}",
                     f"min={_fmt_num(h.get('min'))}",
                     f"max={_fmt_num(h.get('max'))}"]
            for q in ("p50", "p90", "p99"):
                if h.get(q) is not None:
                    parts.append(f"{q}={_fmt_num(h[q])}")
            if h.get("unit"):
                parts.append(h["unit"])
            lines.append(f"  {name:48s} " + "  ".join(parts))
    events = summary["events"]
    if events:
        shown = events if events_limit == 0 else events[-events_limit:]
        lines.append(f"events ({len(events)} total, "
                     f"showing {len(shown)}):")
        for ev in shown:
            fields = ev.get("fields") or {}
            body = "  ".join(f"{k}={_fmt_num(v) if not isinstance(v, str) else v}"
                             for k, v in fields.items())
            lines.append(f"  [{ev.get('name')}] {body}")
    if summary["parse_errors"]:
        lines.append(f"({summary['parse_errors']} unparseable line(s) "
                     f"skipped)")
    return "\n".join(lines)


def _trace_events_for(run: str):
    """(events, source_kind) for a run path: a span dump / flight
    record (host spans) or an xplane capture dir/file (device ops)."""
    from apex_tpu.observability import profiling

    if os.path.isfile(run) and run.endswith(".json"):
        with open(run) as f:
            head = json.load(f)
        kind = head.get("kind") if isinstance(head, dict) else None
        sources = {"apex_tpu.spans": "span-dump",
                   "apex_tpu.flight_record": "flight-record"}
        if kind in sources:
            # both dump kinds embed the identical span/thread_names
            # layout; decode the payload already in hand (a ring dump
            # is multi-MB — re-parsing it via load_spans doubled the
            # work) through the one shared schema gate
            spans, names = profiling.decode_span_payload(
                head, where=run, kinds=tuple(sources))
            return profiling.to_trace_events(
                spans, thread_names=names,
                pid=head.get("pid", 0)), sources[kind]
        raise ValueError(
            f"{run}: JSON is neither a span dump nor a flight record")
    # anything else: treat as an xplane capture location
    return profiling.capture_trace_events(run), "xplane"


def trace_main(args) -> int:
    try:
        events, source = _trace_events_for(args.run)
    except (OSError, ValueError, ImportError) as e:
        print(f"cannot read {args.run}: {e}", file=sys.stderr)
        return 2
    if not any(ev.get("ph") in ("B", "E", "X") for ev in events):
        print(f"no trace events in {args.run}", file=sys.stderr)
        return 1
    base = args.run.rstrip("/")
    out = args.out or (os.path.splitext(base)[0] + ".perfetto.json")
    try:
        with open(out, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"},
                      f)
    except OSError as e:
        print(f"cannot write {out}: {e}", file=sys.stderr)
        return 2
    n = sum(1 for ev in events if ev.get("ph") in ("B", "X"))
    print(f"wrote {out} ({n} span(s) from {source}; open at "
          f"ui.perfetto.dev)")
    return 0


def _render_fleet(report: dict) -> str:
    lines = [f"fleet: {report['rank_count']} rank shard(s)"
             + (f" + {report['legacy_shards']} legacy un-suffixed"
                if report.get("legacy_shards") else "")]
    for rank, info in report["ranks"].items():
        ident = info.get("identity") or {}
        run = ident.get("run_id")
        lines.append(f"  rank {rank}: {os.path.basename(info['path'])}"
                     + (f"  run_id={run}" if run else ""))
    for metric, row in sorted(report["step_time_skew"].items()):
        lines.append(f"  {metric}: fleet median p50 "
                     f"{row['fleet_median_p50']:.3f} ms  skew "
                     f"{row['skew']:+.1%} (slowest rank "
                     f"{row['max_rank']})")
        for rank, p50 in sorted(row["p50_by_rank"].items()):
            p99 = row["p99_by_rank"].get(rank)
            p99_s = f"  p99 {p99:.3f}" if isinstance(
                p99, (int, float)) else ""
            lines.append(f"    rank {rank}: p50 {p50:.3f} ms{p99_s}")
    for verdict in report["stragglers"]:
        lines.append(f"  STRAGGLER rank {verdict['rank']} on "
                     f"{verdict['metric']} (skew {verdict['skew']:.2f})")
    for ev in report["fleet_events"]:
        fields = ev.get("fields") or {}
        body = "  ".join(f"{k}={v}" for k, v in fields.items())
        lines.append(f"  [{ev.get('name')}] rank {ev.get('rank')} "
                     f"{body}")
    if not report["step_time_skew"] and not report["fleet_events"]:
        lines.append("  (no step-time metrics or fleet events in the "
                     "shards)")
    return "\n".join(lines)


def fleet_main(args) -> int:
    from apex_tpu.observability import fleet

    if args.flight:
        try:
            merged = fleet.merge_flight_records(args.flight,
                                                run_id=args.run_id)
        except (OSError, ValueError) as e:
            print(f"cannot merge flight records: {e}", file=sys.stderr)
            return 2 if not isinstance(e, FileNotFoundError) else 1
        if not args.no_write:
            merged["written"] = fleet.write_fleet_record(
                merged, args.flight)
        if args.json:
            print(json.dumps(merged, indent=2))
        else:
            print(f"fleet flight record: {merged['rank_count']} rank(s)")
            for rank, info in merged["ranks"].items():
                where = info.get("last_collective")
                print(f"  rank {rank}: step {info.get('step')} "
                      f"trigger={info.get('trigger')}"
                      + (f" last_collective={where}" if where else ""))
            print(f"  verdict: {merged['verdict'] or 'no stuck rank'}")
            if merged.get("written"):
                print(f"  wrote {merged['written']}")
        return 0
    if not args.paths:
        print("fleet needs shard path(s) or --flight DIR",
              file=sys.stderr)
        return 2
    if args.trace:
        # trace mode: the positional paths are SPAN-DUMP / flight-
        # record shards (rank from the .rank{i} suffix, else the
        # payload's process_index stamp)
        rank_dumps = []
        for path in args.paths:
            rank = fleet.rank_of_path(path)
            if rank is None:
                try:
                    with open(path) as f:
                        rank = json.load(f).get("process_index")
                except (OSError, ValueError) as e:
                    print(f"cannot read {path}: {e}", file=sys.stderr)
                    return 2
            rank_dumps.append((rank, path))
        # legacy shards with neither suffix nor stamp get distinct
        # fallback pids — two of them merging into one Perfetto lane
        # would misrepresent two processes as one
        taken = {r for r, _ in rank_dumps if r is not None}
        next_free = 0
        for i, (rank, path) in enumerate(rank_dumps):
            if rank is None:
                while next_free in taken:
                    next_free += 1
                taken.add(next_free)
                rank_dumps[i] = (next_free, path)
        if len({r for r, _ in rank_dumps}) != len(rank_dumps):
            dupes = sorted(r for r, _ in rank_dumps)
            print(f"duplicate rank(s) across shards: {dupes} — pass "
                  f"one shard per rank", file=sys.stderr)
            return 2
        try:
            events = fleet.fleet_trace_events(rank_dumps)
            with open(args.trace, "w") as f:
                json.dump({"traceEvents": events,
                           "displayTimeUnit": "ms"}, f)
        except (OSError, ValueError) as e:
            print(f"cannot write fleet trace: {e}", file=sys.stderr)
            return 2
        print(f"wrote {args.trace} ({len(rank_dumps)} rank(s), one pid "
              f"per rank; open at ui.perfetto.dev)")
        return 0
    base = args.paths[0] if len(args.paths) == 1 else list(args.paths)
    try:
        report = fleet.merge_fleet(base, run_id=args.run_id)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1
    except (OSError, ValueError) as e:
        print(f"cannot merge fleet shards: {e}", file=sys.stderr)
        return 2
    if args.emit_metrics:
        records = fleet.fleet_metric_records(report)
        try:
            with open(args.emit_metrics, "w") as f:
                for rec in records:
                    f.write(json.dumps(rec) + "\n")
        except OSError as e:
            print(f"cannot write {args.emit_metrics}: {e}",
                  file=sys.stderr)
            return 2
        print(f"wrote {args.emit_metrics} ({len(records)} record(s))",
              file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(_render_fleet(report))
    return 0


def memory_main(args) -> int:
    from apex_tpu.observability import memory as memory_mod

    names = None
    if args.targets:
        names = tuple(t for t in args.targets.split(",") if t)
    try:
        calibration = memory_mod.calibrate_targets(names=names)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    snapshot = memory_mod.memory_snapshot(top_k=args.top_k)
    import jax

    dev = jax.devices()[0]
    payload = {
        "kind": "apex_tpu.memory_snapshot",
        "schema_version": memory_mod.MEMORY_SCHEMA_VERSION,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "device_count": jax.device_count(),
        "snapshot": snapshot,
        "calibration": calibration,
    }
    try:
        from apex_tpu.ops.pallas_config import device_hbm_bytes

        payload["device_hbm_bytes"] = device_hbm_bytes()
    except Exception as e:  # noqa: BLE001 — a malformed live limit is
        # loud in the payload, not fatal to the snapshot
        payload["device_hbm_bytes_error"] = repr(e)[:200]
    if args.out:
        try:
            with open(args.out, "w") as f:
                json.dump(payload, f, indent=1, default=repr)
        except OSError as e:
            print(f"cannot write {args.out}: {e}", file=sys.stderr)
            return 2
        print(f"wrote {args.out}")
    else:
        print(json.dumps(payload, indent=2, default=repr))
    ratios = [row for row in calibration.values() if "ratio" in row]
    for name, row in sorted(calibration.items()):
        if "ratio" in row:
            print(f"  {name}: ratio {row['ratio']:.3f}x "
                  f"(modeled {row['modeled_bytes']} B, measured "
                  f"{row['measured_bytes']} B)", file=sys.stderr)
        else:
            print(f"  {name}: SKIPPED {row['error']}", file=sys.stderr)
    return 0 if ratios else 1


def goodput_main(args) -> int:
    import glob as glob_mod

    from apex_tpu.observability import goodput as goodput_mod
    from apex_tpu.observability.fleet.identity import rank_of_path

    run = args.run
    try:
        if os.path.isdir(run):
            ledger = goodput_mod.RunLedger()
            for path in sorted(glob_mod.glob(os.path.join(run,
                                                          "*.jsonl"))):
                ledger.ingest_records(read_jsonl(path),
                                      rank=rank_of_path(path),
                                      where=path)
            ledger.ingest_record_dir(run)
        elif run.endswith(".jsonl"):
            ledger = goodput_mod.RunLedger()
            ledger.ingest_metrics(run)
        else:
            ledger = goodput_mod.RunLedger.load(run)
        if args.records:
            ledger.ingest_record_dir(args.records)
        if args.ckpt:
            ledger.ingest_checkpoints(args.ckpt)
    except (OSError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    if not ledger.intervals:
        print("no goodput-relevant records found", file=sys.stderr)
        return 1
    accounting, segments = goodput_mod.classify(ledger,
                                                wall_s=args.wall)
    try:
        if args.out:
            ledger.save(args.out)
            print(f"wrote {args.out}", file=sys.stderr)
        if args.trace:
            with open(args.trace, "w") as f:
                json.dump({"traceEvents":
                           goodput_mod.to_trace_events(segments),
                           "displayTimeUnit": "ms"}, f)
            print(f"wrote {args.trace}", file=sys.stderr)
    except OSError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(accounting, indent=2, sort_keys=True))
    else:
        print(goodput_mod.render(accounting))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.observability",
        description="apex_tpu runtime telemetry tools")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="summarize metrics JSONL dump(s)")
    rp.add_argument("paths", nargs="+", help="metrics .jsonl file(s)")
    rp.add_argument("--json", action="store_true",
                    help="emit the merged summary as JSON")
    rp.add_argument("--events", type=int, default=20,
                    help="max event lines to print (0 = all)")
    tp = sub.add_parser(
        "trace", help="export a Perfetto trace-event JSON from a span "
                      "dump, flight record, or xplane capture")
    tp.add_argument("run", help="span dump .json, flight record .json, "
                                "or jax.profiler logdir/.xplane.pb")
    tp.add_argument("--out", default="",
                    help="output path (default: <run>.perfetto.json)")
    fp = sub.add_parser(
        "fleet", help="join per-rank .rank{i} telemetry shards into "
                      "one fleet view (ISSUE 12)")
    fp.add_argument("paths", nargs="*",
                    help="metrics shard base/path(s); with --trace, "
                         "span-dump/flight-record shards")
    fp.add_argument("--json", action="store_true",
                    help="emit the fleet report as JSON")
    fp.add_argument("--run-id", default=None,
                    help="only merge shards stamped with this run_id")
    fp.add_argument("--emit-metrics", default="",
                    help="also write the fleet view as registry-shaped "
                         "JSONL (fleet/* family) to this path")
    fp.add_argument("--trace", default="",
                    help="merged Perfetto export of span-dump shards, "
                         "one pid per rank, to this path")
    fp.add_argument("--flight", default="",
                    help="merge the flightrec_* shards in this "
                         "directory instead of metrics shards")
    fp.add_argument("--no-write", action="store_true",
                    help="with --flight: don't persist the merged "
                         "fleetrec_*.json")
    mp = sub.add_parser(
        "memory", help="live memory snapshot + measured-vs-modeled "
                       "HBM calibration (ISSUE 15)")
    mp.add_argument("--out", default="",
                    help="persist the snapshot JSON here (default: "
                         "print to stdout)")
    mp.add_argument("--targets", default="",
                    help="comma-separated sharding-flow target names "
                         "(default: the calibration set)")
    mp.add_argument("--top-k", type=int, default=5,
                    help="how many largest buffers the snapshot keeps")
    gp = sub.add_parser(
        "goodput", help="run ledger + goodput accounting (ISSUE 17)")
    gp.add_argument("run",
                    help="metrics .jsonl (any .rank shard names its "
                         "family), a run-artifact directory, or a "
                         "saved run-ledger .json")
    gp.add_argument("--json", action="store_true",
                    help="emit the accounting object as JSON")
    gp.add_argument("--wall", type=float, default=None,
                    help="run wall-clock seconds — bounds the unknown "
                         "bucket (default: sum of attributed time)")
    gp.add_argument("--out", default="",
                    help="persist the run ledger JSON here")
    gp.add_argument("--trace", default="",
                    help="Perfetto export (one track per cause) to "
                         "this path")
    gp.add_argument("--records", default="",
                    help="directory of flightrec_*/memrec_*/fleetrec_* "
                         "post-mortems to fold into the ledger")
    gp.add_argument("--ckpt", default="",
                    help="checkpoint directory — record its committed "
                         "steps in the ledger")
    args = ap.parse_args(argv)
    if args.cmd == "trace":
        return trace_main(args)
    if args.cmd == "fleet":
        return fleet_main(args)
    if args.cmd == "memory":
        return memory_main(args)
    if args.cmd == "goodput":
        return goodput_main(args)

    records = []
    for path in args.paths:
        try:
            records.extend(read_jsonl(path))
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
    if not records:
        print("no records found", file=sys.stderr)
        return 1
    summary = summarize(records)
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(_render(summary, args.events))
    return 0
