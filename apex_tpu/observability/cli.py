"""``python -m apex_tpu.observability report <metrics.jsonl> [...]``

Summarize one or more metrics JSONL dumps (bench.py's
``BENCH_METRICS.jsonl``, a training run's step log): counters sum,
gauges keep their last value, histogram/timer stats merge exactly,
events print in order. ``--json`` emits the merged summary as JSON for
scripting; ``--events`` limits how many event lines print (default 20,
0 = all).

Exit codes: 0 ok, 1 no records found, 2 bad usage / unreadable file.
"""

from __future__ import annotations

import argparse
import json
import sys

from apex_tpu.observability.registry import read_jsonl, summarize


def _fmt_num(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _render(summary: dict, events_limit: int) -> str:
    lines = []
    if summary["counters"]:
        lines.append("counters:")
        for name, v in summary["counters"].items():
            lines.append(f"  {name:48s} {_fmt_num(v)}")
    if summary["gauges"]:
        lines.append("gauges:")
        for name, v in summary["gauges"].items():
            lines.append(f"  {name:48s} {_fmt_num(v)}")
    if summary["histograms"]:
        lines.append("histograms:")
        for name, h in summary["histograms"].items():
            parts = [f"n={_fmt_num(h.get('count'))}",
                     f"mean={_fmt_num(h.get('mean'))}",
                     f"min={_fmt_num(h.get('min'))}",
                     f"max={_fmt_num(h.get('max'))}"]
            for q in ("p50", "p90", "p99"):
                if h.get(q) is not None:
                    parts.append(f"{q}={_fmt_num(h[q])}")
            if h.get("unit"):
                parts.append(h["unit"])
            lines.append(f"  {name:48s} " + "  ".join(parts))
    events = summary["events"]
    if events:
        shown = events if events_limit == 0 else events[-events_limit:]
        lines.append(f"events ({len(events)} total, "
                     f"showing {len(shown)}):")
        for ev in shown:
            fields = ev.get("fields") or {}
            body = "  ".join(f"{k}={_fmt_num(v) if not isinstance(v, str) else v}"
                             for k, v in fields.items())
            lines.append(f"  [{ev.get('name')}] {body}")
    if summary["parse_errors"]:
        lines.append(f"({summary['parse_errors']} unparseable line(s) "
                     f"skipped)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.observability",
        description="apex_tpu runtime telemetry tools")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="summarize metrics JSONL dump(s)")
    rp.add_argument("paths", nargs="+", help="metrics .jsonl file(s)")
    rp.add_argument("--json", action="store_true",
                    help="emit the merged summary as JSON")
    rp.add_argument("--events", type=int, default=20,
                    help="max event lines to print (0 = all)")
    args = ap.parse_args(argv)

    records = []
    for path in args.paths:
        try:
            records.extend(read_jsonl(path))
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
    if not records:
        print("no records found", file=sys.stderr)
        return 1
    summary = summarize(records)
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(_render(summary, args.events))
    return 0
