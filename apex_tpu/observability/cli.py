"""``python -m apex_tpu.observability {report,trace} ...``

``report <metrics.jsonl> [...]`` summarizes one or more metrics JSONL
dumps (bench.py's ``BENCH_METRICS.jsonl``, a training run's step log):
counters sum, gauges keep their last value, histogram/timer stats
merge exactly, events print in order. ``--json`` emits the merged
summary as JSON for scripting; ``--events`` limits how many event
lines print (default 20, 0 = all).

``trace <run> [--out trace.json]`` exports a Perfetto-loadable
trace-event JSON (open at ``ui.perfetto.dev``) from any of:

- a span dump (``SpanTracer.save`` / flight-recorder artifact);
- an xplane capture (``jax.profiler`` logdir, run dir or .xplane.pb).

Exit codes: 0 ok, 1 no records found, 2 bad usage / unreadable file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from apex_tpu.observability.registry import read_jsonl, summarize


def _fmt_num(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _render(summary: dict, events_limit: int) -> str:
    lines = []
    if summary["counters"]:
        lines.append("counters:")
        for name, v in summary["counters"].items():
            lines.append(f"  {name:48s} {_fmt_num(v)}")
    if summary["gauges"]:
        lines.append("gauges:")
        for name, v in summary["gauges"].items():
            lines.append(f"  {name:48s} {_fmt_num(v)}")
    if summary["histograms"]:
        lines.append("histograms:")
        for name, h in summary["histograms"].items():
            parts = [f"n={_fmt_num(h.get('count'))}",
                     f"mean={_fmt_num(h.get('mean'))}",
                     f"min={_fmt_num(h.get('min'))}",
                     f"max={_fmt_num(h.get('max'))}"]
            for q in ("p50", "p90", "p99"):
                if h.get(q) is not None:
                    parts.append(f"{q}={_fmt_num(h[q])}")
            if h.get("unit"):
                parts.append(h["unit"])
            lines.append(f"  {name:48s} " + "  ".join(parts))
    events = summary["events"]
    if events:
        shown = events if events_limit == 0 else events[-events_limit:]
        lines.append(f"events ({len(events)} total, "
                     f"showing {len(shown)}):")
        for ev in shown:
            fields = ev.get("fields") or {}
            body = "  ".join(f"{k}={_fmt_num(v) if not isinstance(v, str) else v}"
                             for k, v in fields.items())
            lines.append(f"  [{ev.get('name')}] {body}")
    if summary["parse_errors"]:
        lines.append(f"({summary['parse_errors']} unparseable line(s) "
                     f"skipped)")
    return "\n".join(lines)


def _trace_events_for(run: str):
    """(events, source_kind) for a run path: a span dump / flight
    record (host spans) or an xplane capture dir/file (device ops)."""
    from apex_tpu.observability import profiling

    if os.path.isfile(run) and run.endswith(".json"):
        with open(run) as f:
            head = json.load(f)
        kind = head.get("kind") if isinstance(head, dict) else None
        sources = {"apex_tpu.spans": "span-dump",
                   "apex_tpu.flight_record": "flight-record"}
        if kind in sources:
            # both dump kinds embed the identical span/thread_names
            # layout; decode the payload already in hand (a ring dump
            # is multi-MB — re-parsing it via load_spans doubled the
            # work) through the one shared schema gate
            spans, names = profiling.decode_span_payload(
                head, where=run, kinds=tuple(sources))
            return profiling.to_trace_events(
                spans, thread_names=names,
                pid=head.get("pid", 0)), sources[kind]
        raise ValueError(
            f"{run}: JSON is neither a span dump nor a flight record")
    # anything else: treat as an xplane capture location
    return profiling.capture_trace_events(run), "xplane"


def trace_main(args) -> int:
    try:
        events, source = _trace_events_for(args.run)
    except (OSError, ValueError, ImportError) as e:
        print(f"cannot read {args.run}: {e}", file=sys.stderr)
        return 2
    if not any(ev.get("ph") in ("B", "E", "X") for ev in events):
        print(f"no trace events in {args.run}", file=sys.stderr)
        return 1
    base = args.run.rstrip("/")
    out = args.out or (os.path.splitext(base)[0] + ".perfetto.json")
    try:
        with open(out, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"},
                      f)
    except OSError as e:
        print(f"cannot write {out}: {e}", file=sys.stderr)
        return 2
    n = sum(1 for ev in events if ev.get("ph") in ("B", "X"))
    print(f"wrote {out} ({n} span(s) from {source}; open at "
          f"ui.perfetto.dev)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.observability",
        description="apex_tpu runtime telemetry tools")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="summarize metrics JSONL dump(s)")
    rp.add_argument("paths", nargs="+", help="metrics .jsonl file(s)")
    rp.add_argument("--json", action="store_true",
                    help="emit the merged summary as JSON")
    rp.add_argument("--events", type=int, default=20,
                    help="max event lines to print (0 = all)")
    tp = sub.add_parser(
        "trace", help="export a Perfetto trace-event JSON from a span "
                      "dump, flight record, or xplane capture")
    tp.add_argument("run", help="span dump .json, flight record .json, "
                                "or jax.profiler logdir/.xplane.pb")
    tp.add_argument("--out", default="",
                    help="output path (default: <run>.perfetto.json)")
    args = ap.parse_args(argv)
    if args.cmd == "trace":
        return trace_main(args)

    records = []
    for path in args.paths:
        try:
            records.extend(read_jsonl(path))
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
    if not records:
        print("no records found", file=sys.stderr)
        return 1
    summary = summarize(records)
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(_render(summary, args.events))
    return 0
