"""The serving engine: request loop + telemetry + preemption contract.

``ServingEngine`` wires :class:`ContinuousBatchScheduler` to llama
weights, publishes the ``serving/*`` metric family on the registry,
and implements the PR 5 preemption contract for servers: when the
watcher (or a seeded fault plan) trips between iterations, the engine
stops admitting, drains (the decode loop is host-synchronous, so the
in-flight step has already landed by the time the flag is polled),
emergency-dumps queue + in-flight cache state, and raises
:class:`~apex_tpu.resilience.loop.Preempted` (exit code 75 via
``exit_on_preempt=True`` for process-level supervisors).
:meth:`ServingEngine.resume` rebuilds from the dump — restored K/V
pages land by scatter, not re-prefill, so every resumed request's
remaining tokens are bit-identical to the uninterrupted run.

The dump layout under ``dump_dir``:

- ``kv_pages.npz`` — per-request gathered page arrays (written first);
- ``state.json`` — schema, engine geometry, queued + in-flight request
  records, completed results (written LAST, atomically: its presence
  marks a complete dump).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from apex_tpu.resilience.loop import Preempted
from apex_tpu.resilience.preemption import EXIT_PREEMPTED
from apex_tpu.serving.kv_cache import derive_page_budget
from apex_tpu.serving.scheduler import (
    ContinuousBatchScheduler,
    Request,
    pages_per_request,
)

__all__ = ["ServerMetrics", "ServingEngine"]

DUMP_SCHEMA_VERSION = 1
_STATE_FILE = "state.json"
_PAGES_FILE = "kv_pages.npz"

# engine-geometry keys that must survive a dump/resume round trip:
# identical shapes => identical reduction trees => bit-identical tokens
_GEOMETRY_KEYS = ("page_size", "max_batch", "num_pages",
                  "max_prompt_len", "max_new_cap", "weight_mode",
                  "eos_id")


class ServerMetrics:
    """The ``serving/*`` family on the PR 2 registry: request latency
    and time-to-first-token histograms, lifecycle counters, and the
    occupancy/utilization gauges the bench mirrors into its JSON."""

    def __init__(self, registry=None):
        if registry is None:
            from apex_tpu.observability import get_registry
            registry = get_registry()
        self.registry = registry

    def submitted(self) -> None:
        self.registry.counter("serving/requests_submitted").inc()

    def admitted(self) -> None:
        self.registry.counter("serving/requests_admitted").inc()

    def completed(self, req: Request) -> None:
        self.registry.counter("serving/requests_completed").inc()
        self.registry.counter("serving/tokens_generated").inc(
            len(req.tokens))
        if req.submit_s is not None and req.finish_s is not None:
            self.registry.histogram("serving/request_latency_ms").observe(
                (req.finish_s - req.submit_s) * 1e3)
        if req.submit_s is not None and req.first_token_s is not None:
            self.registry.histogram("serving/ttft_ms").observe(
                (req.first_token_s - req.submit_s) * 1e3)

    def preempted(self, n_outstanding: int) -> None:
        self.registry.counter("serving/requests_preempted").inc(
            n_outstanding)

    def step(self, occupancy: float, page_utilization: float) -> None:
        self.registry.gauge("serving/batch_occupancy").set(occupancy)
        self.registry.gauge("serving/page_utilization").set(
            page_utilization)

    def publish_summary(self, summary: dict) -> None:
        """Mirror a loadgen report's scalars as ``serving/*`` gauges —
        the bench JSON and the metric family stay one source."""
        for key in ("latency_p50_ms", "latency_p99_ms", "ttft_p50_ms",
                    "ttft_p99_ms", "tokens_per_s", "mean_occupancy"):
            value = summary.get(key)
            if value is not None:
                self.registry.gauge(f"serving/{key}").set(float(value))


class ServingEngine:
    """Continuous-batching inference server over llama weights.

    ``num_pages=None`` derives the page budget from the calibrated
    memory tier (:func:`derive_page_budget`), capped at what
    ``max_batch`` concurrent worst-case requests can ever use — the
    budget bounds the cache, the workload bounds the budget.
    """

    def __init__(self, params, cfg, *, page_size: int = 8,
                 max_batch: int = 4, num_pages: Optional[int] = None,
                 max_prompt_len: int = 64, max_new_cap: int = 32,
                 weight_mode: str = "native",
                 eos_id: Optional[int] = None,
                 watcher=None, fault_plan=None, registry=None,
                 dump_dir: Optional[str] = None,
                 exit_on_preempt: bool = False,
                 hbm_safety: float = 0.90):
        self.page_budget = None
        need = max_batch * pages_per_request(max_prompt_len,
                                             max_new_cap, page_size)
        if num_pages is None:
            self.page_budget = derive_page_budget(cfg, page_size,
                                                  safety=hbm_safety)
            num_pages = min(self.page_budget.pages, need)
            one = pages_per_request(max_prompt_len, max_new_cap,
                                    page_size)
            if num_pages < one:
                raise ValueError(
                    f"calibrated page budget {self.page_budget.pages} "
                    f"cannot hold one worst-case request ({one} pages)"
                    f" — lower max_prompt_len/max_new_cap or free HBM "
                    f"(budget: {self.page_budget})")
        self.scheduler = ContinuousBatchScheduler(
            params, cfg, num_pages=num_pages, page_size=page_size,
            max_batch=max_batch, max_prompt_len=max_prompt_len,
            max_new_cap=max_new_cap, weight_mode=weight_mode,
            eos_id=eos_id)
        self.metrics = ServerMetrics(registry)
        self.watcher = watcher
        self.fault_plan = fault_plan
        self.dump_dir = dump_dir
        self.exit_on_preempt = exit_on_preempt
        self.results: Dict[int, dict] = {}
        self.completed: List[Request] = []
        self.iteration = 0
        self.draining = False
        self._next_rid = 0
        self._occ_sum = 0.0
        self._occ_steps = 0
        self._config = {
            "page_size": page_size, "max_batch": max_batch,
            "num_pages": num_pages, "max_prompt_len": max_prompt_len,
            "max_new_cap": max_new_cap,
            "weight_mode": self.scheduler.weight_mode,
            "eos_id": eos_id,
        }

    # -------------------------------------------------------- requests

    @property
    def pending(self) -> bool:
        return self.scheduler.has_work()

    def submit(self, prompt, max_new_tokens: int,
               rid: Optional[int] = None,
               arrival_s: float = 0.0) -> int:
        if self.draining:
            raise RuntimeError("engine is draining; not admitting")
        if rid is None:
            rid = self._next_rid
        self._next_rid = max(self._next_rid, rid + 1)
        req = Request(rid=rid,
                      prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=int(max_new_tokens),
                      arrival_s=float(arrival_s),
                      submit_s=time.monotonic())
        self.scheduler.submit(req)
        self.metrics.submitted()
        return rid

    # ------------------------------------------------------------ loop

    def step(self) -> List[Request]:
        """One engine iteration: poll preemption, admit, decode, evict.
        Returns the requests finished this iteration."""
        self._poll_preemption()
        admitted, finished = self.scheduler.try_admit()
        for _ in admitted:
            self.metrics.admitted()
        occ = self.scheduler.occupancy()
        if self.scheduler.num_active():
            self._occ_sum += occ
            self._occ_steps += 1
        self.metrics.step(occ, self.scheduler.cache.utilization())
        finished = finished + self.scheduler.step_decode()
        for req in finished:
            self._finish(req)
        self.iteration += 1
        return finished

    def run(self, max_iterations: int = 100_000,
            retrace_guard: bool = True) -> Dict[int, dict]:
        """Drive until the queue and every slot are empty. The retrace
        guard is the acceptance contract: steady-state decode must
        never recompile, whatever batch compositions occurred."""
        steps = 0
        while self.pending:
            if steps >= max_iterations:
                raise RuntimeError(
                    f"engine made no exit after {max_iterations} "
                    f"iterations — scheduler wedged?")
            self.step()
            steps += 1
        if retrace_guard:
            retraces = self.scheduler.decode_retraces()
            if retraces:
                raise RuntimeError(
                    f"decode step retraced {retraces}x in steady "
                    f"state — batch composition leaked into shapes")
        return self.results

    def mean_occupancy(self) -> float:
        return self._occ_sum / self._occ_steps if self._occ_steps else 0.0

    def _finish(self, req: Request) -> None:
        self.results[req.rid] = {
            "prompt": [int(t) for t in req.prompt],
            "tokens": [int(t) for t in req.tokens],
        }
        self.completed.append(req)
        self.metrics.completed(req)

    # ------------------------------------------------------ preemption

    def _poll_preemption(self) -> None:
        reason = None
        if (self.fault_plan is not None
                and self.fault_plan.should_fire("preempt",
                                                self.iteration)):
            reason = f"fault-plan preempt@{self.iteration}"
        if (reason is None and self.watcher is not None
                and self.watcher.check()):
            reason = self.watcher.reason or "preempted"
        if reason is not None:
            self._drain(reason)

    def _drain(self, reason: str) -> None:
        """The server drain: stop admitting (in-flight decode has
        already landed — the loop is host-synchronous), dump, exit."""
        self.draining = True
        queued, inflight, arrays = self.scheduler.export_requests()
        path = self.dump_dir
        if path is not None:
            os.makedirs(path, exist_ok=True)
            np.savez(os.path.join(path, _PAGES_FILE), **arrays)
            state = {
                "schema_version": DUMP_SCHEMA_VERSION,
                "iteration": self.iteration,
                "reason": reason,
                "next_rid": self._next_rid,
                "engine": dict(self._config),
                "queued": queued,
                "inflight": inflight,
                "completed": {str(rid): res
                              for rid, res in self.results.items()},
            }
            tmp = os.path.join(path, _STATE_FILE + ".tmp")
            with open(tmp, "w") as f:
                json.dump(state, f, indent=1, sort_keys=True)
            os.replace(tmp, os.path.join(path, _STATE_FILE))
        self.metrics.preempted(len(queued) + len(inflight))
        self.metrics.registry.event(
            "serving_drain", reason=reason, iteration=self.iteration,
            inflight=len(inflight), queued=len(queued),
            dump_dir=path or "")
        if self.exit_on_preempt:
            sys.exit(EXIT_PREEMPTED)
        raise Preempted(self.iteration, path, reason)

    # ---------------------------------------------------------- resume

    @classmethod
    def resume(cls, dump_dir: str, params, cfg,
               **overrides) -> "ServingEngine":
        """Rebuild an engine from an emergency dump. Geometry defaults
        to the dumped engine's (same shapes → bit-identical remaining
        tokens); runtime wiring (watcher, fault_plan, registry,
        dump_dir, exit_on_preempt) comes from ``overrides``."""
        with open(os.path.join(dump_dir, _STATE_FILE)) as f:
            state = json.load(f)
        if state.get("schema_version") != DUMP_SCHEMA_VERSION:
            raise ValueError(
                f"serving dump at {dump_dir} has schema_version "
                f"{state.get('schema_version')}; this engine reads "
                f"[{DUMP_SCHEMA_VERSION}]")
        kw = {k: state["engine"][k] for k in _GEOMETRY_KEYS}
        kw.setdefault("dump_dir", dump_dir)
        kw.update(overrides)
        engine = cls(params, cfg, **kw)
        engine.iteration = state["iteration"]
        engine._next_rid = state["next_rid"]
        engine.results = {int(rid): res
                          for rid, res in state["completed"].items()}
        pages_path = os.path.join(dump_dir, _PAGES_FILE)
        with np.load(pages_path) as pages:
            for rec in state["inflight"]:
                engine.scheduler.import_request(
                    rec, pages[f"k_{rec['rid']}"],
                    pages[f"v_{rec['rid']}"])
                engine.metrics.submitted()
                engine.metrics.admitted()
        for rec in state["queued"]:
            engine.submit(rec["prompt"], rec["max_new_tokens"],
                          rid=rec["rid"],
                          arrival_s=rec.get("arrival_s", 0.0))
        return engine
