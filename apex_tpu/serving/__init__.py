"""apex_tpu.serving — continuous-batching TPU inference runtime.

Paged KV cache (budget from the calibrated memory tier), a
prefill/decode scheduler with one static decode shape, request
telemetry on the metric registry, and the PR 5 drain/resume contract
for preempted servers. See docs/serving.md.
"""

from apex_tpu.serving.engine import ServerMetrics, ServingEngine
from apex_tpu.serving.kv_cache import (
    PageAllocator,
    PageBudget,
    PagedKVCache,
    derive_page_budget,
    page_hbm_bytes,
)
from apex_tpu.serving.loadgen import (
    TraceRequest,
    make_trace,
    run_closed_loop,
    run_sequential,
)
from apex_tpu.serving.scheduler import (
    ContinuousBatchScheduler,
    Request,
    build_decode_step,
    build_prefill,
    fp8_weight_scales,
    pages_per_request,
)

__all__ = [
    "ContinuousBatchScheduler",
    "PageAllocator",
    "PageBudget",
    "PagedKVCache",
    "Request",
    "ServerMetrics",
    "ServingEngine",
    "TraceRequest",
    "build_decode_step",
    "build_prefill",
    "derive_page_budget",
    "fp8_weight_scales",
    "make_trace",
    "page_hbm_bytes",
    "pages_per_request",
    "run_closed_loop",
    "run_sequential",
]
