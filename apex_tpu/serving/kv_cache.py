"""Paged KV cache for the continuous-batching serving runtime.

The cache is two donated device buffers ``[L, P + 1, page_size, nkv, d]``
(k and v) plus a host-side free-list allocator with per-request page
accounting. Requests own page lists; the scheduler maps them into a
static ``[B, max_pages]`` block table consumed by the jit decode step,
so the device side never sees a dynamic shape.

Page ``P`` (the last one) is the *trash page*: inactive batch slots
scatter their (masked, never-read) k/v writes there, which keeps the
decode step total — no ``lax.cond`` per slot, no out-of-bounds scatter.
The allocator never hands it out.

The page *budget* is derived from the calibrated memory tier rather than
guessed: usable HBM = ``device_hbm_bytes()`` × safety − the live
``MemoryMonitor`` watermark, divided by the per-page footprint corrected
by the ``hbm_priors.json`` measured/modeled ratio (PR 18). On hosts with
no calibration the priors' default ratio applies, so the budget is
conservative, not optimistic.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "PageAllocator",
    "PageBudget",
    "PagedKVCache",
    "derive_page_budget",
    "page_hbm_bytes",
]


def page_hbm_bytes(cfg, page_size: int, dtype=None) -> int:
    """Modeled HBM bytes of ONE page: k + v across all layers."""
    dtype = cfg.dtype if dtype is None else dtype
    itemsize = jnp.dtype(dtype).itemsize
    return (2 * cfg.num_layers * page_size * cfg.num_kv_heads
            * cfg.head_dim * itemsize)


@dataclasses.dataclass(frozen=True)
class PageBudget:
    """The derivation trail of a page budget (kept for telemetry/docs —
    a budget that can't explain itself can't be debugged)."""

    pages: int
    page_bytes: int          # modeled bytes per page
    ratio: float             # hbm_priors measured/modeled correction
    hbm_bytes: int           # device HBM limit used
    watermark_bytes: int     # live MemoryMonitor watermark subtracted
    usable_bytes: int        # hbm * safety - watermark (floored at 0)
    safety: float


def derive_page_budget(cfg, page_size: int, *,
                       hbm_bytes: Optional[int] = None,
                       watermark_bytes: Optional[int] = None,
                       priors: Optional[dict] = None,
                       safety: float = 0.90,
                       dtype=None) -> PageBudget:
    """Page budget from the calibrated memory tier.

    ``pages = floor((hbm × safety − watermark) / (page_bytes × ratio))``
    where ``ratio`` is the hbm_priors measured/modeled correction (the
    default ratio when no serving-specific prior exists yet). Every
    input is overridable for tests; defaults read the live tier:
    ``device_hbm_bytes()``, the active ``MemoryMonitor`` watermark (0
    when none is attached), and the committed ``hbm_priors.json``.
    """
    from apex_tpu.analysis.memory_checks import load_hbm_priors, prior_for
    from apex_tpu.ops.pallas_config import device_hbm_bytes

    if not 0.0 < safety <= 1.0:
        raise ValueError(f"safety must be in (0, 1], got {safety}")
    if hbm_bytes is None:
        hbm_bytes = device_hbm_bytes()
    if watermark_bytes is None:
        from apex_tpu.observability.memory.hbm import active_monitor
        mon = active_monitor()
        watermark_bytes = mon.watermark_bytes if mon is not None else 0
    if priors is None:
        priors = load_hbm_priors()
    ratio = prior_for("serving_decode_step", priors, default=True)
    page_bytes = page_hbm_bytes(cfg, page_size, dtype=dtype)
    usable = max(0, int(hbm_bytes * safety) - int(watermark_bytes))
    pages = int(usable // max(1, int(math.ceil(page_bytes * ratio))))
    return PageBudget(pages=pages, page_bytes=page_bytes, ratio=ratio,
                      hbm_bytes=int(hbm_bytes),
                      watermark_bytes=int(watermark_bytes),
                      usable_bytes=usable, safety=safety)


class PageAllocator:
    """Free-list page allocator with per-owner accounting.

    Pages are plain ints in ``[0, num_pages)``; owners are request ids.
    Allocation is all-or-nothing (the admission check), frees are by
    owner (eviction returns every page a request held).
    """

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"need at least 1 page, got {num_pages}")
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._owned: Dict[object, List[int]] = {}

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_pages - len(self._free)

    def owners(self):
        return list(self._owned)

    def pages_of(self, owner) -> List[int]:
        return list(self._owned.get(owner, ()))

    def can_alloc(self, n: int) -> bool:
        return 0 < n <= len(self._free)

    def alloc(self, n: int, owner) -> List[int]:
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        if n > len(self._free):
            raise RuntimeError(
                f"out of KV pages: want {n}, have {len(self._free)} "
                f"free of {self.num_pages} (admission must check "
                f"can_alloc first)")
        pages = [self._free.pop() for _ in range(n)]
        self._owned.setdefault(owner, []).extend(pages)
        return pages

    def free_owner(self, owner) -> int:
        """Return every page held by ``owner``; returns the count."""
        pages = self._owned.pop(owner, [])
        # freed pages go back lowest-first so reuse stays compact
        self._free.extend(pages)
        self._free.sort(reverse=True)
        return len(pages)

    def live_pages(self) -> List[int]:
        return sorted(p for pages in self._owned.values() for p in pages)


class PagedKVCache:
    """The device-side paged cache + its allocator.

    Buffers are ``[L, P + 1, page_size, nkv, d]`` in ``cfg.dtype``; the
    extra page at index ``P`` (:attr:`trash_page`) absorbs inactive-slot
    scatter writes. The scheduler donates both buffers into the decode
    jit each step and stores the outputs back here.
    """

    def __init__(self, cfg, num_pages: int, page_size: int, dtype=None):
        self.cfg = cfg
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.dtype = cfg.dtype if dtype is None else dtype
        self.alloc = PageAllocator(self.num_pages)
        shape = (cfg.num_layers, self.num_pages + 1, self.page_size,
                 cfg.num_kv_heads, cfg.head_dim)
        self.k_pages = jnp.zeros(shape, self.dtype)
        self.v_pages = jnp.zeros(shape, self.dtype)

    @property
    def trash_page(self) -> int:
        return self.num_pages

    def utilization(self) -> float:
        return self.alloc.num_used / self.num_pages

    def hbm_bytes(self) -> int:
        return 2 * int(np.prod(self.k_pages.shape)) * jnp.dtype(
            self.dtype).itemsize

    # --------------------------------------------------------- transfers

    def write_prompt(self, pages: List[int], ks, vs) -> None:
        """Store prefill k/v ``[L, S, nkv, d]`` (S = len(pages) × page
        size) into ``pages`` in order."""
        L = self.cfg.num_layers
        n = len(pages)
        s = ks.shape[1]
        if s != n * self.page_size:
            raise ValueError(f"prefill length {s} != {n} pages × "
                             f"{self.page_size}")
        idx = jnp.asarray(pages, jnp.int32)
        kt = ks.astype(self.dtype).reshape(L, n, self.page_size,
                                           *ks.shape[2:])
        vt = vs.astype(self.dtype).reshape(L, n, self.page_size,
                                           *vs.shape[2:])
        self.k_pages = self.k_pages.at[:, idx].set(kt)
        self.v_pages = self.v_pages.at[:, idx].set(vt)

    def gather_pages(self, pages: List[int]):
        """Fetch ``pages`` to host as ``(k, v)`` numpy arrays
        ``[L, n, page_size, nkv, d]`` — the emergency-dump payload."""
        idx = jnp.asarray(pages, jnp.int32)
        return (np.asarray(self.k_pages[:, idx]),
                np.asarray(self.v_pages[:, idx]))

    def restore_pages(self, pages: List[int], k, v) -> None:
        """Scatter a dumped payload back (resume path). Restoring by
        scatter — not re-prefilling — is what keeps resumed decodes
        bit-identical to the uninterrupted run."""
        idx = jnp.asarray(pages, jnp.int32)
        self.k_pages = self.k_pages.at[:, idx].set(
            jnp.asarray(k, self.dtype))
        self.v_pages = self.v_pages.at[:, idx].set(
            jnp.asarray(v, self.dtype))

    # ------------------------------------------------------------ defrag

    def defrag(self) -> Dict[int, int]:
        """Compact live pages to the front; returns {old: new} so the
        caller can rewrite block tables. A no-op ({}), when already
        compact. One gather-permute per buffer — O(P), no per-page
        copies."""
        live = self.alloc.live_pages()
        mapping = {old: new for new, old in enumerate(live)}
        if all(old == new for old, new in mapping.items()):
            return {}
        taken = set(live)
        perm = list(live)
        perm.extend(p for p in range(self.num_pages) if p not in taken)
        perm.append(self.trash_page)
        idx = jnp.asarray(perm, jnp.int32)
        self.k_pages = jnp.take(self.k_pages, idx, axis=1)
        self.v_pages = jnp.take(self.v_pages, idx, axis=1)
        for owner in self.alloc.owners():
            self.alloc._owned[owner] = [
                mapping[p] for p in self.alloc._owned[owner]]
        n_live = len(live)
        self.alloc._free = list(range(self.num_pages - 1, n_live - 1, -1))
        return mapping
