"""Seeded synthetic traffic + the closed-loop CPU bench driver.

:func:`make_trace` draws a deterministic request trace — Poisson
arrivals (exponential inter-arrival gaps at ``arrival_rate_hz``) with
prompt/output lengths sampled from small categorical distributions —
so every bench run and every chaos test replays the identical
workload for a given seed.

:func:`run_closed_loop` drives a :class:`ServingEngine` over a trace
(wall-clock arrivals, or all-at-once for deterministic tests) and
returns the report the bench emits: p50/p99 request latency, ttft
p50/p99, tokens/s, mean batch occupancy. :func:`run_sequential` is
the honest baseline — one-request-at-a-time ``generate()`` on the
same trace, paying its real per-shape compile and no-batching costs —
that continuous batching must beat on tokens/s.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import List, Sequence

import numpy as np

__all__ = [
    "TraceRequest",
    "make_trace",
    "run_closed_loop",
    "run_sequential",
]


@dataclasses.dataclass(frozen=True)
class TraceRequest:
    rid: int
    arrival_s: float
    prompt: np.ndarray
    max_new_tokens: int


def make_trace(*, seed: int = 0, num_requests: int = 8,
               arrival_rate_hz: float = 50.0,
               prompt_lens: Sequence[int] = (4, 8, 12, 24),
               output_lens: Sequence[int] = (4, 8, 16),
               vocab_size: int = 256) -> List[TraceRequest]:
    """A deterministic Poisson trace (same seed → same trace, token
    for token)."""
    if num_requests < 1 or arrival_rate_hz <= 0:
        raise ValueError("need num_requests >= 1 and a positive "
                         "arrival rate")
    rng = np.random.RandomState(seed)
    t = 0.0
    trace = []
    for rid in range(num_requests):
        t += float(rng.exponential(1.0 / arrival_rate_hz))
        p = int(rng.choice(list(prompt_lens)))
        max_new = int(rng.choice(list(output_lens)))
        prompt = rng.randint(0, vocab_size, size=p).astype(np.int32)
        trace.append(TraceRequest(rid=rid, arrival_s=t, prompt=prompt,
                                  max_new_tokens=max_new))
    return trace


def _percentile(values, q: float) -> float:
    return float(np.percentile(np.asarray(values, np.float64), q))


def summarize(engine, wall_s: float) -> dict:
    """The serving report from an engine's completed requests — the
    shape bench.py emits verbatim as its ``serving`` object."""
    reqs = engine.completed
    lats = [(r.finish_s - r.submit_s) * 1e3 for r in reqs
            if r.finish_s is not None and r.submit_s is not None]
    ttfts = [(r.first_token_s - r.submit_s) * 1e3 for r in reqs
             if r.first_token_s is not None and r.submit_s is not None]
    tokens = sum(len(r.tokens) for r in reqs)
    report = {
        "requests": len(reqs),
        "tokens": tokens,
        "wall_s": round(wall_s, 4),
        "tokens_per_s": round(tokens / wall_s, 2) if wall_s > 0 else 0.0,
        "mean_occupancy": round(engine.mean_occupancy(), 4),
        "decode_steps": engine.scheduler.decode_steps,
        "prefills": engine.scheduler.prefill_count,
        "decode_retraces": engine.scheduler.decode_retraces(),
    }
    if lats:
        report["latency_p50_ms"] = round(_percentile(lats, 50), 3)
        report["latency_p99_ms"] = round(_percentile(lats, 99), 3)
    if ttfts:
        report["ttft_p50_ms"] = round(_percentile(ttfts, 50), 3)
        report["ttft_p99_ms"] = round(_percentile(ttfts, 99), 3)
    return report


def run_closed_loop(engine, trace: List[TraceRequest], *,
                    use_wall_clock: bool = True,
                    publish: bool = True) -> dict:
    """Drive ``engine`` over ``trace`` to completion and report.

    ``use_wall_clock=True`` injects each request when real time passes
    its arrival offset (the bench's arrival dynamics);
    ``use_wall_clock=False`` submits everything up front — fully
    deterministic scheduling for tests. ``publish`` mirrors the report
    as ``serving/*`` gauges on the engine's registry.
    """
    pending = collections.deque(
        sorted(trace, key=lambda t: (t.arrival_s, t.rid)))
    start = time.monotonic()
    while pending or engine.pending:
        now = time.monotonic() - start
        while pending and (not use_wall_clock
                           or pending[0].arrival_s <= now):
            tr = pending.popleft()
            engine.submit(tr.prompt, tr.max_new_tokens, rid=tr.rid,
                          arrival_s=tr.arrival_s)
        if engine.pending:
            engine.step()
        elif pending:
            # idle until the next arrival — nothing to decode
            time.sleep(max(0.0, min(
                0.01, pending[0].arrival_s - (time.monotonic() - start))))
    wall = time.monotonic() - start
    report = summarize(engine, wall)
    if publish:
        engine.metrics.publish_summary(report)
    return report


def run_sequential(params, cfg, trace: List[TraceRequest]) -> dict:
    """The no-batching baseline: each request runs alone through
    ``models.generate.generate`` (greedy), paying the real
    per-(prompt_len, max_new) compile and serialization costs a
    server without continuous batching would pay."""
    from apex_tpu.models.generate import generate

    start = time.monotonic()
    tokens = 0
    results = {}
    for tr in trace:
        import jax.numpy as jnp
        out = generate(params, jnp.asarray(tr.prompt)[None, :], cfg,
                       tr.max_new_tokens)
        out = np.asarray(out)  # block: the request is done when read
        results[tr.rid] = [int(t) for t in out[0, len(tr.prompt):]]
        tokens += tr.max_new_tokens
    wall = time.monotonic() - start
    return {
        "requests": len(trace),
        "tokens": tokens,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(tokens / wall, 2) if wall > 0 else 0.0,
        "results": results,
    }
